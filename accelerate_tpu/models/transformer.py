"""Decoder-only transformer (Llama/GPT family) — the flagship model.

TPU-native design decisions:

* every parameter is created with ``nn.with_partitioning`` and a *logical*
  axis name (``embed/heads/kv/mlp/vocab/expert``); the mesh mapping lives in
  :mod:`accelerate_tpu.parallel.sharding`, so DP/FSDP/TP/EP are config, not
  model surgery (the reference needs Megatron for TP: utils/megatron_lm.py);
* layers run under ``nn.scan`` — one compiled block body iterated
  ``num_layers`` times, keeping XLA compile time flat in depth;
* optional ``nn.remat`` (activation checkpointing — the reference's FSDP
  ``activation_checkpointing`` flag, utils/dataclasses.py:1173) with
  MXU-friendly ``dots`` policies;
* attention dispatches to XLA / Pallas-flash / ring via
  :mod:`accelerate_tpu.ops.attention`;
* MoE layers (Mixtral family) route with a dense one-hot dispatch einsum
  whose expert dim carries the ``expert`` logical axis (GSPMD all-to-all).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import dot_product_attention, paged_attention, paged_update
from .config import TransformerConfig

Dtype = Any


def _dtype(config: TransformerConfig) -> Dtype:
    return jnp.dtype(config.dtype)


def count_params(params: Any) -> int:
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))


# ---------------------------------------------------------------------- #
# building blocks
# ---------------------------------------------------------------------- #
class RMSNorm(nn.Module):
    config: TransformerConfig
    # param_only: declare and RETURN the scale without normalizing — the
    # fused-prologue path (ops/fused.py) applies the norm inside its
    # kernel and only needs the raw scale. Keeps the param at the same
    # tree path either way, so checkpoints interchange with the flag off.
    param_only: bool = False

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        eps = cfg.rms_norm_eps
        # Gemma stores zero-centered scales and multiplies by (1 + w);
        # a zeros init keeps a fresh norm at identity either way
        init = (
            nn.initializers.zeros_init()
            if cfg.norm_offset
            else nn.initializers.ones_init()
        )
        scale = self.param(
            "scale",
            nn.with_partitioning(init, ("norm",)),
            (x.shape[-1],),
            jnp.float32,
        )
        if self.param_only:
            return scale
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        y = x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
        mult = (1.0 + scale) if cfg.norm_offset else scale
        return (y * mult).astype(x.dtype)


def _scale_rope_freqs(freqs: jax.Array, scaling: Optional[dict]) -> jax.Array:
    """Apply HF-style rope frequency scaling to inverse frequencies.

    ``llama3`` mirrors transformers' ``_compute_llama3_parameters``
    (modeling_rope_utils.py): frequencies whose wavelength exceeds the
    original context keep full resolution divided by ``factor``, short
    wavelengths are untouched, and a smooth ramp interpolates between the
    two bands. ``linear`` is plain position-interpolation (freq/factor).
    The parity anchor is reference utils/modeling.py:1608 — its loader is
    architecture-faithful to whatever rope the checkpoint declares.
    """
    from .config import rope_type as _rope_type

    rt = _rope_type(scaling)
    if rt == "default":
        return freqs
    factor = float(scaling["factor"])
    if rt == "linear":
        return freqs / factor
    if rt == "llama3":
        low = float(scaling["low_freq_factor"])
        high = float(scaling["high_freq_factor"])
        old_len = float(scaling["original_max_position_embeddings"])
        wavelen = 2.0 * jnp.pi / freqs
        # smooth ramp between the low/high frequency bands
        smooth = (old_len / wavelen - low) / (high - low)
        smoothed = (1.0 - smooth) * freqs / factor + smooth * freqs
        scaled = jnp.where(wavelen > old_len / low, freqs / factor, freqs)
        is_medium = (wavelen <= old_len / low) & (wavelen >= old_len / high)
        return jnp.where(is_medium, smoothed, scaled)
    raise ValueError(f"unsupported rope_scaling type {rt!r}")


def rope(
    x: jax.Array,
    positions: jax.Array,
    theta: float,
    scaling: Optional[dict] = None,
) -> jax.Array:
    """Rotary position embedding, x: (B, S, H, D), positions: (B, S)."""
    from ..parallel.sharding import live_mesh

    mesh = live_mesh()
    if mesh is not None:
        # The rotation pairs element i with element i + D/2 across the last
        # dim. When the qkv projection's output sharding propagates a
        # head_dim split into here (heuristic FSDP merging heads*head_dim),
        # XLA's SPMD partitioner produces numerically wrong attention
        # downstream of the split/concat (observed ~1e-2 logit divergence
        # vs the same weights replicated; q/k themselves and the attention
        # core are each exact in isolation). Pin head_dim unsplit through
        # the rotation; every other dim stays free for the partitioner.
        from jax.sharding import NamedSharding, PartitionSpec

        spec = PartitionSpec(
            *([PartitionSpec.UNCONSTRAINED] * (x.ndim - 1)), None
        )
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    freqs = _scale_rope_freqs(freqs, scaling)
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs  # (B,S,1,D/2)
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _make_proj(cfg: TransformerConfig, dtype):
    """The shared projection factory: nn.Dense, or Fp8Dense when
    ``cfg.fp8`` (the te.Linear swap, reference utils/transformer_engine.py:36)
    — same param layout either way, so checkpoints interchange. Biases are
    off except where an architecture convention turns them on per-proj
    (``use_bias``/``bias_axis`` — the Qwen2 q/k/v biases)."""

    def proj(name, out_features, axes, use_bias=False, bias_axis=None):
        kernel_init = nn.with_partitioning(nn.initializers.lecun_normal(), axes)
        kw = {}
        if use_bias:
            kw["bias_init"] = nn.with_partitioning(
                nn.initializers.zeros_init(), (bias_axis,)
            )
        if cfg.fp8:
            from ..ops.fp8 import Fp8Dense

            return Fp8Dense(
                out_features, dtype=dtype, param_dtype=jnp.float32,
                kernel_init=kernel_init, use_bias=use_bias, name=name, **kw,
            )
        return nn.Dense(
            out_features,
            use_bias=use_bias,
            dtype=dtype,
            param_dtype=jnp.float32,
            kernel_init=kernel_init,
            name=name,
            **kw,
        )

    return proj


class _ProjParams(nn.Module):
    """Declares exactly nn.Dense's param tree (kernel/bias names, shapes,
    init fns, partitioning, param_dtype) WITHOUT running the matmul — the
    fused prologue (ops/fused.py) consumes the raw arrays instead. Same
    module name => same tree paths AND same per-param init RNG streams,
    so checkpoints and init values interchange with the unfused path."""

    features: int
    axes: tuple
    use_bias: bool = False
    bias_axis: Optional[str] = None

    @nn.compact
    def __call__(self, in_features):
        kernel = self.param(
            "kernel",
            nn.with_partitioning(nn.initializers.lecun_normal(), self.axes),
            (in_features, self.features),
            jnp.float32,
        )
        if hasattr(kernel, "unbox"):
            kernel = kernel.unbox()
        bias = None
        if self.use_bias:
            bias = self.param(
                "bias",
                nn.with_partitioning(
                    nn.initializers.zeros_init(), (self.bias_axis,)
                ),
                (self.features,),
                jnp.float32,
            )
            if hasattr(bias, "unbox"):
                bias = bias.unbox()
        return kernel, bias


def _lora_delta_fn(module: nn.Module, lora, lora_stacks):
    """Per-projection LoRA delta closure for Attention/MLP.

    Returns ``delta(inp, name) -> array | None``: the gathered low-rank
    contribution for projection ``name`` (None when the adapter state
    doesn't target it). Dropout (training only) is applied to the delta's
    INPUT — the standard LoRA placement — via an nn.Dropout owned by the
    calling module, so it needs a "dropout" rng only when actually live.
    """
    if lora is None or lora_stacks is None:
        return lambda inp, name: None
    from ..adapters.runtime import lora_delta

    def delta(inp, name):
        pair = lora_stacks.get(name) if hasattr(lora_stacks, "get") else None
        if pair is None:
            return None
        z = inp
        if lora.dropout_rate > 0.0 and not lora.deterministic:
            z = nn.Dropout(lora.dropout_rate, name=f"lora_drop_{name}")(
                z, deterministic=False
            )
        return lora_delta(z, pair, lora.slot_ids, lora.scales)

    return delta


class Attention(nn.Module):
    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, positions, mask=None, kv_lengths=None,
                 paged=None, layer_window=None, pre_norm_scale=None,
                 lora=None, lora_stacks=None):
        decode = self.decode
        cfg = self.config
        delta = _lora_delta_fn(self, lora, lora_stacks)
        # static homogeneous band, or the per-layer traced one (Gemma-2)
        window = cfg.sliding_window if layer_window is None else layer_window
        # Gemma-2 decouples the attention scale from head_dim
        scale = (
            cfg.query_pre_attn_scalar ** -0.5
            if cfg.query_pre_attn_scalar is not None else None
        )
        dtype = _dtype(cfg)
        q_dim = cfg.num_heads * cfg.head_dim
        kv_dim = cfg.num_kv_heads * cfg.head_dim

        proj = _make_proj(cfg, dtype)

        b, s = x.shape[:2]
        fused_qkv = False
        if pre_norm_scale is not None:
            # Block handed us the RAW residual stream + the norm scale:
            # the fused-kernels path. Fuse norm -> qkv -> rope when the
            # kernel supports the shape; otherwise apply the norm here
            # (exact RMSNorm math) and fall through unfused.
            from ..ops import fused as fused_ops

            # LoRA on q/k/v has to add its delta to the raw projection
            # outputs, which the fused kernel never materializes — force
            # the exact unfused fallback when any qkv target is adapted
            # (o_proj-only adapters keep the fused prologue)
            lora_on_qkv = lora_stacks is not None and any(
                t in lora_stacks for t in ("q_proj", "k_proj", "v_proj")
            )
            if (
                not self.decode
                and not cfg.fp8
                and not lora_on_qkv
                and fused_ops.prologue_supported(
                    cfg.num_heads, cfg.num_kv_heads, cfg.head_dim,
                    b, s, x.shape[-1],
                )
            ):
                wq, bq = _ProjParams(
                    q_dim, ("embed", "heads"), cfg.qkv_bias, "heads",
                    name="q_proj",
                )(x.shape[-1])
                wk, bk = _ProjParams(
                    kv_dim, ("embed", "kv"), cfg.qkv_bias, "kv",
                    name="k_proj",
                )(x.shape[-1])
                wv, bv = _ProjParams(
                    kv_dim, ("embed", "kv"), cfg.qkv_bias, "kv",
                    name="v_proj",
                )(x.shape[-1])
                q, k, v = fused_ops.fused_qkv_prologue(
                    x, pre_norm_scale, wq, wk, wv, bq, bk, bv, positions,
                    eps=cfg.rms_norm_eps, norm_offset=cfg.norm_offset,
                    num_heads=cfg.num_heads, num_kv_heads=cfg.num_kv_heads,
                    head_dim=cfg.head_dim, theta=cfg.rope_theta,
                    scaling=cfg.rope_scaling, dtype=dtype,
                )
                fused_qkv = True
            else:
                x = fused_ops.rms_norm_reference(
                    x, pre_norm_scale, eps=cfg.rms_norm_eps,
                    norm_offset=cfg.norm_offset,
                )
        if not fused_qkv:
            q = proj(
                "q_proj", q_dim, ("embed", "heads"),
                use_bias=cfg.qkv_bias, bias_axis="heads",
            )(x)
            k = proj(
                "k_proj", kv_dim, ("embed", "kv"),
                use_bias=cfg.qkv_bias, bias_axis="kv",
            )(x)
            v = proj(
                "v_proj", kv_dim, ("embed", "kv"),
                use_bias=cfg.qkv_bias, bias_axis="kv",
            )(x)
            dq = delta(x, "q_proj")
            if dq is not None:
                q = q + dq
            dk = delta(x, "k_proj")
            if dk is not None:
                k = k + dk
            dv = delta(x, "v_proj")
            if dv is not None:
                v = v + dv
            q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
            k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
            v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)

        use_paged = False
        if decode and paged is not None:
            # Paged decode (vLLM block tables, static-shape XLA form): the
            # K/V pools are cache variables with NO batch dim — every slot
            # and every prefill call shares ONE pool pytree, routed through
            # the per-call block tables in ``paged`` (ops/attention.py's
            # PagedKVState). The has_variable guard keeps the init pass on
            # the plain path (creation must not write).
            # int8 paged KV: pools store sym-quantized rows, one fp32
            # amax scale per token slot beside them ((num_blocks,
            # block_size) — ~4 bytes/token overhead vs the 2x row
            # shrink). kv_dtype is static PagedKVState metadata, so the
            # branch resolves at trace time: one engine, one lattice.
            kv_int8 = getattr(paged, "kv_dtype", "native") == "int8"
            pool_dtype = jnp.int8 if kv_int8 else k.dtype
            is_initialized = self.has_variable("cache", "key_pool")
            key_pool = self.variable(
                "cache", "key_pool",
                lambda: jnp.zeros(
                    (paged.num_blocks, paged.block_size,
                     cfg.num_kv_heads, cfg.head_dim), pool_dtype,
                ),
            )
            value_pool = self.variable(
                "cache", "value_pool",
                lambda: jnp.zeros(
                    (paged.num_blocks, paged.block_size,
                     cfg.num_kv_heads, cfg.head_dim), pool_dtype,
                ),
            )
            key_scale = value_scale = None
            if kv_int8:
                key_scale = self.variable(
                    "cache", "key_scale",
                    lambda: jnp.zeros(
                        (paged.num_blocks, paged.block_size), jnp.float32
                    ),
                )
                value_scale = self.variable(
                    "cache", "value_scale",
                    lambda: jnp.zeros(
                        (paged.num_blocks, paged.block_size), jnp.float32
                    ),
                )
            use_paged = is_initialized
            decode = False
        elif decode:
            # KV-cache decode (flax decode-cache pattern): a fixed-size
            # per-layer cache collection, updated in place at cache_index.
            # Static shapes throughout — XLA-friendly autoregression.
            # The has_variable guard keeps the init pass from running the
            # update body (it would advance cache_index on creation).
            max_len = cfg.max_seq_len
            is_initialized = self.has_variable("cache", "cached_key")
            cached_key = self.variable(
                "cache", "cached_key",
                lambda: jnp.zeros((b, max_len, cfg.num_kv_heads, cfg.head_dim), k.dtype),
            )
            cached_value = self.variable(
                "cache", "cached_value",
                lambda: jnp.zeros((b, max_len, cfg.num_kv_heads, cfg.head_dim), v.dtype),
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.asarray(0, jnp.int32)
            )
            decode = is_initialized
        if use_paged:
            # per-slot positions: slot b's token i sits at global position
            # cache_len[b] + i (heterogeneous across the batch — the dense
            # path's single scalar index cannot express a decode batch
            # whose members are at different depths)
            positions = paged.cache_len[:, None] + jnp.arange(s)[None, :]
            q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
            k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
            new_ks = new_vs = None
            if kv_int8:
                new_k, new_v, new_ks, new_vs = paged_update(
                    key_pool.value, value_pool.value, k, v, paged,
                    key_scale=key_scale.value,
                    value_scale=value_scale.value,
                )
                key_scale.value = new_ks
                value_scale.value = new_vs
            else:
                new_k, new_v = paged_update(
                    key_pool.value, value_pool.value, k, v, paged
                )
            key_pool.value = new_k
            value_pool.value = new_v
            out = paged_attention(
                q, new_k, new_v, paged, scale=scale,
                softcap=cfg.attn_softcap, window=window,
                key_scale=new_ks, value_scale=new_vs,
            )
        elif decode:
            idx = cache_index.value
            positions = idx + jnp.arange(s)[None, :]  # (1, s) broadcasts over batch
            q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
            k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
            key_cache = jax.lax.dynamic_update_slice(
                cached_key.value, k, (0, idx, 0, 0)
            )
            value_cache = jax.lax.dynamic_update_slice(
                cached_value.value, v, (0, idx, 0, 0)
            )
            cached_key.value = key_cache
            cached_value.value = value_cache
            cache_index.value = idx + s
            # attend over the full cache, masking positions not yet written:
            # col j visible to query i (global pos idx+i) iff j <= idx+i —
            # and, under a sliding window, iff j > idx+i - window (rows
            # are GLOBAL positions, so the band is anchored at the true
            # decode position, not the cache buffer's end)
            cols = jnp.arange(max_len)[None, None, None, :]
            rows = (idx + jnp.arange(s))[None, None, :, None]
            dec_mask = cols <= rows  # (1,1,s,max_len)
            if window is not None:
                dec_mask = jnp.logical_and(dec_mask, cols > rows - window)
            out = dot_product_attention(
                q, key_cache, value_cache, mask=dec_mask, causal=False,
                scale=scale, softcap=cfg.attn_softcap,
                implementation="xla",
            )
        else:
            if not fused_qkv:  # the fused prologue already applied rope
                q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
                k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
            out = dot_product_attention(
                q, k, v, mask=mask, causal=cfg.causal,
                kv_lengths=kv_lengths,
                scale=scale, softcap=cfg.attn_softcap,
                implementation=cfg.attention_impl,
                window=window,
            )
        # named residual: the "save_attn" remat policy keeps exactly these,
        # so backward never recomputes the attention kernel
        out = checkpoint_name(out, "attn_out")
        out = out.reshape(b, s, q_dim)
        y = proj("o_proj", cfg.hidden_size, ("heads", "embed"))(out)
        do = delta(out, "o_proj")
        if do is not None:
            y = y + do
        return y


class MLP(nn.Module):
    """SwiGLU feed-forward (Llama family)."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x, lora=None, lora_stacks=None):
        cfg = self.config
        dtype = _dtype(cfg)
        proj = _make_proj(cfg, dtype)
        delta = _lora_delta_fn(self, lora, lora_stacks)

        # named so the "save_mlp" remat policy can keep exactly these two
        # f-wide activations (the expensive recompute in backward) while
        # everything else recomputes — the long-context middle ground
        # between "full" (recomputes all matmuls) and "dots" (saves every
        # matmul output, OOM at S=8192 on 16G)
        gate = proj("gate_proj", cfg.intermediate_size, ("embed", "mlp"))(x)
        dg = delta(x, "gate_proj")
        if dg is not None:
            gate = gate + dg
        gate = checkpoint_name(gate, "mlp_gate_out")
        up = proj("up_proj", cfg.intermediate_size, ("embed", "mlp"))(x)
        du = delta(x, "up_proj")
        if du is not None:
            up = up + du
        up = checkpoint_name(up, "mlp_up_out")
        act = (
            nn.silu
            if cfg.mlp_activation == "silu"
            else lambda z: nn.gelu(z, approximate=True)  # Gemma gelu_tanh
        )
        mid = act(gate) * up
        y = proj("down_proj", cfg.hidden_size, ("mlp", "embed"))(mid)
        dd = delta(mid, "down_proj")
        if dd is not None:
            y = y + dd
        return y


class MoE(nn.Module):
    """Mixtral-style sparse MoE.

    Expert weights are stacked on a leading ``expert`` logical axis; with
    ``ep_size > 1`` GSPMD shards experts across the ``ep`` mesh axis and the
    dispatch/combine lowers to all-to-all — the expert-parallel capability
    absent from the reference (SURVEY.md §2.4 EP row).

    Three dispatch modes (``config.moe_dispatch``): "ragged" — grouped
    matmuls via jax.lax.ragged_dot, exact at ep==1, shard-capacity
    schedule (moe_ragged_ep) under ep>1 — the default at every ep;
    "capacity" — the GShard-style static-shape schedule (ops/moe.py,
    FLOPs independent of E, the GSPMD-auto alternative and old-jax
    fallback); "dense" — every expert computes every token (O(E) FLOPs,
    exact math, the test oracle).
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        from ..ops.moe import load_balancing_loss, moe_dispatch_combine

        cfg = self.config
        dtype = _dtype(cfg)
        E, K = cfg.num_experts, cfg.num_experts_per_tok
        b, s, h = x.shape
        f = cfg.intermediate_size

        router = nn.Dense(
            E,
            use_bias=False,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_partitioning(nn.initializers.lecun_normal(), ("embed", None)),
            name="router",
        )
        logits = router(x.astype(jnp.float32))  # (B,S,E)
        weights, sel = jax.lax.top_k(jax.nn.softmax(logits, -1), K)  # (B,S,K)
        weights = weights / jnp.sum(weights, -1, keepdims=True)

        def epar(name, shape, axes):
            return self.param(
                name,
                nn.with_partitioning(nn.initializers.lecun_normal(), axes),
                shape,
                jnp.float32,
            )

        w_gate = epar("gate_proj", (E, h, f), ("expert", "embed", "mlp"))
        w_up = epar("up_proj", (E, h, f), ("expert", "embed", "mlp"))
        w_down = epar("down_proj", (E, f, h), ("expert", "mlp", "embed"))

        xc = x.astype(dtype)
        from ..parallel.sharding import live_mesh

        mesh = live_mesh()
        ep_live = mesh is not None and mesh.shape.get("ep", 1) > 1
        dispatch = cfg.moe_dispatch
        if dispatch == "auto":
            # ragged everywhere: exact AND measured faster on a single
            # chip (ops/moe.py numbers); under ep>1 the shard-capacity EP
            # schedule (moe_ragged_ep) beats capacity on both measured
            # axes — at equal capacity_factor it drops 3-10x fewer tokens
            # under skewed routing and its compiled step moves ~2x fewer
            # collective bytes (dp=2 x ep=4 mesh; numbers in
            # moe_ragged_ep's docstring). capacity remains only for jax
            # versions without partial-manual shard_map.
            from ..ops.moe import ragged_ep_supported

            dispatch = (
                "capacity" if ep_live and not ragged_ep_supported()
                else "ragged"
            )
        if dispatch == "ragged":
            from ..ops.moe import moe_ragged, moe_ragged_ep

            if ep_live:
                # expert-parallel ragged: shard-capacity schedule — the
                # sorted rows' per-shard region runs through a static
                # window with ragged-packed local experts (ops/moe.py)
                out = moe_ragged_ep(
                    xc.reshape(b * s, h),
                    sel.reshape(b * s, K),
                    weights.reshape(b * s, K),
                    w_gate.astype(dtype),
                    w_up.astype(dtype),
                    w_down.astype(dtype),
                    mesh=mesh,
                    capacity_factor=cfg.moe_capacity_factor,
                ).reshape(b, s, h)
            else:
                out = moe_ragged(
                    xc.reshape(b * s, h),
                    sel.reshape(b * s, K),
                    weights.reshape(b * s, K),
                    w_gate.astype(dtype),
                    w_up.astype(dtype),
                    w_down.astype(dtype),
                ).reshape(b, s, h)
        elif dispatch == "capacity":
            def experts_fn(buf):  # (E, C, h) -> (E, C, h)
                hidden = jnp.einsum("ech,ehf->ecf", buf, w_gate.astype(dtype))
                hidden = nn.silu(hidden) * jnp.einsum(
                    "ech,ehf->ecf", buf, w_up.astype(dtype)
                )
                return jnp.einsum("ecf,efh->ech", hidden, w_down.astype(dtype))

            out = moe_dispatch_combine(
                xc.reshape(b * s, h),
                sel.reshape(b * s, K),
                weights.reshape(b * s, K),
                experts_fn,
                E,
                capacity_factor=cfg.moe_capacity_factor,
            ).reshape(b, s, h)
        elif dispatch == "dense":
            # combine weights as dense (B,S,E): zero for unselected experts
            combine = jnp.zeros_like(logits).at[
                jnp.arange(b)[:, None, None],
                jnp.arange(s)[None, :, None],
                sel,
            ].add(weights)
            hidden = jnp.einsum("bsh,ehf->ebsf", xc, w_gate.astype(dtype))
            hidden = nn.silu(hidden) * jnp.einsum(
                "bsh,ehf->ebsf", xc, w_up.astype(dtype)
            )
            expert_out = jnp.einsum("ebsf,efh->ebsh", hidden, w_down.astype(dtype))
            out = jnp.einsum("ebsh,bse->bsh", expert_out, combine.astype(dtype))
        else:
            raise ValueError(
                f"unknown moe_dispatch {cfg.moe_dispatch!r}; use 'auto', "
                "'ragged', 'capacity' or 'dense'"
            )
        self.sow(
            "intermediates", "moe_aux_loss", load_balancing_loss(logits, sel, E)
        )
        return out.astype(x.dtype)


class Block(nn.Module):
    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, positions, mask=None, kv_lengths=None,
                 paged=None, lora=None, scanned=None):
        from ..parallel.sharding import constrain_activations

        cfg = self.config
        # ``scanned`` is this layer's slice of the per-layer traced data:
        # either the bare layer-window array (the pre-adapter form) or a
        # dict {"window": ..., "lora": {target: {lora_a, lora_b}}} — both
        # shapes ride nn.scan's in_axes=0 the same way, the dict just
        # scans every leaf
        if isinstance(scanned, dict):
            layer_window = scanned.get("window")
            lora_scan = scanned.get("lora")
        else:
            layer_window, lora_scan = scanned, None
        attn_lora = mlp_lora = None
        if lora_scan is not None:
            attn_lora = {
                t: p for t, p in lora_scan.items()
                if t in ("q_proj", "k_proj", "v_proj", "o_proj")
            } or None
            mlp_lora = {
                t: p for t, p in lora_scan.items()
                if t in ("gate_proj", "up_proj", "down_proj")
            } or None
        if cfg.fused_kernels:
            # fused prologue: hand Attention the raw residual stream plus
            # the norm scale so ops/fused.py can run norm -> qkv -> rope
            # as one kernel (it falls back to the exact unfused math for
            # shapes it can't tile, and for decode/fp8)
            attn_scale = RMSNorm(cfg, name="attn_norm", param_only=True)(x)
            attn_out = Attention(cfg, decode=self.decode, name="attn")(
                x, positions, mask, kv_lengths, paged, layer_window,
                pre_norm_scale=attn_scale, lora=lora, lora_stacks=attn_lora,
            )
        else:
            attn_out = Attention(cfg, decode=self.decode, name="attn")(
                RMSNorm(cfg, name="attn_norm")(x), positions, mask,
                kv_lengths, paged, layer_window,
                lora=lora, lora_stacks=attn_lora,
            )
        if cfg.post_norms:
            # Gemma-2 block: a norm AFTER each sublayer too (pre + post,
            # 4 per block — transformers Gemma2DecoderLayer)
            attn_out = RMSNorm(cfg, name="post_attn_norm")(attn_out)
        h = checkpoint_name(x + attn_out, "attn_res")
        if cfg.num_experts > 0:
            # MoE blocks don't take adapters (the expert weights are the
            # specialization mechanism there); attention adapters still apply
            ff_out = MoE(cfg, name="moe")(RMSNorm(cfg, name="mlp_norm")(h))
        else:
            ff_out = MLP(cfg, name="mlp")(
                RMSNorm(cfg, name="mlp_norm")(h),
                lora=lora, lora_stacks=mlp_lora,
            )
        if cfg.post_norms:
            ff_out = RMSNorm(cfg, name="post_mlp_norm")(ff_out)
        # pin the residual stream's layout once per layer so GSPMD cannot
        # alternate it between batch-sharded and weight-following layouts
        # (each flip is a full resharding per layer)
        return constrain_activations(h + ff_out), None


def _make_embed(cfg: TransformerConfig, dtype, name: Optional[str] = "embed") -> nn.Embed:
    kw = {"name": name} if name is not None else {}
    return nn.Embed(
        cfg.vocab_size,
        cfg.hidden_size,
        dtype=dtype,
        param_dtype=jnp.float32,
        # vocab dim carries BOTH tp and the ZeRO seat (("vocab","zero") ->
        # (tp, fsdp)); the feature dim stays replicated. Sharding the
        # feature dim (what the fsdp heuristic would pick) makes every
        # lookup hidden-sharded and triggers involuntary full reshards
        # against the batch-sharded activation layout, fwd and bwd.
        embedding_init=nn.with_partitioning(
            nn.initializers.normal(0.02), (("vocab", "zero"), "embed")
        ),
        **kw,
    )


_REMAT_POLICIES = {
    "full": lambda: None,
    "dots": lambda: jax.checkpoint_policies.checkpoint_dots,
    # "dots" + grouped-matmul outputs: checkpoint_dots matches only the
    # dot_general primitive, so under moe_dispatch="ragged" the backward
    # would re-run every ragged_dot (the expert FLOPs — the single biggest
    # matmul cost in an MoE block). Saving ragged_dot_general too keeps
    # the remat recompute down to elementwise ops, same as "dots" does
    # for dense blocks.
    "dots_ragged": lambda: jax.checkpoint_policies.save_from_both_policies(
        jax.checkpoint_policies.checkpoint_dots,
        lambda prim, *_, **__: getattr(prim, "name", "")
        == "ragged_dot_general",
    ),
    "dots_with_no_batch_dims": (
        lambda: jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims
    ),
    "save_attn": lambda: jax.checkpoint_policies.save_only_these_names(
        "attn_out"
    ),
    # the long-context (S=8k, B=1) middle ground: keep the f-wide MLP
    # activations, the attention output, and the residual mid — backward
    # then recomputes only the attention path (norm+qkv+kernel, the small
    # fraction of layer FLOPs) instead of the whole layer ("full") while
    # saving far less than "dots" (which keeps every matmul output and
    # OOMs at S=8192 on 16G chips)
    "save_mlp": lambda: jax.checkpoint_policies.save_only_these_names(
        "attn_out", "attn_res", "mlp_gate_out", "mlp_up_out"
    ),
}


def _layer_windows_array(cfg: TransformerConfig):
    """The (num_layers,) int32 per-layer window array for
    ``cfg.layer_windows``, or None. Full-attention layers carry a
    sentinel wider than any sequence, so ONE traced band formula covers
    the whole scan (col > row - w is vacuous at w >= seq)."""
    if cfg.layer_windows is None:
        return None
    return jnp.asarray(
        [w if w is not None else (1 << 30) for w in cfg.layer_windows],
        jnp.int32,
    )


def _apply_layer_stack(cfg: TransformerConfig, x, *extra, decode=False,
                       block_cls=None, num_layers=None, per_layer=None):
    """Run a block stack (scan or unrolled, optional remat) on hidden
    states. Must be called inside an ``nn.compact`` context — the created
    modules attach to the calling module's scope, so CausalLM,
    SequenceClassifier and the seq2seq decoder share one implementation.

    ``extra``: per-call broadcast arguments of the block (positions, mask,
    memory, ...). ``per_layer``: an optional pytree whose every leaf has a
    leading (num_layers, ...) axis, passed as the block's LAST positional
    argument and scanned over that axis — the Gemma-2 per-layer window
    array, or the adapters' {"window", "lora"} dict (nn.scan's in_axes
    applies per-ARGUMENT, so a dict of stacks scans exactly like a bare
    array). ``block_cls``: defaults to :class:`Block`; the seq2seq decoder
    passes :class:`~.seq2seq.DecoderBlock`. Blocks must return
    ``(x, None)``.
    """
    base_cls = block_cls or Block
    block_kwargs = {"decode": decode}  # every block class supports decode
    cls = base_cls
    if cfg.remat:
        cls = nn.remat(
            base_cls,
            policy=_REMAT_POLICIES[cfg.remat](),
            prevent_cse=not cfg.scan_layers,
            static_argnums=(),
        )
    n = num_layers or cfg.num_layers

    if cfg.scan_layers:
        in_axes = tuple(nn.broadcast for _ in extra)
        args = extra
        if per_layer is not None:
            in_axes = in_axes + (0,)
            args = extra + (per_layer,)
        x, _ = nn.scan(
            cls,
            variable_axes={"params": 0, "intermediates": 0, "cache": 0},
            # "dropout": LoRA delta dropout inside the scanned block — the
            # entry is inert unless a dropout rng is actually passed to
            # apply (adapter training with LoraConfig.dropout > 0)
            split_rngs={"params": True, "dropout": True},
            in_axes=in_axes,
            length=n,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(cfg, **block_kwargs, name="layers")(x, *args)
    else:
        for i in range(n):
            if per_layer is None:
                args = extra
            else:
                # slice EVERY leaf's layer axis (per_layer may be a dict
                # of adapter stacks, not just the bare window array)
                args = extra + (jax.tree.map(lambda l: l[i], per_layer),)
            x, _ = cls(cfg, **block_kwargs, name=f"layer_{i}")(x, *args)
    return x


class CausalLM(nn.Module):
    """The language model: embed -> scan(Block) -> norm -> lm_head.

    ``__call__(input_ids, positions=None, mask=None) -> logits``.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, mask=None, decode=False,
                 paged=None, lora=None):
        cfg = self.config
        dtype = _dtype(cfg)
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1])[None, :], input_ids.shape
            )
        from ..parallel.sharding import constrain_activations

        embed = _make_embed(cfg, dtype)
        x = embed(input_ids)
        if cfg.embed_scale:  # Gemma scales embeddings by sqrt(hidden)
            x = x * jnp.asarray(np.sqrt(cfg.hidden_size), x.dtype)
        x = constrain_activations(x)
        # the explicit Nones fill the block's kv_lengths/paged/lora slots
        # so the per-layer scanned pytree (window array and/or adapter
        # stacks) lands on the block's LAST positional argument. ``lora``
        # splits into a broadcast context (slot_ids/scales, shared by all
        # layers) and the per-layer stacks riding the scan axis.
        windows = _layer_windows_array(cfg)
        lora_ctx = lora.context() if lora is not None else None
        scanned = None
        if windows is not None or (lora is not None and lora.stacks is not None):
            scanned = {}
            if windows is not None:
                scanned["window"] = windows
            if lora is not None and lora.stacks is not None:
                scanned["lora"] = lora.stacks
        x = _apply_layer_stack(
            cfg, x, positions, mask, None, paged, lora_ctx, decode=decode,
            per_layer=scanned,
        )
        x = constrain_activations(RMSNorm(cfg, name="final_norm")(x))
        # logits matmul stays in the compute dtype (bf16 on the MXU — fp32
        # here costs ~4x on the biggest matmul); the loss upcasts to fp32
        # before log_softmax, which is where precision actually matters
        if cfg.tie_embeddings:
            logits = embed.attend(x)
        else:
            logits = nn.Dense(
                cfg.vocab_size,
                use_bias=False,
                dtype=dtype,
                param_dtype=jnp.float32,
                kernel_init=nn.with_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "vocab")
                ),
                name="lm_head",
            )(x)
        if cfg.final_softcap is not None:
            # Gemma-2 final-logit soft-capping (in fp32: tanh saturates
            # quickly in bf16 and the caps exist to shape the tail)
            logits = (
                cfg.final_softcap
                * jnp.tanh(logits.astype(jnp.float32) / cfg.final_softcap)
            ).astype(logits.dtype)
        return logits

    # ------------------------------------------------------------------ #
    # convenience: init + loss
    # ------------------------------------------------------------------ #
    def init_params(self, rng, batch_size: int = 1, seq_len: Optional[int] = None):
        seq_len = seq_len or min(self.config.max_seq_len, 128)
        dummy = jnp.zeros((batch_size, seq_len), jnp.int32)
        return self.init(rng, dummy)["params"]

    @staticmethod
    def loss_fn(model: "CausalLM"):
        """Next-token cross-entropy closure for Accelerator.unified_step:
        ``loss_fn(params, batch)`` with batch {input_ids, [loss_mask]}."""

        def fn(params, batch):
            ids = batch["input_ids"]
            logits = model.apply({"params": params}, ids)
            targets = ids[:, 1:]
            logits = logits[:, :-1]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            mask = batch.get("loss_mask")
            if mask is not None:
                mask = mask[:, 1:].astype(jnp.float32)
                return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return jnp.mean(nll)

        # telemetry: step records carry whether this step ran the fused
        # prologue (unified_step reads the attribute off the closure)
        fn.fused_kernels = bool(getattr(model.config, "fused_kernels", False))
        return fn


class SequenceClassifier(nn.Module):
    """Encoder classifier — the BERT-family fine-tune target (reference
    ``examples/nlp_example.py``: AutoModelForSequenceClassification on
    bert-base). Same Block stack as CausalLM with ``config.causal=False``
    (bidirectional self-attention); masked mean-pool + tanh pooler +
    classification head replace the lm_head.

    ``__call__(input_ids, attention_mask=None) -> (B, num_labels) logits``
    with ``attention_mask`` 1 = real token, 0 = padding.

    Attention-mask routing: where the flash kernel actually runs
    (``attention_impl="flash"``, or auto-dispatch selecting flash on TPU)
    the mask is treated as RIGHT padding and lowered to per-row valid
    lengths — the universal HF tokenizer convention (reference
    examples/nlp_example.py:83-96 pads right) — letting padded batches run
    the O(S)-memory flash kernel and skip fully-padded kv blocks. Every
    other path applies the exact dense (B,1,1,S) key mask, correct for ANY
    0/1 pattern. Non-prefix mask rows on the flash path are POISONED with
    NaN (loud failure, never silently-wrong logits) — left-padded or
    non-contiguous masks require ``attention_impl="xla"``.
    """

    config: TransformerConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None):
        cfg = self.config
        dtype = _dtype(cfg)
        b, s = input_ids.shape
        positions = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        # Mask routing (see class docstring): lower the mask to
        # right-padding lengths ONLY where the flash kernel actually runs
        # (explicit "flash", or auto-dispatch selecting it); every other
        # path keeps the exact dense key mask, correct for ANY pattern.
        from ..ops.attention import flash_self_attention_eligible

        attn_mask4d = kv_lengths = is_prefix = None
        if attention_mask is not None:
            # softcap / per-layer windows force the xla path, which can
            # consume the exact dense mask — don't lower to kv_lengths
            # (the dispatch-must-agree contract of dot_product_attention)
            flash_compatible = (
                cfg.attn_softcap is None and cfg.layer_windows is None
            )
            use_flash = flash_compatible and (
                cfg.attention_impl == "flash" or (
                    cfg.attention_impl is None
                    and flash_self_attention_eligible(s)
                )
            )
            if use_flash:
                keep = attention_mask > 0
                kv_lengths = jnp.sum(keep, axis=-1).astype(jnp.int32)
                # lengths are only faithful for right-padded (prefix-form)
                # masks; a non-prefix row (e.g. padding_side="left") would
                # silently attend to pads and drop real tokens — poison
                # such rows with NaN so the loss screams instead
                is_prefix = jnp.all(keep[:, 1:] <= keep[:, :-1], axis=-1)
            else:
                # (B, S) keep-mask -> (B, 1, 1, S): padded keys invisible
                attn_mask4d = attention_mask[:, None, None, :] > 0
        x = _make_embed(cfg, dtype)(input_ids)
        # the explicit Nones fill the block's paged/lora slots so the
        # per-layer window dict (if any) lands on the scanned argument
        windows = _layer_windows_array(cfg)
        x = _apply_layer_stack(
            cfg, x, positions, attn_mask4d, kv_lengths, None, None,
            per_layer={"window": windows} if windows is not None else None,
        )
        if is_prefix is not None:
            x = jnp.where(is_prefix[:, None, None], x, jnp.nan)
        x = RMSNorm(cfg, name="final_norm")(x)

        if attention_mask is None:
            pooled = jnp.mean(x, axis=1)
        else:
            w = attention_mask[:, :, None].astype(x.dtype)
            pooled = jnp.sum(x * w, axis=1) / jnp.maximum(
                jnp.sum(w, axis=1), 1.0
            )
        pooled = nn.tanh(
            nn.Dense(
                cfg.hidden_size,
                dtype=dtype,
                param_dtype=jnp.float32,
                kernel_init=nn.with_partitioning(
                    # ("embed", None): a square kernel must not map one mesh
                    # axis to both dims (invalid PartitionSpec)
                    nn.initializers.lecun_normal(), ("embed", None)
                ),
                name="pooler",
            )(pooled)
        )
        # classifier logits in fp32: the softmax/CE is where precision matters
        return nn.Dense(
            self.num_labels,
            dtype=jnp.float32,
            param_dtype=jnp.float32,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("embed", None)
            ),
            name="classifier",
        )(pooled)

    @staticmethod
    def loss_fn(model: "SequenceClassifier"):
        """Cross-entropy closure for Accelerator.unified_step; batch keys:
        {input_ids, labels, [attention_mask]}."""
        import optax

        def fn(params, batch):
            logits = model.apply(
                {"params": params},
                batch["input_ids"],
                batch.get("attention_mask"),
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), batch["labels"]
            ).mean()

        return fn
