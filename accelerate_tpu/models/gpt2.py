"""Faithful GPT-2 (classic-arch interop family).

The Llama-family :class:`~.transformer.CausalLM` is a modernized
architecture (RMSNorm + rope + SwiGLU, no biases) with no parameter
correspondence to classic checkpoints. This module is the deliberate
exception — an architecture-faithful GPT-2 so *real* ``gpt2`` hub
checkpoints load with matching logits (VERDICT r3 missing #3; the
reference runs any AutoModel checkpoint, big_modeling.py:499):

* learned absolute position embeddings (``wpe``) instead of rope;
* LayerNorm (with bias) instead of RMSNorm;
* biased projections; attention QKV is ONE fused ``c_attn`` matmul —
  exactly HF's Conv1D layout ``(in, 3h)``, which is also the better MXU
  shape (one large matmul instead of three small ones);
* GELU (tanh approximation — HF ``gelu_new``) MLP, width ``4h``;
* pre-LN residual blocks, final ``ln_f``, embeddings always tied.

TPU-native the same way the flagship is: logical-axis partitioning on
every param, ``nn.scan`` over layers (stacked ``(L, ...)`` leaves —
the HF mapping in utils/hf_interop.py unstacks per-layer keys), optional
remat, same static-shape KV-cache decode as
:class:`~.transformer.Attention` so :func:`~.generation.generate` works
unchanged. Conv1D stores ``(in, out)`` like flax Dense, so the mapping
needs NO transposes.

Dropout is intentionally absent (train-time regularization, not a
parameter); fine-tuning runs match HF with dropout disabled.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..ops.attention import dot_product_attention
from .config import TransformerConfig
from .transformer import CausalLM, _apply_layer_stack, _dtype, _make_embed


def _dense(cfg, dtype, out_features, kernel_axes, bias_axis, name):
    return nn.Dense(
        out_features,
        use_bias=True,
        dtype=dtype,
        param_dtype=jnp.float32,
        kernel_init=nn.with_partitioning(
            nn.initializers.normal(0.02), kernel_axes
        ),
        bias_init=nn.with_partitioning(nn.initializers.zeros_init(), (bias_axis,)),
        name=name,
    )


def _layer_norm(cfg, dtype, name):
    return nn.LayerNorm(
        epsilon=cfg.rms_norm_eps,
        dtype=dtype,
        param_dtype=jnp.float32,
        scale_init=nn.with_partitioning(nn.initializers.ones_init(), ("norm",)),
        bias_init=nn.with_partitioning(nn.initializers.zeros_init(), ("norm",)),
        name=name,
    )


class GPT2Attention(nn.Module):
    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, positions, mask=None):
        cfg = self.config
        dtype = _dtype(cfg)
        h = cfg.hidden_size
        b, s = x.shape[:2]

        qkv = _dense(cfg, dtype, 3 * h, ("embed", "heads"), "heads", "c_attn")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, s, cfg.num_heads, cfg.head_dim)
        v = v.reshape(b, s, cfg.num_heads, cfg.head_dim)

        decode = self.decode
        if decode:
            # same fixed-size cache pattern as transformer.Attention (the
            # has_variable guard keeps the init pass from advancing state)
            max_len = cfg.max_seq_len
            is_initialized = self.has_variable("cache", "cached_key")
            cached_key = self.variable(
                "cache", "cached_key",
                lambda: jnp.zeros(
                    (b, max_len, cfg.num_heads, cfg.head_dim), k.dtype
                ),
            )
            cached_value = self.variable(
                "cache", "cached_value",
                lambda: jnp.zeros(
                    (b, max_len, cfg.num_heads, cfg.head_dim), v.dtype
                ),
            )
            cache_index = self.variable(
                "cache", "cache_index", lambda: jnp.asarray(0, jnp.int32)
            )
            decode = is_initialized
        if decode:
            idx = cache_index.value
            key_cache = jax.lax.dynamic_update_slice(
                cached_key.value, k, (0, idx, 0, 0)
            )
            value_cache = jax.lax.dynamic_update_slice(
                cached_value.value, v, (0, idx, 0, 0)
            )
            cached_key.value = key_cache
            cached_value.value = value_cache
            cache_index.value = idx + s
            cols = jnp.arange(max_len)[None, None, None, :]
            rows = (idx + jnp.arange(s))[None, None, :, None]
            dec_mask = cols <= rows  # (1,1,s,max_len)
            out = dot_product_attention(
                q, key_cache, value_cache, mask=dec_mask, causal=False,
                implementation="xla",
            )
        else:
            out = dot_product_attention(
                q, k, v, mask=mask, causal=True,
                implementation=cfg.attention_impl,
            )
        out = checkpoint_name(out, "attn_out")
        return _dense(cfg, dtype, h, ("heads", "embed"), "embed", "c_proj")(
            out.reshape(b, s, h)
        )


class GPT2MLP(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        dtype = _dtype(cfg)
        y = _dense(
            cfg, dtype, cfg.intermediate_size, ("embed", "mlp"), "mlp", "c_fc"
        )(x)
        y = nn.gelu(y, approximate=True)  # HF "gelu_new"
        return _dense(
            cfg, dtype, cfg.hidden_size, ("mlp", "embed"), "embed", "c_proj"
        )(y)


class GPT2Block(nn.Module):
    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, positions, mask=None):
        from ..parallel.sharding import constrain_activations

        cfg = self.config
        dtype = _dtype(cfg)
        h = x + GPT2Attention(cfg, decode=self.decode, name="attn")(
            _layer_norm(cfg, dtype, "ln_1")(x), positions, mask
        )
        y = GPT2MLP(cfg, name="mlp")(_layer_norm(cfg, dtype, "ln_2")(h))
        return constrain_activations(h + y), None


class GPT2LM(nn.Module):
    """``wte + wpe -> scan(GPT2Block) -> ln_f -> tied lm_head``.

    Call signature matches :class:`~.transformer.CausalLM`
    (``input_ids, positions=None, mask=None, decode=False``) so
    Accelerator.unified_step, generation, and the examples drive it
    unchanged.
    """

    config: TransformerConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, mask=None, decode=False):
        cfg = self.config
        dtype = _dtype(cfg)
        from ..parallel.sharding import constrain_activations

        wte = _make_embed(cfg, dtype, name="wte")
        wpe = nn.Embed(
            cfg.max_seq_len,
            cfg.hidden_size,
            dtype=dtype,
            param_dtype=jnp.float32,
            embedding_init=nn.with_partitioning(
                nn.initializers.normal(0.01), (None, "embed")
            ),
            name="wpe",
        )
        if decode:
            # model-level position counter for wpe (each layer's kv cache
            # keeps its own index; the embedding needs one too)
            is_initialized = self.has_variable("cache", "pos_index")
            pos_index = self.variable(
                "cache", "pos_index", lambda: jnp.asarray(0, jnp.int32)
            )
            if is_initialized:
                positions = (
                    pos_index.value + jnp.arange(input_ids.shape[1])[None, :]
                )
                pos_index.value = pos_index.value + input_ids.shape[1]
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(input_ids.shape[1])[None, :], input_ids.shape
            )
        x = constrain_activations(wte(input_ids) + wpe(positions))
        x = _apply_layer_stack(
            cfg, x, positions, mask, decode=decode, block_cls=GPT2Block
        )
        x = constrain_activations(_layer_norm(cfg, dtype, "ln_f")(x))
        return wte.attend(x)  # GPT-2 embeddings are always tied

    def init_params(self, rng, batch_size: int = 1,
                    seq_len: Optional[int] = None):
        seq_len = seq_len or min(self.config.max_seq_len, 128)
        return self.init(
            rng, jnp.zeros((batch_size, seq_len), jnp.int32)
        )["params"]

    # next-token cross-entropy is architecture-agnostic
    loss_fn = CausalLM.loss_fn
