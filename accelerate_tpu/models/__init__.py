"""Model zoo: TPU-native implementations of the reference's benchmark model
families (BASELINE.md: BERT MRPC, GPT-2, Llama-3, Mixtral-MoE).

Models are flax.linen modules annotated with *logical* axis names
(``nn.with_partitioning``); :mod:`accelerate_tpu.parallel.sharding` maps the
names onto the device mesh, so the same model definition runs pure-DP, FSDP,
TP, SP or EP without edits — the whole point of the GSPMD redesign.
"""

from .config import TransformerConfig
from .gpt2 import GPT2LM
from .seq2seq import Seq2SeqLM
from .transformer import CausalLM, SequenceClassifier, count_params

__all__ = [
    "TransformerConfig",
    "CausalLM",
    "GPT2LM",
    "SequenceClassifier",
    "Seq2SeqLM",
    "causal_model_for",
    "count_params",
]


def causal_model_for(config: TransformerConfig):
    """The decoder-LM module class instance matching ``config.arch`` —
    lets arch-agnostic call sites (examples, estimate-memory, interop
    tests) mirror the reference's AutoModel dispatch."""
    return GPT2LM(config) if config.arch == "gpt2" else CausalLM(config)
