"""Encoder-decoder (T5/BART-family) model — completes the reference's
bert/gpt/t5 family coverage (reference utils/megatron_lm.py:1641-1771
parses exactly these three into Megatron args; SURVEY §2.4).

Same TPU-native construction as the decoder-only stack: logical-axis
partitioned params, ``nn.scan`` over layers, optional remat, attention via
:mod:`..ops.attention`. The decoder block adds cross-attention (queries
from the decoder stream, keys/values from the encoder memory — no rope on
the cross path; each stream already carries its own positions).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import dot_product_attention
from .config import TransformerConfig
from .transformer import (
    MLP,
    Attention,
    RMSNorm,
    _apply_layer_stack,
    _dtype,
    _make_embed,
    _make_proj,
)


class CrossAttention(nn.Module):
    """Decoder-to-encoder attention: q from ``x``, k/v from ``memory``.

    Under ``decode`` the memory K/V projections are computed once (first
    step) and cached — they never change during generation, and
    recomputing 2 x (S_src, h, kv) matmuls per layer per token would eat
    the KV-cache win.
    """

    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, memory, memory_mask=None):
        cfg = self.config
        dtype = _dtype(cfg)
        proj = _make_proj(cfg, dtype)
        q_dim = cfg.num_heads * cfg.head_dim
        kv_dim = cfg.num_kv_heads * cfg.head_dim

        b, s = x.shape[:2]
        sm = memory.shape[1]
        q = proj("q_proj", q_dim, ("embed", "heads"))(x)
        k_proj = proj("k_proj", kv_dim, ("embed", "kv"))
        v_proj = proj("v_proj", kv_dim, ("embed", "kv"))

        def compute_kv():
            k = k_proj(memory).reshape(b, sm, cfg.num_kv_heads, cfg.head_dim)
            v = v_proj(memory).reshape(b, sm, cfg.num_kv_heads, cfg.head_dim)
            return k, v

        if self.decode:
            is_init = self.has_variable("cache", "cross_key")
            kv_shape = (b, sm, cfg.num_kv_heads, cfg.head_dim)
            ck = self.variable(
                "cache", "cross_key", lambda: jnp.zeros(kv_shape, dtype)
            )
            cv = self.variable(
                "cache", "cross_value", lambda: jnp.zeros(kv_shape, dtype)
            )
            filled = self.variable(
                "cache", "cross_filled", lambda: jnp.zeros((), bool)
            )
            if not is_init:  # init pass: run the projs so params exist
                k, v = compute_kv()
            else:
                k, v = jax.lax.cond(
                    filled.value,
                    lambda: (ck.value, cv.value),
                    compute_kv,
                )
                ck.value, cv.value = k, v
                filled.value = jnp.ones((), bool)
        else:
            k, v = compute_kv()
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        mask = None
        if memory_mask is not None:  # (B, Sm) source padding -> (B,1,1,Sm)
            mask = memory_mask[:, None, None, :].astype(bool)
        # xla forced: flash supports only causal/no-mask, and ring needs
        # BOTH streams sp-sharded with equal lengths — neither holds for
        # the rectangular (S_dec x S_enc) cross pattern
        out = dot_product_attention(
            q, k, v, mask=mask, causal=False, implementation="xla"
        )
        out = out.reshape(b, s, q_dim)
        return proj("o_proj", cfg.hidden_size, ("heads", "embed"))(out)


class DecoderBlock(nn.Module):
    """Self-attention (causal, KV-cached under ``decode``) +
    cross-attention + MLP, pre-norm."""

    config: TransformerConfig
    decode: bool = False

    @nn.compact
    def __call__(self, x, positions, memory, memory_mask=None):
        from ..parallel.sharding import constrain_activations

        cfg = self.config
        h = x + Attention(cfg, decode=self.decode, name="self_attn")(
            RMSNorm(cfg, name="self_attn_norm")(x), positions, None
        )
        h = h + CrossAttention(cfg, decode=self.decode, name="cross_attn")(
            RMSNorm(cfg, name="cross_attn_norm")(h), memory, memory_mask
        )
        # per-layer layout pin, same rationale as transformer.Block
        return constrain_activations(
            h + MLP(cfg, name="mlp")(RMSNorm(cfg, name="mlp_norm")(h))
        ), None


class _Encoder(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, mask=None):
        return _apply_layer_stack(self.config, x, positions, mask)


class _Decoder(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, memory, memory_mask=None, decode=False):
        cfg = self.config
        return _apply_layer_stack(
            cfg, x, positions, memory, memory_mask,
            decode=decode,
            block_cls=DecoderBlock,
            num_layers=cfg.num_decoder_layers or cfg.num_layers,
        )


class Seq2SeqLM(nn.Module):
    """Encoder-decoder LM: shared embedding, bidirectional encoder, causal
    decoder with cross-attention, tied (or separate) lm head.

    ``__call__(input_ids, decoder_input_ids, attention_mask=None) ->
    logits`` over the decoder positions (teacher forcing). ``encode`` /
    ``decode_logits`` are exposed separately so generation encodes the
    source ONCE and steps the decoder with a KV cache.
    """

    config: TransformerConfig

    def setup(self):
        cfg = self.config
        dtype = _dtype(cfg)
        self.embed = _make_embed(cfg, dtype, name=None)
        self.encoder = _Encoder(dataclasses.replace(cfg, causal=False))
        self.encoder_norm = RMSNorm(cfg)
        # causal forced regardless of the user config: a non-causal decoder
        # would leak future target tokens through teacher forcing
        self.decoder = _Decoder(dataclasses.replace(cfg, causal=True))
        self.final_norm = RMSNorm(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Dense(
                cfg.vocab_size,
                use_bias=False,
                dtype=dtype,
                param_dtype=jnp.float32,
                kernel_init=nn.with_partitioning(
                    nn.initializers.lecun_normal(), ("embed", "vocab")
                ),
            )

    # ------------------------------------------------------------------ #
    def encode(self, input_ids, attention_mask=None):
        """Source -> memory; run ONCE per generation."""
        enc_pos = jnp.broadcast_to(
            jnp.arange(input_ids.shape[1])[None, :], input_ids.shape
        )
        enc_mask = None
        if attention_mask is not None:  # (B, Sm) -> (B,1,1,Sm)
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        memory = self.encoder(self.embed(input_ids), enc_pos, enc_mask)
        return self.encoder_norm(memory)

    def decode_logits(
        self, decoder_input_ids, memory, attention_mask=None, decode=False
    ):
        """Decoder forward over (possibly incremental) target tokens.
        ``decode=True`` uses the per-layer KV cache (mutable="cache")."""
        dec_pos = jnp.broadcast_to(
            jnp.arange(decoder_input_ids.shape[1])[None, :],
            decoder_input_ids.shape,
        )
        x = self.decoder(
            self.embed(decoder_input_ids), dec_pos, memory, attention_mask,
            decode=decode,
        )
        x = self.final_norm(x)
        if self.config.tie_embeddings:
            return self.embed.attend(x)
        return self.lm_head(x)

    def __call__(
        self, input_ids, decoder_input_ids, attention_mask=None, decode=False
    ):
        memory = self.encode(input_ids, attention_mask)
        return self.decode_logits(
            decoder_input_ids, memory, attention_mask, decode=decode
        )

    # ------------------------------------------------------------------ #
    def init_params(self, rng, batch_size: int = 1, seq_len: int = 16):
        dummy = jnp.zeros((batch_size, seq_len), jnp.int32)
        return self.init(rng, dummy, dummy)["params"]

    @staticmethod
    def loss_fn(model: "Seq2SeqLM"):
        """Teacher-forced cross-entropy. Batch keys: ``input_ids``,
        ``decoder_input_ids``, ``labels``, optional ``attention_mask``
        (source padding) and ``decoder_loss_mask``."""

        def fn(params, batch):
            logits = model.apply(
                {"params": params},
                batch["input_ids"],
                batch["decoder_input_ids"],
                batch.get("attention_mask"),
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(
                logp, batch["labels"][..., None], axis=-1
            )[..., 0]
            mask = batch.get("decoder_loss_mask")
            if mask is not None:
                mask = mask.astype(jnp.float32)
                return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return jnp.mean(nll)

        return fn

    def generate(
        self,
        params: Any,
        input_ids: jax.Array,
        max_new_tokens: int = 32,
        bos_token_id: int = 0,
        eos_token_id: Optional[int] = None,
        attention_mask: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Greedy decode with KV caches (self-attention keys/values AND the
        cross-attention memory projections, computed once) — O(L) per token
        instead of the full-recompute O(L^2). One ``lax.scan`` program, so
        it jits whole."""
        from .generation import init_cache

        B = input_ids.shape[0]
        if max_new_tokens + 1 > self.config.max_seq_len:
            raise ValueError(
                f"max_new_tokens ({max_new_tokens}) + bos exceeds the "
                f"decoder cache length (max_seq_len={self.config.max_seq_len})"
            )
        bos = jnp.full((B, 1), bos_token_id, jnp.int32)
        if max_new_tokens <= 0:
            return bos
        memory = self.apply(
            {"params": params}, input_ids, attention_mask,
            method=Seq2SeqLM.encode,
        )
        # cache template at the REAL source length (the cross-KV cache
        # shape depends on it), no spare param materialization
        cache = init_cache(
            self.init,
            jax.random.PRNGKey(0),
            jnp.zeros_like(input_ids),
            jnp.zeros((B, 1), jnp.int32),
            decode=True,
        )

        def step(carry, _):
            cache, tok, done = carry
            logits, mutated = self.apply(
                {"params": params, "cache": cache},
                tok[:, None],
                memory,
                attention_mask,
                decode=True,
                mutable=["cache"],
                method=Seq2SeqLM.decode_logits,
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            return (mutated["cache"], nxt, done), nxt

        done0 = jnp.zeros((B,), bool)
        (_, _, _), toks = jax.lax.scan(
            step, (cache, bos[:, 0], done0), None, length=max_new_tokens
        )
        return jnp.concatenate([bos, toks.T], axis=1)
