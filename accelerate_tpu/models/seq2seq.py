"""Encoder-decoder (T5/BART-family) model — completes the reference's
bert/gpt/t5 family coverage (reference utils/megatron_lm.py:1641-1771
parses exactly these three into Megatron args; SURVEY §2.4).

Same TPU-native construction as the decoder-only stack: logical-axis
partitioned params, ``nn.scan`` over layers, optional remat, attention via
:mod:`..ops.attention`. The decoder block adds cross-attention (queries
from the decoder stream, keys/values from the encoder memory — no rope on
the cross path; each stream already carries its own positions).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import dot_product_attention
from .config import TransformerConfig
from .transformer import (
    MLP,
    Attention,
    RMSNorm,
    _apply_layer_stack,
    _dtype,
    _make_embed,
    _make_proj,
)


class CrossAttention(nn.Module):
    """Decoder-to-encoder attention: q from ``x``, k/v from ``memory``."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x, memory, memory_mask=None):
        cfg = self.config
        dtype = _dtype(cfg)
        proj = _make_proj(cfg, dtype)
        q_dim = cfg.num_heads * cfg.head_dim
        kv_dim = cfg.num_kv_heads * cfg.head_dim

        b, s = x.shape[:2]
        sm = memory.shape[1]
        q = proj("q_proj", q_dim, ("embed", "heads"))(x)
        k = proj("k_proj", kv_dim, ("embed", "kv"))(memory)
        v = proj("v_proj", kv_dim, ("embed", "kv"))(memory)
        q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, sm, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, sm, cfg.num_kv_heads, cfg.head_dim)
        mask = None
        if memory_mask is not None:  # (B, Sm) source padding -> (B,1,1,Sm)
            mask = memory_mask[:, None, None, :].astype(bool)
        # xla forced: flash supports only causal/no-mask, and ring needs
        # BOTH streams sp-sharded with equal lengths — neither holds for
        # the rectangular (S_dec x S_enc) cross pattern
        out = dot_product_attention(
            q, k, v, mask=mask, causal=False, implementation="xla"
        )
        out = out.reshape(b, s, q_dim)
        return proj("o_proj", cfg.hidden_size, ("heads", "embed"))(out)


class DecoderBlock(nn.Module):
    """Self-attention (causal) + cross-attention + MLP, pre-norm."""

    config: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, memory, memory_mask=None):
        from ..parallel.sharding import constrain_activations

        cfg = self.config
        h = x + Attention(cfg, name="self_attn")(
            RMSNorm(cfg, name="self_attn_norm")(x), positions, None
        )
        h = h + CrossAttention(cfg, name="cross_attn")(
            RMSNorm(cfg, name="cross_attn_norm")(h), memory, memory_mask
        )
        # per-layer layout pin, same rationale as transformer.Block
        return constrain_activations(
            h + MLP(cfg, name="mlp")(RMSNorm(cfg, name="mlp_norm")(h))
        ), None


class _Encoder(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, mask=None):
        return _apply_layer_stack(self.config, x, positions, mask)


class _Decoder(nn.Module):
    config: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, memory, memory_mask=None):
        cfg = self.config
        return _apply_layer_stack(
            cfg, x, positions, memory, memory_mask,
            block_cls=DecoderBlock,
            num_layers=cfg.num_decoder_layers or cfg.num_layers,
        )


class Seq2SeqLM(nn.Module):
    """Encoder-decoder LM: shared embedding, bidirectional encoder, causal
    decoder with cross-attention, tied (or separate) lm head.

    ``__call__(input_ids, decoder_input_ids, attention_mask=None) ->
    logits`` over the decoder positions (teacher forcing).
    """

    config: TransformerConfig

    def _encoder_config(self) -> TransformerConfig:
        return dataclasses.replace(self.config, causal=False)

    def _decoder_config(self) -> TransformerConfig:
        # forced regardless of what the user's config says: a non-causal
        # decoder would leak future target tokens through teacher forcing
        return dataclasses.replace(self.config, causal=True)

    @nn.compact
    def __call__(self, input_ids, decoder_input_ids, attention_mask=None):
        cfg = self.config
        dtype = _dtype(cfg)
        embed = _make_embed(cfg, dtype)

        # --- encoder ---
        enc_pos = jnp.broadcast_to(
            jnp.arange(input_ids.shape[1])[None, :], input_ids.shape
        )
        enc_mask = None
        if attention_mask is not None:  # (B, Sm) -> (B,1,1,Sm)
            enc_mask = attention_mask[:, None, None, :].astype(bool)
        memory = _Encoder(self._encoder_config(), name="encoder")(
            embed(input_ids), enc_pos, enc_mask
        )
        memory = RMSNorm(cfg, name="encoder_norm")(memory)

        # --- decoder ---
        dec_pos = jnp.broadcast_to(
            jnp.arange(decoder_input_ids.shape[1])[None, :],
            decoder_input_ids.shape,
        )
        x = _Decoder(self._decoder_config(), name="decoder")(
            embed(decoder_input_ids), dec_pos, memory, attention_mask
        )
        x = RMSNorm(cfg, name="final_norm")(x)
        if cfg.tie_embeddings:
            return embed.attend(x)
        return nn.Dense(
            cfg.vocab_size,
            use_bias=False,
            dtype=dtype,
            param_dtype=jnp.float32,
            kernel_init=nn.with_partitioning(
                nn.initializers.lecun_normal(), ("embed", "vocab")
            ),
            name="lm_head",
        )(x)

    # ------------------------------------------------------------------ #
    def init_params(self, rng, batch_size: int = 1, seq_len: int = 16):
        dummy = jnp.zeros((batch_size, seq_len), jnp.int32)
        return self.init(rng, dummy, dummy)["params"]

    @staticmethod
    def loss_fn(model: "Seq2SeqLM"):
        """Teacher-forced cross-entropy. Batch keys: ``input_ids``,
        ``decoder_input_ids``, ``labels``, optional ``attention_mask``
        (source padding) and ``decoder_loss_mask``."""

        def fn(params, batch):
            logits = model.apply(
                {"params": params},
                batch["input_ids"],
                batch["decoder_input_ids"],
                batch.get("attention_mask"),
            )
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(
                logp, batch["labels"][..., None], axis=-1
            )[..., 0]
            mask = batch.get("decoder_loss_mask")
            if mask is not None:
                mask = mask.astype(jnp.float32)
                return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return jnp.mean(nll)

        return fn

    def generate(
        self,
        params: Any,
        input_ids: jax.Array,
        max_new_tokens: int = 32,
        bos_token_id: int = 0,
        eos_token_id: Optional[int] = None,
        attention_mask: Optional[jax.Array] = None,
    ) -> jax.Array:
        """Greedy decode (full-recompute per step: O(L^2) — correct and
        simple; KV-cached seq2seq decode mirrors the CausalLM cache and is
        a planned optimization)."""
        B = input_ids.shape[0]
        dec = jnp.full((B, 1), bos_token_id, jnp.int32)
        done = jnp.zeros((B,), bool)
        for _ in range(max_new_tokens):
            logits = self.apply(
                {"params": params}, input_ids, dec, attention_mask
            )
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            dec = jnp.concatenate([dec, nxt[:, None]], axis=1)
        return dec
