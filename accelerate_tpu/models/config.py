"""Model architecture configs + presets for the baseline families.

Parity note: the reference consumes HF ``transformers`` models as-is and
parses their configs into Megatron args (reference utils/megatron_lm.py:
1641-1771 — bert/gpt2/t5/llama parsers). Here the config is native and
presets mirror the BASELINE.md targets.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


SUPPORTED_ROPE_TYPES = ("default", "llama3", "linear")
# required rope_scaling keys per type (beyond rope_type itself)
_ROPE_REQUIRED_KEYS = {
    "default": (),
    "linear": ("factor",),
    "llama3": (
        "factor",
        "low_freq_factor",
        "high_freq_factor",
        "original_max_position_embeddings",
    ),
}


def rope_type(scaling: Optional[dict]) -> str:
    """The rope_type of an HF-style ``rope_scaling`` dict (accepting the
    legacy ``type`` key), ``"default"`` when absent — the ONE place this
    extraction lives (used by config validation, hf interop, and the rope
    implementation)."""
    if not scaling:
        return "default"
    return scaling.get("rope_type", scaling.get("type", "default"))


def validate_rope_scaling(scaling: Optional[dict]) -> None:
    """Reject unsupported types AND missing parameters up front: a
    scaling dict that only fails at trace time (KeyError inside jit)
    would defeat the loader's fail-loudly contract."""
    rt = rope_type(scaling)
    if rt not in SUPPORTED_ROPE_TYPES:
        raise ValueError(
            f"unsupported rope_scaling type {rt!r}; "
            f"supported: {', '.join(SUPPORTED_ROPE_TYPES)}"
        )
    missing = [k for k in _ROPE_REQUIRED_KEYS[rt] if k not in (scaling or {})]
    if missing:
        raise ValueError(
            f"rope_scaling type {rt!r} requires keys {missing} "
            f"(got {sorted(scaling)})"
        )


@dataclass
class TransformerConfig:
    # model family: "llama" (the modern default — RMSNorm/rope/SwiGLU,
    # models/transformer.py) or "gpt2" (classic — LayerNorm/learned
    # positions/biases/GELU, models/gpt2.py). Selects the HF parameter
    # mapping in utils/hf_interop.py; build the matching module class
    # (CausalLM vs GPT2LM).
    arch: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 512
    intermediate_size: int = 1408
    num_layers: int = 4
    # encoder-decoder models (Seq2SeqLM): decoder depth; None -> num_layers
    num_decoder_layers: Optional[int] = None
    num_heads: int = 8
    num_kv_heads: Optional[int] = None  # None -> num_heads (MHA); < heads -> GQA
    # bias on the q/k/v projections ONLY (the Qwen2 family convention —
    # o_proj and the MLP stay bias-free); selects the matching HF mapping
    qkv_bias: bool = False
    head_dim: Optional[int] = None  # None -> hidden_size // num_heads
    max_seq_len: int = 2048
    rope_theta: float = 500000.0
    # HF-style rope frequency scaling (Llama-3.1+ ships
    # ``{"rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
    # "high_freq_factor": 4.0, "original_max_position_embeddings": 8192}``);
    # supported rope_types: "llama3", "linear", "default"/None. Applied in
    # models/transformer.rope — keep in sync with transformers'
    # _compute_llama3_parameters so HF checkpoints logits-match.
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    # Gemma-family math switches (key layout is Llama's; only the math
    # differs — utils/hf_interop.py maps model_type "gemma" onto these):
    # RMSNorm multiplies by (1 + scale) with zero-init scales,
    norm_offset: bool = False
    # the MLP gate activation ("silu" = Llama/Mixtral, "gelu_tanh" =
    # Gemma's gelu_pytorch_tanh),
    mlp_activation: str = "silu"
    # and embedding outputs scale by sqrt(hidden_size).
    embed_scale: bool = False
    tie_embeddings: bool = False
    # False -> bidirectional self-attention (BERT-family encoders)
    causal: bool = True
    # sliding-window attention band (Mistral / sliding Qwen2): each query
    # sees at most the last `sliding_window` keys, self included — HF
    # semantics (kv_idx > q_idx - sliding_window AND causal). Applies to
    # EVERY layer (per-layer mixes are rejected by utils/hf_interop.py —
    # the nn.scan layout compiles one homogeneous layer body). xla and
    # flash attention honor it (flash skips below-band kv blocks: work
    # scales with S*window); ring attention rejects it.
    sliding_window: Optional[int] = None
    # Gemma-2 family switches (utils/hf_interop.py maps model_type
    # "gemma2" onto these, on top of the Gemma-1 trio above):
    # per-layer window pattern (tuple of int-or-None, len num_layers —
    # Gemma-2 alternates sliding/full). Heterogeneous patterns ride the
    # scan as a per-layer traced window, which only the xla attention
    # path supports; homogeneous patterns should use sliding_window.
    layer_windows: Optional[tuple] = None
    # attention scale = query_pre_attn_scalar**-0.5 (Gemma-2 sets 256,
    # decoupled from head_dim); None -> head_dim**-0.5
    query_pre_attn_scalar: Optional[float] = None
    # tanh soft-capping: s -> cap * tanh(s / cap) on attention scores
    # (before masking) and on final logits
    attn_softcap: Optional[float] = None
    final_softcap: Optional[float] = None
    # Gemma-2 block: norms AFTER attention and the MLP too (4 per block)
    post_norms: bool = False
    attention_impl: Optional[str] = None  # None=auto | xla | flash | ring
    # MoE (Mixtral family); 0 experts = dense MLP
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # "auto" (default): "ragged" at every ep (falls back to "capacity"
    # only on jax versions without partial-manual shard_map). "ragged":
    # grouped-matmul dispatch (jax.lax.ragged_dot) — exact math at ep==1
    # (no padding, no drops), measured FASTER than capacity at bench
    # shapes (ops/moe.py docstring numbers); under ep>1 it runs the
    # shard-capacity EP schedule (ops/moe.moe_ragged_ep — ragged-packed
    # local experts, per-SHARD headroom: at equal capacity_factor it
    # drops 3-10x fewer tokens and moves ~2x fewer collective bytes than
    # "capacity", measured numbers in moe_ragged_ep's docstring).
    # "capacity": GShard-style static-shape dispatch — FLOPs scale with
    # K*capacity_factor, overflow tokens drop per expert. "dense": every
    # expert sees every token (the exact-math test oracle, O(E) FLOPs)
    moe_dispatch: str = "auto"
    moe_capacity_factor: float = 2.0
    # fp8 projections: e4m3 fwd / e5m2 bwd matmuls (ops/fp8.py) — the
    # TransformerEngine capability; pair with mixed_precision="fp8"
    fp8: bool = False
    # remat: None | "full" | "dots" — trades FLOPs for HBM
    remat: Optional[str] = None
    # fused Pallas step kernels (ops/fused.py): RMSNorm -> QKV -> rope in
    # one kernel per attention block. Param tree and checkpoints are
    # identical either way; numerics match the unfused chain to fp32
    # tolerance (exact-shape fallback to the unfused path when a shape the
    # kernel can't tile comes through, and interpret mode on CPU)
    fused_kernels: bool = False
    # scan over layers: one compiled layer body, num_layers iterations —
    # keeps compile time flat in depth (essential at 8B+)
    scan_layers: bool = True
    dtype: str = "float32"  # activation dtype at apply time

    def __post_init__(self):
        if self.arch not in ("llama", "gpt2"):
            raise ValueError(
                f"unknown arch {self.arch!r}; supported: llama, gpt2"
            )
        if self.mlp_activation not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"unknown mlp_activation {self.mlp_activation!r}; "
                "supported: silu, gelu_tanh"
            )
        # an unsupported/underspecified rope_scaling silently ignored (or
        # crashing only at trace time) would pass every weight check and
        # still diverge from the source model
        validate_rope_scaling(self.rope_scaling)
        if self.sliding_window is not None:
            if self.sliding_window <= 0:
                raise ValueError(
                    f"sliding_window must be positive, got {self.sliding_window}"
                )
            if not self.causal:
                raise ValueError(
                    "sliding_window requires causal attention (the band is "
                    "a causal-mask refinement)"
                )
            if self.attention_impl == "ring":
                raise ValueError(
                    "sliding_window is not supported by ring attention — "
                    "use attention_impl 'flash'/'xla'/None (flash's "
                    "band-skip already bounds work and memory at "
                    "window << seq)"
                )
        if self.layer_windows is not None:
            self.layer_windows = tuple(self.layer_windows)
            if len(self.layer_windows) != self.num_layers:
                raise ValueError(
                    f"layer_windows has {len(self.layer_windows)} entries "
                    f"for {self.num_layers} layers"
                )
            if self.sliding_window is not None:
                raise ValueError(
                    "set either sliding_window (homogeneous) or "
                    "layer_windows (per-layer), not both"
                )
            if not self.causal:
                raise ValueError("layer_windows requires causal attention")
            if self.attention_impl in ("ring", "flash"):
                raise ValueError(
                    "per-layer windows ride the scan as traced values, "
                    "which only the xla attention path supports — use "
                    "attention_impl 'xla' or None"
                )
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.head_dim is None:
            assert self.hidden_size % self.num_heads == 0
            self.head_dim = self.hidden_size // self.num_heads
        assert self.num_heads % self.num_kv_heads == 0

    # ------------------------------------------------------------------ #
    # presets (BASELINE.md model families)
    # ------------------------------------------------------------------ #
    @classmethod
    def tiny(cls, **kw) -> "TransformerConfig":
        kw.setdefault("vocab_size", 1024)
        kw.setdefault("hidden_size", 128)
        kw.setdefault("intermediate_size", 352)
        kw.setdefault("num_layers", 2)
        kw.setdefault("num_heads", 4)
        kw.setdefault("max_seq_len", 256)
        return cls(**kw)

    @classmethod
    def bert_base(cls, **kw) -> "TransformerConfig":
        """BERT-base shape (the reference's nlp_example.py fine-tune target,
        examples/nlp_example.py: bert-base-cased). Bidirectional attention;
        rope replaces learned positions — the TPU build's encoder idiom."""
        kw.setdefault("vocab_size", 30522)
        kw.setdefault("hidden_size", 768)
        kw.setdefault("intermediate_size", 3072)
        kw.setdefault("num_layers", 12)
        kw.setdefault("num_heads", 12)
        kw.setdefault("max_seq_len", 512)
        kw.setdefault("causal", False)
        kw.setdefault("tie_embeddings", True)
        return cls(**kw)

    @classmethod
    def gpt2(cls, **kw) -> "TransformerConfig":
        """The FAITHFUL classic architecture (models/gpt2.GPT2LM):
        learned positions, LayerNorm, biases, GELU — real ``gpt2`` hub
        checkpoints load with matching logits."""
        kw.setdefault("arch", "gpt2")
        kw.setdefault("vocab_size", 50257)
        kw.setdefault("hidden_size", 768)
        kw.setdefault("intermediate_size", 3072)
        kw.setdefault("num_layers", 12)
        kw.setdefault("num_heads", 12)
        kw.setdefault("max_seq_len", 1024)
        kw.setdefault("rms_norm_eps", 1e-5)
        kw.setdefault("tie_embeddings", True)
        return cls(**kw)

    @classmethod
    def llama3_8b(cls, **kw) -> "TransformerConfig":
        kw.setdefault("vocab_size", 128256)
        kw.setdefault("hidden_size", 4096)
        kw.setdefault("intermediate_size", 14336)
        kw.setdefault("num_layers", 32)
        kw.setdefault("num_heads", 32)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("max_seq_len", 8192)
        return cls(**kw)

    @classmethod
    def llama3_70b(cls, **kw) -> "TransformerConfig":
        kw.setdefault("vocab_size", 128256)
        kw.setdefault("hidden_size", 8192)
        kw.setdefault("intermediate_size", 28672)
        kw.setdefault("num_layers", 80)
        kw.setdefault("num_heads", 64)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("max_seq_len", 8192)
        return cls(**kw)

    @classmethod
    def qwen2_7b(cls, **kw) -> "TransformerConfig":
        """Qwen2-7B shape (the qkv-bias interop family)."""
        kw.setdefault("vocab_size", 152064)
        kw.setdefault("hidden_size", 3584)
        kw.setdefault("intermediate_size", 18944)
        kw.setdefault("num_layers", 28)
        kw.setdefault("num_heads", 28)
        kw.setdefault("num_kv_heads", 4)
        kw.setdefault("max_seq_len", 32768)
        kw.setdefault("rope_theta", 1000000.0)
        kw.setdefault("qkv_bias", True)
        return cls(**kw)

    @classmethod
    def t5_base(cls, **kw) -> "TransformerConfig":
        """T5-base shape family (reference megatron t5 parser
        utils/megatron_lm.py:1717): 12+12 layers, 768 hidden. SwiGLU/rope
        replace relu/relative-bias — capability parity, modernized arch."""
        kw.setdefault("vocab_size", 32128)
        kw.setdefault("hidden_size", 768)
        kw.setdefault("intermediate_size", 2048)
        kw.setdefault("num_layers", 12)
        kw.setdefault("num_decoder_layers", 12)
        kw.setdefault("num_heads", 12)
        kw.setdefault("max_seq_len", 512)
        kw.setdefault("tie_embeddings", True)
        return cls(**kw)

    @classmethod
    def mixtral_8x7b(cls, **kw) -> "TransformerConfig":
        kw.setdefault("vocab_size", 32000)
        kw.setdefault("hidden_size", 4096)
        kw.setdefault("intermediate_size", 14336)
        kw.setdefault("num_layers", 32)
        kw.setdefault("num_heads", 32)
        kw.setdefault("num_kv_heads", 8)
        kw.setdefault("num_experts", 8)
        kw.setdefault("num_experts_per_tok", 2)
        kw.setdefault("max_seq_len", 4096)
        return cls(**kw)
