"""Autoregressive generation with a static-shape KV cache.

The inference counterpart of the reference's big-model benchmark surface
(BASELINE.md measures s/token generation; reference drives HF
``model.generate``). TPU-native design: prefill is one forward over the
prompt; the decode loop is a single ``lax.scan`` over token steps — one
compiled program for the whole generation, no per-token dispatch.

Sampling: greedy, temperature, top-k, top-p (nucleus).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .transformer import CausalLM


def _sample_logits(logits, key, temperature, top_k, top_p):
    """(B, V) logits -> (B,) token ids."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; cutoff is the logit of
        # the last token inside that set
        include = cum - probs < top_p
        cutoff = jnp.min(
            jnp.where(include, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def init_cache(model_init, *init_args, **init_kwargs):
    """Zeroed decode-cache template via eval_shape: a full ``model.init``
    here would materialize (and randomly initialize) an entire spare
    parameter tree just to learn the cache shapes — pure HBM/time waste at
    8B+ scale. Shared by CausalLM and Seq2SeqLM generation."""
    cache_shapes = jax.eval_shape(
        lambda: model_init(*init_args, **init_kwargs)["cache"]
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)


def generate(
    model: CausalLM,
    params: Any,
    input_ids: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_token_id: Optional[int] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate continuations; returns (B, prompt_len + max_new_tokens).

    The prompt must fit ``config.max_seq_len - max_new_tokens``. After an
    EOS, positions are padded with EOS (finished sequences stop changing).
    """
    B, prompt_len = input_ids.shape
    if prompt_len + max_new_tokens > model.config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_seq_len ({model.config.max_seq_len})"
        )
    key = key if key is not None else jax.random.PRNGKey(0)
    cache = init_cache(
        model.init, jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
        decode=True,
    )

    # prefill the whole prompt in one forward
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, input_ids, decode=True,
        mutable=["cache"],
    )
    cache = mutated["cache"]
    first = _sample_logits(logits[:, -1], key, temperature, top_k, top_p)

    def step(carry, _):
        cache, token, k, done = carry
        k, sub = jax.random.split(k)
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None], decode=True,
            mutable=["cache"],
        )
        nxt = _sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (mutated["cache"], nxt, k, done), nxt

    done = (
        (first == eos_token_id)
        if eos_token_id is not None
        else jnp.zeros((B,), bool)
    )
    if max_new_tokens > 1:
        (_, _, _, _), rest = jax.lax.scan(
            step, (cache, first, key, done), None, length=max_new_tokens - 1
        )
        new_tokens = jnp.concatenate([first[:, None], rest.T], axis=1)
    else:
        new_tokens = first[:, None]
    return jnp.concatenate([input_ids, new_tokens], axis=1)


def make_generate_fn(
    model: CausalLM,
    max_new_tokens: int = 32,
    **sample_kwargs,
):
    """A jitted generate closure: ``fn(params, input_ids, key) -> ids``.
    Compile once, call per batch (static prompt length)."""

    @jax.jit
    def fn(params, input_ids, key=None):
        return generate(
            model, params, input_ids, max_new_tokens=max_new_tokens,
            key=key, **sample_kwargs,
        )

    return fn
