"""Autoregressive generation with a static-shape KV cache.

The inference counterpart of the reference's big-model benchmark surface
(BASELINE.md measures s/token generation; reference drives HF
``model.generate``). TPU-native design: prefill is one forward over the
prompt; the decode loop is a single ``lax.scan`` over token steps — one
compiled program for the whole generation, no per-token dispatch.

Sampling: greedy, temperature, top-k, top-p (nucleus).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .transformer import CausalLM


def _filter_logits(logits, top_k, top_p):
    """(B, V) fp32 logits -> same, with everything outside the top-k /
    nucleus set at -inf. Shared by batch sampling here and the per-slot
    serving sampler (:mod:`accelerate_tpu.serving.sampling`)."""
    if top_k is not None and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; cutoff is the logit of
        # the last token inside that set
        include = cum - probs < top_p
        cutoff = jnp.min(
            jnp.where(include, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _sample_logits(logits, key, temperature, top_k, top_p):
    """(B, V) logits -> (B,) token ids."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = _filter_logits(logits.astype(jnp.float32) / temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1)


def init_cache(model_init, *init_args, **init_kwargs):
    """Zeroed decode-cache template via eval_shape: a full ``model.init``
    here would materialize (and randomly initialize) an entire spare
    parameter tree just to learn the cache shapes — pure HBM/time waste at
    8B+ scale. Shared by CausalLM and Seq2SeqLM generation."""
    cache_shapes = jax.eval_shape(
        lambda: model_init(*init_args, **init_kwargs)["cache"]
    )
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)


def generate(
    model: CausalLM,
    params: Any,
    input_ids: jax.Array,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_token_id: Optional[int] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate continuations; returns (B, prompt_len + max_new_tokens).

    The prompt must fit ``config.max_seq_len - max_new_tokens``. After an
    EOS, positions are padded with EOS (finished sequences stop changing).
    """
    B, prompt_len = input_ids.shape
    if prompt_len + max_new_tokens > model.config.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds max_seq_len ({model.config.max_seq_len})"
        )
    key = key if key is not None else jax.random.PRNGKey(0)
    cache = init_cache(
        model.init, jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
        decode=True,
    )

    # prefill the whole prompt in one forward
    logits, mutated = model.apply(
        {"params": params, "cache": cache}, input_ids, decode=True,
        mutable=["cache"],
    )
    cache = mutated["cache"]
    first = _sample_logits(logits[:, -1], key, temperature, top_k, top_p)

    def step(carry, _):
        cache, token, k, done = carry
        k, sub = jax.random.split(k)
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, token[:, None], decode=True,
            mutable=["cache"],
        )
        nxt = _sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        if eos_token_id is not None:
            nxt = jnp.where(done, eos_token_id, nxt)
            done = done | (nxt == eos_token_id)
        return (mutated["cache"], nxt, k, done), nxt

    done = (
        (first == eos_token_id)
        if eos_token_id is not None
        else jnp.zeros((B,), bool)
    )
    if max_new_tokens > 1:
        (_, _, _, _), rest = jax.lax.scan(
            step, (cache, first, key, done), None, length=max_new_tokens - 1
        )
        new_tokens = jnp.concatenate([first[:, None], rest.T], axis=1)
    else:
        new_tokens = first[:, None]
    return jnp.concatenate([input_ids, new_tokens], axis=1)


def _prompt_chunks(prompt_len: int) -> list[int]:
    """Descending power-of-two decomposition of a prompt length (13 ->
    [8, 4, 1]): the chunk widths every prompt can be prefilled with."""
    chunks, width = [], 1 << (max(prompt_len, 1).bit_length() - 1)
    while prompt_len:
        if width <= prompt_len:
            chunks.append(width)
            prompt_len -= width
        width >>= 1
    return chunks


def make_generate_fn(
    model: CausalLM,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_token_id: Optional[int] = None,
):
    """A compiled generate closure: ``fn(params, input_ids, key) -> ids``.

    The old closure jitted the WHOLE generate, so every distinct prompt
    length retraced prefill + decode scan — a serving workload with mixed
    prompts recompiled per length (the retrace trap). Here prefill runs
    as descending power-of-two CHUNKS through one shared jitted apply
    (13 tokens -> chunks of 8, 4, 1 written at their true cache offsets —
    the dense decode branch anchors masks at the global position, so the
    math is EXACT, not bucket-padded), and the decode scan is jitted once
    per batch size. Across any mix of prompt lengths at most
    ``log2(max_seq_len)`` prefill programs ever compile.

    ``fn.trace_counts()`` exposes ``{"prefill": n, "decode": m}`` (Python
    trace-time counters) so tests can assert the bound.
    """
    traces = {"prefill": 0, "decode": 0}

    @jax.jit
    def _prefill_chunk(params, cache, chunk):
        traces["prefill"] += 1
        logits, mutated = model.apply(
            {"params": params, "cache": cache}, chunk, decode=True,
            mutable=["cache"],
        )
        return mutated["cache"], logits[:, -1]

    @jax.jit
    def _decode(params, cache, last_logits, key):
        traces["decode"] += 1
        # sampling order matches generate() exactly: first token from the
        # caller's key, scan steps split from it — same key math, same
        # tokens, so the two APIs are interchangeable
        first = _sample_logits(last_logits, key, temperature, top_k, top_p)
        done = (
            (first == eos_token_id)
            if eos_token_id is not None
            else jnp.zeros(first.shape, bool)
        )

        def step(carry, _):
            cache, token, k, done = carry
            k, sub = jax.random.split(k)
            logits, mutated = model.apply(
                {"params": params, "cache": cache}, token[:, None],
                decode=True, mutable=["cache"],
            )
            nxt = _sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
            if eos_token_id is not None:
                nxt = jnp.where(done, eos_token_id, nxt)
                done = done | (nxt == eos_token_id)
            return (mutated["cache"], nxt, k, done), nxt

        if max_new_tokens > 1:
            _, rest = jax.lax.scan(
                step, (cache, first, key, done), None,
                length=max_new_tokens - 1,
            )
            return jnp.concatenate([first[:, None], rest.T], axis=1)
        return first[:, None]

    def fn(params, input_ids, key=None):
        B, prompt_len = input_ids.shape
        if prompt_len + max_new_tokens > model.config.max_seq_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_seq_len ({model.config.max_seq_len})"
            )
        key = key if key is not None else jax.random.PRNGKey(0)
        cache = init_cache(
            model.init, jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32),
            decode=True,
        )
        offset = 0
        for width in _prompt_chunks(prompt_len):
            cache, last = _prefill_chunk(
                params, cache, input_ids[:, offset:offset + width]
            )
            offset += width
        new_tokens = _decode(params, cache, last, key)
        return jnp.concatenate([input_ids, new_tokens], axis=1)

    fn.trace_counts = lambda: dict(traces)
    return fn
