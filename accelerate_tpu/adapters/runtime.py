"""Traced LoRA math: the one delta formula training AND serving share.

The whole multi-tenant story rests on a single invariant — the adapter
contribution is computed the SAME way whether the adapter tree is a
differentiated capacity-1 stack (training) or one row of a resident
multi-adapter stack (serving):

    delta(x) = scale[id] * ((dropout(x) @ A[id]) @ B[id])

``A``/``B`` live in fixed-capacity stacks ``(A_cap, in, r)`` /
``(A_cap, r, out)`` (per layer, ``(L, A_cap, ...)`` before ``nn.scan``
strips the leading axis) and ``id`` is per-batch-row TRACED data — the
per-slot-temperatures idiom from the serving engine, applied to weights.
Loading or evicting an adapter rewrites rows of the stack; shapes never
change, so the compiled program never retraces. Row 0 is reserved as the
all-zero identity adapter (rows without an adapter gather zeros — exact,
not approximate), and an adapter of rank ``r < r_max`` pads A's columns
and B's rows with zeros, which contributes exactly 0 to the product —
rank padding is mathematically exact, not a tolerance.

This module is deliberately model-free (no imports from ``..models``) so
``models/transformer.py`` can import it without a cycle.
"""

from __future__ import annotations

from typing import Any, Optional

import flax
import jax
import jax.numpy as jnp

#: stack key names inside a per-target adapter pair
A_KEY = "lora_a"
B_KEY = "lora_b"


@flax.struct.dataclass
class LoraState:
    """Everything the forward pass needs to apply adapters.

    ``stacks``: ``{target: {"lora_a": (L, A, in, r), "lora_b":
    (L, A, r, out)}}`` — scanned over the leading layer axis by
    ``_apply_layer_stack`` (inside a block the leading ``L`` is gone).
    ``slot_ids``: ``(B,)`` int32 — which stack row each batch row uses
    (0 = the identity adapter). ``scales``: ``(A,)`` float32 — per-row
    ``alpha / rank``. Dropout fields are static (they change the traced
    program, not its data).
    """

    stacks: Any
    slot_ids: jax.Array
    scales: jax.Array
    dropout_rate: float = flax.struct.field(pytree_node=False, default=0.0)
    deterministic: bool = flax.struct.field(pytree_node=False, default=True)

    def context(self) -> "LoraState":
        """The broadcast half (everything but the scanned stacks) —
        what rides next to ``positions``/``mask`` through ``nn.scan``."""
        return self.replace(stacks=None)


def lora_delta(
    x: jax.Array,
    pair: dict,
    slot_ids: jax.Array,
    scales: jax.Array,
) -> jax.Array:
    """The gathered low-rank delta for one projection.

    ``x``: ``(B, S, in)`` activations; ``pair``: ``{"lora_a": (A, in, r),
    "lora_b": (A, r, out)}`` (layer axis already scanned away);
    ``slot_ids``: ``(B,)``; ``scales``: ``(A,)``. Returns ``(B, S, out)``
    in ``x.dtype``. Row b reads ONLY stack row ``slot_ids[b]`` — what the
    other rows hold cannot perturb its value, which is why a mixed batch
    is bitwise-identical per tenant to a single-tenant batch.
    """
    a = jnp.take(pair[A_KEY], slot_ids, axis=0).astype(x.dtype)  # (B, in, r)
    b = jnp.take(pair[B_KEY], slot_ids, axis=0).astype(x.dtype)  # (B, r, out)
    s = jnp.take(scales, slot_ids, axis=0).astype(x.dtype)  # (B,)
    h = jnp.einsum("bsi,bir->bsr", x, a)
    return jnp.einsum("bsr,bro->bso", h, b) * s[:, None, None]


def stack_adapter(adapter_params: Any) -> Any:
    """A single adapter tree ``{target: {lora_a: (L, in, r), lora_b:
    (L, r, out)}}`` -> capacity-1 stacks ``(L, 1, in, r)`` — the training
    form: one tenant, same gather math as serving."""
    return jax.tree.map(lambda l: l[:, None], adapter_params)


def pad_rank(arr: jax.Array, axis: int, r_max: int) -> jax.Array:
    """Zero-pad an adapter leaf's rank axis up to ``r_max`` (exact: zero
    columns of A / rows of B contribute exactly 0 to A @ B)."""
    r = arr.shape[axis]
    if r > r_max:
        raise ValueError(f"adapter rank {r} exceeds registry max rank {r_max}")
    if r == r_max:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[axis] = (0, r_max - r)
    return jnp.pad(arr, pad)


def empty_stacks(
    target_shapes: dict[str, tuple[int, int]],
    num_layers: int,
    capacity: int,
    rank: int,
    dtype: Any = jnp.float32,
) -> dict:
    """All-zero fixed-capacity stacks for a registry: ``{target:
    {"lora_a": (L, cap, in, r), "lora_b": (L, cap, r, out)}}``. Every
    row starts as the identity adapter (zero delta)."""
    stacks = {}
    for target, (in_dim, out_dim) in target_shapes.items():
        stacks[target] = {
            A_KEY: jnp.zeros((num_layers, capacity, in_dim, rank), dtype),
            B_KEY: jnp.zeros((num_layers, capacity, rank, out_dim), dtype),
        }
    return stacks


def write_adapter_row(
    stacks: dict,
    slot: int,
    adapter_params: Any,
    r_max: Optional[int] = None,
) -> dict:
    """Functionally write one adapter into stack row ``slot`` (rank-
    padded); targets the adapter does not carry stay zero (identity).
    Returns new stacks — shapes unchanged, so consumers never retrace."""
    out = {}
    for target, pair in stacks.items():
        if adapter_params is not None and target in adapter_params:
            a = pad_rank(
                jnp.asarray(adapter_params[target][A_KEY], pair[A_KEY].dtype),
                axis=-1, r_max=r_max or pair[A_KEY].shape[-1],
            )
            b = pad_rank(
                jnp.asarray(adapter_params[target][B_KEY], pair[B_KEY].dtype),
                axis=-2, r_max=r_max or pair[B_KEY].shape[-2],
            )
            out[target] = {
                A_KEY: pair[A_KEY].at[:, slot].set(a),
                B_KEY: pair[B_KEY].at[:, slot].set(b),
            }
        else:
            out[target] = {
                A_KEY: pair[A_KEY].at[:, slot].set(0.0),
                B_KEY: pair[B_KEY].at[:, slot].set(0.0),
            }
    return out
