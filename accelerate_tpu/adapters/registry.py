"""Host-side residency manager for the multi-tenant serving stacks.

The registry owns the device-resident adapter stacks the compiled decode
program gathers from: ``{target: {"lora_a": (L, rows, in, r_max),
"lora_b": (L, rows, r_max, out)}}`` plus a ``(rows,)`` scale vector.
Row 0 is permanently the all-zero identity adapter (requests without an
adapter gather exact zeros); rows 1..capacity hold tenants. ``load`` and
``evict`` rewrite ROWS of these fixed-shape arrays — the consuming
decode program's shapes never change, so residency churn causes zero
retraces. Everything else here (names, slots, refcounts, LRU order) is
plain host bookkeeping, deliberately outside the traced world.
"""

from __future__ import annotations

from typing import Any, Optional

import jax.numpy as jnp

from ..models.config import TransformerConfig
from .lora import ALL_TARGETS, LoraConfig, target_shapes
from .runtime import A_KEY, B_KEY, empty_stacks, write_adapter_row


class AdapterRegistry:
    """Load/evict/refcount resident adapters over fixed-capacity stacks.

    ``capacity``: how many tenants can be resident at once (the identity
    row is extra). ``max_rank``: the stacks' rank budget — adapters with
    smaller rank zero-pad (exact). ``target_modules``: the superset of
    projections the stacks cover; a loaded adapter may target any subset
    (untargeted rows stay zero).
    """

    def __init__(
        self,
        model_config: TransformerConfig,
        capacity: int = 4,
        max_rank: int = 8,
        target_modules: tuple = ("q_proj", "v_proj"),
        dtype: Any = jnp.float32,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        unknown = [t for t in target_modules if t not in ALL_TARGETS]
        if unknown:
            raise ValueError(
                f"unknown target_modules {unknown}; "
                f"supported: {', '.join(ALL_TARGETS)}"
            )
        self.model_config = model_config
        self.capacity = int(capacity)
        self.max_rank = int(max_rank)
        self.target_modules = tuple(target_modules)
        shapes = target_shapes(model_config)
        self._stacks = empty_stacks(
            {t: shapes[t] for t in self.target_modules},
            num_layers=model_config.num_layers,
            capacity=self.capacity + 1,  # + the identity row 0
            rank=self.max_rank,
            dtype=dtype,
        )
        self._scales = jnp.zeros((self.capacity + 1,), jnp.float32)
        self._slots: dict[str, int] = {}  # name -> row (1..capacity)
        self._refcounts: dict[str, int] = {}
        self._lru: list[str] = []  # least-recent first
        self.load_total = 0
        self.evict_total = 0

    # -------------------------------------------------------------- #
    # residency
    # -------------------------------------------------------------- #
    def load(self, name: str, adapter_params: dict, config: LoraConfig) -> int:
        """Make ``name`` resident; returns its stack row. Re-loading a
        resident name overwrites its row in place. When full, the
        least-recently-used refcount-0 tenant is evicted; if every row is
        pinned by in-flight requests, raises RuntimeError."""
        self._validate(name, adapter_params, config)
        if name in self._slots:
            slot = self._slots[name]
        else:
            slot = self._free_slot()
            self._slots[name] = slot
            self._refcounts[name] = 0
        self._stacks = write_adapter_row(
            self._stacks, slot, adapter_params, r_max=self.max_rank
        )
        self._scales = self._scales.at[slot].set(config.scaling)
        self._touch(name)
        self.load_total += 1
        return slot

    def evict(self, name: str) -> None:
        if name not in self._slots:
            raise KeyError(f"adapter {name!r} is not resident")
        if self._refcounts.get(name, 0) > 0:
            raise RuntimeError(
                f"adapter {name!r} has {self._refcounts[name]} in-flight "
                "request(s); release them before evicting"
            )
        self._clear_row(self._slots.pop(name))
        self._refcounts.pop(name, None)
        if name in self._lru:
            self._lru.remove(name)
        self.evict_total += 1

    def resident(self, name: Optional[str]) -> bool:
        return name is None or name in self._slots

    def slot_of(self, name: Optional[str]) -> int:
        """The stack row a request should gather: 0 (identity) for no
        adapter, the tenant's row otherwise."""
        if name is None:
            return 0
        return self._slots[name]

    def resident_names(self) -> list[str]:
        return sorted(self._slots)

    # -------------------------------------------------------------- #
    # refcounts (pin resident adapters while requests are in flight)
    # -------------------------------------------------------------- #
    def acquire(self, name: Optional[str]) -> None:
        if name is None:
            return
        if name not in self._slots:
            raise KeyError(f"adapter {name!r} is not resident")
        self._refcounts[name] = self._refcounts.get(name, 0) + 1
        self._touch(name)

    def release(self, name: Optional[str]) -> None:
        if name is None:
            return
        count = self._refcounts.get(name, 0)
        if count <= 0:
            raise RuntimeError(f"adapter {name!r} released more than acquired")
        self._refcounts[name] = count - 1

    # -------------------------------------------------------------- #
    # the traced-side views the engine closes over
    # -------------------------------------------------------------- #
    def stacks(self) -> dict:
        return self._stacks

    def scales(self) -> jnp.ndarray:
        return self._scales

    def hbm_bytes(self) -> int:
        import numpy as np

        import jax

        return sum(
            int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
            for l in jax.tree.leaves(self._stacks)
        ) + self._scales.nbytes

    # -------------------------------------------------------------- #
    # internals
    # -------------------------------------------------------------- #
    def _validate(self, name, adapter_params, config):
        if not name:
            raise ValueError("adapter name must be non-empty")
        if config.rank > self.max_rank:
            raise ValueError(
                f"adapter {name!r} rank {config.rank} exceeds registry "
                f"max_rank {self.max_rank}"
            )
        extra = set(adapter_params) - set(self.target_modules)
        if extra:
            raise ValueError(
                f"adapter {name!r} targets {sorted(extra)} which this "
                f"registry's stacks do not cover (covered: "
                f"{', '.join(self.target_modules)})"
            )
        shapes = target_shapes(self.model_config)
        L = self.model_config.num_layers
        for t, pair in adapter_params.items():
            in_dim, out_dim = shapes[t]
            a, b = pair[A_KEY], pair[B_KEY]
            if a.shape[0] != L or a.shape[1] != in_dim:
                raise ValueError(
                    f"adapter {name!r} {t} lora_a shape {a.shape} does not "
                    f"match model layout (expected ({L}, {in_dim}, r))"
                )
            if b.shape[0] != L or b.shape[2] != out_dim:
                raise ValueError(
                    f"adapter {name!r} {t} lora_b shape {b.shape} does not "
                    f"match model layout (expected ({L}, r, {out_dim}))"
                )

    def _free_slot(self) -> int:
        used = set(self._slots.values())
        for row in range(1, self.capacity + 1):
            if row not in used:
                return row
        # full: evict the least-recently-used unpinned tenant
        for name in self._lru:
            if self._refcounts.get(name, 0) == 0:
                row = self._slots[name]
                self.evict(name)
                return row
        raise RuntimeError(
            f"registry full ({self.capacity} adapters, all with in-flight "
            "requests) — raise capacity or drain traffic"
        )

    def _clear_row(self, row: int) -> None:
        self._stacks = write_adapter_row(self._stacks, row, None)
        self._scales = self._scales.at[row].set(0.0)

    def _touch(self, name: str) -> None:
        if name in self._lru:
            self._lru.remove(name)
        self._lru.append(name)
