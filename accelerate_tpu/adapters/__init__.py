"""Multi-tenant adapters: LoRA/QLoRA training + batched multi-adapter
serving over one base model.

Training: :func:`lora_loss_fn` differentiates ONLY the adapter tree
against a frozen (optionally quantized) base — the carry that threads
through ``Accelerator.unified_step`` holds adapter leaves alone. Serving:
:class:`AdapterRegistry` keeps tenants resident in fixed-shape gathered
stacks indexed per-slot as traced data, so one compiled decode program
serves every tenant with zero retraces. Checkpoints: tiny
``adapter_<name>`` artifacts through the atomic commit protocol.
"""

from .checkpoint import (
    adapter_dir,
    list_adapters,
    load_adapter,
    save_adapter,
)
from .lora import (
    ALL_TARGETS,
    LoraConfig,
    adapter_num_bytes,
    adapter_num_params,
    assert_adapter_only,
    build_lora_state,
    init_adapter,
    lora_loss_fn,
    target_shapes,
)
from .registry import AdapterRegistry
from .runtime import LoraState, lora_delta

__all__ = [
    "ALL_TARGETS",
    "AdapterRegistry",
    "LoraConfig",
    "LoraState",
    "adapter_dir",
    "adapter_num_bytes",
    "adapter_num_params",
    "assert_adapter_only",
    "build_lora_state",
    "init_adapter",
    "list_adapters",
    "load_adapter",
    "lora_delta",
    "lora_loss_fn",
    "save_adapter",
    "target_shapes",
]
