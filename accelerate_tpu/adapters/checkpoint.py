"""Tiny adapter checkpoints through the atomic commit protocol.

An adapter artifact is a directory ``adapter_<name>/`` holding
``adapter_model.safetensors`` (the flattened A/B tree) and
``adapter_config.json`` (the LoraConfig) — written into a ``.tmp`` work
dir and renamed into place by :mod:`..checkpoint_async.commit`, the same
done-marker/COMMITTED discipline the training checkpoints use. Readers
(:func:`load_adapter`, :func:`list_adapters`) only ever see committed
directories; a crash mid-save leaves an orphaned ``.tmp`` that is never
listed. Base weights are never rewritten — the adapter dir is the entire
artifact, which is what makes per-tenant checkpoints ~100x smaller than
the model they adapt.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

from ..checkpoint_async.commit import commit, is_committed, work_dir_for
from ..checkpointing import (
    _SEP,
    _atomic_json_dump,
    _load_named,
    _save_named,
    _to_host,
    flatten_tree,
)
from .lora import LoraConfig

ADAPTER_PREFIX = "adapter_"
WEIGHTS_FILE = "adapter_model.safetensors"
CONFIG_FILE = "adapter_config.json"


def adapter_dir(base_dir: str, name: str) -> str:
    return os.path.join(base_dir, f"{ADAPTER_PREFIX}{name}")


def save_adapter(
    base_dir: str,
    name: str,
    adapter_params: Any,
    lora_config: LoraConfig,
    process_index: int = 0,
    world: int = 1,
) -> str:
    """Commit ``adapter_<name>/`` under ``base_dir``; returns the final
    path. Safe against crashes at any point: the final dir either does
    not exist or is complete and COMMITTED."""
    if not name or "/" in name:
        raise ValueError(f"invalid adapter name {name!r}")
    final = adapter_dir(base_dir, name)
    work = work_dir_for(final)
    os.makedirs(work, exist_ok=True)
    named = flatten_tree(_to_host(adapter_params))
    _save_named(named, os.path.join(work, WEIGHTS_FILE))
    _atomic_json_dump(
        lora_config.to_dict(), os.path.join(work, CONFIG_FILE), indent=2
    )
    commit(work, final, process_index=process_index, world=world)
    return final


def load_adapter(path: str) -> tuple[dict, LoraConfig]:
    """Load a COMMITTED adapter dir -> (adapter tree, LoraConfig).
    Uncommitted/partial directories are refused loudly."""
    if not is_committed(path):
        raise FileNotFoundError(
            f"{path} is not a committed adapter checkpoint (missing "
            "COMMITTED marker — crashed save or wrong path?)"
        )
    named = _load_named(os.path.join(path, WEIGHTS_FILE))
    params: dict = {}
    for key, leaf in named.items():
        node = params
        parts = key.split(_SEP)
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = leaf
    with open(os.path.join(path, CONFIG_FILE)) as f:
        config = LoraConfig.from_dict(json.load(f))
    return params, config


def list_adapters(base_dir: str) -> dict[str, str]:
    """``{name: committed path}`` for every committed adapter under
    ``base_dir``. Work dirs (``.tmp``) and uncommitted dirs are invisible
    by construction."""
    out: dict[str, str] = {}
    if not os.path.isdir(base_dir):
        return out
    for entry in sorted(os.listdir(base_dir)):
        path = os.path.join(base_dir, entry)
        if (
            entry.startswith(ADAPTER_PREFIX)
            and os.path.isdir(path)
            and is_committed(path)
        ):
            out[entry[len(ADAPTER_PREFIX):]] = path
    return out
