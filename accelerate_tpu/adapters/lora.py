"""LoRA/QLoRA training over the native Llama-layout model.

The recipe (Hu et al., 2021 / Dettmers et al., 2023): freeze the base —
optionally int4/int8 via :mod:`..utils.quantization` — and train tiny
low-rank ``A``/``B`` deltas on the projection modules. Here the adapter
tree is the ONLY thing the optimizer ever sees: :func:`lora_loss_fn`
closes over the frozen base (behind ``jax.lax.stop_gradient``, so base
gradients are identically zero, not just unoptimized) and differentiates
w.r.t. the adapter tree alone, which threads through the existing
``Accelerator.unified_step`` unchanged — the fused-adamw epilogue either
applies to the adapter tree or declines gracefully, by design.

Adapter trees are ``{target: {"lora_a": (L, in, r), "lora_b":
(L, r, out)}}`` — the leading layer axis matches the model's ``nn.scan``
stacked-parameter layout, so one adapter leaf per target covers every
layer (and slices per layer on the unrolled path too).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.config import TransformerConfig
from .runtime import A_KEY, B_KEY, LoraState, stack_adapter

#: every module LoRA can target (the 7 Llama-layout projections)
ALL_TARGETS = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)


@dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    target_modules: tuple = ("q_proj", "v_proj")
    dropout: float = 0.0

    def __post_init__(self):
        if self.rank <= 0:
            raise ValueError(f"rank must be positive, got {self.rank}")
        if not (0.0 <= self.dropout < 1.0):
            raise ValueError(f"dropout must be in [0, 1), got {self.dropout}")
        object.__setattr__(
            self, "target_modules", tuple(self.target_modules)
        )
        unknown = [t for t in self.target_modules if t not in ALL_TARGETS]
        if unknown:
            raise ValueError(
                f"unknown target_modules {unknown}; "
                f"supported: {', '.join(ALL_TARGETS)}"
            )
        if not self.target_modules:
            raise ValueError("target_modules must name at least one module")

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank

    def to_dict(self) -> dict:
        return {
            "rank": self.rank,
            "alpha": self.alpha,
            "target_modules": list(self.target_modules),
            "dropout": self.dropout,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LoraConfig":
        return cls(
            rank=int(d["rank"]),
            alpha=float(d["alpha"]),
            target_modules=tuple(d["target_modules"]),
            dropout=float(d.get("dropout", 0.0)),
        )


def target_shapes(cfg: TransformerConfig) -> dict[str, tuple[int, int]]:
    """(in_features, out_features) per targetable projection — the
    native module shapes in ``models/transformer.py``."""
    h = cfg.hidden_size
    q_dim = cfg.num_heads * cfg.head_dim
    kv_dim = cfg.num_kv_heads * cfg.head_dim
    f = cfg.intermediate_size
    return {
        "q_proj": (h, q_dim),
        "k_proj": (h, kv_dim),
        "v_proj": (h, kv_dim),
        "o_proj": (q_dim, h),
        "gate_proj": (h, f),
        "up_proj": (h, f),
        "down_proj": (f, h),
    }


def init_adapter(
    rng: jax.Array,
    model_config: TransformerConfig,
    lora_config: LoraConfig,
    dtype: Any = jnp.float32,
) -> dict:
    """A fresh adapter: A ~ N(0, 0.02), B = 0 — so a freshly-initialized
    adapter's delta is EXACTLY zero and the adapted model starts bitwise
    at the base model's outputs (the LoRA init contract)."""
    shapes = target_shapes(model_config)
    L, r = model_config.num_layers, lora_config.rank
    adapter = {}
    for t in lora_config.target_modules:
        in_dim, out_dim = shapes[t]
        rng, sub = jax.random.split(rng)
        adapter[t] = {
            A_KEY: 0.02 * jax.random.normal(sub, (L, in_dim, r), dtype),
            B_KEY: jnp.zeros((L, r, out_dim), dtype),
        }
    return adapter


def adapter_num_params(
    model_config: TransformerConfig, lora_config: LoraConfig
) -> int:
    """``sum over targets of L * r * (in + out)`` — the sizing formula
    (bytes = this * 4 at fp32; see README "Multi-tenant adapters")."""
    shapes = target_shapes(model_config)
    L, r = model_config.num_layers, lora_config.rank
    return sum(
        L * r * (shapes[t][0] + shapes[t][1])
        for t in lora_config.target_modules
    )


def adapter_num_bytes(tree: Any) -> int:
    return sum(
        int(np.prod(l.shape)) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree.leaves(tree)
    )


def build_lora_state(
    adapter_params: dict,
    lora_config: LoraConfig,
    batch_size: int,
    deterministic: bool = True,
) -> LoraState:
    """Wrap one adapter tree as a capacity-1 ``LoraState`` — every batch
    row indexes stack row 0. Training runs the exact same gather math the
    multi-tenant serving stack does."""
    return LoraState(
        stacks=stack_adapter(adapter_params),
        slot_ids=jnp.zeros((batch_size,), jnp.int32),
        scales=jnp.asarray([lora_config.scaling], jnp.float32),
        dropout_rate=lora_config.dropout,
        deterministic=deterministic,
    )


def lora_loss_fn(
    model,
    base_params: Any,
    lora_config: LoraConfig,
    compute_dtype: Any = None,
):
    """Next-token CE closure for ``Accelerator.unified_step`` whose
    differentiated tree is the ADAPTER, not the model.

    ``fn(adapter_params, batch)`` with batch {input_ids, [loss_mask],
    [dropout_seed]}. The frozen base (plain or quantized — quantized
    leaves dequantize to ``compute_dtype`` on the fly, QLoRA-style) sits
    behind ``jax.lax.stop_gradient``: d(loss)/d(base) is bitwise zero and
    XLA never materializes base gradient buffers. LoRA dropout activates
    only when the config asks for it AND the batch carries a
    ``dropout_seed`` (per-step int32); otherwise the pass is
    deterministic.
    """
    from ..utils.quantization import dequantize_tree

    def fn(adapter_params, batch):
        ids = batch["input_ids"]
        base = jax.lax.stop_gradient(
            dequantize_tree(base_params, compute_dtype)
        )
        use_dropout = lora_config.dropout > 0.0 and "dropout_seed" in batch
        state = build_lora_state(
            adapter_params, lora_config, ids.shape[0],
            deterministic=not use_dropout,
        )
        rngs = (
            {"dropout": jax.random.PRNGKey(batch["dropout_seed"])}
            if use_dropout else None
        )
        logits = model.apply({"params": base}, ids, lora=state, rngs=rngs)
        targets = ids[:, 1:]
        logits = logits[:, :-1]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        mask = batch.get("loss_mask")
        if mask is not None:
            mask = mask[:, 1:].astype(jnp.float32)
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.mean(nll)

    fn.fused_kernels = bool(getattr(model.config, "fused_kernels", False))
    return fn


def assert_adapter_only(tree: Any, lora_config: LoraConfig) -> None:
    """Raise unless ``tree`` is exactly an adapter tree (the acceptance
    assertion that the optimizer carry holds ONLY adapter leaves — no
    frozen-base leaf ever entered the optimizer)."""
    if not isinstance(tree, dict):
        raise AssertionError(f"adapter tree must be a dict, got {type(tree)}")
    extra = set(tree) - set(lora_config.target_modules)
    missing = set(lora_config.target_modules) - set(tree)
    if extra or missing:
        raise AssertionError(
            f"carry is not adapter-only: extra keys {sorted(extra)}, "
            f"missing keys {sorted(missing)}"
        )
    for t, pair in tree.items():
        keys = set(pair)
        if keys != {A_KEY, B_KEY}:
            raise AssertionError(
                f"target {t!r} must hold exactly {{lora_a, lora_b}}, "
                f"got {sorted(keys)}"
            )
