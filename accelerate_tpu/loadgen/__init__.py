"""Heavy-traffic soak & chaos harness (see ISSUE 16 / ROADMAP item 5).

Open-loop seeded load generation against the serving engine, a
warmup → ramp → soak → fault → recovery phase program, serving-scoped
chaos via the ``ACCELERATE_TPU_FAULT_INJECT`` grammar, and an
atomically-written ``soak-report.json`` with goodput-under-SLO and
capacity-at-breach-point headlines. Everything is default-off and
record-only: nothing here runs unless a bench variant, a test, or user
code builds a :class:`SoakHarness`.
"""

from .chaos import ChaosAdapter
from .harness import SoakClock, SoakConfig, SoakHarness
from .phases import Phase, phase_bounds, standard_program, total_duration_s
from .report import (
    REPORT_BASENAME,
    lag_histogram,
    read_report,
    write_report,
)
from .workload import (
    SoakRequest,
    WorkloadConfig,
    build_trace,
    trace_fingerprint,
)

__all__ = [
    "ChaosAdapter",
    "Phase",
    "REPORT_BASENAME",
    "SoakClock",
    "SoakConfig",
    "SoakHarness",
    "SoakRequest",
    "WorkloadConfig",
    "build_trace",
    "lag_histogram",
    "phase_bounds",
    "read_report",
    "standard_program",
    "total_duration_s",
    "trace_fingerprint",
    "write_report",
]
