"""soak-report.json: the machine-readable outcome of one soak run.

Written atomically (``tmp.<pid>`` + ``os.replace``, the flight-recorder
idiom) so a scraper or `accelerate-tpu diagnose` never reads a torn
file, and written from the harness's ``finally`` so a run that dies
mid-burn still leaves its final SLO snapshot and cumulative shed totals
on disk (never silently truncated to the last cadence record).

Schema (version 1) — top-level keys:

* ``headline``: ``goodput_tokens_per_s_at_slo`` (steady-soak tokens/s
  counting only requests whose TTFT met the objective; ``slo_ok`` says
  whether the soak phase's p95 TTFT itself was under the objective),
  ``capacity_rps_at_breach_point`` (highest ramp rate whose phase kept
  both burn windows under threshold; ``capacity_saturated`` True when
  even the top ramp rate never breached).
* ``phases``: per-phase table — offered/achieved rates, goodput,
  latency percentiles, sheds, breach flag.
* ``arrival_lag``: p50/p95/max + histogram of (submit − scheduled).
* ``fault``: armed specs, window bounds, events, damage inside the
  window (sheds + SLO-violating finishes) and ``recovery_s``.
* ``slo_final``: the drain-edge SloTracker snapshot taken at report
  time; ``shed_totals``: cumulative per-reason sheds.
* ``trace_sha256``: fingerprint of the request trace (replay proof).
* ``interrupted``: True when the run loop raised or was cut short.
"""

from __future__ import annotations

import json
import os
from typing import Optional, Sequence

REPORT_VERSION = 1
REPORT_BASENAME = "soak-report.json"

#: arrival-lag histogram bucket upper bounds (seconds); the last bucket
#: is open-ended
LAG_BUCKETS_S = (0.001, 0.01, 0.1, 1.0, 10.0)


def lag_histogram(lags: Sequence[float]) -> dict:
    """p50/p95/max plus fixed-bucket counts over recorded arrival lags."""
    from ..serving.telemetry import percentile

    lags = [max(0.0, float(v)) for v in lags]
    counts = {f"le_{hi:g}s": 0 for hi in LAG_BUCKETS_S}
    overflow = f"gt_{LAG_BUCKETS_S[-1]:g}s"
    counts[overflow] = 0
    for v in lags:
        for hi in LAG_BUCKETS_S:
            if v <= hi:
                counts[f"le_{hi:g}s"] += 1
                break
        else:
            counts[overflow] += 1
    return {
        "count": len(lags),
        "p50_s": percentile(lags, 50) if lags else 0.0,
        "p95_s": percentile(lags, 95) if lags else 0.0,
        "max_s": max(lags) if lags else 0.0,
        "histogram": counts,
    }


def write_report(path: str, report: dict) -> str:
    """Atomic JSON write; returns ``path``."""
    directory = os.path.dirname(os.path.abspath(path))
    os.makedirs(directory, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True, default=_json_safe)
    os.replace(tmp, path)
    return path


def read_report(path: str) -> Optional[dict]:
    """Parse a soak report; None when absent or torn (torn should be
    impossible given the atomic write, but diagnose never crashes on a
    bad input file)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _json_safe(obj):
    try:
        import numpy as np

        if isinstance(obj, np.generic):
            return obj.item()
    except ImportError:
        pass
    if isinstance(obj, tuple):
        return list(obj)
    return str(obj)
