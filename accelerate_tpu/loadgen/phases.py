"""Phase program for the soak harness: warmup → ramp → soak → fault →
recovery.

Every phase fixes an offered rate and an arrival process for its span;
:func:`phase_bounds` turns the sequence into absolute ``[start, end)``
windows on the run clock. Phase ``kind`` is semantic, not cosmetic —
the harness keys its accounting on it:

* ``ramp``   — the breach-point probe. Capacity-at-breach-point is the
  highest ramp rate whose phase saw no multi-window burn breach.
* ``soak``   — the headline window: goodput tokens/s at p95-TTFT-under-
  SLO is measured here.
* ``fault``  — the chaos window: fault specs armed at entry, damage
  (sheds + SLO-violating finishes) accounted inside it.
* ``recovery`` — time-to-recover runs from the fault window's end until
  the burn rate is back under threshold.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

PHASE_KINDS = ("warmup", "ramp", "soak", "fault", "recovery")


@dataclasses.dataclass(frozen=True)
class Phase:
    name: str
    kind: str
    duration_s: float
    rate_rps: float
    process: str = "poisson"  # or "uniform" (deterministic metronome)

    def __post_init__(self):
        if self.kind not in PHASE_KINDS:
            raise ValueError(
                f"phase kind {self.kind!r} not in {PHASE_KINDS}"
            )
        if self.duration_s <= 0:
            raise ValueError("duration_s must be > 0")
        if self.rate_rps < 0:
            raise ValueError("rate_rps must be >= 0")
        if self.process not in ("poisson", "uniform"):
            raise ValueError("process must be 'poisson' or 'uniform'")


def phase_bounds(phases: Sequence[Phase]) -> list[tuple]:
    """``[(phase, start_s, end_s), ...]`` with cumulative boundaries."""
    out = []
    t = 0.0
    for p in phases:
        out.append((p, t, t + p.duration_s))
        t += p.duration_s
    return out


def total_duration_s(phases: Sequence[Phase]) -> float:
    return sum(p.duration_s for p in phases)


def standard_program(
    *,
    warmup_s: float = 2.0,
    warmup_rps: float = 2.0,
    ramp_rates: Sequence[float] = (4.0, 8.0, 16.0, 32.0),
    ramp_step_s: float = 2.0,
    soak_s: float = 4.0,
    soak_rps: float = 8.0,
    fault_s: float = 2.0,
    recovery_s: float = 4.0,
    process: str = "poisson",
) -> tuple:
    """The canonical five-act program. The fault and recovery phases
    keep offering the soak rate — a chaos window with no traffic would
    measure nothing, and recovery is only proven under load."""
    phases = [Phase("warmup", "warmup", warmup_s, warmup_rps, process)]
    for i, rate in enumerate(ramp_rates):
        phases.append(
            Phase(f"ramp-{i + 1}", "ramp", ramp_step_s, rate, process)
        )
    phases.append(Phase("soak", "soak", soak_s, soak_rps, process))
    if fault_s > 0:
        phases.append(Phase("fault", "fault", fault_s, soak_rps, process))
    if recovery_s > 0:
        phases.append(
            Phase("recovery", "recovery", recovery_s, soak_rps, process)
        )
    return tuple(phases)
