"""Replayable workload model for the soak harness.

The whole point of a load *model* (vs. a hand-written request list) is
that one seed pins everything: cohort prefixes, prompt bodies, output
budgets, adapter assignment, AND the arrival timestamps. Two calls to
:func:`build_trace` with the same ``(workload, phases, seed)`` return
bitwise-identical traces — the determinism contract the smoke test
asserts, and the property that makes a soak *replayable* (re-run the
exact traffic that breached, with a fix applied).

The shape mirrors production templated traffic:

* **cohorts** — ``num_cohorts`` templated prefixes (block-aligned system
  prompts); a ``cohort_fraction`` slice of requests opens with one, so a
  prefix-cache-enabled engine sees real chain reuse under load;
* **long tail** — prompt-body and output lengths are Pareto-tailed
  around a median (the 3/4-short / 1/4-long production mix the serving
  bench already uses, generalised to a continuous tail);
* **tenants** — an ``adapter_fraction`` slice carries one of
  ``adapters``' names, exercising registry residency and refcounts.

Arrivals are **open-loop**: inter-arrival gaps come from the arrival
process (Poisson ``exponential(1/rate)`` or deterministic ``1/rate``)
of the phase the clock is in, independent of completions. The harness
submits each request at its scheduled time no matter how far behind the
engine is — coordinated omission cannot flatter latency, it can only
show up as recorded arrival lag.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Optional, Sequence

import numpy as np

from .phases import Phase, phase_bounds


@dataclasses.dataclass(frozen=True)
class WorkloadConfig:
    """Knobs of the request-population model (see module docstring).

    ``max_total_tokens`` clamps ``len(prompt) + max_new_tokens`` so every
    generated request is admissible on the target engine (the scheduler
    rejects requests beyond ``(num_blocks - 1) * block_size``).
    """

    vocab_size: int = 256
    num_cohorts: int = 4
    prefix_tokens: int = 16           # templated cohort prefix length
    cohort_fraction: float = 0.5      # share of requests opening with one
    prompt_tokens_min: int = 2
    prompt_tokens_median: int = 6     # body length (excl. cohort prefix)
    prompt_tokens_max: int = 48
    output_tokens_min: int = 2
    output_tokens_median: int = 6
    output_tokens_max: int = 32
    tail_alpha: float = 2.0           # Pareto tail index (smaller = fatter)
    adapters: tuple = ()              # tenant names to mix in
    adapter_fraction: float = 0.0     # share of requests naming a tenant
    # long-prompt burst (PR 17): this share of requests carries a GIANT
    # body of ``long_prompt_tokens`` (default: prompt_tokens_max) drawn
    # deterministically instead of from the Pareto tail — the traffic
    # that makes unchunked prefill hold every short request's TTFT
    # hostage, and the A/B axis the chunked-prefill soak runs on
    long_prompt_fraction: float = 0.0
    long_prompt_tokens: Optional[int] = None
    max_total_tokens: Optional[int] = None

    def __post_init__(self):
        if self.vocab_size < 2:
            raise ValueError("vocab_size must be >= 2")
        if self.num_cohorts < 0 or self.prefix_tokens < 0:
            raise ValueError("num_cohorts/prefix_tokens must be >= 0")
        for frac in (self.cohort_fraction, self.adapter_fraction,
                     self.long_prompt_fraction):
            if not (0.0 <= frac <= 1.0):
                raise ValueError("fractions must be in [0, 1]")
        if (
            self.long_prompt_tokens is not None
            and self.long_prompt_tokens < self.prompt_tokens_min
        ):
            raise ValueError(
                "long_prompt_tokens must be >= prompt_tokens_min"
            )
        if self.adapter_fraction > 0 and not self.adapters:
            raise ValueError("adapter_fraction > 0 needs adapter names")
        if self.prompt_tokens_min < 1 or self.output_tokens_min < 1:
            raise ValueError("minimum lengths must be >= 1")
        if self.tail_alpha <= 0:
            raise ValueError("tail_alpha must be > 0")


@dataclasses.dataclass(frozen=True)
class SoakRequest:
    """One scheduled request of the trace (hashable, comparable — the
    determinism test compares whole traces with ``==``)."""

    index: int
    arrival_s: float       # scheduled arrival, relative to run start
    phase: str
    cohort: int            # -1 = no templated prefix
    prompt: tuple          # token ids
    max_new_tokens: int
    adapter: Optional[str] = None


def _tail_len(rng, lo: int, median: int, hi: int, alpha: float) -> int:
    """Pareto-tailed length: median-ish body, occasional near-``hi``
    outlier — the long-tail mix that makes run-to-completion batching
    (and any latency percentile) interesting."""
    draw = lo + (median - lo) * (1.0 + float(rng.pareto(alpha)))
    return int(min(hi, max(lo, round(draw))))


def build_trace(
    workload: WorkloadConfig,
    phases: Sequence[Phase],
    seed: int = 0,
) -> list[SoakRequest]:
    """The full request trace for one soak run, arrivals included.

    One ``default_rng(seed)`` drives everything in a fixed draw order,
    so the trace is a pure function of ``(workload, phases, seed)``.
    """
    rng = np.random.default_rng(seed)
    cohorts = [
        tuple(
            int(t)
            for t in rng.integers(1, workload.vocab_size, workload.prefix_tokens)
        )
        for _ in range(workload.num_cohorts)
    ]
    trace: list[SoakRequest] = []
    t = 0.0
    for phase, start_s, end_s in phase_bounds(phases):
        t = max(t, start_s)
        if phase.rate_rps <= 0:
            t = end_s
            continue
        while True:
            if phase.process == "poisson":
                gap = float(rng.exponential(1.0 / phase.rate_rps))
            else:  # "uniform": deterministic metronome
                gap = 1.0 / phase.rate_rps
            if t + gap >= end_s:
                t = end_s
                break
            t += gap
            trace.append(_draw_request(rng, workload, cohorts, len(trace), t, phase))
    return trace


def _draw_request(rng, workload, cohorts, index, arrival_s, phase):
    cohort = -1
    prefix: tuple = ()
    if cohorts and float(rng.random()) < workload.cohort_fraction:
        cohort = int(rng.integers(len(cohorts)))
        prefix = cohorts[cohort]
    # burst giants draw their coin only when the knob is on, so traces
    # generated before the knob existed replay bit-identically
    if (
        workload.long_prompt_fraction > 0.0
        and float(rng.random()) < workload.long_prompt_fraction
    ):
        body_len = (
            workload.long_prompt_tokens
            if workload.long_prompt_tokens is not None
            else workload.prompt_tokens_max
        )
    else:
        body_len = _tail_len(
            rng, workload.prompt_tokens_min, workload.prompt_tokens_median,
            workload.prompt_tokens_max, workload.tail_alpha,
        )
    body = tuple(int(t) for t in rng.integers(1, workload.vocab_size, body_len))
    max_new = _tail_len(
        rng, workload.output_tokens_min, workload.output_tokens_median,
        workload.output_tokens_max, workload.tail_alpha,
    )
    adapter = None
    if workload.adapters and float(rng.random()) < workload.adapter_fraction:
        adapter = workload.adapters[int(rng.integers(len(workload.adapters)))]
    prompt = prefix + body
    if workload.max_total_tokens is not None:
        budget = workload.max_total_tokens
        if len(prompt) + max_new > budget:
            keep = max(1, budget - max_new)
            prompt = prompt[:keep]
            max_new = max(1, min(max_new, budget - len(prompt)))
    return SoakRequest(
        index=index,
        arrival_s=round(arrival_s, 9),
        phase=phase.name,
        cohort=cohort,
        prompt=prompt,
        max_new_tokens=max_new,
        adapter=adapter,
    )


def trace_fingerprint(trace: Sequence[SoakRequest]) -> str:
    """Order-sensitive sha256 over every field of every request — the
    value the soak report embeds so a re-run can prove (or disprove)
    that it replayed the identical traffic."""
    h = hashlib.sha256(b"accelerate_tpu.loadgen.trace\x00")
    for r in trace:
        h.update(
            repr((r.index, r.arrival_s, r.phase, r.cohort, r.prompt,
                  r.max_new_tokens, r.adapter)).encode()
        )
    return h.hexdigest()
