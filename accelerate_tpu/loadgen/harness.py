"""The soak harness: open-loop load against a live serving engine.

Closed-loop load generators wait for a response before sending the next
request, so a slow server *slows the load down* and the measured
latency distribution quietly drops exactly the requests that hurt —
coordinated omission. This harness is closed-loop only in the trivial
sense that one thread drives the engine; the *arrival process* is open
loop: every request has a scheduled arrival time drawn up front from
one seed (see :mod:`.workload`), and it is submitted at that time no
matter how far behind the engine is. When the submit loop itself falls
behind schedule (a wedged decode step, a long stall), the gap is
recorded as **arrival lag** per request — visible damage, not silently
stretched inter-arrival gaps.

Clocking: the harness owns the run clock and the engine must stamp from
the same one. Two modes:

* **virtual** (``step_dt_s`` set): a :class:`SoakClock` starts at 0 and
  advances ``step_dt_s`` per engine step — the whole run is
  deterministic in virtual time and takes however long the host needs
  (no sleeping). Build the engine with ``now=clock``.
* **wall** (``step_dt_s=None``): ``time.monotonic`` on both sides; the
  harness sleeps only when idle.

The run is a phase program (:mod:`.phases`); fault specs in the PR 9/11
grammar are armed when the clock enters the ``fault`` phase, with spec
steps shifted to be *relative to the fault window's first engine step*
(``stall_decode@0:secs=1`` = "stall for 1s at the window's start").
Everything observed lands in an atomically-written ``soak-report.json``
(:mod:`.report`) — including, via the ``finally`` path, the final SLO
snapshot and cumulative shed totals of a run that died mid-burn.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Optional

from ..test_utils.fault_injection import FAULT_ENV, FaultInjector, FaultSpec
from .chaos import ChaosAdapter
from .phases import Phase, phase_bounds, standard_program, total_duration_s
from .report import REPORT_VERSION, lag_histogram, write_report
from .workload import WorkloadConfig, build_trace, trace_fingerprint


class SoakClock:
    """The virtual run clock (monotonic, harness-advanced). Pass the
    SAME instance as the engine's ``now=`` so scheduler deadlines, SLO
    windows and span stamps all live on soak time."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@dataclasses.dataclass
class SoakConfig:
    """One soak run: workload x phase program x clocking x chaos.

    ``step_dt_s``: virtual seconds per engine step (None = wall clock).
    ``step_cost``: virtual mode only — a callable taking the engine and
    returning THIS step's virtual duration, consulted after each engine
    step instead of the flat ``step_dt_s`` quantum. Use it to charge
    steps by the work they actually issued (e.g. a delta of the
    engine's ``prefill_bucket_tokens_total``), so compute serialization
    — a giant prefill stalling the whole batch for one long step — is
    visible on hosts whose wall clock is all dispatch overhead. Idle
    gaps still advance at the flat quantum.
    ``fault_specs``: ``ACCELERATE_TPU_FAULT_INJECT``-grammar string with
    steps relative to the fault-window entry step; empty string reads
    the env var (and stays inert if that is unset too).
    ``slo``: an :class:`~accelerate_tpu.serving.SLOConfig` (or existing
    tracker) attached for the run; None leaves the engine's posture
    untouched. ``report_path``: where soak-report.json lands (None
    skips the file; the report dict is still returned).
    """

    workload: WorkloadConfig = dataclasses.field(
        default_factory=WorkloadConfig
    )
    phases: tuple = dataclasses.field(default_factory=standard_program)
    seed: int = 0
    step_dt_s: Optional[float] = 0.01
    step_cost: Optional[Callable] = None
    slo: object = None
    gauge_interval: int = 4
    fault_specs: str = ""
    report_path: Optional[str] = None
    drain_grace_s: float = 60.0
    recovery_poll_steps: int = 8
    max_engine_steps: int = 2_000_000
    label: str = "soak"


def _phase_acc(phase: Phase) -> dict:
    return {
        "phase": phase, "offered": 0, "finished": 0, "new_tokens": 0,
        "goodput_tokens": 0, "slo_violations": 0, "sheds": {},
        "ttfts": [], "itls": [], "lags": [], "breach_seen": False,
        "ran_s": 0.0,
    }


class SoakHarness:
    """Drives one engine through one :class:`SoakConfig`.

    The engine is duck-typed: ``add_request``/``step``/``has_work`` are
    required, everything else (``set_observability``, ``slo_tracker``,
    ``stats``, ``pool``, ``adapters``, ``trace_counts``) is optional —
    fake engines on a fake clock exercise the arrival process and the
    coordinated-omission guard without jax in sight.
    """

    def __init__(
        self,
        engine,
        config: Optional[SoakConfig] = None,
        clock: Optional[Callable[[], float]] = None,
        telemetry=None,
        on_phase_end: Optional[Callable[[dict], None]] = None,
    ):
        self.engine = engine
        self.config = config or SoakConfig()
        if clock is None:
            clock = (
                SoakClock() if self.config.step_dt_s is not None
                else time.monotonic
            )
        self.clock = clock
        self.telemetry = telemetry
        self.on_phase_end = on_phase_end
        self.report: Optional[dict] = None
        # run state
        self._steps = 0
        self._t0 = 0.0
        self._cur = 0  # current phase index
        self._accs: list[dict] = []
        self._interrupted = False
        self._stop_reason: Optional[str] = None
        self._warm_traces: Optional[dict] = None
        self._fault_window: Optional[tuple] = None  # (start_rel, end_rel)
        self._fault_armed = False
        self._recovering = False
        self._recovered_after_s: Optional[float] = None
        self._fault_sheds = 0
        self._fault_violations = 0
        self._fault_preempts = 0
        self._preempts_total = 0
        self.slo_tracker = None
        self.chaos: Optional[ChaosAdapter] = None

    # ------------------------------------------------------------------ #
    # run loop
    # ------------------------------------------------------------------ #
    def run(self) -> dict:
        cfg = self.config
        trace = build_trace(cfg.workload, cfg.phases, cfg.seed)
        self._trace_sha = trace_fingerprint(trace)
        bounds = phase_bounds(cfg.phases)
        self._accs = [_phase_acc(p) for p in cfg.phases]
        for p, start, end in bounds:
            if p.kind == "fault" and self._fault_window is None:
                self._fault_window = (start, end)
        raw = cfg.fault_specs or os.environ.get(FAULT_ENV, "")
        self._specs = [
            FaultSpec.parse(s) for s in raw.split(";") if s.strip()
        ]
        injector = FaultInjector([], rank=0, generation=0)
        self.chaos = ChaosAdapter(
            self.engine, injector, self.clock, restore=self._load_tenants
        )
        self._injector = injector
        if cfg.phases and cfg.phases[0].kind == "fault":
            self._arm_fault()
        self._attach_observability()
        self._load_tenants()
        total_s = total_duration_s(cfg.phases)
        self._t0 = self.clock()
        next_i = 0
        try:
            while True:
                now = self.clock()
                rel = now - self._t0
                self._cross_phase_boundaries(bounds, rel)
                # open-loop arrivals: everything scheduled up to now
                # goes in, stalled engine or not — lag is the record
                while (
                    next_i < len(trace)
                    and trace[next_i].arrival_s <= rel
                ):
                    req = trace[next_i]
                    lag = rel - req.arrival_s
                    acc = self._accs[min(self._cur, len(self._accs) - 1)]
                    acc["offered"] += 1
                    acc["lags"].append(lag)
                    self.engine.add_request(
                        list(req.prompt),
                        max_new_tokens=req.max_new_tokens,
                        adapter=req.adapter,
                        request_id=f"soak-{req.index}",
                    )
                    next_i += 1
                self.chaos.poll()
                drained = next_i >= len(trace) and not self.engine.has_work
                if rel >= total_s and drained:
                    break
                if rel >= total_s + cfg.drain_grace_s:
                    self._stop_reason = "drain_timeout"
                    break
                if self._steps >= cfg.max_engine_steps:
                    self._stop_reason = "step_backstop"
                    self._interrupted = True
                    break
                if self.chaos.stalled():
                    # decode wedged: time passes, arrivals keep landing
                    self._advance_idle(rel, trace, next_i, total_s)
                    continue
                if self.engine.has_work:
                    self._steps += 1
                    self._injector.maybe_fire(self._step_key())
                    if self.chaos.stalled():
                        continue  # the fault fired on THIS step
                    self.engine.step()
                    if cfg.step_dt_s is not None:
                        self.clock.advance(
                            cfg.step_cost(self.engine)
                            if cfg.step_cost is not None
                            else cfg.step_dt_s
                        )
                    self._poll_recovery()
                else:
                    self._advance_idle(rel, trace, next_i, total_s)
        except BaseException:
            self._interrupted = True
            raise
        finally:
            self.chaos.release()
            try:
                self.report = self._finalize(trace, next_i, bounds)
            except Exception:
                if not self._interrupted:
                    raise
        return self.report

    def _step_key(self) -> int:
        """Engine-step key the injector matches on: 0 for the first
        step taken inside the fault window, counting up from there."""
        if not self._fault_armed:
            return -1
        return self._steps - self._fault_entry_step - 1

    def _advance_idle(self, rel, trace, next_i, total_s) -> None:
        cfg = self.config
        if cfg.step_dt_s is None:
            time.sleep(0.001)
            return
        # virtual idle: jump straight to the next scheduled event
        targets = [rel + cfg.step_dt_s]
        if next_i < len(trace):
            targets.append(trace[next_i].arrival_s)
        nxt = max(rel + 1e-9, min(t for t in targets if t > rel))
        if self.chaos.stalled():
            # never jump past the stall's end in one go — pins/stall
            # release and damage accounting need the edge
            nxt = min(nxt, rel + cfg.step_dt_s)
        self.clock.advance(min(nxt, total_s + cfg.drain_grace_s) - rel)

    # ------------------------------------------------------------------ #
    # phase machinery
    # ------------------------------------------------------------------ #
    def _cross_phase_boundaries(self, bounds, rel: float) -> None:
        while self._cur < len(bounds) and rel >= bounds[self._cur][2]:
            phase, start, end = bounds[self._cur]
            self._close_phase(self._cur, end - start)
            self._cur += 1
            if self._cur < len(bounds):
                entering, _, _ = bounds[self._cur]
                if entering.kind == "fault" and not self._fault_armed:
                    self._arm_fault()
                if entering.kind == "recovery":
                    self.chaos.release()
                    self._recovering = True

    def _arm_fault(self) -> None:
        self._fault_armed = True
        self._fault_entry_step = self._steps
        self._injector.specs = list(self._specs)

    def _close_phase(self, idx: int, ran_s: float) -> None:
        acc = self._accs[idx]
        if acc["ran_s"]:
            return  # already closed (finalize path)
        acc["ran_s"] = ran_s
        phase = acc["phase"]
        if phase.kind == "warmup" and self._warm_traces is None:
            tc = getattr(self.engine, "trace_counts", None)
            self._warm_traces = dict(tc()) if tc else None
        if self.slo_tracker is not None:
            snap = self.slo_tracker.snapshot(self.clock())
            acc["breach_seen"] = acc["breach_seen"] or bool(snap["breach"])
        rec = self._phase_record(acc)
        self._emit_soak(rec)
        if self.on_phase_end is not None:
            self.on_phase_end(rec)

    def _phase_record(self, acc: dict) -> dict:
        from ..serving.telemetry import percentile

        phase: Phase = acc["phase"]
        ran = acc["ran_s"] or 1e-9
        ttfts = acc["ttfts"]
        return {
            "phase": phase.name,
            "kind": phase.kind,
            "duration_s": round(ran, 6),
            "offered": acc["offered"],
            "offered_rps": round(phase.rate_rps, 6),
            "achieved_rps": round(acc["finished"] / ran, 6),
            "finished": acc["finished"],
            "shed": sum(acc["sheds"].values()),
            "sheds_by_reason": dict(acc["sheds"]),
            "new_tokens": acc["new_tokens"],
            "goodput_tokens": acc["goodput_tokens"],
            "goodput_tokens_per_s": round(acc["goodput_tokens"] / ran, 6),
            "slo_violations": acc["slo_violations"],
            "p50_ttft_s": percentile(ttfts, 50) if ttfts else None,
            "p95_ttft_s": percentile(ttfts, 95) if ttfts else None,
            "p50_itl_s": (
                percentile(acc["itls"], 50) if acc["itls"] else None
            ),
            "p95_itl_s": (
                percentile(acc["itls"], 95) if acc["itls"] else None
            ),
            "arrival_lag_p95_s": (
                percentile(acc["lags"], 95) if acc["lags"] else 0.0
            ),
            "breached": bool(acc["breach_seen"]),
        }

    # ------------------------------------------------------------------ #
    # observability tee
    # ------------------------------------------------------------------ #
    def _attach_observability(self) -> None:
        cfg = self.config
        tee = _TelemetryTee(self, self.telemetry)
        setter = getattr(self.engine, "set_observability", None)
        if setter is not None:
            slo = cfg.slo
            if slo is None:
                slo = self._default_slo()
            setter(
                telemetry=tee, gauge_interval=cfg.gauge_interval,
                slo=slo, spans=True,
            )
            self.slo_tracker = self.engine.slo_tracker
        else:
            self.slo_tracker = getattr(self.engine, "slo_tracker", None)

    def _default_slo(self):
        """Objectives scaled to the run clock: in virtual time, "fast"
        means a small multiple of the per-step quantum."""
        from ..serving.slo import SLOConfig

        dt = self.config.step_dt_s or 0.01
        total = total_duration_s(self.config.phases)
        return SLOConfig(
            ttft_objective_s=50 * dt,
            e2e_objective_s=500 * dt,
            target=0.9,
            fast_window_s=max(10 * dt, total / 16.0),
            slow_window_s=max(20 * dt, total / 4.0),
            burn_threshold=1.0,
            interval_steps=8,
            min_requests=3,
        )

    def _ttft_objective(self) -> Optional[float]:
        if self.slo_tracker is not None:
            return self.slo_tracker.config.ttft_objective_s
        return None

    def _in_fault_window(self, rel: float) -> bool:
        return (
            self._fault_window is not None
            and self._fault_window[0] <= rel <= self._fault_window[1]
        )

    # tee callbacks ----------------------------------------------------- #
    def _on_serve(self, fields: dict) -> None:
        rel = self.clock() - self._t0
        acc = self._accs[min(self._cur, len(self._accs) - 1)]
        acc["finished"] += 1
        new_tokens = int(fields.get("new_tokens") or 0)
        acc["new_tokens"] += new_tokens
        ttft = fields.get("ttft_s")
        obj = self._ttft_objective()
        met = ttft is not None and (obj is None or ttft <= obj)
        if ttft is not None:
            acc["ttfts"].append(float(ttft))
        # inter-token latency: the decode-side experience a prefill
        # burst degrades on a colocated engine (the disagg headline)
        dtps = fields.get("decode_tokens_per_s")
        if dtps:
            acc["itls"].append(1.0 / float(dtps))
        if met:
            acc["goodput_tokens"] += new_tokens
        else:
            acc["slo_violations"] += 1
            if self._in_fault_window(rel):
                self._fault_violations += 1

    def _on_preempt(self, fields: dict) -> None:
        rel = self.clock() - self._t0
        self._preempts_total += 1
        if self._in_fault_window(rel):
            self._fault_preempts += 1

    def _on_shed(self, fields: dict) -> None:
        rel = self.clock() - self._t0
        acc = self._accs[min(self._cur, len(self._accs) - 1)]
        reason = fields.get("reason") or "unknown"
        acc["sheds"][reason] = acc["sheds"].get(reason, 0) + 1
        if self._in_fault_window(rel):
            self._fault_sheds += 1

    def _on_slo(self, fields: dict) -> None:
        acc = self._accs[min(self._cur, len(self._accs) - 1)]
        if fields.get("breach"):
            acc["breach_seen"] = True
        self._check_recovered(fields)

    def _poll_recovery(self) -> None:
        if (
            self._recovering
            and self.slo_tracker is not None
            and self._steps % max(1, self.config.recovery_poll_steps) == 0
        ):
            self._check_recovered(self.slo_tracker.snapshot(self.clock()))

    def _check_recovered(self, snap: dict) -> None:
        if not self._recovering or self.slo_tracker is None:
            return
        threshold = self.slo_tracker.config.burn_threshold
        if snap.get("max_burn_rate", 0.0) < threshold:
            fault_end = (
                self._fault_window[1] if self._fault_window else 0.0
            )
            self._recovered_after_s = max(
                0.0, (self.clock() - self._t0) - fault_end
            )
            self._recovering = False

    # ------------------------------------------------------------------ #
    # tenants (zero-weight identity adapters are valid residents)
    # ------------------------------------------------------------------ #
    def _load_tenants(self) -> None:
        names = self.config.workload.adapters
        registry = getattr(self.engine, "adapters", None)
        if not names or registry is None:
            return
        import numpy as np

        from ..adapters.lora import LoraConfig, target_shapes

        shapes = target_shapes(registry.model_config)
        layers = registry.model_config.num_layers
        cfg = LoraConfig(
            rank=1, alpha=1.0, target_modules=registry.target_modules
        )
        params = {
            t: {
                "lora_a": np.zeros((layers, shapes[t][0], 1), np.float32),
                "lora_b": np.zeros((layers, 1, shapes[t][1]), np.float32),
            }
            for t in registry.target_modules
        }
        for name in names:
            if not registry.resident(name):
                try:
                    registry.load(name, params, cfg)
                except RuntimeError:
                    break  # registry pinned full; requests will shed

    # ------------------------------------------------------------------ #
    # report
    # ------------------------------------------------------------------ #
    def _finalize(self, trace, submitted: int, bounds) -> dict:
        import time as _time

        cfg = self.config
        now = self.clock()
        rel = now - self._t0
        # close every phase that ran, including a partial current one
        for idx in range(len(bounds)):
            phase, start, end = bounds[idx]
            if rel > start and not self._accs[idx]["ran_s"]:
                self._close_phase(idx, min(end, max(rel, start + 1e-9)) - start)
        phase_records = [
            self._phase_record(acc) for acc in self._accs if acc["ran_s"]
        ]
        slo_final = (
            self.slo_tracker.snapshot(now)
            if self.slo_tracker is not None else None
        )
        stats = getattr(self.engine, "stats", None)
        shed_totals = (
            dict(stats.shed_counts)
            if stats is not None and hasattr(stats, "shed_counts") else {}
        )
        tc = getattr(self.engine, "trace_counts", None)
        traces = dict(tc()) if tc else None
        decode_retraces = None
        if traces is not None and self._warm_traces is not None:
            decode_retraces = (
                traces.get("decode", 0) - self._warm_traces.get("decode", 0)
            )
        all_lags = [l for acc in self._accs for l in acc["lags"]]
        headline = self._headline(phase_records)
        report = {
            "version": REPORT_VERSION,
            "kind": "soak_report",
            "label": cfg.label,
            "rank": int(os.environ.get("ACCELERATE_TPU_PROCESS_ID", "0")),
            "time_unix": _time.time(),
            "seed": cfg.seed,
            "clock": "virtual" if cfg.step_dt_s is not None else "wall",
            "step_dt_s": cfg.step_dt_s,
            "trace_sha256": self._trace_sha,
            "requests_planned": len(trace),
            "requests_submitted": submitted,
            "requests_finished": sum(a["finished"] for a in self._accs),
            "requests_shed": sum(
                sum(a["sheds"].values()) for a in self._accs
            ),
            "elapsed_s": round(rel, 6),
            "engine_steps": self._steps,
            "headline": headline,
            "phases": phase_records,
            "arrival_lag": lag_histogram(all_lags),
            "fault": self._fault_report(),
            "slo_final": slo_final,
            "shed_totals": shed_totals,
            "trace_counts": traces,
            "decode_retraces": decode_retraces,
            "interrupted": self._interrupted,
            "stop_reason": self._stop_reason,
        }
        # fleet soaks: the engine is a FleetRouter — surface its
        # placement/re-route ledger (policy, per-replica routed counts,
        # requeued vs lost) alongside the serving numbers
        rsum = getattr(self.engine, "router_summary", None)
        if rsum is not None:
            report["router"] = rsum()
        # disagg fleets: the KV hand-off ledger (plane totals, dedup
        # ratio, per-role replica gauges, stall/drop damage)
        tsum = getattr(self.engine, "transfer_summary", None)
        if tsum is not None:
            section = tsum()
            if section:
                report["transfer"] = section
        # sharding X-ray: the compiled-collective audit roll-up (ICI/DCN
        # bytes per program, violation verdicts) when audit_programs ran
        asum = getattr(self.engine, "audit_summary", None)
        if asum is not None:
            try:
                section = asum()
            except Exception:  # noqa: BLE001 — observability never fatal
                section = {}
            if section:
                report["audit"] = section
        self._emit_soak_final(report)
        if cfg.report_path:
            write_report(cfg.report_path, report)
        return report

    def _headline(self, phase_records) -> dict:
        soaks = [p for p in phase_records if p["kind"] == "soak"]
        ramps = [p for p in phase_records if p["kind"] == "ramp"]
        obj = self._ttft_objective()
        goodput = soaks[-1]["goodput_tokens_per_s"] if soaks else None
        p95 = soaks[-1]["p95_ttft_s"] if soaks else None
        p95_itl = soaks[-1].get("p95_itl_s") if soaks else None
        ok_rates = [p["offered_rps"] for p in ramps if not p["breached"]]
        breach_found = any(p["breached"] for p in ramps)
        return {
            "goodput_tokens_per_s_at_slo": goodput,
            "soak_p95_ttft_s": p95,
            "soak_p95_itl_s": p95_itl,
            "ttft_objective_s": obj,
            "slo_ok": (
                p95 is not None and obj is not None and p95 <= obj
                if soaks else None
            ),
            "capacity_rps_at_breach_point": (
                max(ok_rates) if ok_rates else 0.0
            ),
            "capacity_saturated": bool(ramps) and not breach_found,
        }

    def _fault_report(self) -> dict:
        window = self._fault_window
        return {
            "specs": [s.render() for s in self._specs],
            "window_start_s": window[0] if window else None,
            "window_end_s": window[1] if window else None,
            "events": list(self.chaos.events) if self.chaos else [],
            "sheds_in_window": self._fault_sheds,
            "slo_violations_in_window": self._fault_violations,
            # preemption turns would-be sheds into pauses: the soak's
            # acceptance check compares sheds_in_window against a
            # shed-only baseline and expects strictly fewer here
            "preempts_in_window": self._fault_preempts,
            "preempts_total": self._preempts_total,
            "recovery_s": (
                round(self._recovered_after_s, 6)
                if self._recovered_after_s is not None else None
            ),
            "recovered": self._recovered_after_s is not None,
        }

    # ------------------------------------------------------------------ #
    # kind="soak" telemetry records
    # ------------------------------------------------------------------ #
    def _emit_soak(self, rec: dict) -> None:
        fn = getattr(self.telemetry, "record_soak", None)
        if fn is None:
            return
        fn(
            label=self.config.label,
            phase=rec["phase"],
            phase_kind=rec["kind"],
            offered_rps=rec["offered_rps"],
            achieved_rps=rec["achieved_rps"],
            goodput_tokens_per_s=rec["goodput_tokens_per_s"],
            arrival_lag_p95_s=rec["arrival_lag_p95_s"],
            shed=rec["shed"],
            slo_violations=rec["slo_violations"],
            breach=rec["breached"],
        )

    def _emit_soak_final(self, report: dict) -> bool:
        fn = getattr(self.telemetry, "record_soak", None)
        if fn is None:
            return False
        head = report["headline"]
        fn(
            label=self.config.label,
            phase="final",
            phase_kind="final",
            goodput_tokens_per_s=head["goodput_tokens_per_s_at_slo"],
            capacity_rps_at_breach_point=head["capacity_rps_at_breach_point"],
            arrival_lag_p95_s=report["arrival_lag"]["p95_s"],
            recovery_s=report["fault"]["recovery_s"],
            sheds_in_fault_window=report["fault"]["sheds_in_window"],
            breach=bool(
                report["slo_final"] and report["slo_final"].get("breach")
            ),
            interrupted=report["interrupted"],
        )
        return True


class _TelemetryTee:
    """Sits where the engine expects a telemetry collector: the records
    the harness accounts on (serve/shed/slo) are teed into it, and
    EVERYTHING — including kinds the harness ignores — forwards to the
    wrapped inner collector when one is attached. The engine's ``_tele``
    dispatch is ``getattr``-guarded, so missing methods (no inner) are
    simply skipped."""

    def __init__(self, harness: SoakHarness, inner=None):
        self._harness = harness
        self._inner = inner

    def record_serve(self, **fields):
        self._harness._on_serve(fields)
        if self._inner is not None:
            fn = getattr(self._inner, "record_serve", None)
            if fn is not None:
                fn(**fields)

    def record_shed(self, **fields):
        self._harness._on_shed(fields)
        if self._inner is not None:
            fn = getattr(self._inner, "record_shed", None)
            if fn is not None:
                fn(**fields)

    def record_slo(self, **fields):
        self._harness._on_slo(fields)
        if self._inner is not None:
            fn = getattr(self._inner, "record_slo", None)
            if fn is not None:
                fn(**fields)

    def record_preempt(self, **fields):
        self._harness._on_preempt(fields)
        if self._inner is not None:
            fn = getattr(self._inner, "record_preempt", None)
            if fn is not None:
                fn(**fields)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)
