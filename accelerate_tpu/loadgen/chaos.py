"""Chaos adapter: the fault-injection grammar pointed at a live engine.

The PR 9/11 ``ACCELERATE_TPU_FAULT_INJECT`` grammar fires process-fatal
faults (kill/hang) for the elastic supervisor tests. A soak needs the
*serving* analogues — faults the engine is supposed to absorb, with the
damage measured instead of hoped about:

* ``stall_decode@step:secs=N`` — wedges the decode loop for N seconds.
  The harness keeps injecting arrivals on schedule while stalled (the
  open-loop contract), so the fault shows up as queue growth, arrival
  lag, TTFT misses and burn — never as a flattened arrival process.
* ``pool_pressure@step[:secs=N]`` — pins half the free KV blocks so
  admission sees a nearly-exhausted pool; released after ``secs`` (or
  at :meth:`ChaosAdapter.release`).
* ``adapter_churn@step`` — loads a capacity-full wave of throwaway
  adapters, evicting every unpinned resident tenant (in-flight tenants
  are refcount-protected and survive — that invariant is part of what
  the soak verifies). :meth:`release` invokes the ``restore`` callback
  so the harness can re-load its tenants and recovery is measurable.
* ``replica_kill@step:replica=N`` / ``replica_slow@step:replica=N:secs=S``
  — fleet faults: when the harness's "engine" is a
  :class:`~accelerate_tpu.router.FleetRouter`, kill marks replica N
  dead (unadmitted queue re-routed to survivors, seated requests lost
  — both counted in the router ledger) and slow freezes replica N's
  step loop for S seconds so placement must route around it. Against a
  single engine both are skipped with an event (``not_a_fleet``) —
  existing soaks can never be broken by a fleet spec.
* ``transfer_stall@step[:secs=S][:replica=N]`` /
  ``transfer_drop@step[:replica=N]`` — disaggregation faults: stall
  wedges KV hand-off delivery (the transfer ledger waits, seated
  decodes keep stepping), drop loses every in-flight manifest on the
  wire (damage bounded to a re-queue — each dropped chain's prompt
  re-prefills under its original id). ``replica=N`` filters to one
  SOURCE prefill replica; omitted = all sources. Against a
  non-disagg engine both skip with ``not_a_disagg_fleet``.

Handlers install on a :class:`FaultInjector` via ``install_handler`` —
spec *steps* are engine steps, and the soak harness shifts them to be
relative to the fault window's entry step (``stall_decode@0`` = "at the
window's first step").
"""

from __future__ import annotations

from typing import Callable, Optional

from ..test_utils.fault_injection import (
    SERVING_ACTIONS,
    FaultInjector,
    FaultSpec,
)

#: stall length when a spec omits ``secs=`` — a serving stall must
#: always end (the process-fatal "forever" semantics belong to hang)
DEFAULT_STALL_SECS = 1.0

_MAX_EVENTS = 64  # bounded event log: a soak runs for minutes


class ChaosAdapter:
    """Installs serving-fault handlers on ``injector`` and tracks the
    damage window. ``now`` is the harness clock (the same injectable
    clock the engine stamps from); ``restore`` re-loads the harness's
    tenant adapters after a churn."""

    def __init__(
        self,
        engine,
        injector: FaultInjector,
        now: Callable[[], float],
        restore: Optional[Callable[[], None]] = None,
    ):
        self.engine = engine
        self.injector = injector
        self._now = now
        self._restore = restore
        self._stall_until: float = float("-inf")
        self._pinned_blocks: list = []
        self._pin_release_at: Optional[float] = None
        self._churned = False
        self.events: list[dict] = []
        for action in SERVING_ACTIONS:
            injector.install_handler(action, getattr(self, "_on_" + action))

    # ------------------------------------------------------------------ #
    # the harness-facing surface
    # ------------------------------------------------------------------ #
    def stalled(self) -> bool:
        """True while the decode loop is wedged — the harness skips
        ``engine.step()`` but keeps submitting scheduled arrivals."""
        return self._now() < self._stall_until

    def poll(self) -> None:
        """Cheap per-iteration upkeep: release expired block pins."""
        if (
            self._pin_release_at is not None
            and self._now() >= self._pin_release_at
        ):
            self._release_pins()

    def release(self) -> None:
        """End the damage window: unpin blocks, restore churned
        tenants, clear any residual stall. Idempotent — the harness
        calls it at recovery entry AND from its ``finally``."""
        self._release_pins()
        self._stall_until = float("-inf")
        if self._churned and self._restore is not None:
            self._restore()
            self._churned = False

    def _event(self, action: str, **fields) -> None:
        if len(self.events) < _MAX_EVENTS:
            self.events.append(
                {"action": action, "time_s": self._now(), **fields}
            )

    # ------------------------------------------------------------------ #
    # handlers (called by FaultInjector._execute)
    # ------------------------------------------------------------------ #
    def _on_stall_decode(self, spec: FaultSpec) -> None:
        secs = spec.stall_secs or DEFAULT_STALL_SECS
        self._stall_until = self._now() + secs
        self._event("stall_decode", step=spec.step, secs=secs)

    def _on_pool_pressure(self, spec: FaultSpec) -> None:
        pool = getattr(self.engine, "pool", None)
        if pool is None:
            # a fleet router has no single pool; per-replica pressure
            # would need per-replica specs (not modeled yet)
            self._event("pool_pressure", step=spec.step, pinned=0,
                        skipped="no_pool")
            return
        n = pool.num_free // 2
        if n < 1:
            self._event("pool_pressure", step=spec.step, pinned=0,
                        skipped="no_free_blocks")
            return
        self._pinned_blocks.extend(pool.allocate(n))
        if spec.stall_secs:
            self._pin_release_at = self._now() + spec.stall_secs
        self._event("pool_pressure", step=spec.step, pinned=n,
                    secs=spec.stall_secs or None)

    def _release_pins(self) -> None:
        if self._pinned_blocks:
            self.engine.pool.free(self._pinned_blocks)
            self._event("pool_release", released=len(self._pinned_blocks))
            self._pinned_blocks = []
        self._pin_release_at = None

    def _on_adapter_churn(self, spec: FaultSpec) -> None:
        registry = getattr(self.engine, "adapters", None)
        if registry is None:
            self._event("adapter_churn", step=spec.step, loads=0,
                        skipped="no_adapter_registry")
            return
        import numpy as np

        from ..adapters.lora import LoraConfig, target_shapes

        shapes = target_shapes(registry.model_config)
        layers = registry.model_config.num_layers
        cfg = LoraConfig(
            rank=1, alpha=1.0, target_modules=registry.target_modules
        )
        params = {
            t: {
                "lora_a": np.zeros((layers, shapes[t][0], 1), np.float32),
                "lora_b": np.zeros((layers, 1, shapes[t][1]), np.float32),
            }
            for t in registry.target_modules
        }
        evict_before = registry.evict_total
        loads = 0
        chaff = []
        for i in range(registry.capacity + 1):
            name = f"chaos-churn-{spec.step}-{i}"
            try:
                registry.load(name, params, cfg)
            except RuntimeError:
                break  # every row pinned by in-flight requests: bounded
            chaff.append(name)
            loads += 1
        # clear our own chaff so rows are reusable; real tenants stay
        # evicted until the harness's restore callback re-loads them
        for name in chaff:
            if registry.resident(name):
                try:
                    registry.evict(name)
                except RuntimeError:
                    pass
        self._churned = bool(loads)
        self._event(
            "adapter_churn", step=spec.step, loads=loads,
            evictions=registry.evict_total - evict_before,
        )

    # -- fleet faults (engine is a FleetRouter) ------------------------- #
    def _fleet_replica(self, action: str, spec: FaultSpec):
        """Resolve ``spec.replica`` against the router, or record why
        the fault was skipped (single-engine soaks stay inert)."""
        replicas = getattr(self.engine, "replicas", None)
        if replicas is None or not hasattr(self.engine, "kill"):
            self._event(action, step=spec.step, skipped="not_a_fleet")
            return None
        idx = spec.replica if spec.replica is not None else 0
        if not 0 <= idx < len(replicas):
            self._event(action, step=spec.step, replica=idx,
                        skipped="replica_out_of_range")
            return None
        return replicas[idx]

    def _on_replica_kill(self, spec: FaultSpec) -> None:
        rep = self._fleet_replica("replica_kill", spec)
        if rep is None:
            return
        outcome = self.engine.kill(rep.name)
        self._event(
            "replica_kill", step=spec.step, replica=rep.name,
            requeued=outcome["requeued"], lost=outcome["lost"],
        )

    def _on_replica_slow(self, spec: FaultSpec) -> None:
        rep = self._fleet_replica("replica_slow", spec)
        if rep is None:
            return
        secs = spec.stall_secs or DEFAULT_STALL_SECS
        self.engine.slow(rep.name, secs)
        self._event(
            "replica_slow", step=spec.step, replica=rep.name, secs=secs
        )

    # -- transfer faults (engine is a disagg FleetRouter) ---------------- #
    def _transfer_src(self, action: str, spec: FaultSpec):
        """Resolve the optional ``replica=`` source filter for a
        transfer fault: None targets ALL in-flight hand-offs. Returns
        ``(ok, name)`` — a non-disagg engine skips with an event, like
        the fleet faults on a single engine."""
        if not hasattr(self.engine, "stall_transfers"):
            self._event(action, step=spec.step, skipped="not_a_disagg_fleet")
            return False, None
        if spec.replica is None:
            return True, None
        replicas = getattr(self.engine, "replicas", None) or []
        if not 0 <= spec.replica < len(replicas):
            self._event(action, step=spec.step, replica=spec.replica,
                        skipped="replica_out_of_range")
            return False, None
        return True, replicas[spec.replica].name

    def _on_transfer_stall(self, spec: FaultSpec) -> None:
        ok, name = self._transfer_src("transfer_stall", spec)
        if not ok:
            return
        secs = spec.stall_secs or DEFAULT_STALL_SECS
        self.engine.stall_transfers(secs, replica=name)
        self._event(
            "transfer_stall", step=spec.step, replica=name, secs=secs
        )

    def _on_transfer_drop(self, spec: FaultSpec) -> None:
        ok, name = self._transfer_src("transfer_drop", spec)
        if not ok:
            return
        outcome = self.engine.drop_transfers(replica=name)
        self._event(
            "transfer_drop", step=spec.step, replica=name,
            dropped=outcome["dropped"],
        )
