"""Placement policies: pure functions of candidate snapshots.

A policy never touches replicas — the router builds a candidate list
(live, non-draining replicas with their cached
:class:`~accelerate_tpu.router.replica.ReplicaSnapshot` and, when the
policy wants it, the request's cached-chain overlap) and the policy
picks one. Keeping the policies pure makes them individually testable
on fake snapshots and individually benchmarkable on the same trace
(the ``fleet_soak`` bench's three arms).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

from .replica import ReplicaSnapshot


@dataclasses.dataclass
class Candidate:
    """One routable replica as the policy sees it."""

    name: str
    order: int                 # registration order (the RR/tie-break axis)
    snapshot: ReplicaSnapshot
    #: prompt tokens already cached on this replica (0 when the policy
    #: did not ask for overlap, or nothing matched)
    overlap_tokens: int = 0


def load_score(snap: ReplicaSnapshot) -> float:
    """Scalar load used for least-loaded ordering and the affinity
    penalty: queued requests dominate (each is a whole request of
    unstarted work), busy seats count one each, and pool utilization
    breaks ties between equally-seated replicas (a fuller pool is
    closer to admission-blocking)."""
    return (
        float(snap.queue_depth)
        + float(snap.slots_active)
        + float(snap.pool_utilization)
    )


class RoundRobinPolicy:
    """The baseline: cycle through candidates in registration order,
    ignoring load and cache state entirely."""

    name = "round_robin"
    needs_overlap = False

    def __init__(self):
        self._next = 0

    def choose(self, candidates: Sequence[Candidate]) -> Candidate:
        # pick the first candidate at/after the cursor in registration
        # order; dead/draining replicas are already filtered out, so the
        # cursor just skips their order slots
        pick = min(
            candidates,
            key=lambda c: ((c.order - self._next) % _span(candidates), c.order),
        )
        self._next = pick.order + 1
        return pick


def _span(candidates: Sequence[Candidate]) -> int:
    return max(c.order for c in candidates) + 1


class LeastLoadedPolicy:
    """Route to the replica with the lowest :func:`load_score`;
    registration order breaks exact ties (deterministic placement for
    deterministic tests)."""

    name = "least_loaded"
    needs_overlap = False

    def choose(self, candidates: Sequence[Candidate]) -> Candidate:
        return min(
            candidates, key=lambda c: (load_score(c.snapshot), c.order)
        )


class PrefixAffinityPolicy:
    """Route on ``overlap_tokens − load_penalty × load_score``.

    ``overlap_tokens`` is the request's longest cached chain prefix on
    the candidate (computed host-side by the router from the replica's
    published key digest — block-granular, tenant-scoped). The penalty
    converts load into token units: ``load_penalty`` is "how many
    cached prefix tokens one unit of load is worth", so a replica with
    a deep queue must offer a proportionally longer warm prefix to win.
    With no overlap anywhere this degrades to exactly least-loaded —
    cold traffic spreads, templated cohorts concentrate.
    """

    name = "prefix_affinity"
    needs_overlap = True

    def __init__(self, load_penalty: float = 8.0):
        if load_penalty < 0:
            raise ValueError("load_penalty must be >= 0")
        self.load_penalty = load_penalty

    def choose(self, candidates: Sequence[Candidate]) -> Candidate:
        return max(
            candidates,
            key=lambda c: (
                c.overlap_tokens - self.load_penalty * load_score(c.snapshot),
                -load_score(c.snapshot),
                -c.order,
            ),
        )


_POLICIES = {
    "round_robin": RoundRobinPolicy,
    "least_loaded": LeastLoadedPolicy,
    "prefix_affinity": PrefixAffinityPolicy,
}


def make_policy(policy, load_penalty: Optional[float] = None):
    """Resolve a policy name (or pass an instance through). The string
    form is what the bench/CLI use; ``load_penalty`` only applies to
    ``prefix_affinity``."""
    if not isinstance(policy, str):
        return policy
    try:
        cls = _POLICIES[policy]
    except KeyError:
        raise ValueError(
            f"unknown routing policy {policy!r}; "
            f"want one of {sorted(_POLICIES)}"
        ) from None
    if cls is PrefixAffinityPolicy and load_penalty is not None:
        return cls(load_penalty=load_penalty)
    return cls()
