"""Replica handles: the router's view of one serving engine.

Two transports, one duck-typed surface:

* :class:`InProcessReplica` wraps a live ``ServingEngine`` object —
  CPU tests and the virtual-clock soak drive a whole fleet in one
  process, gauges read directly off the scheduler/pool (no HTTP, no
  serialization);
* :class:`HTTPReplica` is the metrics-plane client for real
  deployments: it scrapes ``/debug/state`` for gauges, ``/healthz``
  for liveness/draining, and ``/debug/prefix`` for the cached-chain
  digest. It is a PLACEMENT client only — submission goes through
  whatever ingress the deployment already has; the router's
  :meth:`~accelerate_tpu.router.FleetRouter.select` returns the chosen
  replica's name for the caller to dispatch on.

Every fetch can fail (replica mid-restart, scrape racing a drain); the
ROUTER owns staleness policy — handles just raise, and the router
degrades to the last cached snapshot instead of wedging admission.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Optional

from ..logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass
class ReplicaSnapshot:
    """One replica's load posture at ``taken_at`` (router clock). The
    four gauges every placement policy consumes, nothing more — a
    snapshot must stay cheap to fetch, serialize and cache."""

    queue_depth: int = 0
    slots_active: int = 0
    slot_occupancy: float = 0.0
    pool_utilization: float = 0.0
    tokens_in_flight: int = 0
    taken_at: float = 0.0
    #: True when this is a cached snapshot served after a failed refresh
    stale: bool = False

    @classmethod
    def from_gauges(cls, gauges: dict, taken_at: float) -> "ReplicaSnapshot":
        return cls(
            queue_depth=int(gauges.get("queue_depth") or 0),
            slots_active=int(gauges.get("slots_active") or 0),
            slot_occupancy=float(gauges.get("slot_occupancy") or 0.0),
            pool_utilization=float(gauges.get("pool_utilization") or 0.0),
            tokens_in_flight=int(gauges.get("tokens_in_flight") or 0),
            taken_at=taken_at,
        )


class InProcessReplica:
    """A ``ServingEngine`` held in this process. The engine is
    duck-typed exactly like the soak harness's: ``add_request`` /
    ``step`` / ``has_work`` required, everything else getattr-guarded —
    the fake engines the router unit tests run on a fake clock need no
    jax."""

    def __init__(self, name: str, engine: Any):
        self.name = name
        self.engine = engine
        self._dead = False

    # -- lifecycle ----------------------------------------------------- #
    @property
    def alive(self) -> bool:
        return not self._dead

    def mark_dead(self) -> None:
        """A ``replica_kill`` landed: the handle stays registered (its
        trace counts and stats still merge into fleet totals) but takes
        no traffic and no steps."""
        self._dead = True

    def health(self) -> dict:
        if self._dead:
            return {"ok": False, "state": "dead"}
        fn = getattr(self.engine, "health", None)
        if fn is not None:
            return dict(fn())
        return {"ok": True, "state": "serving"}

    @property
    def draining(self) -> bool:
        return bool(getattr(self.engine, "draining", False))

    def drain(self) -> list:
        """Stop this replica's admission and harvest its unadmitted
        queue (the router re-routes the harvest). In-flight seats keep
        decoding to completion — rotation without shedding."""
        fn = getattr(self.engine, "drain", None)
        return list(fn()) if fn is not None else []

    # -- serving surface ----------------------------------------------- #
    def add_request(self, prompt, **kwargs) -> str:
        return self.engine.add_request(prompt, **kwargs)

    def step(self):
        return self.engine.step()

    @property
    def has_work(self) -> bool:
        return bool(self.engine.has_work) and not self._dead

    def result(self, request_id: str):
        fn = getattr(self.engine, "result", None)
        return fn(request_id) if fn is not None else None

    def shed_reason(self, request_id: str):
        fn = getattr(self.engine, "shed_reason", None)
        return fn(request_id) if fn is not None else None

    # -- placement inputs ---------------------------------------------- #
    def fetch_snapshot(self, now: float) -> ReplicaSnapshot:
        gauges_fn = getattr(self.engine, "_gauge_fields", None)
        if gauges_fn is None:
            return ReplicaSnapshot(taken_at=now)
        return ReplicaSnapshot.from_gauges(gauges_fn(), now)

    def fetch_digest(self, max_entries: int) -> dict:
        fn = getattr(self.engine, "prefix_digest", None)
        if fn is None:
            return {"entries": [], "block_size": 0, "fingerprint": ""}
        return fn(max_entries)

    def queued_requests(self) -> list:
        """The unadmitted queue entries (``Request`` objects) — what a
        kill-time ejection can still save. Seated requests' KV lives on
        the dead device; they are LOST, and counted as such.

        Disaggregated roles widen the harvest: manifests still parked
        in the transfer outbox/inbox never reached a decode seat, and a
        :class:`~accelerate_tpu.serving.TransferManifest` duck-types as
        a ``Request`` for re-queueing — those prompts re-prefill on a
        survivor instead of dying with the replica."""
        out: list = []
        sched = getattr(self.engine, "scheduler", None)
        if sched is not None:
            out.extend(sched.queue)
            sched.queue.clear()
        for box in ("_outbox", "_inbox"):
            pending = getattr(self.engine, box, None)
            if pending:
                out.extend(pending)
                pending.clear()
        return out

    def seated_count(self) -> int:
        sched = getattr(self.engine, "scheduler", None)
        if sched is None:
            return 0
        n = sum(1 for s in sched.slots if s.busy)
        return n + len(getattr(self.engine, "_swapped_reqs", ()))


class HTTPReplica:
    """Metrics-plane client against a replica's scrape endpoint (the
    PR 8 ``MetricsHTTPExporter``). Stdlib ``urllib`` only; every call
    has a bounded timeout and raises on failure — staleness tolerance
    is the router's job, not this client's."""

    def __init__(self, name: str, base_url: str, timeout_s: float = 1.0):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.timeout_s = timeout_s
        self._dead = False
        self.digest_failures_total = 0
        self._digest_failing = False

    @property
    def alive(self) -> bool:
        return not self._dead

    def mark_dead(self) -> None:
        self._dead = True

    def _get_json(self, path: str) -> Any:
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(
                self.base_url + path, timeout=self.timeout_s
            ) as resp:
                return json.loads(resp.read().decode())
        except urllib.error.HTTPError as exc:
            # /healthz serves its JSON body on 503 too (draining/dead
            # posture is data, not an error)
            if path == "/healthz":
                try:
                    return json.loads(exc.read().decode())
                except Exception:
                    pass
            raise

    def health(self) -> dict:
        if self._dead:
            return {"ok": False, "state": "dead"}
        body = self._get_json("/healthz")
        if not isinstance(body, dict):
            return {"ok": bool(body), "state": "serving"}
        body.setdefault("state", "serving" if body.get("ok") else "down")
        return body

    @property
    def draining(self) -> bool:
        try:
            return self.health().get("state") == "draining"
        except Exception:
            return False

    def fetch_snapshot(self, now: float) -> ReplicaSnapshot:
        state = self._get_json("/debug/state")
        gauges = state.get("gauges") or {} if isinstance(state, dict) else {}
        return ReplicaSnapshot.from_gauges(gauges, now)

    def fetch_digest(self, max_entries: int) -> dict:
        """Scrape the cached-chain digest, degrading to an EMPTY digest
        on error/timeout instead of raising: a dead ``/debug/prefix``
        must cost this replica its affinity bonus for the tick, not
        fail placement for the whole fleet — the same
        staleness-tolerant posture the load snapshot already has. The
        degraded digest is marked ``stale`` and the failure logged
        (once per consecutive-failure run, not per tick)."""
        try:
            digest = self._get_json("/debug/prefix")
            self._digest_failing = False
            return digest
        except Exception as exc:
            self.digest_failures_total += 1
            if not self._digest_failing:
                self._digest_failing = True
                logger.warning(
                    "replica %s /debug/prefix unreachable (%s): serving "
                    "empty digest (no affinity) until the scrape recovers",
                    self.name, exc,
                )
            return {
                "entries": [], "block_size": 0, "fingerprint": "",
                "stale": True,
            }

    # -- placement-only client: no in-band submission ------------------- #
    def add_request(self, prompt, **kwargs) -> str:
        raise NotImplementedError(
            "HTTPReplica is a metrics-plane placement client; submit via "
            "the replica's own ingress (use FleetRouter.select to pick it)"
        )

    def step(self):
        return []

    @property
    def has_work(self) -> bool:
        return False

    def result(self, request_id: str):
        return None

    def shed_reason(self, request_id: str):
        return None

    def drain(self) -> list:
        return []

    def queued_requests(self) -> list:
        return []

    def seated_count(self) -> int:
        return 0

    def engine_attr(self, name: str, default=None):
        return default

    @property
    def engine(self) -> Optional[Any]:
        return None
