"""Fleet serving: a host-side router over N ``ServingEngine`` replicas.

One engine became feature-rich (continuous batching, prefix cache,
speculation, capacity levers); this package turns it into a FLEET. The
router is pure host policy over the observability plane the engines
already export — live gauges (PR 8), content-addressed prefix chain
keys (PR 13), ``/healthz`` — so placement needs no new device code and
no engine changes beyond ``drain()`` and the bounded prefix digest.

Three composable placement policies (:mod:`.policies`):

* **round-robin** — the baseline every other policy is benchmarked
  against;
* **least-loaded** — admission from live replica gauges (queue depth,
  seat occupancy, pool utilization, tokens in flight), read directly
  from in-process replicas or scraped over HTTP, with
  staleness-tolerant cached snapshots (a dead scrape degrades to the
  last known posture — it never wedges admission);
* **prefix-affinity** — replicas publish a bounded digest of their
  cached chain keys; the router computes cached-chain overlap per
  candidate host-side and routes on ``overlap_tokens − load_penalty ×
  load``, so templated cohorts pile onto the replica that already
  holds their prefix instead of duplicating it N ways (the
  Mooncake/DistServe placement insight).

Session affinity rides on top of any base policy: bounded per-key
state, graceful spill when the pinned replica drains or dies.

Prefill/decode disaggregation (PR 19) builds on the same machinery:
``FleetRouter(placement="disagg")`` splits the fleet by engine role —
prompts land on the least-loaded prefill replica (prefix affinity
still applies), and each finished KV chain hands off to the decode
replica with the deepest cached-chain overlap through an in-flight
transfer ledger (per-request ``transfer_ms`` + bytes, block dedup
against the destination's CACHED index, re-queue on a dead endpoint,
``transfer_stall``/``transfer_drop`` chaos arms).

Everything is default-OFF: nothing in the single-engine path imports or
consults this package, and a :class:`FleetRouter` only exists where
user code (or the ``fleet_soak`` bench) builds one. The router is
duck-type compatible with :class:`~accelerate_tpu.loadgen.SoakHarness`'s
engine surface (``add_request`` / ``step`` / ``has_work`` / ...), so
the PR 16 soak harness drives a fleet unchanged.
"""

from .policies import (
    LeastLoadedPolicy,
    PrefixAffinityPolicy,
    RoundRobinPolicy,
    load_score,
    make_policy,
)
from .replica import HTTPReplica, InProcessReplica, ReplicaSnapshot
from .router import FleetRouter

__all__ = [
    "FleetRouter",
    "HTTPReplica",
    "InProcessReplica",
    "LeastLoadedPolicy",
    "PrefixAffinityPolicy",
    "ReplicaSnapshot",
    "RoundRobinPolicy",
    "load_score",
    "make_policy",
]
