"""The fleet router: lifecycle, placement, and re-route accounting.

:class:`FleetRouter` fronts N replicas and presents the soak harness's
duck-typed engine surface (``add_request`` / ``step`` / ``has_work`` /
``result`` / ``trace_counts`` / ``stats`` / ``set_observability``), so
the PR 16 harness drives a fleet exactly as it drives one engine — one
router step steps every live replica once.

Placement is snapshot-driven and never blocks: gauges are cached per
replica with a max age, a failed refresh serves the last known
snapshot marked stale (``stale_snapshot_routes_total`` counts how
often), and a replica with NO snapshot yet routes on an optimistic
zero-load default. Admission can therefore mis-place under stale data
— that is the designed trade; it can never wedge.

Lifecycle:

* :meth:`register` / :meth:`remove` — add/drop a replica;
* :meth:`drain` — stop the replica's admission (its ``/healthz`` turns
  ``draining``), re-route its unadmitted queue to the rest of the
  fleet (counted in ``rerouted_total`` / ``requests_requeued``), let
  seated work finish — rotation without shedding;
* :meth:`kill` — a crash/`replica_kill` chaos action: the unadmitted
  queue is re-queued onto survivors, seated requests are LOST (their
  KV died with the replica) and counted in ``requests_lost``;
* health-driven ejection: every :meth:`step` polls ``health()`` and a
  replica that stops reporting ok is ejected through the same path as
  :meth:`kill`.

Session affinity (``session_affinity=True``) pins ``session_id`` →
replica in a bounded LRU map; a pinned replica that drains or dies
spills the session to the base policy (``session_spills_total``).
"""

from __future__ import annotations

import json
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Iterable, Optional

from ..serving.transfer import TransferPlane, _TransferRecord
from .policies import Candidate, load_score, make_policy
from .replica import ReplicaSnapshot


class _FleetStats:
    """The slice of ``ServeStats`` the soak harness reads off its
    engine, merged across replicas on demand."""

    def __init__(self, router: "FleetRouter"):
        self._router = router

    @property
    def shed_counts(self) -> dict:
        merged: dict[str, int] = {}
        for rep in self._router._all_replicas():
            stats = getattr(rep.engine, "stats", None)
            counts = getattr(stats, "shed_counts", None)
            if counts:
                for reason, n in counts.items():
                    merged[reason] = merged.get(reason, 0) + n
        return merged


class FleetRouter:
    """Host-side multi-replica router (see module docstring).

    ``policy``: ``"round_robin"`` | ``"least_loaded"`` |
    ``"prefix_affinity"`` or a policy instance. ``now`` must be the
    same injectable clock the replicas' engines stamp from (the soak
    harness's virtual clock in tests/benches, ``time.monotonic`` in
    production).
    """

    def __init__(
        self,
        replicas: Iterable = (),
        *,
        policy="least_loaded",
        load_penalty: Optional[float] = None,
        session_affinity: bool = False,
        max_sessions: int = 4096,
        snapshot_max_age_s: float = 0.0,
        digest_max_age_s: float = 0.05,
        digest_max_entries: int = 512,
        placement: str = "colocated",
        transfer_plane: Optional[TransferPlane] = None,
        now: Callable[[], float] = time.monotonic,
    ):
        if max_sessions < 1:
            raise ValueError("max_sessions must be >= 1")
        if placement not in ("colocated", "disagg"):
            raise ValueError(
                f'placement must be "colocated" or "disagg", '
                f"got {placement!r}"
            )
        self.policy = make_policy(policy, load_penalty=load_penalty)
        self.session_affinity = session_affinity
        self.max_sessions = max_sessions
        self.snapshot_max_age_s = snapshot_max_age_s
        self.digest_max_age_s = digest_max_age_s
        self.digest_max_entries = digest_max_entries
        self._now = now
        self._replicas: "OrderedDict[str, Any]" = OrderedDict()
        self._order: dict[str, int] = {}
        self._next_order = 0
        self._snaps: dict[str, ReplicaSnapshot] = {}
        self._digests: dict[str, dict] = {}  # name -> {keys,set, meta, at}
        self._sessions: "OrderedDict[str, str]" = OrderedDict()
        # bounded rid -> replica-name map for result()/shed_reason()
        self._placements: "OrderedDict[str, str]" = OrderedDict()
        self._max_placements = 65536
        self._slow_until: dict[str, float] = {}
        # accounting (the soak report's router section)
        self.routed_total = 0
        self.routed_by_replica: dict[str, int] = {}
        self.rerouted_total = 0
        self.requests_requeued = 0
        self.requests_lost = 0
        self.session_spills_total = 0
        self.stale_snapshot_routes_total = 0
        self.ejections_total = 0
        # PR 19 disaggregation: prompts route onto the prefill pool and
        # finished KV chains hand off to the decode pool through an
        # in-flight transfer ledger (see _pump_transfers)
        self.placement = placement
        if transfer_plane is None and placement == "disagg":
            transfer_plane = TransferPlane(now=now)
        self.transfer_plane = transfer_plane
        self._transfers: list[_TransferRecord] = []
        # bounded per-request transfer accounting: rid -> delivery facts
        self._transfer_log: "OrderedDict[str, dict]" = OrderedDict()
        self._max_transfer_log = 65536
        # retained hand-off timeline slices for export_trace: delivered
        # and dropped records leave _transfers (and _transfer_log keeps
        # only derived facts), so the fleet trace rides its own ring
        self._transfer_trace: deque = deque(maxlen=4096)
        self._transfer_stall_until = 0.0
        self._transfer_stall_src: Optional[str] = None
        self._transfer_stall_started: Optional[float] = None
        self.transfers_delivered_total = 0
        self.transfers_dropped_total = 0
        self.transfer_stalls_total = 0
        self.transfer_stall_recovery_s = 0.0
        self.stats = _FleetStats(self)
        for rep in replicas:
            self.register(rep)

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def register(self, replica) -> None:
        if replica.name in self._replicas:
            raise ValueError(f"replica {replica.name!r} already registered")
        self._replicas[replica.name] = replica
        self._order[replica.name] = self._next_order
        self._next_order += 1
        self.routed_by_replica.setdefault(replica.name, 0)

    @property
    def replicas(self) -> list:
        return list(self._replicas.values())

    def replica(self, name: str):
        return self._replicas[name]

    def drain(self, name: str) -> dict:
        """Graceful rotation: stop ``name``'s admission and re-route
        its unadmitted queue onto the rest of the fleet. Returns the
        re-route accounting for this drain."""
        rep = self._replicas[name]
        harvested = rep.drain()
        requeued = self._requeue(harvested, exclude=name)
        return {"replica": name, "requeued": requeued, "lost": 0}

    def kill(self, name: str) -> dict:
        """Ungraceful loss (crash / ``replica_kill`` chaos): re-queue
        what never reached a seat, count what died with the replica."""
        rep = self._replicas[name]
        if not rep.alive:
            return {"replica": name, "requeued": 0, "lost": 0}
        harvested = rep.queued_requests()
        seated_lost = rep.seated_count()
        rep.mark_dead()
        self.ejections_total += 1
        requeued = self._requeue(harvested, exclude=name)
        self.requests_lost += seated_lost
        # lost = seats that died with the replica + harvested entries
        # no survivor could take (those are already in requests_lost
        # via the _requeue failure path)
        lost = seated_lost + (len(harvested) - requeued)
        return {"replica": name, "requeued": requeued, "lost": lost}

    def remove(self, name: str) -> None:
        """Unregister a replica (drain it first for a graceful exit —
        remove does not harvest)."""
        self._replicas.pop(name)
        self._order.pop(name, None)
        self._snaps.pop(name, None)
        self._digests.pop(name, None)
        self._slow_until.pop(name, None)

    def slow(self, name: str, secs: float) -> None:
        """``replica_slow`` chaos: the replica takes no steps until
        ``now + secs`` — queued work piles up on it, and load-aware
        policies route around it."""
        self._slow_until[name] = self._now() + max(0.0, secs)

    def _eject_unhealthy(self) -> None:
        for name, rep in list(self._replicas.items()):
            if not rep.alive:
                continue
            try:
                ok = bool(rep.health().get("ok"))
            except Exception:
                ok = False
            if not ok:
                self.kill(name)

    def _requeue(self, requests, exclude: Optional[str] = None) -> int:
        n = 0
        for req in requests:
            try:
                self.add_request(
                    list(req.prompt),
                    max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature,
                    eos_token_id=req.eos_token_id,
                    request_id=req.request_id,
                    adapter=req.adapter,
                    priority=req.priority,
                    _exclude=exclude,
                )
                n += 1
            except RuntimeError:
                # nowhere left to put it: the request is lost, not
                # silently dropped
                self.requests_lost += 1
        self.rerouted_total += n
        self.requests_requeued += n
        return n

    # ------------------------------------------------------------------ #
    # placement
    # ------------------------------------------------------------------ #
    def _routable(self, exclude: Optional[str] = None) -> list:
        return [
            r for name, r in self._replicas.items()
            if r.alive and not r.draining and name != exclude
        ]

    @staticmethod
    def _role_of(rep) -> str:
        return getattr(getattr(rep, "engine", None), "role", None) \
            or "colocated"

    def _pool(self, role: str, exclude: Optional[str] = None) -> list:
        return [
            r for r in self._routable(exclude=exclude)
            if self._role_of(r) == role
        ]

    def _snapshot(self, rep) -> ReplicaSnapshot:
        now = self._now()
        cached = self._snaps.get(rep.name)
        if (
            cached is not None
            and not cached.stale
            and now - cached.taken_at < self.snapshot_max_age_s
        ):
            # strict <: the 0.0 default means "always refetch", which
            # is right for in-process replicas where a fetch is a dict
            # read; HTTP fleets set a real tolerance to bound scrapes
            return cached
        try:
            snap = rep.fetch_snapshot(now)
        except Exception:
            # staleness tolerance: a dead scrape must never wedge
            # admission — serve the last known posture (or an
            # optimistic zero-load default) and count it
            self.stale_snapshot_routes_total += 1
            snap = cached or ReplicaSnapshot(taken_at=now)
            snap.stale = True
        self._snaps[rep.name] = snap
        return snap

    def _digest(self, rep) -> Optional[dict]:
        now = self._now()
        cached = self._digests.get(rep.name)
        if cached is not None and now - cached["at"] <= self.digest_max_age_s:
            return cached
        try:
            raw = rep.fetch_digest(self.digest_max_entries)
        except Exception:
            return cached  # stale digest beats no digest
        if raw.get("stale") and cached is not None:
            # the handle degraded to an empty placeholder (scrape
            # error/timeout): last-known-good still beats it
            return cached
        entry = {
            "at": now,
            "keys": set(raw.get("entries") or ()),
            "block_size": int(raw.get("block_size") or 0),
            "fingerprint": raw.get("fingerprint") or "",
        }
        self._digests[rep.name] = entry
        return entry

    def _overlap_tokens(self, rep, prompt, adapter) -> int:
        digest = self._digest(rep)
        if not digest or not digest["keys"] or not digest["block_size"]:
            return 0
        from ..serving.block_pool import prefix_keys

        block_size = digest["block_size"]
        keys = prefix_keys(digest["fingerprint"], adapter, prompt, block_size)
        n = 0
        for k in keys:
            if k.hex() not in digest["keys"]:
                break
            n += 1
        # the admission tail always keeps >= 1 prompt token, so a
        # full-prompt chain is worth at most len(prompt) - 1 cached
        # tokens on the replica — mirror that here
        return min(n * block_size, max(len(prompt) - 1, 0))

    def select(
        self,
        prompt,
        adapter: Optional[str] = None,
        session_id: Optional[str] = None,
        _exclude: Optional[str] = None,
    ) -> str:
        """Pick a replica name for this request (placement only — the
        deployment's ingress does the submission when replicas are
        HTTP handles). Raises ``RuntimeError`` when no live,
        non-draining replica exists."""
        if self.placement == "disagg":
            # prompts only ever land on the prefill pool — decode
            # replicas take work exclusively through manifest hand-off
            routable = self._pool("prefill", exclude=_exclude)
            if not routable:
                raise RuntimeError(
                    "no live non-draining prefill replica to route to"
                )
        else:
            routable = self._routable(exclude=_exclude)
            if not routable:
                raise RuntimeError(
                    "no live non-draining replica to route to"
                )
        if self.session_affinity and session_id is not None:
            pinned = self._sessions.get(session_id)
            if pinned is not None:
                rep = self._replicas.get(pinned)
                if (
                    rep is not None and rep.alive and not rep.draining
                    and pinned != _exclude
                ):
                    self._sessions.move_to_end(session_id)
                    return pinned
                # pinned replica shed/drained/died: graceful spill
                self.session_spills_total += 1
        cands = []
        for rep in routable:
            snap = self._snapshot(rep)
            overlap = (
                self._overlap_tokens(rep, prompt, adapter)
                if getattr(self.policy, "needs_overlap", False) else 0
            )
            cands.append(
                Candidate(
                    name=rep.name, order=self._order[rep.name],
                    snapshot=snap, overlap_tokens=overlap,
                )
            )
        choice = self.policy.choose(cands).name
        if self.session_affinity and session_id is not None:
            self._sessions[session_id] = choice
            self._sessions.move_to_end(session_id)
            while len(self._sessions) > self.max_sessions:
                self._sessions.popitem(last=False)
        return choice

    # ------------------------------------------------------------------ #
    # the harness-facing engine surface
    # ------------------------------------------------------------------ #
    def add_request(
        self,
        prompt,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        eos_token_id: Optional[int] = None,
        request_id: str = "",
        adapter: Optional[str] = None,
        priority: int = 0,
        session_id: Optional[str] = None,
        _exclude: Optional[str] = None,
    ) -> str:
        name = self.select(
            prompt, adapter=adapter, session_id=session_id,
            _exclude=_exclude,
        )
        rid = self._replicas[name].add_request(
            prompt,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            eos_token_id=eos_token_id,
            request_id=request_id,
            adapter=adapter,
            priority=priority,
        )
        self.routed_total += 1
        self.routed_by_replica[name] = self.routed_by_replica.get(name, 0) + 1
        self._placements[rid] = name
        while len(self._placements) > self._max_placements:
            self._placements.popitem(last=False)
        return rid

    def step(self) -> list:
        """One fleet iteration: eject replicas whose health went bad
        (re-queueing what can be saved), then step every live replica
        that is not chaos-slowed. Returns the merged token events."""
        self._eject_unhealthy()
        now = self._now()
        events: list = []
        for name, rep in self._replicas.items():
            if not rep.alive:
                continue
            until = self._slow_until.get(name)
            if until is not None:
                if now < until:
                    continue
                del self._slow_until[name]
            if rep.has_work:
                out = rep.step()
                if out:
                    events.extend(out)
        if self.placement == "disagg":
            self._pump_transfers()
        return events

    @property
    def has_work(self) -> bool:
        if any(r.alive and r.has_work for r in self._replicas.values()):
            return True
        # in-flight hand-offs are work: a manifest in the ledger still
        # owes the fleet a seated decode (or a re-queue)
        return any(
            rec.state in ("pending", "stalled") for rec in self._transfers
        )

    # ------------------------------------------------------------------ #
    # KV hand-off (disagg placement)
    # ------------------------------------------------------------------ #
    def _pump_transfers(self) -> None:
        """Harvest finished prefills into the in-flight ledger, then
        deliver each manifest to the decode replica with the deepest
        cached-chain overlap (least-loaded tie-break). Delivery honors
        an active ``transfer_stall`` window; a dead/refusing endpoint
        just means the record stays pending for the next pump — and if
        the decode pool is gone entirely, the prompt re-queues."""
        now = self._now()
        for name, rep in self._replicas.items():
            if not rep.alive:
                continue
            pop = getattr(getattr(rep, "engine", None), "pop_manifests", None)
            if pop is None:
                continue
            for m in pop():
                m.src = name
                self._transfers.append(
                    _TransferRecord(manifest=m, started_at=now)
                )
        if not self._transfers:
            return
        decodes = self._pool("decode")
        done: list[_TransferRecord] = []
        for rec in self._transfers:
            if rec.state not in ("pending", "stalled"):
                done.append(rec)
                continue
            if self._stalled(rec, now):
                rec.state = "stalled"
                continue
            was_stalled = rec.state == "stalled"
            rec.state = "pending"
            if not decodes:
                # decode pool gone: the chain has no destination — give
                # the prompt back to the prefill pool instead of
                # stranding the request in the ledger forever
                self._drop_record(rec, now, reason="no_decode_replica")
                done.append(rec)
                continue
            if self._deliver(rec, decodes, now):
                if was_stalled and self._transfer_stall_started is not None:
                    self.transfer_stall_recovery_s = max(
                        self.transfer_stall_recovery_s,
                        now - self._transfer_stall_started,
                    )
                done.append(rec)
        for rec in done:
            self._transfers.remove(rec)

    def _stalled(self, rec: _TransferRecord, now: float) -> bool:
        if now >= self._transfer_stall_until:
            return False
        src = self._transfer_stall_src
        return src is None or rec.manifest.src == src

    def _deliver(
        self, rec: _TransferRecord, decodes: list, now: float
    ) -> bool:
        m = rec.manifest
        ranked = []
        for rep in decodes:
            digest = self._digest(rep)
            overlap = 0
            if digest and digest["keys"]:
                for k in m.keys:
                    if k.hex() not in digest["keys"]:
                        break
                    overlap += 1
            snap = self._snapshot(rep)
            ranked.append(
                (-overlap, load_score(snap), self._order[rep.name], rep)
            )
        ranked.sort(key=lambda t: t[:3])
        for _, _, _, rep in ranked:
            rec.attempts += 1
            try:
                res = rep.engine.acquire(m)
            except Exception:
                continue  # endpoint died mid-delivery: try the next
            rec.state = "delivered"
            rec.dst = rep.name
            rec.done_at = now
            rec.moved_blocks = int(res.get("moved_blocks", m.n_blocks))
            rec.deduped_blocks = int(res.get("reused_blocks", 0))
            rec.moved_bytes = int(
                res.get("moved_bytes", m.bytes_per_block() * rec.moved_blocks)
            )
            self.transfers_delivered_total += 1
            # the request now lives on the decode replica: result() and
            # shed_reason() must resolve there
            self._placements[m.request_id] = rep.name
            self._placements.move_to_end(m.request_id)
            ms = (now - rec.started_at) * 1000.0
            self._transfer_log[m.request_id] = {
                "src": m.src,
                "dst": rep.name,
                "transfer_ms": ms,
                "bytes": rec.moved_bytes,
                "blocks_moved": rec.moved_blocks,
                "blocks_deduped": rec.deduped_blocks,
                "attempts": rec.attempts,
            }
            while len(self._transfer_log) > self._max_transfer_log:
                self._transfer_log.popitem(last=False)
            self._transfer_trace.append({
                "request_id": m.request_id,
                "src": m.src,
                "dst": rep.name,
                "state": "delivered",
                "started_at": rec.started_at,
                "done_at": now,
                "bytes": rec.moved_bytes,
                "blocks": rec.moved_blocks,
            })
            if self.transfer_plane is not None:
                self.transfer_plane.record_delivery(
                    m,
                    src=m.src,
                    dst=rep.name,
                    moved_blocks=rec.moved_blocks,
                    deduped_blocks=rec.deduped_blocks,
                    moved_bytes=rec.moved_bytes,
                    ms=ms,
                )
            return True
        return False

    def _drop_record(
        self, rec: _TransferRecord, now: float, reason: str
    ) -> None:
        rec.state = "dropped"
        rec.done_at = now
        self.transfers_dropped_total += 1
        self._transfer_trace.append({
            "request_id": rec.manifest.request_id,
            "src": rec.manifest.src,
            "dst": None,
            "state": "dropped",
            "reason": reason,
            "started_at": rec.started_at,
            "done_at": now,
            "bytes": 0,
            "blocks": 0,
        })
        if self.transfer_plane is not None:
            self.transfer_plane.record_drop(rec.manifest, reason)
        # a TransferManifest duck-types as a Request for _requeue (same
        # prompt/knob/id attributes) — the prompt re-prefills from
        # scratch on the prefill pool, preserving its request_id
        self._requeue([rec.manifest])

    def stall_transfers(
        self, secs: float, replica: Optional[str] = None
    ) -> None:
        """``transfer_stall`` chaos: wedge hand-off delivery for
        ``secs`` (all sources, or just ``replica``'s outbound). Seated
        decodes are untouched — only the ledger waits."""
        now = self._now()
        self._transfer_stall_until = now + max(0.0, secs)
        self._transfer_stall_src = replica
        self._transfer_stall_started = now
        self.transfer_stalls_total += 1
        if self.transfer_plane is not None:
            self.transfer_plane.record_stall(max(0.0, secs), replica)

    def drop_transfers(self, replica: Optional[str] = None) -> dict:
        """``transfer_drop`` chaos: every in-flight hand-off (or just
        ``replica``'s outbound) is lost on the wire. Damage is bounded
        to a re-queue: each dropped chain's prompt goes back to the
        prefill pool under its original request id."""
        now = self._now()
        dropped = 0
        for rec in list(self._transfers):
            if rec.state not in ("pending", "stalled"):
                continue
            if replica is not None and rec.manifest.src != replica:
                continue
            self._drop_record(rec, now, reason="chaos_drop")
            self._transfers.remove(rec)
            dropped += 1
        return {"dropped": dropped}

    def transfer_record(self, request_id: str) -> Optional[dict]:
        """Per-request hand-off accounting (None = never transferred)."""
        return self._transfer_log.get(request_id)

    def transfer_summary(self) -> dict:
        """The soak report's ``transfer`` section: plane totals plus
        the fleet's per-role hand-off gauges and the ledger posture.
        Empty for a colocated fleet that never handed anything off —
        pre-disagg soak reports keep their exact shape."""
        if (
            self.placement != "disagg"
            and self.transfer_plane is None
            and not self._transfers
            and not self.transfers_delivered_total
        ):
            return {}
        per_replica = {}
        for name, rep in self._replicas.items():
            fn = getattr(getattr(rep, "engine", None), "transfer_gauges",
                         None)
            role = self._role_of(rep)
            if fn is None or role == "colocated":
                continue
            per_replica[name] = dict(fn(), role=role)
        return {
            "placement": self.placement,
            "plane": (
                self.transfer_plane.summary()
                if self.transfer_plane is not None else None
            ),
            "in_flight": sum(
                1 for rec in self._transfers
                if rec.state in ("pending", "stalled")
            ),
            "delivered_total": self.transfers_delivered_total,
            "dropped_total": self.transfers_dropped_total,
            "stalls_total": self.transfer_stalls_total,
            "stall_recovery_s": self.transfer_stall_recovery_s,
            "replicas": per_replica,
        }

    def export_trace(self, path: str) -> str:
        """Merge every replica's span log (plus the retained KV
        hand-off ledger slices) into ONE Chrome-trace/Perfetto JSON at
        ``path``: a named process row per replica and a ``kv-transfer``
        row, all referenced to the fleet's shared clock origin — a
        disaggregated request's prefill → transfer → decode hand-off
        reads left-to-right on a single timeline. Returns ``path``."""
        from ..serving.spans import spans_to_chrome_trace

        per_replica: list = []
        for name, rep in self._replicas.items():
            log = getattr(getattr(rep, "engine", None), "span_log", None)
            if log is None:
                continue
            spans = list(log.closed) + log.open_spans
            per_replica.append((name, spans))
        origin = min(
            [s.submit_t for _, spans in per_replica for s in spans]
            + [t["started_at"] for t in self._transfer_trace],
            default=0.0,
        )

        def us(t: float) -> float:
            return (t - origin) * 1e6

        events: list = []
        for pid, (name, spans) in enumerate(per_replica):
            events.append({
                "ph": "M", "name": "process_name", "pid": pid,
                "args": {"name": name},
            })
            payload = spans_to_chrome_trace(
                spans, process_index=pid, time_origin=origin,
            )
            events.extend(payload["traceEvents"])
        if self._transfer_trace:
            tpid = len(per_replica)
            events.append({
                "ph": "M", "name": "process_name", "pid": tpid,
                "args": {"name": "kv-transfer"},
            })
            for tid, t in enumerate(self._transfer_trace):
                events.append({
                    "ph": "M", "name": "thread_name", "pid": tpid,
                    "tid": tid, "args": {"name": t["request_id"]},
                })
                slice_name = (
                    f"transfer:{t['src']}->{t['dst']}"
                    if t["state"] == "delivered"
                    else f"transfer-drop:{t.get('reason')}"
                )
                events.append({
                    "ph": "X", "name": slice_name, "cat": "transfer",
                    "pid": tpid, "tid": tid,
                    "ts": us(t["started_at"]),
                    "dur": max(us(t["done_at"]) - us(t["started_at"]), 0.0),
                    "args": {
                        k: t.get(k)
                        for k in ("request_id", "src", "dst", "state",
                                  "bytes", "blocks")
                    },
                })
        with open(path, "w") as f:
            json.dump(
                {"traceEvents": events, "displayTimeUnit": "ms"}, f,
            )
        return path

    def result(self, request_id: str):
        name = self._placements.get(request_id)
        if name is not None and name in self._replicas:
            return self._replicas[name].result(request_id)
        for rep in self._replicas.values():
            out = rep.result(request_id)
            if out is not None:
                return out
        return None

    def shed_reason(self, request_id: str):
        name = self._placements.get(request_id)
        if name is not None and name in self._replicas:
            return self._replicas[name].shed_reason(request_id)
        for rep in self._replicas.values():
            out = rep.shed_reason(request_id)
            if out is not None:
                return out
        return None

    def trace_counts(self) -> dict:
        """Fleet-merged compiled-program counts. Dead replicas keep
        contributing their final counts — a kill must never make the
        zero-retrace delta go negative."""
        merged: dict[str, int] = {}
        for rep in self._all_replicas():
            fn = getattr(rep.engine, "trace_counts", None)
            if fn is None:
                continue
            for prog, n in fn().items():
                merged[prog] = merged.get(prog, 0) + n
        return merged

    def set_observability(
        self,
        *,
        telemetry: Any = None,
        gauge_interval: int = 1,
        slo: Any = None,
        spans: bool = True,
    ) -> None:
        """Attach ONE observability plane to the whole fleet: every
        replica engine tees into the same collector and the same
        :class:`~accelerate_tpu.serving.SloTracker` (fleet-level SLO
        attainment — a burn on any replica is a burn on the fleet)."""
        tracker = None
        if slo is not None:
            from ..serving.slo import SloTracker

            tracker = slo if isinstance(slo, SloTracker) else SloTracker(slo)
        self.slo_tracker = tracker
        for rep in self._all_replicas():
            setter = getattr(rep.engine, "set_observability", None)
            if setter is not None:
                setter(
                    telemetry=telemetry, gauge_interval=gauge_interval,
                    slo=tracker, spans=spans,
                )

    slo_tracker: Any = None

    def _all_replicas(self):
        return self._replicas.values()

    # ------------------------------------------------------------------ #
    # reporting
    # ------------------------------------------------------------------ #
    def router_summary(self) -> dict:
        """The soak report's ``router`` section: placement policy,
        per-replica posture, and the re-route ledger (what a kill or
        drain re-queued vs lost)."""
        reps = []
        for name, rep in self._replicas.items():
            try:
                state = rep.health().get("state", "serving")
            except Exception:
                state = "unreachable"
            reps.append({
                "name": name,
                "state": state,
                "routed": self.routed_by_replica.get(name, 0),
            })
        return {
            "policy": getattr(self.policy, "name", type(self.policy).__name__),
            "session_affinity": self.session_affinity,
            "replicas_total": len(self._replicas),
            "replicas_alive": sum(
                1 for r in self._replicas.values() if r.alive
            ),
            "replicas": reps,
            "routed_total": self.routed_total,
            "rerouted_total": self.rerouted_total,
            "requests_requeued": self.requests_requeued,
            "requests_lost": self.requests_lost,
            "ejections_total": self.ejections_total,
            "session_spills_total": self.session_spills_total,
            "sessions_tracked": len(self._sessions),
            "stale_snapshot_routes_total": self.stale_snapshot_routes_total,
        }
