"""The bench variant registry: what can run, in what order, at what cost.

Each :class:`Variant` carries the scheduling metadata the deadline
scheduler needs — ``priority`` (lower runs earlier; the headline
``dense`` is 0 and always first), ``group`` (variants sharing a model
config run in ONE child process, cutting the serial process-spawn +
recompile tax that ate r05), ``fast`` (membership in the CI ``--fast``
subset), and ``default_estimate_s`` (the cost guess used until a
measured estimate is persisted next to the XLA cache).

Within a group the registration order is the run order, chosen so an
expected-informative failure (``longseq_xla`` OOMing on 16G) is LAST and
cannot take down a measurable sibling.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

ENV_ITERS = "ACCELERATE_TPU_BENCH_ITERS"  # test/debug: stretch train loops


@dataclass(frozen=True)
class Variant:
    name: str
    kind: str  # "train" | "ckpt" | "accum" | "decode" | "decode_load" | "serve" | "serve_soak" | "fleet_soak" | "disagg_soak" | "overhead" | "lora"
    priority: int
    group: str
    args: tuple = field(default_factory=tuple)
    fast: bool = False
    headline: bool = False
    default_estimate_s: float = 600.0
    expected_oom: bool = False  # failure is itself the informative outcome


class VariantRegistry:
    def __init__(self, variants: list[Variant]):
        self._variants = {v.name: v for v in variants}
        self._order = [v.name for v in variants]

    def __contains__(self, name: str) -> bool:
        return name in self._variants

    @property
    def names(self) -> list[str]:
        return list(self._order)

    def get(self, name: str) -> Variant:
        return self._variants[name]

    @property
    def headline(self) -> Optional[str]:
        for name in self._order:
            if self._variants[name].headline:
                return name
        return None

    def select(self, names: Optional[list[str]] = None,
               fast: bool = False) -> "VariantRegistry":
        if names is not None:
            unknown = [n for n in names if n not in self._variants]
            if unknown:
                raise KeyError(
                    f"unknown bench variant(s) {unknown}; "
                    f"choose from {sorted(self._variants)}"
                )
            return VariantRegistry(
                [self._variants[n] for n in self._order if n in set(names)]
            )
        if fast:
            return VariantRegistry(
                [self._variants[n] for n in self._order
                 if self._variants[n].fast]
            )
        return self

    def groups(self) -> list[tuple[str, list[Variant]]]:
        """Process groups ordered by (best member priority, registration
        order); member order inside a group is registration order."""
        by_group: dict[str, list[Variant]] = {}
        first_seen: dict[str, int] = {}
        for i, name in enumerate(self._order):
            v = self._variants[name]
            by_group.setdefault(v.group, []).append(v)
            first_seen.setdefault(v.group, i)
        return sorted(
            by_group.items(),
            key=lambda kv: (
                min(v.priority for v in kv[1]), first_seen[kv[0]],
            ),
        )


def _iters_override(iters: int, kind: str) -> int:
    """Test/debug hook: ACCELERATE_TPU_BENCH_ITERS stretches the measured
    loop of train variants (the SIGKILL partial-recovery test needs a
    child that is reliably mid-measurement when killed)."""
    if kind != "train":
        return iters
    env = os.environ.get(ENV_ITERS)
    return int(env) if env else iters


def _variant(name, kind, priority, group, args, **kw) -> Variant:
    cfg, batch, seq, iters, warmup = args[:5]
    rest = args[5:]
    return Variant(
        name=name, kind=kind, priority=priority, group=group,
        args=(cfg, batch, seq, _iters_override(iters, kind), warmup, *rest),
        **kw,
    )


def build_registry(on_tpu: bool) -> VariantRegistry:
    from accelerate_tpu.models import TransformerConfig

    if not on_tpu:  # CI/CPU smoke: tiny shapes, same code paths
        # default estimates are deliberately tight (tiny configs compile
        # + run in seconds): a 120s --fast deadline must PLAN the whole
        # subset, not starve the tail on guesses
        tiny = TransformerConfig.tiny()
        return VariantRegistry([
            # accum registers FIRST inside the shared child: the round's
            # first-run variant eats every cold persistent-cache compile
            # (BENCH_r06: dense 61 misses / 10 hits vs 70-72 hits on every
            # later variant — the headline was paying the whole round's
            # cold-start bill as its own compile badput). dense keeps
            # priority 0 + headline, so the group still schedules first
            # and the consolidated block still leads with it; only the
            # in-child run order moves the cold misses onto accum.
            _variant("accum", "accum", 1, "dense",
                     (tiny, 4, 64, 6, 2), fast=True, default_estimate_s=12),
            # trailing True = fused A/B axis: _run measures an unfused
            # pass and a fused_kernels+fused_adamw pass in one variant
            # (step_time_s for both in extra; the estimate covers both)
            _variant("dense", "train", 0, "dense",
                     (tiny, 4, 128, 3, 1, "adamw", True),
                     fast=True, headline=True, default_estimate_s=30),
            _variant(
                "moe", "train", 2, "moe",
                (TransformerConfig.tiny(num_experts=4, num_experts_per_tok=2),
                 4, 128, 3, 1),
                default_estimate_s=20,
            ),
            # B=8 S=256 keeps CPU steps ~0.3s: big enough that the per-
            # step telemetry cost (fixed, host-side) measures well under
            # the 2% bar instead of being amplified by a tiny step
            _variant("overhead", "overhead", 2, "overhead",
                     (tiny, 8, 256, 20, 3), fast=True, default_estimate_s=30),
            # continuous-batched paged decode vs sequential fixed-batch
            # generate; NOT in --fast (it compiles every prefill bucket
            # plus two decode paths — too heavy for the 120s deadline).
            # args: (cfg, max_slots, block_size, n_requests, seed)
            # estimate covers the headline engine+baseline passes, the
            # observability-overhead A/B rounds (4 extra trace replays
            # on the warm engine), the prefix-caching cold/warm A/B on
            # the templated cohort (2 warmup + 2 timed passes), and the
            # speculation A/B (3 arms, each a fresh engine compiling its
            # own program set plus a warmup + timed drain)
            _variant("serve", "serve", 3, "serve", (tiny, 4, 8, 16, 0),
                     default_estimate_s=240),
            # soak & chaos: the loadgen harness drives the same tiny
            # serving config through warmup->ramp->soak->fault->recovery
            # on the wall clock (open-loop arrivals, stall_decode fault
            # mid-soak). Rates self-calibrate from a closed-loop probe,
            # so the ~10-25s program cost is host-independent; NOT fast
            # because the wall-clock phases cannot be shrunk below the
            # SLO windows. After the main program, six short A/B arms
            # (chunked prefill, preemption-vs-shed under pool_pressure,
            # fp-vs-int8 KV) each pay a fresh engine compile — the
            # estimate covers them. args: (cfg, max_slots, block_size,
            # target_requests, seed)
            _variant("serve_soak", "serve_soak", 4, "serve",
                     (tiny, 4, 8, 96, 0), default_estimate_s=240),
            # fleet serving: FOUR in-process replicas behind the router,
            # all on ONE virtual clock (step_dt_s), so the whole
            # multi-replica program is host-speed-independent. Three
            # policy arms (round_robin / least_loaded / prefix_affinity)
            # replay the SAME templated-cohort trace, plus a
            # replica_kill chaos arm measuring re-route damage and
            # time-to-recover. args: (cfg, max_slots_per_replica,
            # block_size, target_requests_per_arm, seed)
            _variant("fleet_soak", "fleet_soak", 5, "serve",
                     (tiny, 2, 8, 64, 0), default_estimate_s=180),
            # prefill/decode disaggregation A/B: 2 prefill + 2 decode
            # replicas hand off KV chains through the router's transfer
            # ledger vs 4 colocated replicas on the SAME bursty
            # long-prompt trace, plus a transfer_stall chaos arm.
            # args mirror fleet_soak's
            _variant("disagg_soak", "disagg_soak", 5, "serve",
                     (tiny, 2, 8, 48, 0), default_estimate_s=240),
            _variant("ckpt", "ckpt", 3, "ckpt", (tiny, 4, 64, 8, 2),
                     fast=True, default_estimate_s=15),
            # adapter-only vs full fine-tune economics + the multi-tenant
            # zero-retrace serving check; shares the dense group's tiny
            # config so it rides the same warm compile cache
            _variant("lora", "lora", 2, "lora", (tiny, 4, 64, 3, 1),
                     fast=True, default_estimate_s=40),
        ])

    import dataclasses

    dense = TransformerConfig(
        # ~916M params (Llama-8B width, depth cut to fit one 16G v5e chip
        # with fp32 master + AdamW state). remat="dots" saves matmul
        # outputs so backward recomputes only elementwise ops — measured
        # ~11% faster than remat="full" at this size.
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=3, num_heads=32, num_kv_heads=8, max_seq_len=1024,
        dtype="bfloat16", remat="dots",
    )
    moe = TransformerConfig(
        # Mixtral-family slice (BASELINE.md supporting config): 8 experts,
        # top-2, MIXTRAL-WIDTH experts (h=4096 — expert matmul width is
        # what drives MXU efficiency), depth cut to fit fp32 master +
        # AdamW on one 16G v5e chip. Round-4 single-chip sweep (20 iters,
        # B=16, S=1024, tokens/s/chip -> MFU):
        #   h=1024 L=4 capacity/dots   74.1k  0.311   (round-3 config)
        #   h=1024 L=4 ragged/dots_rg  74.5k  0.312
        #   h=2048 L=2 capacity/dots   53.5k  0.380
        #   h=4096 L=1 capacity/dots   58.7k  0.475
        #   h=4096 L=1 capacity/none   60.7k  0.490
        #   h=4096 L=1 ragged/dots_rg  62.9k  0.509
        #   h=4096 L=1 ragged/none     63.8k  0.516   <- this config
        # ragged (exact, no capacity padding or drops) beats capacity-1.25
        # at every width once remat stops recomputing ragged_dot; at L=1
        # no remat is needed at all.
        #
        # r5 structural bound for the residual vs the 0.60 bar (xplane
        # trace of 3 steps on v5e + ablations, all at this exact shape):
        #   per-step device time: 29.2% lm_head matmuls (49.4% of counted
        #   FLOPs — ~0.88 MFU-equiv), 26.7% expert ragged_dots (33.2% of
        #   FLOPs — ~0.64), 14.3% attention path (1.6% of FLOPs; shared
        #   with every other line), ~10.5% moe dispatch machinery
        #   (scatter-add combine ~5.5%, routed gathers ~2.1%, router +
        #   combine-weight math ~2.9%, the argsort itself ~0%), ~9%
        #   AdamW update + bf16-cast traffic on the FULL 8-expert stacks
        #   (all experts train, only K=2 compute — MFU's active-FLOPs
        #   accounting correctly charges this as overhead), 3.5% loss
        #   log_softmax over the f32 (16,1023,32000) logits.
        # Ablations: a dense MLP with IDENTICAL active matmul FLOPs
        # (f=7168, no routing) measures 81.8k tok/s = 0.661 MFU — the
        # no-dispatch skeleton ceiling; 0.518 = 0.661 x (200.2/254.3 ms).
        # Combine alternatives measured: inverse-permutation gather+sum
        # is 2.7% SLOWER than the scatter-add (261.3 vs 254.3 ms);
        # folding combine weights into the w_down ragged_dot input is
        # noise (+0.4%). Even with dispatch entirely free, the
        # all-expert AdamW/cast traffic (~23 ms) exceeds the 19.3 ms
        # gap to 0.60 — the shape's ceiling under AdamW is ~0.59, so
        # 0.52 stands as measured, bounded, and attributed rather than
        # unexplained.
        vocab_size=32000, hidden_size=4096, intermediate_size=3584,
        num_layers=1, num_heads=32, num_kv_heads=8, max_seq_len=1024,
        num_experts=8, num_experts_per_tok=2, moe_dispatch="ragged",
        moe_capacity_factor=1.25, dtype="bfloat16", remat=None,
    )
    longseq = TransformerConfig(
        # the long-context regime (VERDICT r2 #10: the S=8k single-chip
        # flash point): S^2 score tensors never materialize. Round-4
        # remat sweep at this shape (B=1, adamw, MFU):
        #   L=3 remat="full"       0.475   (round-3 config; 0.63 dense
        #       ceiling x 6/8 full-recompute bound = 0.47 — the number
        #       is exactly the remat tax, not kernel inefficiency)
        #   L=3 remat="save_attn"  0.474   (kernel fwd recompute is tiny)
        #   L=3 remat="dots"       OOM     (saves every matmul output)
        #   L=3 remat="save_mlp"   OOM by 1.0G (AdamW state crowds it out)
        #   L=2 remat="full"       0.473
        #   L=2 remat="save_mlp"   0.505   <- this config (keeps f-wide
        #       MLP activations; backward recomputes only the attn path)
        # Residual gap to 0.60 is structural at B=1/S=8192: ~11% of
        # counted FLOPs are attention (flash bwd runs below dense-matmul
        # MXU efficiency) plus the remaining attn-path recompute.
        # r5: the one lever the accounting pointed at — a fused
        # single-pass flash backward (5 matmuls/pair vs two-pass's 7) —
        # was built and MEASURED at this shape: 8,137 ms/step vs the
        # two-pass 310/312 ms (chip re-verified healthy between runs).
        # TPU Pallas's consecutive-output-visit rule forces the fused
        # form through a collapsing index map + full-sequence VMEM
        # scratch that defeats Mosaic pipelining (and 1024-blocks
        # overflow the 16 MiB scoped vmem). The two-pass backward is
        # the structural optimum here — see ops/flash_attention.py's
        # FUSED_BWD block for the full record.
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=2, num_heads=32, num_kv_heads=8, max_seq_len=8192,
        dtype="bfloat16", remat="save_mlp", attention_impl="flash",
    )
    decode = TransformerConfig(
        # GPT-J-6B-class decoder (~5.5B params, bf16-resident ~11G on the
        # 16G chip) for the reference's HEADLINE metric: big-model
        # generation s/token (benchmarks/README.md:31 — GPT-J-6B fp16 at
        # 0.05 s/token on 2x Titan RTX)
        vocab_size=32000, hidden_size=4096, intermediate_size=14336,
        num_layers=24, num_heads=32, num_kv_heads=8, max_seq_len=512,
        dtype="bfloat16",
    )
    small = TransformerConfig(
        # modest width for the accum/ckpt mechanism variants: their
        # metrics (dispatch count, blocked seconds) only need enough
        # compute that the measured overhead is unmistakable next to it
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_layers=2, num_heads=16, num_kv_heads=8, max_seq_len=512,
        dtype="bfloat16",
    )
    return VariantRegistry([
        # headline FIRST on the fresh chip (round 3 lost this line to a
        # late-session tunnel transient); the runner re-prints the
        # consolidated block with dense LAST for the parse-the-last-line
        # driver. accum shares the dense child: one spawn, one jax init.
        # trailing True = fused A/B axis (unfused + fused_kernels passes
        # in one variant — the estimate covers both compiles + loops)
        _variant("dense", "train", 0, "dense",
                 (dense, 8, 1024, 20, 3, "adamw", True),
                 fast=True, headline=True, default_estimate_s=900),
        _variant("accum", "accum", 1, "dense", (small, 4, 512, 8, 2),
                 fast=True, default_estimate_s=500),
        _variant("decode", "decode", 2, "decode", (decode, 1, 128, 64, 1),
                 default_estimate_s=600),  # B, prompt, new_tokens, reps
        # serving line on the same ~5.5B decode model (shares its child
        # process and resident weights-compile budget); args:
        # (cfg, max_slots, block_size, n_requests, seed)
        _variant("serve", "serve", 3, "decode", (decode, 4, 16, 8, 0),
                 default_estimate_s=2000),
        # soak & chaos on the ~5.5B decode model (same child process /
        # resident compile budget); args mirror serve's. The capacity
        # A/B arms (chunked/preempt/int8) add six engine compiles at
        # this size — the estimate covers them.
        _variant("serve_soak", "serve_soak", 4, "decode",
                 (decode, 4, 16, 64, 0), default_estimate_s=1200),
        # fleet serving on the ~5.5B decode model: 4 in-process replicas
        # per arm share the child's resident-weights budget — each arm
        # compiles its replicas' programs once (virtual clock hides the
        # pauses); 4 arms x 4 replicas drive the estimate
        _variant("fleet_soak", "fleet_soak", 5, "decode",
                 (decode, 2, 16, 48, 0), default_estimate_s=1600),
        # disaggregated prefill/decode on the ~5.5B decode model:
        # 3 arms x 4 replicas (2P+2D or 4 colocated) plus the bitwise
        # hand-off probe — the block transfers ride the PR 17 swap
        # programs already in each replica's compile budget
        _variant("disagg_soak", "disagg_soak", 5, "decode",
                 (decode, 2, 16, 32, 0), default_estimate_s=1600),
        _variant("moe", "train", 3, "moe", (moe, 16, 1024, 20, 3),
                 default_estimate_s=600),
        _variant("longseq", "train", 3, "longseq", (longseq, 1, 8192, 8, 2),
                 default_estimate_s=600),
        # S=4096 comparison pair, where the dense-attention path FITS 16G:
        # guarantees a non-null flash_speedup_vs_xla even when the S=8192
        # xla point OOMs/fails (it was null in rounds 2 and 3). Both run
        # under SGD: with AdamW the ~916M model carries ~11G of fp32
        # master+m+v state and the xla side's fp32 S^2 score tensors push
        # past 16G (measured: 18.26G at S=4096) — the flash/xla RATIO is
        # what this pair exists for, and it is optimizer-invariant as
        # long as both sides match. remat="full" on BOTH sides isolates
        # the kernel delta (measured ~1.5x; under "save_mlp" the saved
        # f-wide buffers perturb the flash side's fusion and the ratio
        # drops to 1.14x while measuring remat interplay, not the kernel).
        _variant(
            "longseq4k", "train", 4, "longseq",
            (dataclasses.replace(longseq, max_seq_len=4096, remat="full"),
             1, 4096, 8, 2, "sgd"),
            default_estimate_s=400,
        ),
        # telemetry+diagnostics ON-vs-OFF A/B: the harness proving itself
        # cheap every round (harness_overhead_pct rides the artifact)
        _variant("overhead", "overhead", 4, "overhead",
                 (TransformerConfig.tiny(), 8, 256, 30, 3),
                 fast=True, default_estimate_s=240),
        # the xla pair is its own group: the S=8192 point is EXPECTED to
        # OOM on 16G chips (itself the flash story), so it runs last in
        # the group where a crash cannot cost the measurable 4k point
        _variant(
            "longseq_xla4k", "train", 5, "longseq_xla",
            (dataclasses.replace(
                longseq, max_seq_len=4096, attention_impl="xla",
                remat="full"),
             1, 4096, 8, 2, "sgd"),
            default_estimate_s=400,
        ),
        _variant(
            "longseq_xla", "train", 6, "longseq_xla",
            (dataclasses.replace(longseq, attention_impl="xla"), 1, 8192, 4, 2),
            default_estimate_s=400, expected_oom=True,
        ),
        # fp8 projections (e4m3 fwd / e5m2 bwd, ops/fp8.py) on the dense
        # headline shape: tokens/s with the matmuls quantized vs the bf16
        # dense line above. TPU-only (CPU has no fp8 MXU paths worth
        # timing) and not in --fast.
        _variant("fp8", "train", 6, "fp8",
                 (dataclasses.replace(dense, fp8=True), 8, 1024, 20, 3),
                 default_estimate_s=600),
        # checkpoint-open -> device-resident for the decode model; its own
        # group so a slow/failed load can never cost the decode headline.
        # decode_load moves ~11 GiB across the ~0.03 GiB/s axon tunnel —
        # genuinely slow, not hung
        _variant("decode_load", "decode_load", 7, "decode_load",
                 (decode, 1, 0, 0, 0), default_estimate_s=1200),
        # LAST so its disk IO (a ~1 GiB carry written 4x per mode) can
        # never perturb the throughput headlines
        _variant("ckpt", "ckpt", 8, "ckpt", (small, 8, 512, 16, 3),
                 fast=True, default_estimate_s=600),
        # adapter-only vs full fine-tune on the small shape + the
        # multi-tenant zero-retrace serving check; its own group (the
        # serving phase's engine compiles must not warm-start a
        # throughput sibling's cache accounting)
        _variant("lora", "lora", 8, "lora", (small, 4, 512, 8, 2),
                 default_estimate_s=600),
    ])
