"""Per-variant measurement bodies.

Each ``_run_*`` measures one variant kind and :func:`result_line` wraps
it into the emitted JSON record ``{"metric", "value", "unit",
"vs_baseline", "extra"}``. For training lines ``vs_baseline`` = achieved
MFU / 0.60 (BASELINE.md north-star >= 60% MFU); for the decode line it
is 0.05 / (s/token), the speedup over the reference's GPT-J-6B number;
>= 1.0 means "meets/beats the reference target" in both cases.

Measured loops stream progress through a :class:`~.partial.PartialWriter`
(fsync'd after warmup and every N measured iters) so a budget-killed
child still yields a usable ``{"partial": true}`` number — precision
lost, measurement kept. The loops therefore sync at CHUNK boundaries
(``writer.chunk(iters)`` iters apart) instead of once at the end; the
chunk sync costs one pipeline drain per quarter-loop, noise next to a
step, and is what makes a partial value honest.
"""

from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .partial import PartialWriter

# bf16 peak FLOPs per chip by device kind (public cloud specs)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, so vs_baseline stays defined on CPU test runs
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "cpu")
    for name, flops in PEAK_FLOPS.items():
        if name.lower() in str(kind).lower():
            return flops
    return 197e12 if device.platform == "tpu" else 1e12


def _reset_state():
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


def _device_kind() -> str:
    return str(getattr(jax.devices()[0], "device_kind", "cpu"))


def _noop_writer(name: str) -> PartialWriter:
    return PartialWriter(None, name)


def _mfu(cfg, n_params: int, seq: int, tokens_per_sec_chip: float) -> float:
    # Honest model-FLOP accounting (remat recompute NOT counted — standard
    # MFU convention):
    #   * 6N counts only matmul-active params: the untied input embedding
    #     is a gather in forward (no MXU work), so it is excluded; lm_head
    #     is a real matmul and stays in (tied embeddings would count once).
    #   * attention: QK^T + PV are 4*S*(nh*hd) fwd flops/token/layer, 3x
    #     for fwd+bwd = 12*S*(nh*hd), halved for causal masking (the flash
    #     kernel really skips the masked blocks) -> 6*S*nh*hd per layer.
    matmul_params = n_params
    if not cfg.tie_embeddings:
        matmul_params -= cfg.vocab_size * cfg.hidden_size
    if cfg.num_experts > 0:
        # sparse MoE: each token computes only K of E experts — count the
        # ACTIVE expert params (capacity-padding overhead is real runtime
        # but not useful FLOPs, so it correctly depresses MFU)
        expert_params = (
            cfg.num_experts * 3 * cfg.hidden_size * cfg.intermediate_size
            * cfg.num_layers
        )
        matmul_params -= expert_params
        matmul_params += (
            expert_params * cfg.num_experts_per_tok // cfg.num_experts
        )
    attn_flops_per_token = 6 * seq * cfg.num_heads * cfg.head_dim * cfg.num_layers
    flops_per_token = 6 * matmul_params + attn_flops_per_token
    return tokens_per_sec_chip * flops_per_token / _peak_flops(jax.devices()[0])


def _run(cfg, batch_size: int, seq: int, iters: int, warmup: int,
         optimizer: str = "adamw", partial: Optional[PartialWriter] = None,
         fused: bool = False):
    """Train-step throughput for one config -> (tokens/s/chip, step_s, n_params).

    ``fused=True`` is the step-speed-kernel pass of the dense A/B axis:
    the same shapes with ``fused_kernels=True`` (Pallas prologue) and
    ``fused_adamw`` (Pallas epilogue). On CPU the kernels run in
    interpret mode — exact, slow — so the A/B number exists everywhere
    but only means throughput on TPU.
    """
    import dataclasses

    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import CausalLM, count_params

    partial = partial or _noop_writer("train")
    _reset_state()
    if fused:
        cfg = dataclasses.replace(cfg, fused_kernels=True)
    model = CausalLM(cfg)
    acc = Accelerator(mixed_precision="bf16")
    params = acc.prepare(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))["params"]
    )
    n_params = count_params(params)
    if fused and optimizer == "adamw":
        from accelerate_tpu.ops.fused import fused_adamw

        base_opt = fused_adamw(3e-4)
    else:
        base_opt = (
            optax.adamw(3e-4) if optimizer == "adamw" else optax.sgd(3e-4)
        )
    opt = acc.prepare(base_opt)
    carry = acc.init_carry(params, opt)
    step = acc.unified_step(CausalLM.loss_fn(model), max_grad_norm=1.0)

    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch_size, seq)),
        jnp.int32,
    )
    batch = {"input_ids": ids}

    # sync by fetching a scalar that depends on the whole step chain
    # (axon quirk: block_until_ready is unreliable/slow through the tunnel)
    for _ in range(warmup):
        carry, metrics = step(carry, batch)
    np.asarray(metrics["loss"])
    partial.update(phase="warmup_done", iters_measured=0)

    chunk = partial.chunk(iters)
    tokens_per_step = batch_size * seq / jax.device_count()
    measured = 0
    t0 = time.perf_counter()
    while measured < iters:
        n = min(chunk, iters - measured)
        for _ in range(n):
            carry, metrics = step(carry, batch)
        np.asarray(metrics["loss"])  # chunk boundary: honest partial value
        measured += n
        dt = time.perf_counter() - t0
        partial.update(
            phase="measuring", iters_measured=measured,
            metric="train_tokens_per_sec_per_chip",
            value=round(tokens_per_step * measured / dt, 1),
            unit="tokens/s/chip",
            extra={"step_time_s": round(dt / measured, 4),
                   "params": n_params, "device": _device_kind(),
                   "batch": batch_size, "seq": seq},
        )

    step_time = dt / iters
    tokens_per_sec_chip = tokens_per_step / step_time
    return tokens_per_sec_chip, step_time, n_params


def _run_ckpt(cfg, batch_size: int, seq: int, iters: int, warmup: int,
              partial: Optional[PartialWriter] = None):
    """Step-time perturbation of cadence checkpoints: sync vs async saves.

    Runs the SAME train loop twice (fresh state each time), saving every
    few steps through CheckpointManager — once synchronously, once through
    the async subsystem — and reports the train-loop-blocked seconds per
    save (the ``kind="checkpoint"`` telemetry field) plus the step-time
    spike a save adds on top of a quiet step. ``vs_baseline`` is
    sync_blocked / async_blocked: >= 1 means async hides the IO.
    """
    import shutil
    import tempfile

    import optax

    from accelerate_tpu import Accelerator, CheckpointManager, ProjectConfiguration
    from accelerate_tpu.models import CausalLM, count_params

    partial = partial or _noop_writer("ckpt")
    every_n = max(2, iters // 4)
    out: dict[str, dict] = {}
    n_params = 0
    for mode in ("sync", "async"):
        _reset_state()
        project_dir = tempfile.mkdtemp(prefix=f"bench_ckpt_{mode}_")
        try:
            model = CausalLM(cfg)
            acc = Accelerator(
                mixed_precision="bf16",
                project_config=ProjectConfiguration(
                    project_dir=project_dir,
                    automatic_checkpoint_naming=True,
                    total_limit=2,
                ),
                telemetry=True,
            )
            params = acc.prepare(
                model.init(
                    jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
                )["params"]
            )
            n_params = count_params(params)
            opt = acc.prepare(optax.adamw(3e-4))
            carry = acc.init_carry(params, opt)
            step = acc.unified_step(CausalLM.loss_fn(model))
            ids = jnp.asarray(
                np.random.default_rng(0).integers(
                    0, cfg.vocab_size, (batch_size, seq)
                ),
                jnp.int32,
            )
            batch = {"input_ids": ids}
            for _ in range(warmup):
                carry, metrics = step(carry, batch)
            np.asarray(metrics["loss"])
            partial.update(phase=f"{mode}_warmup_done", iters_measured=0)

            mgr = CheckpointManager(
                acc, every_n_steps=every_n, handle_signals=False,
                async_saves=(mode == "async"),
            )
            save_steps, quiet_steps = [], []
            for i in range(1, iters + 1):
                t0 = time.perf_counter()
                carry, metrics = step(carry, batch)
                np.asarray(metrics["loss"])  # step fully done before the save
                saved = mgr.step(carry)
                dt = time.perf_counter() - t0
                (save_steps if saved else quiet_steps).append(dt)
            mgr.wait()
            mgr.close()
            recs = [
                r for r in acc.telemetry.records
                if r.get("kind") == "checkpoint"
            ]
            out[mode] = {
                "saves": len(recs),
                "blocked_s": float(np.mean([r["blocked_s"] for r in recs])),
                "background_s": float(
                    np.mean([r["background_s"] for r in recs])
                ),
                "bytes_written": int(recs[-1]["bytes_written"]),
                "write_bandwidth_gib_s": round(
                    float(
                        np.mean([
                            r["write_bandwidth_bytes_per_s"] or 0.0
                            for r in recs
                        ])
                    ) / 2**30,
                    3,
                ),
                "save_step_s": float(np.mean(save_steps)),
                "quiet_step_s": float(np.mean(quiet_steps)),
                "save_step_overhead_s": float(
                    np.mean(save_steps) - np.mean(quiet_steps)
                ),
            }
            # a sync-only pass is already a publishable blocked-time
            # number; the async pass refines it into the ratio
            partial.update(
                phase=f"{mode}_done", iters_measured=iters,
                metric="ckpt_async_save_blocked_seconds",
                value=round(out[mode]["blocked_s"], 4), unit="s",
                extra={mode: {k: round(v, 4) if isinstance(v, float) else v
                              for k, v in out[mode].items()}},
            )
        finally:
            shutil.rmtree(project_dir, ignore_errors=True)

    sync_b, async_b = out["sync"]["blocked_s"], out["async"]["blocked_s"]
    return {
        "metric": "ckpt_async_save_blocked_seconds",
        "value": round(async_b, 4),
        "unit": "s",
        "vs_baseline": round(sync_b / async_b, 3) if async_b > 0 else None,
        "extra": {
            "sync": {k: round(v, 4) if isinstance(v, float) else v
                     for k, v in out["sync"].items()},
            "async": {k: round(v, 4) if isinstance(v, float) else v
                      for k, v in out["async"].items()},
            "every_n_steps": every_n,
            "params": n_params,
            "device": _device_kind(),
            "batch": batch_size, "seq": seq,
        },
    }


def _run_accum(cfg, batch_size: int, seq: int, iters: int, warmup: int,
               accum_steps: int = 8,
               partial: Optional[PartialWriter] = None):
    """Per-OPTIMIZER-step cost of gradient accumulation at K=accum_steps:
    the fused ``lax.scan`` path (one dispatch per optimizer step over a
    stacked ``[K, B, S]`` batch) vs the unfused per-microbatch
    ``lax.cond`` path (K dispatches). Both modes run the same model for
    the same number of optimizer steps; ``dispatches_per_opt_step`` is
    read back from the telemetry step records (the field exists so this
    win is visible in production sinks, not just here). ``vs_baseline``
    is unfused/fused per-opt-step wall time: >= 1 means fused wins.
    """
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.utils.dataclasses import GradientAccumulationPlugin

    partial = partial or _noop_writer("accum")
    K = accum_steps
    out: dict[str, dict] = {}
    n_params = 0
    for mode in ("unfused", "fused"):
        fused = mode == "fused"
        _reset_state()
        model = CausalLM(cfg)
        acc = Accelerator(
            mixed_precision="bf16",
            gradient_accumulation_plugin=GradientAccumulationPlugin(
                num_steps=K, fused=fused
            ),
            telemetry=True,
        )
        params = acc.prepare(
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))[
                "params"
            ]
        )
        n_params = count_params(params)
        opt = acc.prepare(optax.adamw(3e-4))
        carry = acc.init_carry(params, opt)
        step = acc.unified_step(CausalLM.loss_fn(model), max_grad_norm=1.0)
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch_size, seq)
        ).astype(np.int32)
        micro = {"input_ids": jnp.asarray(ids)}
        batch = (
            {"input_ids": jnp.asarray(np.stack([ids] * K))} if fused else micro
        )
        calls_per_opt_step = 1 if fused else K
        for _ in range(warmup * calls_per_opt_step):
            carry, metrics = step(carry, batch)
        np.asarray(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(iters * calls_per_opt_step):
            carry, metrics = step(carry, batch)
        np.asarray(metrics["loss"])
        dt = time.perf_counter() - t0
        recs = [
            r for r in acc.telemetry.records if r.get("kind") == "step"
        ]
        out[mode] = {
            "opt_step_s": dt / iters,
            "dispatches_per_opt_step": recs[-1]["dispatches_per_opt_step"],
            "microbatches_per_record": recs[-1]["microbatches"],
            "opt_steps_timed": iters,
        }
        partial.update(
            phase=f"{mode}_done", iters_measured=iters,
            metric="accum_fused_opt_step_seconds",
            value=round(dt / iters, 4), unit="s",
            extra={mode: {k: round(v, 4) if isinstance(v, float) else v
                          for k, v in out[mode].items()},
                   "accum_steps": K},
        )

    fused_s = out["fused"]["opt_step_s"]
    unfused_s = out["unfused"]["opt_step_s"]
    return {
        "metric": "accum_fused_opt_step_seconds",
        "value": round(fused_s, 4),
        "unit": "s",
        "vs_baseline": round(unfused_s / fused_s, 3) if fused_s > 0 else None,
        "extra": {
            "accum_steps": K,
            "fused": {k: round(v, 4) if isinstance(v, float) else v
                      for k, v in out["fused"].items()},
            "unfused": {k: round(v, 4) if isinstance(v, float) else v
                        for k, v in out["unfused"].items()},
            "params": n_params,
            "device": _device_kind(),
            "batch": batch_size, "seq": seq,
        },
    }


def _run_decode(cfg, batch_size: int, prompt_len: int, new_tokens: int,
                reps: int, partial: Optional[PartialWriter] = None):
    """Autoregressive generation benchmark -> (s/token, n_params).

    Params are random-initialized DIRECTLY in bf16 on device (a standard
    fp32 init of a ~5.5B model would not fit 16G); decode quality is
    irrelevant to throughput — the per-token cost is reading the resident
    weights once per step (memory-bound), which random weights measure
    exactly.

    Load time is measured by the separate ``decode_load`` helper variant
    (folded into this line's extra as ``load_s``) so a slow or failed
    load can never cost the decode headline.
    """
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.models.generation import make_generate_fn
    from accelerate_tpu.parallel.sharding import unbox_params

    partial = partial or _noop_writer("decode")
    _reset_state()
    model = CausalLM(cfg)
    abstract = unbox_params(
        jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )
        )
    )["params"]
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    keys = jax.random.split(jax.random.PRNGKey(0), len(leaves))

    @jax.jit
    def init_bf16():
        return jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.bfloat16)
            * (0.02 if l.ndim > 1 else 1.0)
            for k, l in zip(keys, leaves)
        ])

    params = init_bf16()
    n_params = count_params(params)
    gen = make_generate_fn(model, max_new_tokens=new_tokens)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch_size, prompt_len)
        ),
        jnp.int32,
    )
    out = gen(params, ids)
    np.asarray(out[:, -1])  # full sync (compile + warmup)
    partial.update(phase="warmup_done", iters_measured=0)
    t0 = time.perf_counter()
    for rep in range(1, reps + 1):
        out = gen(params, ids)
        np.asarray(out[:, -1])
        dt = time.perf_counter() - t0
        partial.update(
            phase="measuring", iters_measured=rep,
            metric="generate_seconds_per_token",
            value=round(dt / (rep * new_tokens), 4), unit="s/token",
            extra={"params": n_params, "device": _device_kind(),
                   "batch": batch_size, "prompt_len": prompt_len,
                   "new_tokens": new_tokens},
        )
    return dt / (reps * new_tokens), n_params


def _run_decode_load(cfg, partial: Optional[PartialWriter] = None):
    """Checkpoint-open -> device-resident seconds for the decode model
    (VERDICT r4 missing #4: the reference's headline table couples load
    seconds with s/token — GPT-J 8.7 s, benchmarks/README.md:31).

    The sharded bf16 safetensors checkpoint is synthesized HOST-side
    (same shapes the decode variant serves; writing from device would pay
    an 11 GiB device->host pull that measures nothing). The timed section
    is the real serving cold path users run: streamed
    ``load_checkpoint_and_dispatch`` from disk to device-resident.
    On this rig the chip is axon-tunneled at ~0.03 GiB/s each way, so
    device residency is link-bound, not framework-bound — the
    disk->host streaming time (the framework's own work) and the
    host->device push are reported separately so the number stays
    interpretable against the reference's local-PCIe 8.7 s.
    """
    import shutil
    import tempfile

    import ml_dtypes

    from accelerate_tpu.big_modeling import load_checkpoint_and_dispatch
    from accelerate_tpu.checkpointing import save_model_weights
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.parallel.sharding import unbox_params

    partial = partial or _noop_writer("decode_load")
    _reset_state()
    model = CausalLM(cfg)
    abstract = unbox_params(
        jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )
        )
    )["params"]
    rng = np.random.default_rng(0)
    host = jax.tree.map(
        lambda l: rng.standard_normal(l.shape, np.float32)
        .astype(ml_dtypes.bfloat16),
        abstract,
    )
    n_params = count_params(host)
    nbytes = sum(l.nbytes for l in jax.tree_util.tree_leaves(host))
    ckpt_dir = tempfile.mkdtemp(prefix="bench_decode_ckpt_")
    try:
        save_model_weights(host, ckpt_dir, max_shard_size="2GB")
        del host
        abstract_bf16 = jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(l.shape, jnp.bfloat16), abstract
        )
        from accelerate_tpu.big_modeling import _lazy_checkpoint_reader
        from accelerate_tpu.checkpointing import _path_str

        # attribution leg: the framework's own streaming work —
        # checkpoint-open + assemble every tensor host-side, no jax
        # placement (pure disk + numpy)
        read = _lazy_checkpoint_reader(ckpt_dir)
        flat, _ = jax.tree_util.tree_flatten_with_path(abstract_bf16)
        t0 = time.perf_counter()
        acc = 0
        for path, _tmpl in flat:
            acc += read(_path_str(path)).nbytes
        disk_to_host_s = time.perf_counter() - t0
        assert acc == nbytes
        # the disk->host leg alone is a usable framework-side number if
        # the tunnel-bound device push gets budget-killed
        partial.update(
            phase="disk_to_host_done", iters_measured=1,
            metric="checkpoint_load_seconds",
            value=round(disk_to_host_s, 2), unit="s",
            extra={"disk_to_host_s": round(disk_to_host_s, 2),
                   "gib": round(nbytes / 2**30, 2), "params": n_params},
        )

        # the serving cold path users run: checkpoint-open ->
        # device-resident in one streamed call (peak host = one leaf)
        t1 = time.perf_counter()
        params = load_checkpoint_and_dispatch(
            abstract_bf16, ckpt_dir, device_map={"": 0},
        )
        np.asarray(jax.tree_util.tree_leaves(params)[-1].ravel()[:1])
        load_s = time.perf_counter() - t1
        return {
            "metric": "checkpoint_load_seconds",
            "value": round(load_s, 2),
            "unit": "s",
            # reference pairs 8.7 s load with its decode headline
            "vs_baseline": round(8.7 / load_s, 4),
            "extra": {
                "disk_to_host_s": round(disk_to_host_s, 2),
                "host_to_device_s": round(load_s - disk_to_host_s, 2),
                "gib": round(nbytes / 2**30, 2),
                "params": n_params,
                "load_ref_s": 8.7,
                "note": "host->device rides the axon tunnel "
                "(~0.03 GiB/s measured) — link-bound, not framework-bound; "
                "disk_to_host_s is the framework's own streaming time",
            },
        }
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def _run_serve(cfg, max_slots: int, block_size: int, n_requests: int,
               seed: int, partial: Optional[PartialWriter] = None):
    """Aggregate serving throughput: continuous-batched paged decode
    (ServingEngine) vs sequential fixed-batch ``generate`` on the SAME
    long-tailed request trace (mostly short answers, a fat tail of long
    ones — the production shape where run-to-completion batching stalls
    a whole chunk on its longest member). Both paths run the full trace
    once as warmup (all prefill buckets + the decode step compile), then
    once timed; ``vs_baseline`` is engine/baseline aggregate USEFUL
    tokens per second (each request's own new tokens — the padding
    tokens the fixed batch generates for already-satisfied rows count
    for nothing). The acceptance bar is >= 2.

    Also reports the analytic HBM-bytes-per-generated-token of the KV
    cache under each scheme: dense reserves ``max_seq_len`` positions
    per request; paged reserves ``ceil((P+N)/block_size)`` blocks.
    """
    from accelerate_tpu.models import (
        CausalLM,
        TransformerConfig,
        count_params,
    )
    from accelerate_tpu.models.generation import make_generate_fn
    from accelerate_tpu.parallel.sharding import unbox_params
    from accelerate_tpu.serving import ServingEngine, SpecConfig

    partial = partial or _noop_writer("serve")
    _reset_state()
    model = CausalLM(cfg)
    # random bf16 params directly on device (same rationale as decode:
    # throughput reads the resident weights; quality is irrelevant)
    abstract = unbox_params(
        jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )
        )
    )["params"]
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    keys = jax.random.split(jax.random.PRNGKey(0), len(leaves))

    @jax.jit
    def init_bf16():
        return jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.bfloat16)
            * (0.02 if l.ndim > 1 else 1.0)
            for k, l in zip(keys, leaves)
        ])

    params = init_bf16()
    n_params = count_params(params)

    # long-tailed trace: ~3/4 short completions, ~1/4 long ones, mixed
    # prompt lengths — every chunk of a fixed batch almost surely holds
    # one long request that the short ones must wait out
    rng = np.random.default_rng(seed)
    max_prompt = max(8, min(cfg.max_seq_len // 4, 64))
    long_new = min(64, cfg.max_seq_len - max_prompt)
    requests = []
    for i in range(n_requests):
        p = int(rng.integers(4, max_prompt + 1))
        if rng.random() < 0.25:
            n = int(rng.integers(long_new // 2, long_new + 1))
        else:
            n = int(rng.integers(4, 9))
        prompt = rng.integers(0, cfg.vocab_size, p).astype(np.int32)
        requests.append((prompt, n))
    useful_tokens = sum(n for _, n in requests)
    prompt_tokens = sum(len(p) for p, _ in requests)

    engine = ServingEngine(
        model, params, max_slots=max_slots, block_size=block_size
    )

    def run_engine():
        for prompt, n in requests:
            engine.add_request(prompt.tolist(), max_new_tokens=n)
        for _ in engine.stream():
            pass

    run_engine()  # warmup: compiles every prefill bucket + the decode step
    warm_traces = engine.trace_counts()
    partial.update(phase="engine_warm", iters_measured=0)
    t0 = time.perf_counter()
    run_engine()
    engine_s = time.perf_counter() - t0
    engine_tps = useful_tokens / engine_s
    decode_retraces = engine.trace_counts()["decode"] - warm_traces["decode"]
    partial.update(
        phase="engine_done", iters_measured=n_requests,
        metric="serve_tokens_per_sec",
        value=round(engine_tps, 1), unit="tokens/s",
        extra={"engine_wall_s": round(engine_s, 3),
               "useful_new_tokens": useful_tokens,
               "device": _device_kind()},
    )

    # baseline: run-to-completion fixed batches of max_slots, each padded
    # to its chunk's max prompt length and decoded to its chunk's max
    # new-token budget (what a generate() serving loop actually does);
    # short chunks are padded back up to max_slots — a fixed batch cannot
    # shrink without retracing
    chunks = [
        requests[i:i + max_slots] for i in range(0, n_requests, max_slots)
    ]
    fns: dict = {}

    def run_baseline():
        for chunk in chunks:
            rows = list(chunk) + [chunk[0]] * (max_slots - len(chunk))
            p_max = max(len(p) for p, _ in rows)
            n_max = max(n for _, n in rows)
            fn = fns.setdefault(
                n_max, make_generate_fn(model, max_new_tokens=n_max)
            )
            batch = np.zeros((max_slots, p_max), np.int32)
            for j, (p, _) in enumerate(rows):
                batch[j, :len(p)] = p
            out = fn(params, jnp.asarray(batch))
            np.asarray(out[:, -1])

    run_baseline()  # warmup: same chunk shapes as the timed pass
    partial.update(phase="baseline_warm", iters_measured=n_requests)
    t1 = time.perf_counter()
    run_baseline()
    baseline_s = time.perf_counter() - t1
    baseline_tps = useful_tokens / baseline_s
    partial.update(
        phase="baseline_done", iters_measured=n_requests,
        metric="serve_tokens_per_sec", value=round(engine_tps, 1),
        unit="tokens/s",
        extra={"baseline_tokens_per_s": round(baseline_tps, 1)},
    )

    # --- observability overhead A/B + SLO attainment ------------------- #
    # The SAME warm engine replays the trace with the observability
    # plane detached, then attached (spans + every-step gauges + SLO
    # tracking + live Prometheus sink) in interleaved rounds — the
    # `_run_overhead` pattern: per-round deltas subtract host drift, the
    # median resists one-off hiccups. Objectives are derived from the
    # headline pass's own p95s (x1.5 headroom) so attainment is a
    # meaningful number on any hardware, not a hardcoded wall-clock.
    import statistics

    from accelerate_tpu.serving import SLOConfig
    from accelerate_tpu.serving.slo import SloTracker
    from accelerate_tpu.telemetry import PrometheusTextSink, StepTelemetry

    summary = engine.summary()
    ttft_obj = (summary.get("ttft_s_p95") or 0.5) * 1.5
    e2e_obj = (summary.get("e2e_s_p95") or 5.0) * 1.5
    slo_tracker = SloTracker(SLOConfig(
        ttft_objective_s=ttft_obj, e2e_objective_s=e2e_obj,
        target=0.99, interval_steps=16,
    ))
    obs_tel = StepTelemetry(True)
    obs_tel.add_sink(PrometheusTextSink(path=None))  # in-memory scrape text

    obs_rounds = 2
    off_times: list = []
    on_times: list = []
    obs_deltas: list = []
    for r in range(obs_rounds):
        engine.set_observability(
            telemetry=None, gauge_interval=0, slo=None, spans=False
        )
        t_off = time.perf_counter()
        run_engine()
        off_s = time.perf_counter() - t_off
        engine.set_observability(
            telemetry=obs_tel, gauge_interval=1, slo=slo_tracker, spans=True
        )
        t_on = time.perf_counter()
        run_engine()
        on_s = time.perf_counter() - t_on
        off_times.append(off_s)
        on_times.append(on_s)
        obs_deltas.append(on_s - off_s)
        partial.update(
            phase="obs_ab", iters_measured=n_requests * 2 * (r + 1),
            metric="serve_tokens_per_sec", value=round(engine_tps, 1),
            unit="tokens/s",
        )
    obs_overhead_pct = (
        statistics.median(obs_deltas) / statistics.median(off_times) * 100.0
    )
    slo_snap = slo_tracker.snapshot()
    obs_tel.close()
    # the whole A/B ran on the warm programs: any observability-induced
    # retrace would show here, so recompute the contract over ALL passes
    decode_retraces = engine.trace_counts()["decode"] - warm_traces["decode"]

    # --- prefix caching A/B: cold vs warm TTFT on a templated trace ---- #
    # The production-templated cohort: every prompt shares a long system
    # prompt (block-aligned) plus a short unique suffix. The SAME warm
    # engine runs the cohort cold (caching off) and warm (template
    # published, every request reuses the cached chain and prefills only
    # its suffix) — the delta is pure prefill work saved. Requests drain
    # sequentially so each one sees the published template (concurrent
    # admission would race the publish and understate hits).
    from accelerate_tpu.serving.telemetry import ServeStats

    suffix_len = max(2, block_size // 2)
    prefix_new = 8
    # template as long as the budget allows (capped for bench runtime):
    # cold pays the full-prompt prefill bucket, warm only the suffix tail
    template_blocks = max(4, min(
        24, (cfg.max_seq_len - suffix_len - prefix_new - 4) // block_size
    ))
    template_len = template_blocks * block_size
    n_templated = min(12, n_requests)
    trng = np.random.default_rng(seed + 1)
    template = trng.integers(0, cfg.vocab_size, template_len).astype(np.int32)
    templated = [
        np.concatenate([
            template,
            trng.integers(0, cfg.vocab_size, suffix_len).astype(np.int32),
        ])
        for _ in range(n_templated)
    ]
    # the seed request's prompt covers every full template block, so one
    # drain publishes the whole chain
    seed_prompt = np.concatenate([template, template[:1]])

    def run_templated():
        outs = []
        for prompt in templated:
            rid = engine.add_request(
                prompt.tolist(), max_new_tokens=prefix_new
            )
            for _ in engine.stream():
                pass
            outs.append(engine.result(rid))
        return outs

    def seed_cache():
        engine.add_request(seed_prompt.tolist(), max_new_tokens=1)
        for _ in engine.stream():
            pass

    engine.set_observability(
        telemetry=None, gauge_interval=0, slo=None, spans=False
    )
    # bucket warmup: both arms' prefill widths compile OUTSIDE the timed
    # passes (cold: full-prompt bucket; warm: seed + tail bucket), so the
    # timed section can assert zero new prefill programs
    engine.set_prefix_cache(False)
    run_templated()
    engine.set_prefix_cache(True)
    seed_cache()
    run_templated()
    prefix_warm_traces = engine.trace_counts()
    partial.update(phase="prefix_warm", iters_measured=0)

    # cold arm (disabling clears the published chain)
    engine.set_prefix_cache(False)
    engine.stats = ServeStats()
    t_cold = time.perf_counter()
    cold_out = run_templated()
    prefix_cold_s = time.perf_counter() - t_cold
    cold_sum = engine.stats.summary()

    # warm arm: re-seed, then every cohort request hits the full chain
    engine.set_prefix_cache(True)
    seed_cache()
    saved_before = engine.prefix_cache.tokens_saved_total
    engine.stats = ServeStats()
    t_warm = time.perf_counter()
    warm_out = run_templated()
    prefix_warm_s = time.perf_counter() - t_warm
    warm_sum = engine.stats.summary()
    prefill_saved = engine.prefix_cache.tokens_saved_total - saved_before
    templated_prompt_tokens = sum(len(p) for p in templated)
    prefix_stats = engine.prefix_cache.stats()
    engine.set_prefix_cache(False)
    prefix_new_prefill = (
        engine.trace_counts()["prefill"] - prefix_warm_traces["prefill"]
    )
    decode_retraces = engine.trace_counts()["decode"] - warm_traces["decode"]
    cold_p50 = cold_sum.get("ttft_s_p50") or 0.0
    warm_p50 = warm_sum.get("ttft_s_p50") or 0.0
    partial.update(
        phase="prefix_ab_done", iters_measured=n_templated * 2,
        metric="serve_tokens_per_sec", value=round(engine_tps, 1),
        unit="tokens/s",
    )

    # --- speculative decoding A/B: off vs n-gram vs draft model -------- #
    # Speculation needs a draft the target actually agrees with, and
    # with random weights no independently-initialized small model
    # predicts another — so the pair is built SELF-CONSISTENTLY: a
    # target whose upper layers are residual no-ops (attention and MLP
    # output projections zeroed, so layers >= 1 add exact zeros to the
    # residual stream) and a one-layer draft holding the target's bottom
    # layer, embedding and head. Their logits agree bitwise, which turns
    # the draft arm into the engine's ceiling at a real ~num_layers x
    # compute asymmetry (accept_rate ~1); the n-gram arm shows the
    # honest no-draft number on the same non-repetitive trace. fp32 on
    # purpose: the outputs-match bar compares argmax across the decode
    # and verify programs, and bf16 reduction-order tie-flips would make
    # that assertion flaky without changing the mechanism measured.
    from dataclasses import replace as _dc_replace

    spec_cfg = TransformerConfig.tiny(
        num_layers=6, hidden_size=256, intermediate_size=704,
        num_heads=4, max_seq_len=256,
    )
    spec_target = CausalLM(spec_cfg)
    spec_params = spec_target.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    for block, proj in (("attn", "o_proj"), ("mlp", "down_proj")):
        spec_params["layers"][block][proj] = jax.tree_util.tree_map(
            lambda x: x.at[1:].set(0.0),
            spec_params["layers"][block][proj],
        )
    spec_draft = CausalLM(_dc_replace(spec_cfg, num_layers=1))
    spec_draft_params = dict(spec_params)
    spec_draft_params["layers"] = jax.tree_util.tree_map(
        lambda x: x[:1], spec_params["layers"]
    )

    # decode-heavy long-tail cohort: short prompts, long completions —
    # the regime where the one-token-per-step wall actually binds
    spec_k = 4
    n_spec = min(8, n_requests)
    spec_new = min(180, spec_cfg.max_seq_len - 16 - spec_k)
    sprng = np.random.default_rng(seed + 2)
    spec_requests = [
        sprng.integers(
            1, spec_cfg.vocab_size, int(sprng.integers(4, 12))
        ).astype(np.int32)
        for _ in range(n_spec)
    ]

    def run_spec_arm(spec):
        # fresh engine per arm (fresh jit closures); the cohort runs
        # once as warmup — deterministic greedy outputs mean the timed
        # replay hits exactly the warmed program set, so any retrace in
        # the timed drain is a real contract break
        eng = ServingEngine(
            spec_target, spec_params,
            max_slots=max_slots, block_size=block_size,
        )
        if spec is not None:
            eng.set_speculation(spec)
        for p in spec_requests:
            eng.add_request(p.tolist(), max_new_tokens=spec_new)
        for _ in eng.stream():
            pass
        warm = eng.trace_counts()
        rids = [
            eng.add_request(p.tolist(), max_new_tokens=spec_new)
            for p in spec_requests
        ]
        t_arm = time.perf_counter()
        for _ in eng.stream():
            pass
        wall = time.perf_counter() - t_arm
        outs = [eng.result(r) for r in rids]
        after = eng.trace_counts()
        return {
            "tps": sum(len(o) for o in outs) / wall,
            "outs": outs,
            "accept": eng.summary().get(
                "speculation", {}
            ).get("accept_rate"),
            "retraces": sum(
                after.get(k2, 0) - warm.get(k2, 0)
                for k2 in ("decode", "verify", "draft_step")
            ),
        }

    spec_off = run_spec_arm(None)
    spec_ngram = run_spec_arm(SpecConfig(k=spec_k))
    spec_draft_arm = run_spec_arm(SpecConfig(
        k=spec_k, method="draft_model",
        draft_model=spec_draft, draft_params=spec_draft_params,
    ))
    partial.update(
        phase="spec_ab_done", iters_measured=n_spec * 6,
        metric="serve_tokens_per_sec", value=round(engine_tps, 1),
        unit="tokens/s",
    )

    # sharding X-ray: audit every captured serving program against the
    # params-derived contract (replicated here ⇒ zero collectives), so
    # collective/DCN bytes become regression-tracked BENCH axes
    audit_fields: dict = {}
    try:
        from accelerate_tpu.profiling.registry import ProgramRegistry

        audit_registry = ProgramRegistry()
        engine.audit_programs(audit_registry, emit=False)
        audit_sum = engine.audit_summary(audit_registry)
        audit_fields = {
            "audit_programs": audit_sum.get("num_programs_audited", 0),
            "audit_collective_bytes": int(
                audit_sum.get("ici_bytes_total", 0)
                + audit_sum.get("dcn_bytes_total", 0)
            ),
            "audit_dcn_bytes": int(audit_sum.get("dcn_bytes_total", 0)),
            "audit_violations": int(audit_sum.get("violations_total", 0)),
        }
    except Exception:  # noqa: BLE001 — observability never fatal
        audit_fields = {}

    # analytic KV-cache HBM traffic per useful token (bf16 K+V)
    itemsize = 2
    bytes_per_pos = (
        cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 2 * itemsize
    )
    dense_kv = n_requests * cfg.max_seq_len * bytes_per_pos
    paged_kv = sum(
        -(-(len(p) + n) // block_size) * block_size for p, n in requests
    ) * bytes_per_pos
    return {
        "metric": "serve_tokens_per_sec",
        "value": round(engine_tps, 1),
        "unit": "tokens/s",
        # acceptance bar: continuous-batched paged decode >= 2x the
        # sequential fixed-batch path on this trace
        "vs_baseline": round(engine_tps / baseline_tps, 3),
        "extra": {
            "baseline_tokens_per_s": round(baseline_tps, 1),
            "engine_wall_s": round(engine_s, 3),
            "baseline_wall_s": round(baseline_s, 3),
            "requests": n_requests,
            "max_slots": max_slots,
            "block_size": block_size,
            "useful_new_tokens": useful_tokens,
            "prompt_tokens": prompt_tokens,
            "decode_retraces_after_warmup": decode_retraces,
            "prefill_traces": engine.trace_counts()["prefill"],
            **audit_fields,
            **{
                k: round(v, 4) if v is not None else None
                for k, v in (
                    ("ttft_p50_s", summary.get("ttft_s_p50")),
                    ("ttft_p95_s", summary.get("ttft_s_p95")),
                    ("decode_tokens_per_s_p50",
                     summary.get("decode_tokens_per_s_p50")),
                    ("decode_tokens_per_s_p95",
                     summary.get("decode_tokens_per_s_p95")),
                )
            },
            "hbm_kv_bytes_per_token_paged": round(
                paged_kv / useful_tokens, 1
            ),
            "hbm_kv_bytes_per_token_dense": round(
                dense_kv / useful_tokens, 1
            ),
            "kv_bytes_saved_vs_dense": round(1 - paged_kv / dense_kv, 3),
            # span+gauge+SLO overhead, same-engine interleaved A/B
            # (acceptance bar: < 2%)
            "obs_overhead_pct": round(obs_overhead_pct, 2),
            "obs_rounds": obs_rounds,
            "obs_ab_wall_s": round(sum(off_times) + sum(on_times), 3),
            # attainment vs objectives derived from this run's own p95s
            "slo_ttft_objective_s": round(ttft_obj, 4),
            "slo_e2e_objective_s": round(e2e_obj, 4),
            "slo_ttft_attainment": (
                round(slo_snap["ttft_attainment"], 4)
                if slo_snap["ttft_attainment"] is not None else None
            ),
            "slo_e2e_attainment": (
                round(slo_snap["e2e_attainment"], 4)
                if slo_snap["e2e_attainment"] is not None else None
            ),
            # prefix caching cold-vs-warm A/B on the templated cohort
            # (acceptance bar: warm TTFT p50 >= 3x better, outputs
            # bitwise identical, zero new programs in the timed passes)
            "prefix_ttft_p50_cold_s": round(cold_p50, 5),
            "prefix_ttft_p50_warm_s": round(warm_p50, 5),
            "prefix_ttft_p95_cold_s": round(
                cold_sum.get("ttft_s_p95") or 0.0, 5
            ),
            "prefix_ttft_p95_warm_s": round(
                warm_sum.get("ttft_s_p95") or 0.0, 5
            ),
            "prefix_ttft_speedup_p50": (
                round(cold_p50 / warm_p50, 2) if warm_p50 > 0 else None
            ),
            "prefill_tokens_saved_pct": round(
                100.0 * prefill_saved / templated_prompt_tokens, 1
            ),
            "prefix_outputs_match": cold_out == warm_out,
            "prefix_cache_hit_rate": round(prefix_stats["hit_rate"], 3),
            "prefix_cow_copies_total": prefix_stats["cow_copies_total"],
            "prefix_new_prefill_traces": prefix_new_prefill,
            "prefix_cold_wall_s": round(prefix_cold_s, 3),
            "prefix_warm_wall_s": round(prefix_warm_s, 3),
            "prefix_templated_requests": n_templated,
            "prefix_template_tokens": template_len,
            # speculative decoding A/B on the decode-heavy cohort
            # (acceptance bar: draft arm >= 2x off at token-for-token
            # identical outputs, zero retraces in every timed drain)
            "spec_tokens_per_s_off": round(spec_off["tps"], 1),
            "spec_tokens_per_s_ngram": round(spec_ngram["tps"], 1),
            "spec_tokens_per_s_draft": round(spec_draft_arm["tps"], 1),
            "spec_speedup": round(
                spec_draft_arm["tps"] / spec_off["tps"], 3
            ),
            "spec_accept_rate_ngram": (
                round(spec_ngram["accept"], 4)
                if spec_ngram["accept"] is not None else None
            ),
            "spec_accept_rate_draft": (
                round(spec_draft_arm["accept"], 4)
                if spec_draft_arm["accept"] is not None else None
            ),
            "spec_outputs_match": (
                spec_ngram["outs"] == spec_off["outs"]
                and spec_draft_arm["outs"] == spec_off["outs"]
            ),
            "spec_decode_retraces": (
                spec_off["retraces"] + spec_ngram["retraces"]
                + spec_draft_arm["retraces"]
            ),
            "spec_k": spec_k,
            "spec_requests": n_spec,
            "spec_new_tokens": spec_new,
            "params": n_params,
            "device": _device_kind(),
        },
    }


def _run_serve_soak(cfg, max_slots: int, block_size: int,
                    target_requests: int, seed: int,
                    partial: Optional[PartialWriter] = None):
    """Soak & chaos line: the loadgen harness drives ONE ServingEngine
    through warmup -> ramp -> soak -> fault -> recovery with an
    OPEN-LOOP arrival process on the wall clock (arrivals land on
    schedule no matter how far behind the engine is — coordinated
    omission shows up as arrival lag and queueing TTFT, not as silently
    stretched gaps). A short closed-loop probe first measures this
    host's capacity and TTFT so the ramp rates (0.5x..2x capacity) and
    the SLO objective scale to the hardware instead of hardcoding
    wall-clock numbers; the top ramp intentionally overruns capacity so
    the breach point is a real measurement. Mid-soak a
    ``stall_decode`` chaos fault wedges the decode loop; the record
    reports the bounded damage (sheds + SLO violations inside the
    window) and the measured time-to-recover.

    Headline: goodput tokens/s during the soak phase counting only
    requests whose TTFT met the objective. ``vs_baseline`` is
    objective / soak-p95-TTFT (>= 1 means the soak rate held the SLO).

    After the main soak, three short paired A/B arms measure the PR 17
    capacity levers on identical traces: chunked prefill OFF/ON over a
    long-prompt-burst mix (soak p95 TTFT must improve, zero retraces),
    shed-only vs preemption under ``pool_pressure`` chaos (fault-window
    sheds must drop; resumed outputs bitwise-match the control), and
    fp-vs-int8 KV (census-verified ``kv_cache`` bytes fund >= 1.8x the
    seats at fixed HBM; greedy outputs identical).
    """
    import os

    from accelerate_tpu.loadgen import (
        Phase,
        SoakConfig,
        SoakHarness,
        WorkloadConfig,
        build_trace,
    )
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.parallel.sharding import unbox_params
    from accelerate_tpu.serving import ServingEngine, SLOConfig
    from accelerate_tpu.serving.telemetry import ServeStats

    partial = partial or _noop_writer("serve_soak")
    _reset_state()
    model = CausalLM(cfg)
    abstract = unbox_params(
        jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )
        )
    )["params"]
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    keys = jax.random.split(jax.random.PRNGKey(0), len(leaves))

    @jax.jit
    def init_bf16():
        return jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.bfloat16)
            * (0.02 if l.ndim > 1 else 1.0)
            for k, l in zip(keys, leaves)
        ])

    params = init_bf16()
    n_params = count_params(params)

    max_prompt = max(8, min(cfg.max_seq_len // 4, 48))
    workload = WorkloadConfig(
        vocab_size=cfg.vocab_size,
        prompt_tokens_min=4,
        prompt_tokens_median=max(6, max_prompt // 4),
        prompt_tokens_max=max_prompt,
        output_tokens_min=2,
        output_tokens_median=6,
        output_tokens_max=24,
        max_total_tokens=cfg.max_seq_len,
    )

    # closed-loop capacity probe (doubles as compile warmup): drain a
    # deterministic burst twice — first pass pays the prefill buckets +
    # decode compile, second pass is the timed measurement (its stats
    # start from zero so the compile-laden first drain cannot inflate
    # the derived TTFT objective)
    engine = ServingEngine(
        model, params, max_slots=max_slots, block_size=block_size
    )
    calib = build_trace(
        workload,
        (Phase("calib", "warmup", duration_s=1.0, rate_rps=16.0,
               process="uniform"),),
        seed + 1,
    )

    def drain(reqs):
        for req in reqs:
            engine.add_request(
                list(req.prompt), max_new_tokens=req.max_new_tokens
            )
        while engine.has_work:
            engine.step()

    drain(calib)
    engine.stats = ServeStats()
    t0 = time.perf_counter()
    drain(calib)
    calib_s = max(time.perf_counter() - t0, 1e-6)
    capacity_rps = len(calib) / calib_s
    # the probe's p95 TTFT includes the burst's own queueing (16 deep on
    # max_slots seats) — x2 of it is an objective the engine holds near
    # capacity but loses when the open-loop backlog outgrows the burst
    ttft_obj = max(0.02, (engine.summary().get("ttft_s_p95") or 0.1) * 2.0)
    engine.stats = ServeStats()  # the soak accounts from zero
    # production posture for the overload phases: bound the queue by the
    # deadline clients would abandon at, so the 2x-capacity ramp SHEDS
    # (observable damage) instead of dragging an unbounded backlog into
    # the soak phase's steady-state measurement
    engine.scheduler.max_queue_delay_s = 2.5 * ttft_obj
    partial.update(
        phase="calibrated", iters_measured=len(calib),
        extra={"capacity_rps_closed_loop": round(capacity_rps, 2)},
    )

    # phase program scaled so total offered load ~= target_requests at
    # the measured capacity. The ramp tops out at 2x capacity (the
    # breach point must be real), the cooldown drains the ramp's
    # residual queue so the soak measures STEADY state at 0.6x
    # capacity, and the recovery window is long enough for the burn
    # windows to clear after the stall's backlog drains.
    c, u = capacity_rps, min(
        3.0, max(0.6, target_requests / (9.1 * capacity_rps))
    )
    program = (
        Phase("warmup", "warmup", u, max(1.0, 0.25 * c)),
        Phase("ramp-1", "ramp", u, 0.5 * c),
        Phase("ramp-2", "ramp", u, 1.0 * c),
        Phase("ramp-3", "ramp", u, 1.5 * c),
        Phase("ramp-4", "ramp", u, 2.0 * c),
        Phase("cooldown", "warmup", u, max(1.0, 0.25 * c)),
        Phase("soak", "soak", 2 * u, 0.6 * c),
        Phase("fault", "fault", u, 0.6 * c),
        Phase("recovery", "recovery", 3 * u, 0.6 * c),
    )
    unit_s = u
    stall_secs = round(min(1.0, unit_s / 2), 2)
    slo = SLOConfig(
        ttft_objective_s=ttft_obj,
        e2e_objective_s=ttft_obj * 10,
        target=0.9,
        fast_window_s=max(0.2, unit_s / 2),
        slow_window_s=max(0.4, unit_s),
        burn_threshold=1.0,
        interval_steps=8,
        min_requests=3,
    )
    report_path = (
        os.path.join(os.path.dirname(partial.path), "soak-report.json")
        if partial.path else None
    )
    soak_cfg = SoakConfig(
        workload=workload,
        phases=program,
        seed=seed,
        step_dt_s=None,  # wall clock on both sides (engine default)
        slo=slo,
        fault_specs=f"stall_decode@0:secs={stall_secs:g}",
        report_path=report_path,
        drain_grace_s=30.0,
        label="serve_soak",
    )

    finished_total = [0]

    def on_phase(rec):
        finished_total[0] += rec["finished"]
        partial.update(
            phase=f"soak_{rec['phase']}",
            iters_measured=finished_total[0],
            metric="soak_goodput_tokens_per_s",
            value=rec["goodput_tokens_per_s"], unit="tokens/s",
        )

    t_soak = time.perf_counter()
    harness = SoakHarness(engine, soak_cfg, on_phase_end=on_phase)
    report = harness.run()
    soak_wall_s = time.perf_counter() - t_soak

    # --- capacity A/B axes (PR 17) --------------------------------- #
    # three short paired arms over IDENTICAL traces (same workload +
    # seed), each isolating one serve-more-users-per-chip lever. The
    # chunked and preemption arms run on the VIRTUAL clock (step_dt_s):
    # TTFT and deadline aging are measured in engine steps, so the
    # comparison captures the SCHEDULING change — which is what these
    # levers are — instead of host speed and compile-pause noise.
    #   chunked  — long-prompt-burst mix on a CONSTRAINED pool, plain
    #              engine vs chunked prefill (+ its chunk-aware
    #              admission reservation, which needs the preemption
    #              escape hatch): soak-phase TTFT p95 must improve —
    #              the OFF arm's FIFO head can't fund a giant's full
    #              footprint and head-of-line-blocks admission until
    #              the pool half-drains; the ON arm admits on the first
    #              chunk and grows per chunk. Zero decode retraces;
    #   preempt  — pool_pressure chaos at ramp-past-capacity rate,
    #              shed-only vs preemption ON: fault-window sheds must
    #              drop (sheds become pauses) and resumed outputs
    #              bitwise-match the shed-only control;
    #   int8     — same pool geometry fp vs int8 KV: census-verified
    #              kv_cache owner bytes fund >= 1.8x the seats at a
    #              fixed HBM budget, greedy outputs identical.
    from dataclasses import replace as _dc_replace

    from accelerate_tpu.loadgen import SoakClock
    from accelerate_tpu.serving.engine import _next_pow2
    from accelerate_tpu.telemetry import StepTelemetry

    ab_dt = 0.01  # virtual seconds per engine step
    # analytic seat throughput in requests per VIRTUAL second: a median
    # request holds its seat ~ (prefill + median output) steps
    vcap = max_slots / ((2 + workload.output_tokens_median) * ab_dt)

    def _arm_engine(**kw):
        clock = SoakClock()
        eng = ServingEngine(
            model, params, max_slots=max_slots, block_size=block_size,
            now=clock, **kw,
        )
        return eng, clock

    def _prime(eng, lens):
        """Compile every program the arm's trace can hit BEFORE the
        measured window (pow2 prefill buckets, chunk buckets, decode) —
        the virtual clock hides compile pauses from TTFT, but priming
        keeps the arms' step loops doing identical work."""
        rng_p = np.random.default_rng(seed + 99)
        for n in lens:
            eng.add_request(
                rng_p.integers(1, workload.vocab_size, size=n).tolist(),
                max_new_tokens=2,
            )
        while eng.has_work:
            eng.step()
        from accelerate_tpu.serving.telemetry import ServeStats
        eng.stats = ServeStats()

    def _arm_report(name, eng, clock, workload_arm, phases, fault="",
                    step_cost=None):
        arm_path = (
            os.path.join(
                os.path.dirname(partial.path), f"soak-report-{name}.json"
            ) if partial.path else None
        )
        arm_cfg = SoakConfig(
            workload=workload_arm, phases=phases, seed=seed + 17,
            step_dt_s=ab_dt, step_cost=step_cost, fault_specs=fault,
            report_path=arm_path, drain_grace_s=60.0,
            label=f"serve_soak_{name}",
        )
        rep = SoakHarness(eng, arm_cfg, clock=clock).run()
        partial.update(phase=f"ab_{name}", iters_measured=finished_total[0])
        return rep

    def _soak_p95(rep):
        for p in rep["phases"]:
            if p["phase"] == "soak":
                return p["p95_ttft_s"]
        return None

    # giants: long enough that the full-footprint reservation dwarfs the
    # pool while staying admissible (prompt + output <= max_total)
    long_tokens = max(
        workload.prompt_tokens_max,
        (workload.max_total_tokens or 4 * workload.prompt_tokens_max)
        - 2 * workload.output_tokens_max,
    )
    # giants are a BURST, not the population: ~3% of arrivals, so the
    # p95 statistic sits on the shorts the giants disrupt. Chunking
    # deliberately trades the giant's own TTFT (it ingests over several
    # steps instead of one long stall) for everyone else's — at a high
    # giant fraction p95 lands on the giants themselves and measures
    # the cost side of that trade, not the benefit. The longer decode
    # tail (median 16) keeps seats and pool genuinely occupied, so a
    # giant's arrival actually collides with live work
    giant_frac = 0.03
    burst_out_median = 16
    burst_workload = _dc_replace(
        workload, long_prompt_fraction=giant_frac,
        long_prompt_tokens=long_tokens,
        output_tokens_min=burst_out_median // 2,
        output_tokens_median=burst_out_median,
    )
    # pool sized to ONE giant's full footprint plus four seats of median
    # shorts: the OFF arm's FIFO head can only fund a giant after the
    # pool drains to almost nothing — and every short behind the giant
    # waits out that drain with it. The ON arm admits the giant on its
    # first chunk's blocks and grows per chunk
    giant_fp = (
        (long_tokens + workload.output_tokens_max + block_size - 1)
        // block_size
    )
    short_fp = (
        (workload.prompt_tokens_median + burst_out_median
         + block_size - 1) // block_size
    )
    ab_blocks = 1 + giant_fp + 4 * short_fp
    # budget: a giant ingests in ~4 chunks — small enough that chunking
    # is real, large enough that SRPT leftovers still drain giants
    chunk_budget = max(4 * block_size, _next_pow2(long_tokens // 4))
    # the per-step base cost relative to one budget-sized chunk of
    # prefill: a decode step computes max_slots token positions vs the
    # chunk's ``chunk_budget``, so it is a small fraction of a chunk —
    # pricing it at a FULL quantum would bill the ON arm one phantom
    # quantum per chunk step and bury the stall signal under it
    step_base = 0.25
    # rates come from the WORK-WEIGHTED capacity, not the seat count:
    # under _work_cost a request consumes a prefill-step base + its
    # prompt's bucket tokens / budget + its full-batch share of the
    # decode steps. Offering the flat-clock seat capacity here would
    # put BOTH arms in runaway overload and measure nothing but queue
    # explosion
    avg_prompt = (
        (1.0 - giant_frac) * workload.prompt_tokens_median
        + giant_frac * long_tokens
    )
    chunk_quanta = (
        step_base + avg_prompt / chunk_budget
        + step_base * burst_out_median / max_slots
    )
    vcap_chunk = 1.0 / (chunk_quanta * ab_dt)
    burst_phases = (
        Phase("warmup", "warmup", 1.0, 0.3 * vcap_chunk),
        Phase("soak", "soak", 3.5, 0.8 * vcap_chunk),
    )
    prime_lens = sorted({
        4, workload.prompt_tokens_median, workload.prompt_tokens_max,
        chunk_budget, long_tokens,
    })
    def _work_cost(eng):
        """Work-weighted virtual step cost, identical for both arms: a
        base quantum of decode/dispatch plus one quantum per
        ``chunk_budget`` of padded prefill tokens the step issued. This
        is the physics chunking trades in — a giant's one-shot prefill
        is one LONG step that stalls every seated request, a chunk is a
        short one — and a flat-quantum clock (which prices a 256-token
        prefill the same as a decode) erases it."""
        last = [eng.prefill_bucket_tokens_total]
        def cost(_):
            cur = eng.prefill_bucket_tokens_total
            d, last[0] = cur - last[0], cur
            return ab_dt * (step_base + d / chunk_budget)
        return cost

    eng_off, clk_off = _arm_engine(num_blocks=ab_blocks)
    _prime(eng_off, prime_lens)
    rep_off = _arm_report("chunked-off", eng_off, clk_off, burst_workload,
                          burst_phases, step_cost=_work_cost(eng_off))
    eng_on, clk_on = _arm_engine(
        num_blocks=ab_blocks, prefill_chunk_tokens=chunk_budget,
        preemption=True,
    )
    _prime(eng_on, prime_lens)
    rep_on = _arm_report("chunked-on", eng_on, clk_on, burst_workload,
                         burst_phases, step_cost=_work_cost(eng_on))
    ttft_off, ttft_on = _soak_p95(rep_off), _soak_p95(rep_on)

    # preemption A/B: past-capacity arrivals while pool_pressure pins
    # half the free blocks — the shed-only arm ages its queue past the
    # deadline, the preemption arm pauses seated work instead. The pool
    # is sized off the MEDIAN footprint so it (not the seat count) is
    # the binding resource: ~3 median requests in flight fill it, yet
    # the largest single request still fits
    median_fp = (
        (workload.prompt_tokens_median + workload.output_tokens_median
         + block_size - 1) // block_size
    )
    max_fp = (
        (workload.prompt_tokens_max + workload.output_tokens_max
         + block_size - 1) // block_size
    )
    pressure_blocks = 1 + max(3 * median_fp, max_fp + 1)
    pressure_phases = (
        Phase("warmup", "warmup", 1.0, 0.35 * vcap),
        Phase("fault", "fault", 2.0, 1.3 * vcap),
        Phase("recovery", "recovery", 1.0, 0.35 * vcap),
    )
    pressure_fault = "pool_pressure@0:secs=1.2"
    delay = 0.3  # 30 virtual steps of queue patience
    eng_shed, clk_shed = _arm_engine(
        num_blocks=pressure_blocks, max_queue_delay_s=delay,
    )
    _prime(eng_shed, prime_lens[:-1])
    rep_shed = _arm_report("preempt-off", eng_shed, clk_shed, workload,
                           pressure_phases, fault=pressure_fault)
    eng_pre, clk_pre = _arm_engine(
        num_blocks=pressure_blocks, max_queue_delay_s=delay,
        preemption=True,
    )
    _prime(eng_pre, prime_lens[:-1])
    rep_pre = _arm_report("preempt-on", eng_pre, clk_pre, workload,
                          pressure_phases, fault=pressure_fault)
    # every request preempted+resumed under chaos must finish with the
    # same tokens the uncontended (shed-only) arm produced for it —
    # requests the control shed have no reference and are skipped
    preempted_ids = [
        r["request_id"] for r in eng_pre.stats.requests
        if r.get("preempted_count")
    ]
    preempt_outputs_match = all(
        eng_pre.result(rid) == eng_shed.result(rid)
        for rid in preempted_ids if eng_shed.result(rid) is not None
    )

    tel_fp, tel_i8 = StepTelemetry(True), StepTelemetry(True)
    eng_fp = ServingEngine(
        model, params, max_slots=max_slots, block_size=block_size,
        telemetry=tel_fp,
    )
    eng_i8 = ServingEngine(
        model, params, max_slots=max_slots, block_size=block_size,
        telemetry=tel_i8, kv_dtype="int8",
    )
    kv_fp = (tel_fp.sample_memory(force=True) or {}).get(
        "census_owner_bytes", {}
    ).get("kv_cache", 0)
    kv_i8 = (tel_i8.sample_memory(force=True) or {}).get(
        "census_owner_bytes", {}
    ).get("kv_cache", 0)
    kv_ratio = kv_fp / kv_i8 if kv_i8 else None
    # fixed-HBM-budget seat arithmetic from the CENSUS bytes: the fp
    # pool's measured footprint, spent on int8-priced blocks, funds
    # this many concurrent median-shaped requests instead
    pool_blocks = eng_fp.pool.num_blocks
    footprint = eng_fp.pool.blocks_for_tokens(
        workload.prompt_tokens_median + workload.output_tokens_median
    )
    seats_fp = (pool_blocks - 1) // footprint
    i8_blocks = int(kv_fp // (kv_i8 / pool_blocks)) if kv_i8 else 0
    seats_i8 = max(0, i8_blocks - 1) // footprint
    seat_ratio = seats_i8 / seats_fp if seats_fp else None

    def _drain_outputs(eng):
        ids = [
            eng.add_request(list(r.prompt),
                            max_new_tokens=r.max_new_tokens)
            for r in calib
        ]
        while eng.has_work:
            eng.step()
        return [eng.result(rid) for rid in ids]

    int8_match = _drain_outputs(eng_fp) == _drain_outputs(eng_i8)
    ab_wall_s = time.perf_counter() - t_soak - soak_wall_s

    head = report["headline"]
    fault = report["fault"]
    return {
        "metric": "soak_goodput_tokens_per_s_at_slo",
        "value": round(head["goodput_tokens_per_s_at_slo"] or 0.0, 1),
        "unit": "tokens/s",
        # acceptance bar: the soak phase (0.75x measured capacity) holds
        # its p95 TTFT under the objective
        "vs_baseline": (
            round(ttft_obj / head["soak_p95_ttft_s"], 3)
            if head["soak_p95_ttft_s"] else None
        ),
        "extra": {
            "capacity_rps_closed_loop": round(capacity_rps, 2),
            "capacity_rps_at_breach_point": round(
                head["capacity_rps_at_breach_point"], 2
            ),
            "capacity_saturated": head["capacity_saturated"],
            "slo_ok": head["slo_ok"],
            "soak_p95_ttft_s": (
                round(head["soak_p95_ttft_s"], 5)
                if head["soak_p95_ttft_s"] is not None else None
            ),
            "ttft_objective_s": round(ttft_obj, 4),
            "max_queue_delay_s": round(4.0 * ttft_obj, 4),
            "shed_totals": report["shed_totals"],
            "requests_planned": report["requests_planned"],
            "requests_finished": report["requests_finished"],
            "requests_shed": report["requests_shed"],
            "arrival_lag_p95_s": report["arrival_lag"]["p95_s"],
            "fault_specs": fault["specs"],
            "fault_sheds_in_window": fault["sheds_in_window"],
            "fault_slo_violations_in_window": (
                fault["slo_violations_in_window"]
            ),
            "recovery_s": fault["recovery_s"],
            "recovered": fault["recovered"],
            "decode_retraces_after_warmup": report["decode_retraces"],
            "engine_steps": report["engine_steps"],
            # chunked prefill A/B: soak p95 TTFT on the long-prompt-
            # burst trace (acceptance: ON strictly better, 0 retraces)
            "chunked_budget_tokens": chunk_budget,
            "chunked_soak_p95_ttft_off_s": (
                round(ttft_off, 5) if ttft_off is not None else None
            ),
            "chunked_soak_p95_ttft_on_s": (
                round(ttft_on, 5) if ttft_on is not None else None
            ),
            "chunked_ttft_improvement": (
                round(ttft_off / ttft_on, 3)
                if ttft_off and ttft_on else None
            ),
            "chunked_decode_retraces": (
                rep_off["decode_retraces"] + rep_on["decode_retraces"]
            ),
            "chunked_prefill_chunks_total": eng_on._prefill_chunks_total,
            # preemption A/B under pool_pressure chaos (acceptance: ON
            # sheds strictly fewer in the fault window; resumed outputs
            # bitwise-match the shed-only control)
            "preempt_fault_sheds_off": (
                rep_shed["fault"]["sheds_in_window"]
            ),
            "preempt_fault_sheds_on": rep_pre["fault"]["sheds_in_window"],
            "preempt_fault_preempts_on": (
                rep_pre["fault"]["preempts_in_window"]
            ),
            "preempt_resumes_total": eng_pre._resumes_total,
            "preempt_requests_resumed_finished": len(preempted_ids),
            "preempt_outputs_match": preempt_outputs_match,
            # int8 KV: census-verified kv_cache owner bytes + the
            # fixed-budget seat arithmetic (acceptance: >= 1.8x)
            "int8_kv_bytes_census_fp": int(kv_fp),
            "int8_kv_bytes_census_int8": int(kv_i8),
            "int8_kv_bytes_ratio": (
                round(kv_ratio, 3) if kv_ratio else None
            ),
            "int8_concurrent_requests_fp": seats_fp,
            "int8_concurrent_requests_int8": seats_i8,
            "int8_capacity_ratio": (
                round(seat_ratio, 3) if seat_ratio else None
            ),
            "int8_greedy_outputs_match": int8_match,
            "ab_wall_s": round(ab_wall_s, 3),
            "soak_wall_s": round(soak_wall_s, 3),
            "calib_wall_s": round(calib_s, 3),
            "unit_s": round(unit_s, 3),
            "trace_sha256": report["trace_sha256"],
            "phases": report["phases"],
            "report_path": report_path,
            "max_slots": max_slots,
            "block_size": block_size,
            "params": n_params,
            "device": _device_kind(),
        },
    }


def _run_fleet_soak(cfg, max_slots: int, block_size: int,
                    target_requests: int, seed: int,
                    partial: Optional[PartialWriter] = None):
    """Fleet serving line: the soak harness drives a FOUR-replica fleet
    through the PR 18 router, entirely on the virtual clock (step_dt_s)
    so the multi-replica program costs engine steps, not host seconds.

    Three policy arms replay the SAME templated-cohort trace (90% of
    requests open with one of four block-aligned cohort prefixes —
    production templated traffic) against fresh replicas:

      round_robin     — the placement baseline,
      least_loaded    — live-gauge admission,
      prefix_affinity — cached-chain overlap minus a load penalty.

    Acceptance bar: prefix-affinity shows STRICTLY higher fleet-wide
    warm-prefix hit rate AND no-worse goodput@SLO than round-robin —
    affinity concentrates each cohort's chain on one replica instead of
    duplicating the prefill N ways. A fourth arm re-runs affinity with
    ``replica_kill@0:replica=1`` mid-soak and reports the re-route
    ledger (requeued vs lost) and measured time-to-recover. Every arm
    also asserts the per-replica zero-retrace contract: decode compiled
    once per replica during priming and never again.

    Headline: affinity-arm fleet goodput@SLO; ``vs_baseline`` is
    affinity/round-robin goodput (>= 1 means affinity is no worse while
    winning on warm hits).
    """
    import os

    from accelerate_tpu.loadgen import (
        Phase,
        SoakClock,
        SoakConfig,
        SoakHarness,
        WorkloadConfig,
    )
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.parallel.sharding import unbox_params
    from accelerate_tpu.router import FleetRouter, InProcessReplica
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.serving.telemetry import ServeStats

    partial = partial or _noop_writer("fleet_soak")
    _reset_state()
    model = CausalLM(cfg)
    abstract = unbox_params(
        jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )
        )
    )["params"]
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    keys = jax.random.split(jax.random.PRNGKey(0), len(leaves))

    @jax.jit
    def init_bf16():
        return jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.bfloat16)
            * (0.02 if l.ndim > 1 else 1.0)
            for k, l in zip(keys, leaves)
        ])

    params = init_bf16()
    n_params = count_params(params)

    n_replicas = 4
    prefix_tokens = 3 * block_size  # cohort prefix: 3 full chain blocks
    workload = WorkloadConfig(
        vocab_size=cfg.vocab_size,
        num_cohorts=4,
        prefix_tokens=prefix_tokens,
        cohort_fraction=0.9,
        prompt_tokens_min=2,
        prompt_tokens_median=4,
        prompt_tokens_max=2 * block_size,
        output_tokens_min=2,
        output_tokens_median=6,
        output_tokens_max=16,
        max_total_tokens=cfg.max_seq_len,
    )

    ab_dt = 0.01  # virtual seconds per fleet step (one step per replica)
    # analytic FLEET seat throughput in requests per virtual second
    vcap = n_replicas * max_slots / (
        (2 + workload.output_tokens_median) * ab_dt
    )
    # unit sized so one policy arm offers ~= target_requests:
    # warmup(0.25c, u) + soak(0.55c, 2u) = 1.35 * c * u requests
    u = max(0.2, target_requests / (1.35 * vcap))
    policy_phases = (
        Phase("warmup", "warmup", u, 0.25 * vcap),
        Phase("soak", "soak", 2 * u, 0.55 * vcap),
    )
    kill_phases = (
        Phase("warmup", "warmup", u, 0.25 * vcap),
        Phase("soak", "soak", u, 0.55 * vcap),
        Phase("fault", "fault", u, 0.55 * vcap),
        Phase("recovery", "recovery", 2 * u, 0.55 * vcap),
    )

    max_prompt = prefix_tokens + workload.prompt_tokens_max
    prime_lens = []
    m = 2
    while m < 2 * max_prompt and m + 2 <= cfg.max_seq_len:
        prime_lens.append(min(m, max_prompt))
        m *= 2

    def _prime(eng):
        """Compile every prefill bucket the trace can hit plus the one
        decode program BEFORE the arm starts, then reset stats and the
        prefix index — arms measure placement on cold caches, and the
        zero-retrace delta is taken from this point."""
        rng_p = np.random.default_rng(seed + 99)
        for n in prime_lens:
            eng.add_request(
                rng_p.integers(1, workload.vocab_size, size=n).tolist(),
                max_new_tokens=2,
            )
        while eng.has_work:
            eng.step()
        eng.set_prefix_cache(False)
        eng.set_prefix_cache(True, "fleet-bench")
        eng.stats = ServeStats()

    def _arm(name, policy, phases, fault=""):
        clock = SoakClock()
        engines = []
        for i in range(n_replicas):
            eng = ServingEngine(
                model, params, max_slots=max_slots,
                block_size=block_size, now=clock,
                prefix_cache=True, model_fingerprint="fleet-bench",
            )
            _prime(eng)
            engines.append(eng)
        primed = [dict(e.trace_counts()) for e in engines]
        router = FleetRouter(
            [InProcessReplica(f"r{i}", e) for i, e in enumerate(engines)],
            policy=policy, now=clock,
        )
        arm_path = (
            os.path.join(
                os.path.dirname(partial.path),
                f"soak-report-fleet-{name}.json",
            ) if partial.path else None
        )
        arm_cfg = SoakConfig(
            workload=workload, phases=phases, seed=seed + 17,
            step_dt_s=ab_dt, fault_specs=fault, report_path=arm_path,
            drain_grace_s=60.0, label=f"fleet_soak_{name}",
        )
        rep = SoakHarness(router, arm_cfg, clock=clock).run()
        cache = [e.prefix_cache.stats() for e in engines]
        out = {
            "report": rep,
            "goodput": rep["headline"]["goodput_tokens_per_s_at_slo"],
            "warm_lookups": sum(c["lookups"] for c in cache),
            "warm_hits": sum(c["hits"] for c in cache),
            "prefill_tokens_saved": sum(
                c["prefill_tokens_saved_total"] for c in cache
            ),
            # per-replica zero-retrace: decode compiles since priming
            "decode_retraces": sum(
                e.trace_counts().get("decode", 0) - p.get("decode", 0)
                for e, p in zip(engines, primed)
            ),
            "router": rep.get("router") or {},
            "report_path": arm_path,
        }
        out["warm_hit_rate"] = (
            out["warm_hits"] / out["warm_lookups"]
            if out["warm_lookups"] else 0.0
        )
        partial.update(
            phase=f"fleet_{name}",
            metric="fleet_goodput_tokens_per_s_at_slo",
            value=out["goodput"], unit="tokens/s",
            extra={"warm_hit_rate": round(out["warm_hit_rate"], 4)},
        )
        return out

    t0 = time.perf_counter()
    arms = {
        name: _arm(name, name, policy_phases)
        for name in ("round_robin", "least_loaded", "prefix_affinity")
    }
    kill = _arm(
        "replica_kill", "prefix_affinity", kill_phases,
        fault="replica_kill@0:replica=1",
    )
    fleet_wall_s = time.perf_counter() - t0

    rr, affinity = arms["round_robin"], arms["prefix_affinity"]
    fault_rep = kill["report"]["fault"]

    def _arm_extra(a):
        return {
            "goodput_tokens_per_s_at_slo": (
                round(a["goodput"], 1) if a["goodput"] is not None else None
            ),
            "warm_hit_rate": round(a["warm_hit_rate"], 4),
            "warm_hits": a["warm_hits"],
            "warm_lookups": a["warm_lookups"],
            "prefill_tokens_saved": a["prefill_tokens_saved"],
            "decode_retraces": a["decode_retraces"],
            "requests_finished": a["report"]["requests_finished"],
            "requests_shed": a["report"]["requests_shed"],
            "routed_by_replica": {
                r["name"]: r["routed"]
                for r in a["router"].get("replicas") or []
            },
        }

    return {
        "metric": "fleet_goodput_tokens_per_s_at_slo",
        "value": round(affinity["goodput"] or 0.0, 1),
        "unit": "tokens/s",
        # acceptance bar: affinity holds goodput while winning warm
        # hits — >= 1 means no-worse than the round-robin baseline
        "vs_baseline": (
            round(affinity["goodput"] / rr["goodput"], 3)
            if affinity["goodput"] and rr["goodput"] else None
        ),
        "extra": {
            "n_replicas": n_replicas,
            "max_slots_per_replica": max_slots,
            "block_size": block_size,
            "cohort_fraction": workload.cohort_fraction,
            "prefix_tokens": prefix_tokens,
            "arms": {name: _arm_extra(a) for name, a in arms.items()},
            "affinity_vs_rr_warm_hit_rate": (
                round(affinity["warm_hit_rate"] - rr["warm_hit_rate"], 4)
            ),
            "affinity_beats_rr_on_warm_hits": (
                affinity["warm_hits"] > rr["warm_hits"]
            ),
            "decode_retraces_all_arms": sum(
                a["decode_retraces"] for a in arms.values()
            ) + kill["decode_retraces"],
            # replica_kill chaos arm: re-route damage + recovery
            "kill_goodput_tokens_per_s_at_slo": (
                round(kill["goodput"], 1)
                if kill["goodput"] is not None else None
            ),
            "kill_requests_requeued": (
                kill["router"].get("requests_requeued")
            ),
            "kill_requests_lost": kill["router"].get("requests_lost"),
            "kill_rerouted_total": kill["router"].get("rerouted_total"),
            "kill_replicas_alive": kill["router"].get("replicas_alive"),
            "kill_sheds_in_window": fault_rep["sheds_in_window"],
            "kill_slo_violations_in_window": (
                fault_rep["slo_violations_in_window"]
            ),
            "kill_recovery_s": fault_rep["recovery_s"],
            "kill_recovered": fault_rep["recovered"],
            "kill_report_path": kill["report_path"],
            "report_paths": {
                name: a["report_path"] for name, a in arms.items()
            },
            "fleet_wall_s": round(fleet_wall_s, 3),
            "virtual_capacity_rps": round(vcap, 1),
            "unit_s": round(u, 3),
            "params": n_params,
            "device": _device_kind(),
        },
    }


def _run_disagg_soak(cfg, max_slots: int, block_size: int,
                     target_requests: int, seed: int,
                     partial: Optional[PartialWriter] = None):
    """Prefill/decode disaggregation A/B (PR 19): the SAME seeded
    bursty long-prompt trace replays against two four-chip fleets on
    the virtual clock —

      colocated — four ``role="colocated"`` replicas (the PR 18 fleet),
      disagg    — two prefill replicas hand finished KV chains to two
                  decode replicas through the router's transfer ledger
                  (``placement="disagg"``, host_buffer plane).

    The headline is the decode-side EXPERIENCE: soak-window p95
    inter-token latency. The run uses the harness's ``step_cost`` hook
    (built for exactly this) to charge compute serialization: a
    replica's step that issued prefill work while it was HOSTING seated
    decodes stretches by the padded prefill bucket — on a colocated
    engine a giant prompt's ingestion holds that replica's whole decode
    batch for one long step. Replicas are parallel chips, so the fleet
    step charges the slowest such replica; a prefill-role replica's
    ingestion overlaps the decode pool's stepping (it hosts no decode
    seats — the disaggregation claim), and a decode-role replica never
    runs a prefill program at all, so the disagg decode pool steps at
    the flat quantum through the burst. ``vs_baseline`` is
    colocated-p95-ITL / disagg-p95-ITL (> 1 means the split strictly
    wins), and the record also reports the goodput@SLO ratio (>= 1
    means disaggregation pays for itself on the same four chips), the
    plane's block dedup ratio (warm cohort prefixes ride the decode
    pool's CACHED index instead of the wire), and the per-pool
    zero-retrace contract: decode replicas compile ZERO prefill or
    decode programs after priming.

    A third arm re-runs the disagg topology with
    ``transfer_stall@0:secs=1`` wedging the transfer plane mid-soak:
    damage must be bounded to requests awaiting hand-off (none lost,
    re-queued or delivered after the stall lifts) with measured
    recovery. A closed-loop probe asserts greedy outputs across the
    hand-off are BITWISE the colocated engine's.
    """
    import os

    from accelerate_tpu.loadgen import (
        Phase,
        SoakClock,
        SoakConfig,
        SoakHarness,
        WorkloadConfig,
    )
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.parallel.sharding import unbox_params
    from accelerate_tpu.router import FleetRouter, InProcessReplica
    from accelerate_tpu.serving import ServingEngine, TransferPlane
    from accelerate_tpu.serving.telemetry import ServeStats

    partial = partial or _noop_writer("disagg_soak")
    _reset_state()
    model = CausalLM(cfg)
    abstract = unbox_params(
        jax.eval_shape(
            lambda: model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
            )
        )
    )["params"]
    leaves, treedef = jax.tree_util.tree_flatten(abstract)
    keys = jax.random.split(jax.random.PRNGKey(0), len(leaves))

    @jax.jit
    def init_bf16():
        return jax.tree_util.tree_unflatten(treedef, [
            jax.random.normal(k, l.shape, jnp.bfloat16)
            * (0.02 if l.ndim > 1 else 1.0)
            for k, l in zip(keys, leaves)
        ])

    params = init_bf16()
    n_params = count_params(params)

    n_prefill = n_decode = 2
    n_replicas = n_prefill + n_decode
    prefix_tokens = 3 * block_size   # cohort prefix: 3 full chain blocks
    long_tokens = 8 * block_size     # the burst giants' prompt body
    workload = WorkloadConfig(
        vocab_size=cfg.vocab_size,
        num_cohorts=4,
        prefix_tokens=prefix_tokens,
        cohort_fraction=0.8,
        prompt_tokens_min=2,
        prompt_tokens_median=4,
        prompt_tokens_max=2 * block_size,
        long_prompt_fraction=0.25,
        long_prompt_tokens=long_tokens,
        output_tokens_min=2,
        output_tokens_median=6,
        output_tokens_max=16,
        max_total_tokens=cfg.max_seq_len,
    )

    ab_dt = 0.01  # virtual seconds per fleet step (one step per replica)
    # offered load sized to the DISAGG bottleneck — the two-replica
    # decode pool. The colocated fleet spends the same four chips, so a
    # goodput ratio >= 1 means the split pays for itself at this rate.
    vcap = n_decode * max_slots / (
        (2 + workload.output_tokens_median) * ab_dt
    )
    u = max(0.2, target_requests / (1.35 * vcap))
    ab_phases = (
        Phase("warmup", "warmup", u, 0.25 * vcap),
        Phase("burst", "soak", 2 * u, 0.55 * vcap),
    )
    stall_phases = (
        Phase("warmup", "warmup", u, 0.25 * vcap),
        Phase("soak", "soak", u, 0.55 * vcap),
        Phase("fault", "fault", u, 0.55 * vcap),
        Phase("recovery", "recovery", 2 * u, 0.55 * vcap),
    )

    max_prompt = prefix_tokens + long_tokens
    prime_lens = []
    m = 2
    while m < 2 * max_prompt and m + 2 <= cfg.max_seq_len:
        prime_lens.append(min(m, max_prompt))
        m *= 2

    def _prime(eng):
        """Compile every prefill bucket plus the decode program BEFORE
        the arm starts (every replica primes COLOCATED — roles are
        assigned after), then reset stats and the prefix index; the
        zero-retrace deltas are taken from this point."""
        rng_p = np.random.default_rng(seed + 99)
        for n in prime_lens:
            eng.add_request(
                rng_p.integers(1, workload.vocab_size, size=n).tolist(),
                max_new_tokens=2,
            )
        while eng.has_work:
            eng.step()
        eng.set_prefix_cache(False)
        eng.set_prefix_cache(True, "disagg-bench")
        eng.stats = ServeStats()

    def _arm(name, phases, disagg, fault=""):
        clock = SoakClock()
        plane = TransferPlane("host_buffer", now=clock) if disagg else None
        roles = (
            ["prefill"] * n_prefill + ["decode"] * n_decode
            if disagg else ["colocated"] * n_replicas
        )
        engines = []
        for role in roles:
            eng = ServingEngine(
                model, params, max_slots=max_slots,
                block_size=block_size, now=clock,
                prefix_cache=True, model_fingerprint="disagg-bench",
                transfer_plane=plane,
            )
            _prime(eng)
            if role != "colocated":
                eng.set_role(role)
            engines.append(eng)
        primed = [dict(e.trace_counts()) for e in engines]

        # compute-serialization cost model: a replica whose step issued
        # prefill work while it entered the step with seated decodes
        # stalls those decodes for the prefill's duration (the padded
        # bucket); parallel replicas overlap, so the fleet step charges
        # the slowest decode-hosting one. Seed the counters at the
        # post-priming totals so priming's buckets are not billed.
        # a full giant bucket (16 blocks) bills 8 decode quanta — far
        # below its real compute ratio vs a 2-row decode step, so the
        # colocated arm is charged conservatively
        prefill_cost = ab_dt / (2 * block_size)  # virtual s per token
        issued_at = {id(e): e.prefill_bucket_tokens_total for e in engines}
        hosted = {id(e): 0 for e in engines}

        def _step_cost(_router):
            surcharge = 0.0
            for e in engines:
                issued = e.prefill_bucket_tokens_total - issued_at[id(e)]
                issued_at[id(e)] = e.prefill_bucket_tokens_total
                if issued and hosted[id(e)]:
                    surcharge = max(surcharge, issued * prefill_cost)
                hosted[id(e)] = sum(
                    1 for s in e.scheduler.slots
                    if s.busy and not s.done and not s.mid_prefill
                )
            return ab_dt + surcharge

        router = FleetRouter(
            [
                InProcessReplica(f"{role[0]}{i}", eng)
                for i, (role, eng) in enumerate(zip(roles, engines))
            ],
            policy="prefix_affinity", now=clock,
            placement="disagg" if disagg else "colocated",
            transfer_plane=plane,
        )
        arm_path = (
            os.path.join(
                os.path.dirname(partial.path),
                f"soak-report-disagg-{name}.json",
            ) if partial.path else None
        )
        arm_cfg = SoakConfig(
            workload=workload, phases=phases, seed=seed + 17,
            step_dt_s=ab_dt, step_cost=_step_cost, fault_specs=fault,
            report_path=arm_path, drain_grace_s=60.0,
            label=f"disagg_soak_{name}",
        )
        rep = SoakHarness(router, arm_cfg, clock=clock).run()
        out = {
            "report": rep,
            "goodput": rep["headline"]["goodput_tokens_per_s_at_slo"],
            "p95_itl_s": rep["headline"].get("soak_p95_itl_s"),
            # per-pool zero-retrace: programs compiled since priming
            "decode_retraces": sum(
                e.trace_counts().get("decode", 0) - p.get("decode", 0)
                for e, p in zip(engines, primed)
            ),
            "decode_pool_prefills": sum(
                e.trace_counts().get("prefill", 0) - p.get("prefill", 0)
                for e, p, role in zip(engines, primed, roles)
                if role == "decode"
            ),
            "transfer": rep.get("transfer") or {},
            "router": rep.get("router") or {},
            "report_path": arm_path,
        }
        partial.update(
            phase=f"disagg_{name}",
            metric="soak_p95_itl_s",
            value=out["p95_itl_s"], unit="s",
            extra={"goodput_tokens_per_s_at_slo": out["goodput"]},
        )
        return out

    def _bitwise_probe():
        """Closed-loop greedy determinism check: the same prompts
        through a colocated engine and a hand-pumped prefill->decode
        pair must produce IDENTICAL results."""
        rng_b = np.random.default_rng(seed + 7)
        prompts = [
            rng_b.integers(1, cfg.vocab_size, size=n).tolist()
            for n in (block_size + 4, 2 * block_size,
                      3 * block_size + 1, 5)
        ]

        def _mk(role="colocated", plane=None):
            return ServingEngine(
                model, params, max_slots=max_slots,
                block_size=block_size, prefix_cache=True,
                model_fingerprint="disagg-bench", role=role,
                transfer_plane=plane,
            )

        base_eng = _mk()
        rids = [
            base_eng.add_request(p, max_new_tokens=8, request_id=f"bw{i}")
            for i, p in enumerate(prompts)
        ]
        while base_eng.has_work:
            base_eng.step()
        base = {r: base_eng.result(r) for r in rids}
        plane = TransferPlane("host_buffer")
        pre = _mk("prefill", plane)
        dec = _mk("decode", plane)
        for i, p in enumerate(prompts):
            pre.add_request(p, max_new_tokens=8, request_id=f"bw{i}")
        for _ in range(500):
            if not (pre.has_work or dec.has_work):
                break
            pre.step()
            for mani in pre.pop_manifests():
                dec.acquire(mani)
            dec.step()
        return {r: dec.result(r) for r in rids} == base

    t0 = time.perf_counter()
    colo = _arm("colocated", ab_phases, disagg=False)
    dis = _arm("disagg", ab_phases, disagg=True)
    stall = _arm(
        "transfer_stall", stall_phases, disagg=True,
        fault="transfer_stall@0:secs=1",
    )
    bitwise = _bitwise_probe()
    disagg_wall_s = time.perf_counter() - t0

    fault_rep = stall["report"]["fault"]
    plane_sum = (dis["transfer"].get("plane") or {})
    colo_itl, dis_itl = colo["p95_itl_s"], dis["p95_itl_s"]

    def _arm_extra(a):
        return {
            "goodput_tokens_per_s_at_slo": (
                round(a["goodput"], 1) if a["goodput"] is not None else None
            ),
            "soak_p95_itl_s": (
                round(a["p95_itl_s"], 5)
                if a["p95_itl_s"] is not None else None
            ),
            "decode_retraces": a["decode_retraces"],
            "decode_pool_prefills": a["decode_pool_prefills"],
            "requests_finished": a["report"]["requests_finished"],
            "requests_shed": a["report"]["requests_shed"],
            "transfers_delivered": a["transfer"].get("delivered_total"),
            "transfers_dropped": a["transfer"].get("dropped_total"),
        }

    return {
        "metric": "disagg_soak_p95_itl_s",
        "value": round(dis_itl, 5) if dis_itl is not None else None,
        "unit": "s",
        # acceptance bar: the decode pool's burst-window p95 ITL is
        # STRICTLY better than colocated — > 1 means disagg wins
        "vs_baseline": (
            round(colo_itl / dis_itl, 3)
            if colo_itl and dis_itl else None
        ),
        "extra": {
            "n_prefill": n_prefill,
            "n_decode": n_decode,
            "max_slots_per_replica": max_slots,
            "block_size": block_size,
            "long_prompt_fraction": workload.long_prompt_fraction,
            "long_prompt_tokens": long_tokens,
            "colocated_p95_itl_s": (
                round(colo_itl, 5) if colo_itl is not None else None
            ),
            # same four chips: >= 1 means the split costs no goodput
            "goodput_ratio_disagg_vs_colocated": (
                round(dis["goodput"] / colo["goodput"], 3)
                if dis["goodput"] and colo["goodput"] else None
            ),
            "dedup_ratio": plane_sum.get("dedup_ratio"),
            "blocks_moved_total": plane_sum.get("blocks_moved_total"),
            "blocks_deduped_total": plane_sum.get("blocks_deduped_total"),
            "bytes_moved_total": plane_sum.get("bytes_moved_total"),
            "transfer_ms_p95": plane_sum.get("transfer_ms_p95"),
            "bitwise_identical": bitwise,
            "arms": {
                "colocated": _arm_extra(colo),
                "disagg": _arm_extra(dis),
                "transfer_stall": _arm_extra(stall),
            },
            # transfer_stall chaos arm: damage bounded to the hand-off
            "stall_requests_lost": stall["router"].get("requests_lost"),
            "stall_requests_requeued": (
                stall["router"].get("requests_requeued")
            ),
            "stall_transfer_recovery_s": (
                stall["transfer"].get("stall_recovery_s")
            ),
            "stall_sheds_in_window": fault_rep["sheds_in_window"],
            "stall_slo_violations_in_window": (
                fault_rep["slo_violations_in_window"]
            ),
            "stall_recovery_s": fault_rep["recovery_s"],
            "stall_recovered": fault_rep["recovered"],
            "stall_report_path": stall["report_path"],
            "report_paths": {
                "colocated": colo["report_path"],
                "disagg": dis["report_path"],
            },
            "disagg_wall_s": round(disagg_wall_s, 3),
            "virtual_capacity_rps": round(vcap, 1),
            "unit_s": round(u, 3),
            "params": n_params,
            "device": _device_kind(),
        },
    }


def _run_overhead(cfg, batch_size: int, seq: int, iters: int, warmup: int,
                  partial: Optional[PartialWriter] = None):
    """Telemetry+diagnostics ON-vs-OFF A/B: the harness proving ITSELF
    cheap. The same train loop runs twice over the same compiled shapes —
    once with the collector disabled (no per-step host sync), once with
    telemetry AND the full diagnostics stack (goodput fold, anomaly
    baselines, flight ring) — and the record reports
    ``harness_overhead_pct``, the median-step-time delta. Medians, not
    means: one GC pause or host scheduler hiccup must not fake an
    overhead regression. ``vs_baseline`` is 2 / pct against the <2%
    budget (>= 1 means the harness is within budget).

    The two modes are measured in INTERLEAVED short chunks, not two
    sequential phases: on a busy host the machine itself drifts
    (allocator state, thermal throttle, background load) over the
    seconds a phase takes, and a sequential A/B silently charges that
    drift to whichever mode ran second. Alternating chunks puts both
    modes through the same drift.

    The ON mode runs with ``anomaly_sample_every=8``: the median/MAD
    fold is the one non-O(1) piece of ``DiagnosticsManager.observe``,
    and sampling it is exactly how a production loop with
    sub-millisecond steps is expected to bound it. The record reports
    the setting so the measurement is honest about its configuration.
    """
    import statistics

    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.diagnostics import DiagnosticsConfig
    from accelerate_tpu.models import CausalLM, count_params

    partial = partial or _noop_writer("overhead")
    _reset_state()
    setups: dict[str, dict] = {}
    n_params = 0
    for mode in ("off", "on"):
        model = CausalLM(cfg)
        acc = Accelerator(
            mixed_precision="bf16",
            telemetry=(mode == "on"),
            diagnostics=(
                DiagnosticsConfig(anomaly_sample_every=8)
                if mode == "on" else None
            ),
        )
        params = acc.prepare(
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))[
                "params"
            ]
        )
        n_params = count_params(params)
        opt = acc.prepare(optax.adamw(3e-4))
        carry = acc.init_carry(params, opt)
        step = acc.unified_step(CausalLM.loss_fn(model), max_grad_norm=1.0)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(
                0, cfg.vocab_size, (batch_size, seq)
            ),
            jnp.int32,
        )
        batch = {"input_ids": ids}
        for _ in range(warmup):
            carry, metrics = step(carry, batch)
        np.asarray(metrics["loss"])
        setups[mode] = {
            "acc": acc, "carry": carry, "step": step, "batch": batch,
            "times": [],
        }
        partial.update(
            phase=f"{mode}_warm", iters_measured=0,
            metric="harness_overhead_pct",
        )

    # short rounds: more pairs to median over, and a tighter time window
    # per pair (less host drift inside each one)
    chunk = max(1, min(3, iters // 6))
    measured = 0
    round_deltas: list[float] = []
    while measured < iters:
        n = min(chunk, iters - measured)
        round_med = {}
        for mode in ("off", "on"):
            s = setups[mode]
            ts = []
            for _ in range(n):
                t0 = time.perf_counter()
                s["carry"], metrics = s["step"](s["carry"], s["batch"])
                np.asarray(metrics["loss"])  # same sync in both modes
                ts.append(time.perf_counter() - t0)
            s["times"].extend(ts)
            round_med[mode] = statistics.median(ts)
        # pair the two chunks of THIS round: they sit in the same ~few-
        # second window, so whatever the host was doing hits both
        round_deltas.append(round_med["on"] - round_med["off"])
        measured += n
        partial.update(
            phase="measuring", iters_measured=measured,
            metric="harness_overhead_pct",
        )

    medians = {m: statistics.median(s["times"]) for m, s in setups.items()}
    acc_on = setups["on"]["acc"]
    records_on = sum(
        1 for r in acc_on.telemetry.records if r.get("kind") == "step"
    )
    sample_every = (
        acc_on.telemetry.diagnostics.config.anomaly_sample_every
        if acc_on.telemetry.diagnostics is not None else None
    )
    for s in setups.values():
        s["acc"].telemetry.close()

    # the median of per-round deltas, not the delta of global medians:
    # each delta already has that round's host conditions subtracted out
    pct = statistics.median(round_deltas) / medians["off"] * 100.0
    return {
        "metric": "harness_overhead_pct",
        "value": round(pct, 2),
        "unit": "%",
        # the harness's own acceptance bar: overhead must stay under 2%
        "vs_baseline": round(2.0 / pct, 3) if pct > 0 else None,
        "extra": {
            "median_step_on_s": round(medians["on"], 6),
            "median_step_off_s": round(medians["off"], 6),
            "iters": iters,
            "step_records_emitted_on": records_on,
            "anomaly_sample_every": sample_every,
            "params": n_params,
            "device": _device_kind(),
            "batch": batch_size, "seq": seq,
        },
    }


def _run_lora(cfg, batch_size: int, seq: int, iters: int, warmup: int,
              partial: Optional[PartialWriter] = None):
    """Multi-tenant adapter economics: adapter-only vs full fine-tune,
    plus the serving-side retrace check.

    Phase 1/2 run the SAME shapes through ``unified_step`` twice — once
    differentiating the full parameter tree (classic fine-tune), once
    differentiating ONLY a rank-8 LoRA adapter over an int8-quantized
    frozen base (QLoRA) — and report the optimizer-visible param bytes
    and step wall time of each. Phase 3 serves a mixed multi-adapter
    trace through a warm ServingEngine and asserts the decode program
    compiled ONCE: adding tenants costs zero retraces (adapters are
    traced data, not trace constants).

    ``vs_baseline`` is full_param_bytes / adapter_param_bytes — how many
    times smaller the optimizer payload is (the multi-tenant headline:
    that factor is also how many MORE tenants fit in the same optimizer
    HBM).
    """
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.adapters import (
        AdapterRegistry,
        LoraConfig,
        adapter_num_bytes,
        init_adapter,
        lora_loss_fn,
    )
    from accelerate_tpu.models import CausalLM, count_params
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.utils.quantization import (
        QuantizationConfig,
        quantize_params,
    )

    partial = partial or _noop_writer("lora")
    lcfg = LoraConfig(rank=8, alpha=16.0, target_modules=("q_proj", "v_proj"))
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (batch_size, seq)),
        jnp.int32,
    )
    batch = {"input_ids": ids}

    def timed_loop(step, carry):
        for _ in range(warmup):
            carry, metrics = step(carry, batch)
        np.asarray(metrics["loss"])
        t0 = time.perf_counter()
        for _ in range(iters):
            carry, metrics = step(carry, batch)
        np.asarray(metrics["loss"])
        return (time.perf_counter() - t0) / iters

    # phase 1: full fine-tune — every base param in the optimizer
    _reset_state()
    model = CausalLM(cfg)
    acc = Accelerator(mixed_precision="bf16")
    params = acc.prepare(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))["params"]
    )
    n_params = count_params(params)
    full_bytes = adapter_num_bytes(params)
    opt = acc.prepare(optax.adamw(3e-4))
    carry = acc.init_carry(params, opt)
    full_step_s = timed_loop(
        acc.unified_step(CausalLM.loss_fn(model), max_grad_norm=1.0), carry
    )
    partial.update(phase="full_done", iters_measured=iters)

    # phase 2: adapter-only over an int8 frozen base (QLoRA). The adapter
    # tree must be the LAST tree prepared before init_carry — prepare()
    # re-infers shardings per call and unified_step pins the carry to the
    # most recent set.
    _reset_state()
    acc = Accelerator(mixed_precision="bf16")
    base = acc.prepare(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))["params"]
    )
    qbase = quantize_params(base, QuantizationConfig(load_in_8bit=True))
    adapter = acc.prepare(init_adapter(jax.random.PRNGKey(1), cfg, lcfg))
    adapter_bytes = adapter_num_bytes(adapter)
    opt = acc.prepare(optax.adamw(3e-4))
    carry = acc.init_carry(adapter, opt)
    lora_step_s = timed_loop(
        acc.unified_step(
            lora_loss_fn(model, qbase, lcfg, compute_dtype=jnp.bfloat16),
            max_grad_norm=1.0,
        ),
        carry,
    )
    partial.update(phase="adapter_done", iters_measured=iters)

    # phase 3: multi- vs single-adapter decode retraces on a warm engine
    _reset_state()
    registry = AdapterRegistry(
        cfg, capacity=4, max_rank=lcfg.rank,
        target_modules=lcfg.target_modules,
    )
    engine = ServingEngine(
        model, base, max_slots=4, block_size=16, adapters=registry
    )
    rng = np.random.default_rng(0)

    def serve(names):
        for i, name in enumerate(names):
            prompt = rng.integers(0, cfg.vocab_size, 4 + i).astype(np.int32)
            engine.add_request(prompt.tolist(), max_new_tokens=4, adapter=name)
        for _ in engine.stream():
            pass

    registry.load("t0", init_adapter(jax.random.PRNGKey(2), cfg, lcfg), lcfg)
    serve(["t0", "t0"])  # warmup: compiles prefill buckets + decode
    warm = engine.trace_counts()["decode"]
    serve(["t0", "t0", None])
    single_retraces = engine.trace_counts()["decode"] - warm
    for i in (1, 2):
        registry.load(
            f"t{i}", init_adapter(jax.random.PRNGKey(2 + i), cfg, lcfg), lcfg
        )
    serve(["t0", "t1", "t2", None])  # 3 tenants + base in ONE batch
    multi_retraces = engine.trace_counts()["decode"] - warm - single_retraces
    partial.update(phase="serve_done", iters_measured=iters)

    bytes_ratio = full_bytes / max(adapter_bytes, 1)
    return {
        "metric": "lora_param_bytes_ratio",
        "value": round(bytes_ratio, 1),
        "unit": "x",
        # >= 1 means the adapter payload really is smaller — the
        # acceptance bar upstream is the checkpoint-size assertion; here
        # the ratio IS the headline
        "vs_baseline": round(bytes_ratio, 1),
        "extra": {
            "full_step_s": round(full_step_s, 4),
            "lora_step_s": round(lora_step_s, 4),
            "step_speedup": round(full_step_s / max(lora_step_s, 1e-9), 3),
            "full_param_bytes": full_bytes,
            "adapter_param_bytes": adapter_bytes,
            "adapter_rank": lcfg.rank,
            "single_adapter_decode_retraces": single_retraces,
            "multi_adapter_decode_retraces": multi_retraces,
            "params": n_params,
            "device": _device_kind(),
            "batch": batch_size, "seq": seq, "iters": iters,
        },
    }


def _compile_probe():
    """Arm the process-wide CompileMonitor; the returned closure yields
    the compile cost accrued since (JSON-ready). ``compile_time_s`` is
    XLA backend-compile seconds — it does NOT accrue on a persistent-
    cache hit, so warm-cache runs show the cache working: hits > 0,
    compile_time_s ~ 0, and the headline step time is pure steady-state."""
    from accelerate_tpu.compilation import (
        get_compile_monitor,
        persistent_cache_dir,
    )

    mon = get_compile_monitor()
    before = mon.snapshot()

    def done() -> dict:
        delta = mon.delta(before)
        return {
            "compile_time_s": round(
                float(delta.get("compile_time_s", 0.0)), 3
            ),
            "persistent_cache_hits": int(
                delta.get("persistent_cache_hits", 0)
            ),
            "persistent_cache_misses": int(
                delta.get("persistent_cache_misses", 0)
            ),
            "compile_cache_dir": persistent_cache_dir(),
        }

    return done


def _goodput_fields(wall_s, productive_s, compile_s=0.0,
                    checkpoint_s=0.0) -> dict:
    """Variant-level goodput line: fold the quantities the bench already
    measures through the production GoodputAccounting (synthetic `now`
    injection — live per-step telemetry would add the per-step
    block_until_ready the aggregate-timing design deliberately avoids).
    `idle` is the unaccounted remainder: model init, prepare, warmup
    steps, teardown."""
    from accelerate_tpu.diagnostics.goodput import (
        BADPUT_BUCKETS,
        GoodputAccounting,
    )

    wall_s = max(float(wall_s), 1e-9)
    g = GoodputAccounting(window_s=wall_s, now=0.0)
    g.add("productive", float(productive_s), now=wall_s)
    g.add("compile", float(compile_s), now=wall_s)
    g.add("checkpoint", float(checkpoint_s), now=wall_s)
    snap = g.snapshot(now=wall_s)
    return {
        "goodput_pct": round(snap["goodput_pct"], 1),
        **{
            f"badput_{b}_s": round(snap["buckets"][b], 3)
            for b in BADPUT_BUCKETS
        },
    }


def result_line(variant, partial: Optional[PartialWriter] = None) -> dict:
    """Measure one registry :class:`~.registry.Variant` and build its
    emitted record. ``extra.variant_wall_s`` is the whole-variant wall
    cost (prepare + compile + warmup + timed loop) — the number the
    scheduler persists as next round's estimate."""
    name, kind = variant.name, variant.kind
    cfg, batch_size, seq, iters, warmup = variant.args[:5]
    optimizer = variant.args[5] if len(variant.args) > 5 else "adamw"
    # compile attribution covers the WHOLE variant (prepare + warmup +
    # timed loop) — any jit in the process accrues, so the emitted line
    # separates total compile cost from the steady-state measurement
    wall_t0 = time.perf_counter()
    probe = _compile_probe()
    checkpoint_s = 0.0
    if kind == "decode_load":
        rec = _run_decode_load(cfg, partial=partial)
        rec["extra"].update(probe())
        # a pure load/restore variant trains nothing: goodput is honestly 0
        productive_s = 0.0
    elif kind == "ckpt":
        rec = _run_ckpt(cfg, batch_size, seq, iters, warmup, partial=partial)
        rec["extra"].update(probe())
        extra = rec["extra"]
        productive_s = sum(
            extra[m]["quiet_step_s"] * iters for m in ("sync", "async")
        )
        checkpoint_s = sum(
            extra[m]["blocked_s"] * extra[m]["saves"] for m in ("sync", "async")
        )
    elif kind == "accum":
        rec = _run_accum(cfg, batch_size, seq, iters, warmup, partial=partial)
        rec["extra"].update(probe())
        extra = rec["extra"]
        productive_s = sum(
            extra[m]["opt_step_s"] * extra[m]["opt_steps_timed"]
            for m in ("fused", "unfused")
        )
    elif kind == "overhead":
        rec = _run_overhead(
            cfg, batch_size, seq, iters, warmup, partial=partial
        )
        rec["extra"].update(probe())
        # both A/B loops are real measured steps
        productive_s = (
            rec["extra"]["median_step_on_s"]
            + rec["extra"]["median_step_off_s"]
        ) * iters
    elif kind == "serve":
        max_slots, block_size, n_requests, seed = batch_size, seq, iters, warmup
        rec = _run_serve(
            cfg, max_slots, block_size, n_requests, seed, partial=partial
        )
        rec["extra"].update(probe())
        # the engine pass, the fixed-batch baseline, AND the
        # observability A/B replays are all real measured generation
        productive_s = (
            rec["extra"]["engine_wall_s"]
            + rec["extra"]["baseline_wall_s"]
            + rec["extra"]["obs_ab_wall_s"]
        )
    elif kind == "serve_soak":
        max_slots, block_size, n_requests, seed = batch_size, seq, iters, warmup
        rec = _run_serve_soak(
            cfg, max_slots, block_size, n_requests, seed, partial=partial
        )
        rec["extra"].update(probe())
        # the whole open-loop program plus its closed-loop calibration
        # probe is real measured generation under load
        productive_s = (
            rec["extra"]["soak_wall_s"] + rec["extra"]["calib_wall_s"]
        )
    elif kind == "fleet_soak":
        max_slots, block_size, n_requests, seed = batch_size, seq, iters, warmup
        rec = _run_fleet_soak(
            cfg, max_slots, block_size, n_requests, seed, partial=partial
        )
        rec["extra"].update(probe())
        productive_s = rec["extra"]["fleet_wall_s"]
    elif kind == "disagg_soak":
        max_slots, block_size, n_requests, seed = batch_size, seq, iters, warmup
        rec = _run_disagg_soak(
            cfg, max_slots, block_size, n_requests, seed, partial=partial
        )
        rec["extra"].update(probe())
        productive_s = rec["extra"]["disagg_wall_s"]
    elif kind == "lora":
        rec = _run_lora(cfg, batch_size, seq, iters, warmup, partial=partial)
        rec["extra"].update(probe())
        # both fine-tune loops are real measured training steps; the
        # serving phase is a correctness check, not throughput
        productive_s = (
            rec["extra"]["full_step_s"] + rec["extra"]["lora_step_s"]
        ) * iters
    elif kind == "decode":
        prompt_len, new_tokens, reps = seq, iters, warmup
        s_token, n_params = _run_decode(
            cfg, batch_size, prompt_len, new_tokens, reps, partial=partial
        )
        productive_s = s_token * new_tokens * reps
        rec = {
            "metric": "generate_seconds_per_token",
            "value": round(s_token, 4),
            "unit": "s/token",
            # reference headline: GPT-J-6B fp16 at 0.05 s/token
            # (benchmarks/README.md:31); >= 1 beats it
            "vs_baseline": round(0.05 / s_token, 3),
            "extra": {
                "params": n_params,
                "device": _device_kind(),
                "batch": batch_size, "prompt_len": prompt_len,
                "new_tokens": new_tokens,
                **probe(),
            },
        }
    else:
        fused_ab = bool(variant.args[6]) if len(variant.args) > 6 else False
        tps, step_time, n_params = _run(
            cfg, batch_size, seq, iters, warmup, optimizer, partial=partial
        )
        mfu = _mfu(cfg, n_params, seq, tps)
        productive_s = step_time * iters
        ab_extra: dict = {}
        if fused_ab:
            # second pass of the A/B axis: same shapes through the Pallas
            # prologue + fused_adamw epilogue. The headline stays the
            # faster of the two passes — on TPU that is the fused step,
            # on CPU the interpret-mode kernels lose and the unfused
            # number stands (the A/B delta is still the evidence).
            f_tps, f_step, _ = _run(
                cfg, batch_size, seq, iters, warmup, optimizer,
                partial=None, fused=True,
            )
            f_mfu = _mfu(cfg, n_params, seq, f_tps)
            productive_s += f_step * iters
            ab_extra = {
                "unfused": {"step_time_s": round(step_time, 4),
                            "tokens_per_sec_per_chip": round(tps, 1),
                            "mfu": round(mfu, 4)},
                "fused": {"step_time_s": round(f_step, 4),
                          "tokens_per_sec_per_chip": round(f_tps, 1),
                          "mfu": round(f_mfu, 4)},
                "fused_speedup": round(step_time / f_step, 3),
                "headline_mode": "fused" if f_step <= step_time else "unfused",
            }
            if f_step <= step_time:
                tps, step_time, mfu = f_tps, f_step, f_mfu
        rec = {
            "metric": f"train_tokens_per_sec_per_chip_{name}"
            if name != "dense" else "train_tokens_per_sec_per_chip",
            "value": round(tps, 1),
            "unit": "tokens/s/chip",
            "vs_baseline": round(mfu / 0.60, 4),
            "extra": {
                "step_time_s": round(step_time, 4),
                "mfu": round(mfu, 4),
                "params": n_params,
                "device": _device_kind(),
                "batch": batch_size, "seq": seq,
                **ab_extra,
                **probe(),
            },
        }
    wall_s = time.perf_counter() - wall_t0
    rec["extra"]["variant_wall_s"] = round(wall_s, 2)
    rec["extra"].update(
        _goodput_fields(
            wall_s=wall_s,
            productive_s=productive_s,
            compile_s=rec["extra"].get("compile_time_s", 0.0),
            checkpoint_s=checkpoint_s,
        )
    )
    return rec
