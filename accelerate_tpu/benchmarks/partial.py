"""Partial-result streaming between a bench child and the scheduler.

The r05 failure mode was binary: a variant either printed its one final
JSON line or — when the driver's wall clock closed first — contributed
nothing at all. The fix is a tmp **partial-result file** per variant:
the measurement loops write a small JSON snapshot after warmup and every
N measured iters (tmp file + flush + fsync + ``os.replace``, so a
SIGKILL can never leave a torn read), and the parent, after killing a
child at its budget, turns the last snapshot into a
``{"partial": true, "iters_measured": k}`` record. A budget kill now
costs precision, never the measurement.
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

#: directory the child writes ``partial_<variant>.json`` files into
ENV_PARTIAL_DIR = "ACCELERATE_TPU_BENCH_PARTIAL_DIR"
#: override the flush cadence (measured iters between fsync'd snapshots)
ENV_PARTIAL_EVERY = "ACCELERATE_TPU_BENCH_PARTIAL_EVERY"


def partial_path(directory: str, variant: str) -> str:
    return os.path.join(directory, f"partial_{variant}.json")


class PartialWriter:
    """Child-side snapshot writer for one variant.

    ``update`` is called from inside the measurement loop; every write is
    atomic (tmp + fsync + rename) so the parent can read mid-kill. A
    ``None`` path makes every method a no-op — measurement code calls the
    writer unconditionally.
    """

    def __init__(self, path: Optional[str], variant: str,
                 flush_every: Optional[int] = None):
        self.path = path
        self.variant = variant
        if flush_every is None:
            env = os.environ.get(ENV_PARTIAL_EVERY)
            flush_every = int(env) if env else None
        self.flush_every = flush_every
        self._t0 = time.perf_counter()

    def chunk(self, iters: int) -> int:
        """Measured iters between snapshots: the env/ctor override, else
        quarters of the loop (at least 1)."""
        if self.flush_every:
            return max(1, min(self.flush_every, iters))
        return max(1, iters // 4)

    def update(
        self,
        *,
        phase: str,
        iters_measured: int = 0,
        elapsed_s: Optional[float] = None,
        metric: Optional[str] = None,
        value: Optional[float] = None,
        unit: Optional[str] = None,
        extra: Optional[dict] = None,
    ) -> None:
        if self.path is None:
            return
        payload = {
            "variant": self.variant,
            "phase": phase,
            "iters_measured": int(iters_measured),
            "elapsed_s": round(
                time.perf_counter() - self._t0
                if elapsed_s is None else float(elapsed_s), 4,
            ),
            "time_unix": time.time(),
        }
        if metric is not None:
            payload["metric"] = metric
        if value is not None:
            payload["value"] = value
        if unit is not None:
            payload["unit"] = unit
        if extra:
            payload["extra"] = extra
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            # a full/readonly tmp disk must never fail the measurement
            pass


def read_partial(path: str) -> Optional[dict]:
    """Parent-side read of the last committed snapshot (None when the
    child died before its first write, or the file is unreadable)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def partial_record(snapshot: dict, *, reason: str = "budget") -> Optional[dict]:
    """Turn a snapshot into a publishable result record, or None when the
    child never measured anything usable (killed before/within warmup)."""
    if snapshot is None or snapshot.get("value") is None:
        return None
    if not snapshot.get("iters_measured"):
        return None
    rec = {
        "variant": snapshot["variant"],
        "metric": snapshot.get("metric") or f"partial_{snapshot['variant']}",
        "value": snapshot["value"],
        "unit": snapshot.get("unit"),
        "vs_baseline": None,
        "partial": True,
        "partial_reason": reason,
        "iters_measured": int(snapshot["iters_measured"]),
        "extra": dict(snapshot.get("extra") or {}),
    }
    rec["extra"].setdefault("phase", snapshot.get("phase"))
    rec["extra"].setdefault("elapsed_s", snapshot.get("elapsed_s"))
    return rec
