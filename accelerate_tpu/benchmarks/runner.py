"""Parent-side bench orchestration.

The runner walks the registry's process groups in priority order, asks
the :class:`~.scheduler.DeadlineScheduler` for a runtime budget, launches
one child per group (one spawn + one jax init per shared model config —
the serial spawn/recompile tax that ate r05), and turns whatever comes
back into the output stream:

* every landed record is emitted IMMEDIATELY with ``"provisional": true``
  (a driver wall-clock kill can no longer erase completed measurements);
* a child killed at its budget yields the last fsync'd partial snapshot
  as a ``{"partial": true, "iters_measured": k}`` record;
* a variant that never ran emits ``{"skipped": "deadline", ...}``;
* the consolidated final block re-prints folded records with the
  headline LAST, for the parse-the-last-line driver.

``launch``, ``emit``, ``log`` and the scheduler's clock are injectable so
every path above is unit-testable without subprocesses or wall time.
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .partial import partial_path, partial_record, read_partial
from .registry import Variant, VariantRegistry
from .scheduler import DeadlineScheduler, Estimates, skip_record


@dataclass
class LaunchResult:
    returncode: int
    stdout: str
    stderr: str
    timed_out: bool = False


class SubprocessLauncher:
    """Spawn one bench child for a member list: ``python -m
    accelerate_tpu.benchmarks --child <members...> --budget S
    --partial-dir D``. The parent's ``timeout=`` is the hard budget
    enforcement (SIGKILL); the child's ``--budget`` only lets it skip
    later members it can see won't fit."""

    def __init__(self, partial_dir: str):
        self.partial_dir = partial_dir

    def __call__(self, members: Sequence[str],
                 budget_s: Optional[float]) -> LaunchResult:
        cmd = [
            sys.executable, "-m", "accelerate_tpu.benchmarks",
            "--child", *members, "--partial-dir", self.partial_dir,
        ]
        timeout = None
        if budget_s is not None and math.isfinite(budget_s):
            timeout = max(1.0, float(budget_s))
            cmd += ["--budget", f"{timeout:.1f}"]
        env = dict(os.environ)
        env["PYTHONUNBUFFERED"] = "1"
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env["PYTHONPATH"] = (
            repo_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else repo_root
        )
        try:
            proc = subprocess.run(
                cmd, text=True, capture_output=True, timeout=timeout, env=env,
            )
        except subprocess.TimeoutExpired as exc:
            def _s(x):
                if x is None:
                    return ""
                return x.decode(errors="replace") if isinstance(x, bytes) else x

            return LaunchResult(-9, _s(exc.stdout), _s(exc.stderr),
                                timed_out=True)
        return LaunchResult(proc.returncode, proc.stdout, proc.stderr)


def _implausible(rec: dict) -> bool:
    # the tunneled chip occasionally degrades ~20x right after long
    # multi-process sessions (observed: dense at 1.2k tok/s vs the usual
    # 26k, recovering by itself a minute later) — a train variant
    # reporting under 10% MFU on real hardware is that transient, not a
    # real measurement
    return (
        rec.get("unit") == "tokens/s/chip"
        and rec.get("extra", {}).get("mfu", 1.0) < 0.10
        and not rec.get("partial")
    )


def _oom_line(err: str) -> Optional[str]:
    return next(
        (l.strip() for l in err.splitlines()
         if "RESOURCE_EXHAUSTED" in l or "Ran out of memory" in l),
        None,
    )


#: units where a SMALLER value is the better measurement (times,
#: latencies, overhead percentages); every other unit (tokens/s,
#: tokens/s/chip, speedup "x") improves upward
_LOWER_IS_BETTER_UNITS = frozenset({"s", "ms", "s/token", "%", "pct"})


def parse_baseline_records(text: str) -> dict[str, dict]:
    """Parse one prior bench output into ``{variant: record}``.

    Accepts either the driver's ``BENCH_*.json`` wrapper (``{"n", "cmd",
    "rc", "tail"}`` where ``tail`` holds the JSON-lines stream) or a raw
    JSON-lines stream. The stream prints every record twice on a clean
    run — provisionally at land time, finally in the consolidated block
    — so the LAST line per variant wins and final records (no
    ``provisional`` flag) displace provisional ones."""
    meta: dict = {}
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and "tail" in obj:
        meta = {"prev_round": obj.get("n")}
        text = obj.get("tail") or ""
    provisional: dict[str, dict] = {}
    final: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        name = rec.get("variant")
        if not name or rec.get("skipped") or rec.get("value") is None:
            continue
        rec.update(meta)
        if rec.get("provisional"):
            provisional[name] = rec
        else:
            final[name] = rec
    return {**provisional, **final}


def load_baseline(
    path: Optional[str] = None, search_dir: str = ".",
) -> dict[str, dict]:
    """The previous run's records for regression stamping: an explicit
    ``path`` (``--baseline``), else the newest ``BENCH_*.json`` in
    ``search_dir`` by round number. Empty dict when nothing is found —
    the first round of a fresh checkout has no trend."""
    if path is None:
        import glob

        candidates = sorted(
            glob.glob(os.path.join(search_dir, "BENCH_*.json"))
        )
        if not candidates:
            return {}
        path = candidates[-1]
    try:
        with open(path) as f:
            return parse_baseline_records(f.read())
    except OSError:
        return {}


class BenchRunner:
    def __init__(
        self,
        registry: VariantRegistry,
        scheduler: DeadlineScheduler,
        estimates: Estimates,
        launch: Callable[[Sequence[str], Optional[float]], LaunchResult],
        *,
        partial_dir: Optional[str] = None,
        emit: Optional[Callable[[str], None]] = None,
        log: Optional[Callable[[str], None]] = None,
        sleep: Callable[[float], None] = time.sleep,
        settle_s: float = 60.0,
        on_tpu: bool = True,
        baseline: Optional[dict[str, dict]] = None,
    ):
        self.registry = registry
        self.scheduler = scheduler
        self.estimates = estimates
        self.launch = launch
        self.partial_dir = partial_dir
        self.emit = emit or (lambda s: print(s, flush=True))
        self.log = log or (
            lambda s: print(s, file=sys.stderr, flush=True)
        )
        self.sleep = sleep
        # the tunnel transient recovers on its own within ~a minute; a
        # retry without the settle usually measures the same degradation
        self.settle_s = settle_s
        self.on_tpu = on_tpu
        # {variant: prior record} from the previous round — every landed
        # record passes through _publish, so stamping there covers the
        # provisional stream and the consolidated block alike
        self.baseline = baseline or {}
        self.results: dict[str, dict] = {}
        self.errors: dict[str, str] = {}
        self.skipped: list[dict] = []
        self.oom_reports: dict[str, str] = {}  # variant -> autopsy path

    # ---------------------------------------------------------------- run
    def run(self) -> int:
        groups = self.registry.groups()
        members = {g: [v.name for v in vs] for g, vs in groups}
        variants = {v.name: v for _, vs in groups for v in vs}
        items = [
            (g, sum(self._estimate(v) for v in vs)) for g, vs in groups
        ]
        planned, plan_skips = self.scheduler.plan(items, members=members)
        for sk in plan_skips:
            for name in members[sk["variant"]]:
                self._skip(variants[name], sk["remaining_s"])
        reserved = [sum(p.budget_s for p in planned[i + 1:])
                    for i in range(len(planned))]
        for item, reserved_later in zip(planned, reserved):
            group_members = [variants[n] for n in item.members]
            budget = self.scheduler.grant(item, reserved_later_s=reserved_later)
            if budget is None:
                for v in group_members:
                    self._skip(v, self.scheduler.deadline.remaining())
                continue
            self._run_group(group_members, budget)
        self._fold()
        self._final_block()
        self.estimates.save()
        headline = self.registry.headline
        return 0 if headline in self.results else 1

    # ------------------------------------------------------------ helpers
    def _estimate(self, v: Variant) -> float:
        return self.estimates.estimate(v.name, v.default_estimate_s)

    def _skip(self, v: Variant, remaining_s: float) -> None:
        rec = skip_record(v.name, self._estimate(v), remaining_s)
        self.skipped.append(rec)
        self.emit(json.dumps(rec))

    def _stamp_trend(self, name: str, rec: dict) -> None:
        """Run-to-run trend: attach the previous round's value and flag
        a >10% degradation of the variant's metric. Partial records are
        stamped with ``prev_*`` but never flagged — a budget-killed
        measurement is not evidence of a regression."""
        prev = self.baseline.get(name)
        if prev is None or rec.get("value") is None:
            return
        rec["prev_value"] = prev.get("value")
        if prev.get("prev_round") is not None:
            rec["prev_round"] = prev["prev_round"]
        prev_value = prev.get("value")
        if not prev_value or rec.get("partial"):
            return
        unit = rec.get("unit") or prev.get("unit") or ""
        change = (float(rec["value"]) - float(prev_value)) / float(prev_value)
        rec["prev_delta_pct"] = round(100.0 * change, 2)
        degraded = (
            change > 0.10 if unit in _LOWER_IS_BETTER_UNITS
            else change < -0.10
        )
        if degraded:
            rec["regression"] = True
            self.log(
                f"REGRESSION: {name} {rec.get('metric')} "
                f"{prev_value} -> {rec['value']} {unit} "
                f"({rec['prev_delta_pct']:+.1f}%)"
            )

    def _publish(self, name: str, rec: dict) -> None:
        rec.setdefault("variant", name)
        self._stamp_trend(name, rec)
        self.results[name] = rec
        # Emit the record the moment the variant lands, flushed, so a
        # driver wall-clock kill cannot discard completed measurements
        # (BENCH_r05 was rc=124 with an empty tail). The consolidated
        # block at the end re-prints the FINAL (folded) records with the
        # headline last — consumers of the whole stream skip provisional
        # lines, the parse-the-last-line driver never sees them on a
        # clean run.
        self.emit(json.dumps({**rec, "provisional": True}))
        extra = rec.get("extra", {})
        if not rec.get("partial") and "variant_wall_s" in extra:
            # feed the cost model: round n+1 schedules against this
            self.estimates.observe(
                name, extra["variant_wall_s"],
                step_time_s=extra.get("step_time_s"),
                compile_time_s=extra.get("compile_time_s"),
            )

    def _fail(self, name: str, err: str) -> None:
        self.errors[name] = err
        self.log(f"bench variant {name} failed (provisional): {err[:160]}")

    def _parse(self, stdout: str) -> tuple[dict[str, dict], dict[str, dict]]:
        """Split the child's JSON lines into (final records, child-side
        skip records), keyed by variant name."""
        recs: dict[str, dict] = {}
        skips: dict[str, dict] = {}
        for line in stdout.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            name = obj.get("variant")
            if not name:
                continue
            if obj.get("skipped"):
                skips[name] = obj
            else:
                recs[name] = obj
        return recs, skips

    def _harvest_partial(self, v: Variant, reason: str) -> bool:
        """Turn the child's last fsync'd snapshot into a published
        partial record. True when something usable was recovered."""
        if not self.partial_dir:
            return False
        snap = read_partial(partial_path(self.partial_dir, v.name))
        rec = partial_record(snap, reason=reason) if snap else None
        if rec is None:
            return False
        self._publish(v.name, rec)
        self.log(
            f"variant {v.name} killed at its budget; recovered partial "
            f"result at iters_measured={rec['iters_measured']}"
        )
        return True

    def _harvest_oom_autopsy(self, crashed: list[Variant]) -> None:
        """An OOM child wrote its ``oom-report.json`` autopsy next to the
        partial snapshots before dying; surface it in the stream so the
        expected-OOM variants (``longseq_xla``) leave a machine-readable
        artifact instead of just a stderr line."""
        if not self.partial_dir:
            return
        try:
            from ..profiling.oom import OOM_REPORT_NAME, read_oom_report
        except Exception:  # noqa: BLE001 — forensics stay best-effort
            return
        report = read_oom_report(self.partial_dir)
        if report is None:
            return
        path = os.path.join(self.partial_dir, OOM_REPORT_NAME)
        for v in crashed:
            self.oom_reports[v.name] = path
            self.emit(json.dumps({
                "variant": v.name,
                "oom_report": path,
                "oom_context": report.get("context"),
                "oom_requested_bytes": report.get("requested_bytes"),
            }))
        self.log(f"OOM autopsy recovered: {path}")

    # --------------------------------------------------------- group loop
    def _run_group(self, group_members: list[Variant],
                   budget_s: float) -> None:
        pending = list(group_members)
        first_recs: dict[str, dict] = {}
        budget = budget_s
        for attempt in (0, 1):
            res = self.launch([v.name for v in pending], budget)
            recs, child_skips = self._parse(res.stdout)
            retry: list[Variant] = []
            crashed: list[Variant] = []
            for v in pending:
                if v.name in child_skips:
                    self.skipped.append(child_skips[v.name])
                    self.emit(json.dumps(child_skips[v.name]))
                    continue
                rec = recs.get(v.name)
                if rec is not None:
                    prior = first_recs.get(v.name)
                    if (
                        prior is None and attempt == 0 and self.on_tpu
                        and _implausible(rec)
                    ):
                        first_recs[v.name] = rec
                        retry.append(v)
                        continue
                    if prior is not None:
                        # keep the better of the two attempts: a
                        # genuinely-slow variant measures the same twice
                        # (the number stands), the degraded-chip
                        # transient recovers on the retry
                        if prior.get("value", 0) > rec.get("value", 0):
                            rec = prior
                        rec["extra"]["retried"] = True
                    self._publish(v.name, rec)
                    continue
                if res.timed_out:
                    if not self._harvest_partial(v, reason="budget"):
                        self._fail(v.name, f"timeout after {budget:.0f}s")
                else:
                    crashed.append(v)
            # CRASH path. Round 3 lost its dense headline here: the crash
            # was a transient tunnel error but only implausibly-slow
            # *successes* were retried. Retry crashes once after a settle
            # — except deterministic OOMs, where a retry just re-pays the
            # compile (and for the longseq_xla variants OOM is the
            # expected, informative outcome).
            if crashed:
                err = (res.stderr or "no output").strip()
                oom = _oom_line(err)
                if oom or attempt == 1:
                    if oom:
                        self._harvest_oom_autopsy(crashed)
                    for v in crashed:
                        self._fail(v.name, oom or err[-300:] or "no output")
                    crashed = []
            pending = retry + crashed
            if not pending or attempt == 1:
                break
            if res.timed_out:
                # a timeout is NOT retried: another budget would risk the
                # global window — fall through to the first_rec fallback
                break
            rem = self.scheduler.deadline.remaining()
            need = sum(self._estimate(v) for v in pending)
            if need > rem - self.settle_s:
                break  # the window can't fund a retry
            what = "implausibly slow" if retry else "crashed"
            self.log(
                f"variant(s) {[v.name for v in pending]} {what}; retrying "
                f"after a {self.settle_s:.0f}s settle"
            )
            self.sleep(self.settle_s)
            if math.isfinite(budget):
                budget = min(budget, self.scheduler.deadline.remaining())
        # fallback: an implausible-but-MEASURED first attempt whose retry
        # timed out, crashed, or could not be funded is still a
        # measurement — publish it marked retried+partial instead of
        # erroring (the old bench.py timeout path silently discarded it)
        variants = {v.name: v for v in group_members}
        for name, prior in first_recs.items():
            if name in self.results:
                continue
            self.errors.pop(name, None)
            prior["extra"]["retried"] = True
            prior["extra"]["implausible"] = True
            prior["partial"] = True
            prior["partial_reason"] = "retry_failed"
            v = variants[name]
            if "iters_measured" not in prior:
                prior["iters_measured"] = (
                    int(v.args[3]) if len(v.args) > 3 else 0
                )
            self._publish(name, prior)
        for v in pending:
            if v.name not in self.results and v.name not in self.errors:
                self._fail(v.name, "retry window exhausted")

    # ------------------------------------------------------------ folding
    def _fold(self) -> None:
        results, errors = self.results, self.errors
        # fold the load-time helper into the decode line (never the
        # reverse: a failed load leaves the decode headline intact with
        # load_s null)
        if "decode" in results:
            extra = results["decode"]["extra"]
            if "decode_load" in results:
                rec_l = results.pop("decode_load")
                extra["load_s"] = rec_l["value"]
                le = rec_l["extra"]
                extra["load_disk_to_host_s"] = le.get("disk_to_host_s")
                extra["load_host_to_device_s"] = le.get("host_to_device_s")
                extra["load_gib"] = le.get("gib")
                extra["load_ref_s"] = 8.7
                if "note" in le:
                    extra["load_note"] = le["note"]
                if rec_l.get("partial"):
                    extra["load_partial"] = True
            elif "decode_load" in errors:
                extra["load_s"] = None
                extra["load_error"] = errors.pop("decode_load")[:160]
            elif any(s["variant"] == "decode_load" for s in self.skipped):
                extra["load_s"] = None
                extra["load_skipped"] = "deadline"

        helpers = ("longseq_xla", "longseq4k", "longseq_xla4k")
        if "longseq" in results:
            extra = results["longseq"]["extra"]
            if "longseq_xla" in results:
                xla_step = results["longseq_xla"]["extra"]["step_time_s"]
                extra["xla_step_time_s"] = xla_step
                extra["flash_speedup_vs_xla"] = round(
                    xla_step / extra["step_time_s"], 3
                )
            else:
                # numeric fields stay numeric (None) for machine
                # consumers; the error text gets its own key
                extra["xla_step_time_s"] = None
                extra["flash_speedup_vs_xla"] = None
                if "longseq_xla" in errors:
                    extra["xla_error"] = errors.pop("longseq_xla")[:160]
                if "longseq_xla" in self.oom_reports:
                    # the expected-OOM comparison point: its autopsy IS
                    # the artifact (requested bytes + ledger + census)
                    extra["xla_oom_report"] = self.oom_reports["longseq_xla"]
            # the S=4096 pair, where dense attention fits 16G: always
            # record whichever step times landed (even a lone one — never
            # discard a valid measurement), and let the pair supply the
            # headline speedup when the S=8192 dense point failed (null
            # in rounds 2 and 3)
            if "longseq4k" in results:
                extra["flash_step_s_s4096"] = (
                    results["longseq4k"]["extra"]["step_time_s"]
                )
            if "longseq_xla4k" in results:
                extra["xla_step_s_s4096"] = (
                    results["longseq_xla4k"]["extra"]["step_time_s"]
                )
            if "longseq4k" in results and "longseq_xla4k" in results:
                flash4k = results["longseq4k"]["extra"]["step_time_s"]
                xla4k = results["longseq_xla4k"]["extra"]["step_time_s"]
                if extra["flash_speedup_vs_xla"] is None:
                    extra["flash_speedup_vs_xla"] = round(xla4k / flash4k, 3)
                    extra["speedup_measured_at_seq"] = 4096
                    extra["speedup_optimizer"] = "sgd"
            for name in helpers:
                results.pop(name, None)
        # when longseq itself failed, measured helper records stay in
        # ``results`` and print as their own lines — a valid measurement
        # is never silently discarded

    def _final_block(self) -> None:
        headline = self.registry.headline
        order = [n for n in self.results if n != headline]
        if headline in self.results:
            order.append(headline)
        for name in order:
            self.emit(json.dumps(self.results[name]))
        for name, err in self.errors.items():
            qualifier = (
                " (expected on 16G chips — the dense-attention comparison"
                " point)"
                if name == "longseq_xla" else ""
            )
            self.log(f"bench variant {name} failed{qualifier}: {err}")
        if self.skipped:
            self.log(
                "skipped (deadline): "
                + ", ".join(sorted({s["variant"] for s in self.skipped}))
            )
