"""Deadline-aware benchmark subsystem.

The former monolithic ``bench.py`` split along its real seams:

* :mod:`.registry` — what can run, priorities, process groups, costs
* :mod:`.scheduler` — deadline, persisted estimates, budget allocation
* :mod:`.measure` — the per-variant measurement bodies
* :mod:`.partial` — fsync'd partial-result streaming child -> parent
* :mod:`.runner` — group launching, retries, folding, the output stream
* :mod:`.cli` — ``python bench.py`` / ``python -m accelerate_tpu.benchmarks``
"""

from .partial import PartialWriter, partial_path, partial_record, read_partial
from .registry import Variant, VariantRegistry, build_registry
from .runner import BenchRunner, LaunchResult, SubprocessLauncher
from .scheduler import (
    Deadline,
    DeadlineScheduler,
    Estimates,
    Planned,
    skip_record,
)

__all__ = [
    "BenchRunner",
    "Deadline",
    "DeadlineScheduler",
    "Estimates",
    "LaunchResult",
    "PartialWriter",
    "Planned",
    "SubprocessLauncher",
    "Variant",
    "VariantRegistry",
    "build_registry",
    "partial_path",
    "partial_record",
    "read_partial",
    "skip_record",
]
