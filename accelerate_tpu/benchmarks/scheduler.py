"""Deadline-aware budget allocation for the bench matrix.

The driver gives the whole bench one wall-clock window; r05 spent it all
on serial compiles and got killed with an empty tail. The scheduler
turns that window into explicit per-group budgets:

* a :class:`Deadline` tracks the global window (``--deadline`` /
  ``ACCELERATE_TPU_BENCH_DEADLINE_S``; absent = unbounded);
* :class:`Estimates` persists each variant's measured wall cost
  (compile + warmup + iters) next to the XLA compile cache, so round
  *n*+1 schedules against round *n*'s reality instead of guesses;
* :class:`DeadlineScheduler.plan` walks the groups in priority order and
  either grants a budget (sum of grants never exceeds the window) or
  emits an explicit ``{"skipped": "deadline", "estimated_s": ...}``
  record — a variant that does not run is visible, never vanished.

Everything takes an injectable ``clock`` so the budget arithmetic is
unit-testable with a fake clock.
"""

from __future__ import annotations

import json
import math
import os
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence

ENV_DEADLINE = "ACCELERATE_TPU_BENCH_DEADLINE_S"


class Deadline:
    """A wall-clock window starting at construction. ``seconds=None``
    means unbounded (``remaining()`` is ``inf``, nothing ever expires)."""

    def __init__(self, seconds: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        if seconds is not None and seconds <= 0:
            raise ValueError("deadline seconds must be > 0")
        self.seconds = float(seconds) if seconds is not None else None
        self._clock = clock
        self._t0 = clock()

    @classmethod
    def from_env(cls, override: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> "Deadline":
        if override is not None:
            return cls(override, clock=clock)
        env = os.environ.get(ENV_DEADLINE)
        return cls(float(env) if env else None, clock=clock)

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def remaining(self) -> float:
        if self.seconds is None:
            return math.inf
        return self.seconds - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def fits(self, estimate_s: float) -> bool:
        return estimate_s <= self.remaining()


class Estimates:
    """Per-variant measured wall cost, persisted NEXT TO the XLA cache
    (``<cache_dir>.estimates.json``) so it shares the cache's lifetime:
    wiping the compile cache also resets the cost model to defaults,
    which is exactly when estimates go stale."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or self.default_path()
        self.data: dict[str, dict] = {}

    @staticmethod
    def default_path() -> str:
        cache = os.environ.get("ACCELERATE_TPU_COMPILE_CACHE")
        if not cache:
            from ..compilation import persistent_cache_dir

            cache = persistent_cache_dir() or os.path.join(
                tempfile.gettempdir(), "accelerate_tpu_bench_xla_cache"
            )
        return os.path.abspath(cache) + ".estimates.json"

    def load(self) -> "Estimates":
        try:
            with open(self.path) as f:
                data = json.load(f)
            if isinstance(data, dict):
                self.data = {
                    k: v for k, v in data.items() if isinstance(v, dict)
                }
        except (OSError, ValueError):
            self.data = {}
        return self

    def save(self) -> None:
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self.data, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
        except OSError:
            pass

    def observe(self, variant: str, total_s: float,
                step_time_s: Optional[float] = None,
                compile_time_s: Optional[float] = None) -> None:
        self.data[variant] = {
            "total_s": round(float(total_s), 3),
            "step_time_s": step_time_s,
            "compile_time_s": compile_time_s,
            "time_unix": time.time(),
        }

    def estimate(self, variant: str, default: float) -> float:
        """Estimated minimum wall cost: last measured total (which already
        contains that round's compile + warmup + iters), else the
        registry default."""
        rec = self.data.get(variant)
        if rec and isinstance(rec.get("total_s"), (int, float)):
            return float(rec["total_s"])
        return float(default)


def skip_record(variant: str, estimated_s: float, remaining_s: float,
                reason: str = "deadline") -> dict:
    """The explicit record a variant emits instead of silently vanishing."""
    return {
        "variant": variant,
        "skipped": reason,
        "estimated_s": round(float(estimated_s), 1),
        "remaining_s": (
            None if math.isinf(remaining_s) else round(float(remaining_s), 1)
        ),
        "time_unix": time.time(),
    }


@dataclass
class Planned:
    """One scheduled unit (a process group) with its granted budget."""

    name: str
    estimate_s: float
    budget_s: float
    members: tuple[str, ...] = field(default_factory=tuple)


class DeadlineScheduler:
    """Allocates the deadline across priority-ordered items.

    ``plan`` is the static pass: walking the items in order, each gets
    ``min(pool, max(slack * estimate, min_budget))`` out of a pool that
    starts at the remaining deadline — so the **sum of granted budgets
    can never exceed the global window** — and items whose bare estimate
    no longer fits the pool become skip records. ``grant`` is the
    runtime pass: just before launch, a planned item's budget is
    re-clamped to actual remaining wall clock (minus what later planned
    items reserved), so early finishers donate their slack forward and
    overruns upstream shrink (or void) downstream budgets.
    """

    def __init__(self, deadline: Deadline, *, slack: float = 1.5,
                 min_budget_s: float = 60.0):
        if slack < 1.0:
            raise ValueError("slack must be >= 1")
        self.deadline = deadline
        self.slack = slack
        self.min_budget_s = min_budget_s

    def plan(
        self, items: Sequence[tuple[str, float]],
        members: Optional[dict[str, Sequence[str]]] = None,
    ) -> tuple[list[Planned], list[dict]]:
        """``items``: (name, estimate_s) in priority order. Returns the
        planned runs and the skip records for everything that didn't fit."""
        members = members or {}
        pool = self.deadline.remaining()
        planned: list[Planned] = []
        skipped: list[dict] = []
        for name, est in items:
            if est > pool:
                skipped.append(skip_record(name, est, pool))
                continue
            budget = min(pool, max(est * self.slack, self.min_budget_s))
            planned.append(Planned(
                name, float(est), budget, tuple(members.get(name, (name,))),
            ))
            if not math.isinf(pool):
                pool -= budget
        return planned, skipped

    def grant(self, item: Planned, reserved_later_s: float = 0.0
              ) -> Optional[float]:
        """Runtime budget for ``item`` right now, or None when its
        estimate exceeds the remaining window (caller emits the skip)."""
        rem = self.deadline.remaining()
        if item.estimate_s > rem:
            return None
        if math.isinf(rem):
            return item.budget_s
        return min(rem, max(item.budget_s, rem - reserved_later_s))
