"""Bench entry point (``python bench.py`` / ``python -m
accelerate_tpu.benchmarks``).

Three modes:

* **parent** (default): detect the backend in a subprocess (never
  initialize an exclusively-locked TPU in the parent), build the
  registry, plan against the deadline, launch one child per process
  group through :class:`~.runner.BenchRunner`.
* **child** (``--child A B ... --budget S --partial-dir D``): run the
  listed members in-process under a self-enforced budget, stream
  fsync'd partial snapshots, print one JSON line per member. Explicit
  buffer teardown (``gc.collect`` + ``jax.clear_caches``) between
  members keeps a shared child from carrying one config's HBM into the
  next.
* **direct** (``python bench.py accum``): bare variant names with no
  ``--deadline`` run in-process and print their lines — the historical
  single-variant interface (Makefile smokes use it).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Optional

from .partial import ENV_PARTIAL_DIR, PartialWriter, partial_path
from .registry import build_registry
from .runner import BenchRunner, SubprocessLauncher, load_baseline
from .scheduler import (
    ENV_DEADLINE,
    Deadline,
    DeadlineScheduler,
    Estimates,
    skip_record,
)


def _detect_backend() -> str:
    """Backend without initializing it in THIS process: on hosts where
    the TPU is an exclusively-locked local device, a parent that touches
    it would starve the per-variant child processes."""
    import subprocess

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            capture_output=True, text=True, timeout=300,
        )
        return probe.stdout.strip().splitlines()[-1]
    except Exception:  # noqa: BLE001 — fall back to in-process detection
        import jax

        return jax.default_backend()


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bench", description="accelerate_tpu benchmark harness",
    )
    p.add_argument("variants", nargs="*",
                   help="variant names to run (default: the full matrix)")
    p.add_argument("--fast", action="store_true",
                   help="CI subset: the CPU-safe fast-flagged variants")
    p.add_argument("--deadline", type=float, default=None,
                   help=f"global wall-clock budget in seconds "
                        f"(env {ENV_DEADLINE})")
    p.add_argument("--list", action="store_true",
                   help="print the registry (names, priorities, groups)")
    p.add_argument("--baseline", default=None,
                   help="previous BENCH_*.json (or raw JSON-lines output) "
                        "to stamp prev_*/regression trend fields against; "
                        "default: the newest BENCH_*.json in the cwd")
    p.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    p.add_argument("--budget", type=float, default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--partial-dir", default=None, help=argparse.SUPPRESS)
    return p


def _run_child(names: list[str], budget_s: Optional[float],
               partial_dir: Optional[str]) -> int:
    """Run ``names`` in THIS process under a self-enforced budget.

    The parent's subprocess timeout is the hard kill; the child's own
    Deadline only lets it skip later members it can already see won't
    fit — an explicit ``{"skipped": "budget"}`` line beats dying
    mid-compile."""
    import gc

    import jax

    from accelerate_tpu.compilation import activate_persistent_cache
    from accelerate_tpu.utils.dataclasses import CompilePlugin

    from .measure import result_line

    # join the cache dir the parent exported (covers the decode/
    # generation variants too, which never build an Accelerator — the
    # training path would also pick the env var up through CompilePlugin)
    activate_persistent_cache(CompilePlugin())  # no-op when env unset
    on_tpu = jax.default_backend() == "tpu"
    registry = build_registry(on_tpu)
    estimates = Estimates().load()
    deadline = Deadline(budget_s)
    rc = 0
    for i, name in enumerate(names):
        variant = registry.get(name)
        est = estimates.estimate(name, variant.default_estimate_s)
        if i > 0 and not deadline.fits(est):
            # later group member that can't fit the leftover budget:
            # skip explicitly rather than get SIGKILLed mid-compile
            print(json.dumps(skip_record(
                name, est, deadline.remaining(), reason="budget",
            )), flush=True)
            continue
        writer = PartialWriter(
            partial_path(partial_dir, name) if partial_dir else None, name,
        )
        try:
            rec = result_line(variant, partial=writer)
        except Exception as exc:  # noqa: BLE001 — isolate group members
            from accelerate_tpu.profiling.oom import (
                is_resource_exhausted,
                write_oom_report,
            )

            if is_resource_exhausted(exc):
                # the autopsy lands next to the partial snapshots, where
                # the parent harvests it (expected-OOM variants included)
                write_oom_report(
                    exc, context=f"bench:{name}", directory=partial_dir,
                )
            print(f"variant {name} failed: {exc!r}",
                  file=sys.stderr, flush=True)
            rc = 1
        else:
            print(json.dumps({"variant": name, **rec}), flush=True)
        finally:
            if i < len(names) - 1:
                # explicit buffer teardown between group members: drop
                # python refs, then the jit executable + donated-buffer
                # caches, so the next config starts with a clean device
                gc.collect()
                jax.clear_caches()
                gc.collect()
    return rc


def _run_direct(names: list[str]) -> int:
    """Historical interface: run the named variants in-process and print
    their lines (``python bench.py accum``)."""
    from accelerate_tpu.compilation import activate_persistent_cache
    from accelerate_tpu.utils.dataclasses import CompilePlugin

    from .measure import result_line

    import jax

    activate_persistent_cache(CompilePlugin())
    registry = build_registry(jax.default_backend() == "tpu")
    partial_dir = os.environ.get(ENV_PARTIAL_DIR)
    for name in names:
        variant = registry.get(name)
        writer = PartialWriter(
            partial_path(partial_dir, name) if partial_dir else None, name,
        )
        rec = result_line(variant, partial=writer)
        print(json.dumps({"variant": name, **rec}), flush=True)
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    args = _parser().parse_args(argv)

    if args.child:
        return _run_child(args.variants, args.budget, args.partial_dir)

    on_tpu = _detect_backend() == "tpu"
    registry = build_registry(on_tpu)
    try:
        registry = registry.select(
            names=args.variants or None, fast=args.fast,
        )
    except KeyError as exc:
        print(exc.args[0], file=sys.stderr)
        return 2

    if args.list:
        for name in registry.names:
            v = registry.get(name)
            print(json.dumps({
                "variant": v.name, "kind": v.kind, "priority": v.priority,
                "group": v.group, "fast": v.fast, "headline": v.headline,
                "default_estimate_s": v.default_estimate_s,
            }))
        return 0

    if args.variants and args.deadline is None and not args.fast:
        # bare names, no scheduling flags: the historical in-process path
        return _run_direct(args.variants)

    # One persistent XLA cache dir shared by every variant child (they
    # inherit the env; CompilePlugin reads it). The variants share model
    # shapes across retries and the longseq/longseq4k pairs, so repeated
    # programs deserialize instead of recompiling — the rc=124 driver
    # timeouts that erased BENCH_r05 were mostly serial compile time.
    # Children run SERIALLY, so sharing is safe (concurrent writers to
    # one cache dir deadlocked in a past parallel-pytest measurement —
    # do not copy this pattern into parallel workers).
    os.environ.setdefault(
        "ACCELERATE_TPU_COMPILE_CACHE",
        os.path.join(tempfile.gettempdir(),
                     "accelerate_tpu_bench_xla_cache"),
    )

    deadline = Deadline.from_env(args.deadline)
    estimates = Estimates().load()
    scheduler = DeadlineScheduler(
        deadline,
        # CPU CI variants finish in seconds; a 60s floor would let one
        # group starve the plan on a 120s deadline
        min_budget_s=60.0 if on_tpu else 30.0,
    )
    partial_dir = tempfile.mkdtemp(prefix="accelerate_tpu_bench_partial_")
    runner = BenchRunner(
        registry, scheduler, estimates,
        SubprocessLauncher(partial_dir),
        partial_dir=partial_dir,
        settle_s=60.0 if on_tpu else 5.0,
        on_tpu=on_tpu,
        baseline=load_baseline(args.baseline),
    )
    return runner.run()
