"""Whole-training-state checkpointing.

Parity: reference ``src/accelerate/checkpointing.py`` (``save_accelerator_state``
:51, ``load_accelerator_state`` :152, ``save_custom_state`` :257,
``load_custom_state`` :267) plus the Accelerator-side orchestration
(``save_state`` accelerator.py:2858 — automatic naming/rotation :2899-2915 —
and ``load_state`` :3023) and the inference-ready sharded weight writer
(``save_model`` :2712, ``shard_checkpoint`` utils/modeling.py:206).

TPU-native redesign: training state is ONE pytree (the step carry: params +
opt state + counters + loss scale), not a bag of stateful objects, so
checkpointing is "flatten pytree -> named arrays -> safetensors shards" and
restore is "fill an abstract template and device_put onto the template's
shardings" — the sharded-restore path that FSDP needs ``dist_cp`` for
(reference utils/fsdp_utils.py:60-215) falls out of NamedSharding here.
Host-side state (python/numpy RNG, schedulers, samplers, custom objects)
keeps the reference's file-per-object naming scheme; formats differ (json /
safetensors here vs torch pickles there), so checkpoints are not byte-level
interchangeable with the reference.
"""

from __future__ import annotations

import json
import math
import os
import pickle
import re
import shutil
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .logging import get_logger
from .utils.constants import (
    CUSTOM_STATE_NAME,
    METADATA_NAME,
    MODEL_NAME,
    OPTIMIZER_NAME,
    RNG_STATE_NAME,
    SAFE_WEIGHTS_INDEX_NAME,
    SAFE_WEIGHTS_NAME,
    SAMPLER_NAME,
    SCHEDULER_NAME,
)

logger = get_logger(__name__)

_SEP = "//"  # pytree path separator in flattened safetensors keys


# ---------------------------------------------------------------------- #
# pytree <-> named-array flattening
# ---------------------------------------------------------------------- #
def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return _SEP.join(parts) if parts else "__root__"

def flatten_tree(tree: Any) -> dict[str, Any]:
    """Pytree -> {path: leaf} with deterministic, invertible names."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return {_path_str(path): leaf for path, leaf in flat}

def unflatten_into(template: Any, named: dict[str, Any]) -> Any:
    """Fill ``template``'s structure with arrays from ``named``; each leaf is
    placed on the template leaf's sharding (the sharded-restore path)."""
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, tleaf in paths_and_leaves:
        key = _path_str(path)
        if key not in named:
            raise KeyError(f"checkpoint missing tensor {key!r}")
        value = named[key]
        if isinstance(tleaf, jax.Array):
            value = jnp.asarray(value, tleaf.dtype)
            if value.shape != tleaf.shape:
                # only 1-element leaves may be reshaped (scalar counters the
                # file format stores as (1,)); anything else is corruption
                # and must fail loudly, not silently scramble a kernel
                if value.size == tleaf.size and value.size == 1:
                    value = value.reshape(tleaf.shape)
                else:
                    raise ValueError(
                        f"checkpoint tensor {key!r} has shape {value.shape}, "
                        f"template expects {tleaf.shape}"
                    )
            if isinstance(tleaf.sharding, jax.sharding.NamedSharding):
                value = jax.device_put(value, tleaf.sharding)
            # non-Named shardings (e.g. scalar counters from init_carry):
            # keep the array uncommitted so jit may co-locate it freely —
            # committing to one device breaks multi-device steps.
        leaves.append(value)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _to_host(tree: Any) -> Any:
    """Fetch every leaf to host numpy. Fully-addressable leaves come over
    in ONE batched ``jax.device_get`` (a per-leaf ``np.asarray`` would pay
    a round-trip per leaf); only leaves that are genuinely not addressable
    from this process are all-gathered (multi-process pods) so rank0 holds
    full arrays."""
    leaves, treedef = jax.tree.flatten(tree)
    out: list[Any] = []
    batch_idx: list[int] = []
    batch: list[jax.Array] = []
    for i, x in enumerate(leaves):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            from jax.experimental import multihost_utils

            out.append(
                np.asarray(multihost_utils.process_allgather(x, tiled=True))
            )
        elif isinstance(x, jax.Array):
            batch_idx.append(i)
            batch.append(x)
            out.append(None)
        else:
            out.append(np.asarray(x))
    if batch:
        for i, host in zip(batch_idx, jax.device_get(batch)):
            out[i] = np.asarray(host)
    return jax.tree.unflatten(treedef, out)


# ---------------------------------------------------------------------- #
# atomic small-file io
# ---------------------------------------------------------------------- #
def _atomic_write(path: str, write_fn, mode: str = "w") -> None:
    """Write via a same-dir tmp file + ``os.replace`` so a crash mid-write
    can never leave a truncated file under the real name for a later
    ``load_state`` to choke on."""
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def _atomic_json_dump(obj: Any, path: str, **kwargs) -> None:
    _atomic_write(path, lambda f: json.dump(obj, f, **kwargs))


def _atomic_pickle_dump(obj: Any, path: str) -> None:
    _atomic_write(path, lambda f: pickle.dump(obj, f), mode="wb")


# ---------------------------------------------------------------------- #
# safetensors io
# ---------------------------------------------------------------------- #
def _save_named(named: dict[str, np.ndarray], path: str, safe: bool = True):
    if safe:
        from safetensors.numpy import save_file

        # safetensors rejects non-contiguous / object arrays
        named = {k: np.ascontiguousarray(v) for k, v in named.items()}
        save_file(named, path)
    else:
        _atomic_pickle_dump(named, path)

def _load_named(path: str) -> dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        from safetensors.numpy import load_file

        return load_file(path)
    with open(path, "rb") as f:
        return pickle.load(f)


# ---------------------------------------------------------------------- #
# sharded model-weight writer (reference save_model accelerator.py:2712
# + shard_checkpoint utils/modeling.py:206)
# ---------------------------------------------------------------------- #
def parse_size(size: str | int) -> int:
    if isinstance(size, int):
        return size
    m = re.fullmatch(r"(\d+\.?\d*)\s*([KMGT]?B)", size.strip(), re.IGNORECASE)
    if not m:
        raise ValueError(f"unparseable size {size!r}")
    mult = {"B": 1, "KB": 2**10, "MB": 2**20, "GB": 2**30, "TB": 2**40}
    return int(float(m.group(1)) * mult[m.group(2).upper()])

def shard_checkpoint(
    named: dict[str, np.ndarray],
    max_shard_size: str | int = "10GB",
    weights_name: str = SAFE_WEIGHTS_NAME,
) -> tuple[list[dict[str, np.ndarray]], Optional[dict]]:
    """Greedy split of a named-tensor dict into <=max_shard_size shards
    (reference utils/modeling.py:206)."""
    limit = parse_size(max_shard_size)
    shards: list[dict[str, np.ndarray]] = [{}]
    sizes = [0]
    for key, arr in named.items():
        nbytes = arr.nbytes
        if sizes[-1] + nbytes > limit and shards[-1]:
            shards.append({})
            sizes.append(0)
        shards[-1][key] = arr
        sizes[-1] += nbytes
    if len(shards) == 1:
        return shards, None
    index = {"metadata": {"total_size": int(sum(sizes))}, "weight_map": {}}
    stem, ext = os.path.splitext(weights_name)
    for i, shard in enumerate(shards):
        name = f"{stem}-{i + 1:05d}-of-{len(shards):05d}{ext}"
        for key in shard:
            index["weight_map"][key] = name
    return shards, index

def save_model_weights(
    params: Any,
    save_directory: str,
    max_shard_size: str | int = "10GB",
    safe_serialization: bool = True,
) -> None:
    """Inference-ready (possibly sharded) weight files + index
    (reference accelerator.py:2712-2825)."""
    os.makedirs(save_directory, exist_ok=True)
    named = flatten_tree(_to_host(params))
    weights_name = SAFE_WEIGHTS_NAME if safe_serialization else MODEL_NAME + ".bin"
    if jax.process_index() != 0:
        return
    shards, index = shard_checkpoint(named, max_shard_size, weights_name)
    if index is None:
        _save_named(shards[0], os.path.join(save_directory, weights_name), safe_serialization)
        return
    stem, ext = os.path.splitext(weights_name)
    for i, shard in enumerate(shards):
        name = f"{stem}-{i + 1:05d}-of-{len(shards):05d}{ext}"
        _save_named(shard, os.path.join(save_directory, name), safe_serialization)
    with open(os.path.join(save_directory, SAFE_WEIGHTS_INDEX_NAME), "w") as f:
        json.dump(index, f, indent=2, sort_keys=True)

def load_model_weights(load_directory: str) -> dict[str, np.ndarray]:
    """Load (possibly sharded) weight files back into a named-tensor dict."""
    index_path = os.path.join(load_directory, SAFE_WEIGHTS_INDEX_NAME)
    if os.path.isfile(index_path):
        with open(index_path) as f:
            index = json.load(f)
        named: dict[str, np.ndarray] = {}
        for fname in sorted(set(index["weight_map"].values())):
            named.update(_load_named(os.path.join(load_directory, fname)))
        return named
    for candidate in (SAFE_WEIGHTS_NAME, MODEL_NAME + ".bin"):
        path = os.path.join(load_directory, candidate)
        if os.path.isfile(path):
            return _load_named(path)
    raise FileNotFoundError(f"no model weights found under {load_directory}")


# ---------------------------------------------------------------------- #
# whole-state save/load (reference checkpointing.py:51,152 + accelerator
# save_state/load_state :2858/:3023)
# ---------------------------------------------------------------------- #
def _checkpoint_dir(accelerator, output_dir: Optional[str]) -> str:
    """Resolve automatic naming/rotation (reference accelerator.py:2880-2915).

    Rotation runs on the main process only; the already-exists guard then
    runs on EVERY process between two barriers (first so rotation is done,
    second so no process reaches makedirs while another is still checking)
    — a main-only raise would leave the other processes hanging at the
    next collective instead of failing everywhere.
    """
    pc = accelerator.project_configuration
    if pc.automatic_checkpoint_naming:
        base = os.path.join(pc.project_dir or output_dir or ".", "checkpoints")
        out = os.path.join(base, f"checkpoint_{pc.iteration}")
        if accelerator.is_main_process:
            os.makedirs(base, exist_ok=True)
            existing = _list_checkpoints(base)
            if pc.total_limit is not None and len(existing) + 1 > pc.total_limit:
                for stale in existing[: len(existing) + 1 - pc.total_limit]:
                    logger.info(
                        f"Deleting {stale} to respect total_limit={pc.total_limit}"
                    )
                    shutil.rmtree(stale, ignore_errors=True)
        accelerator.wait_for_everyone()
        exists = os.path.exists(out)
        accelerator.wait_for_everyone()
        if exists:
            raise ValueError(
                f"Checkpoint directory {out} already exists — either load "
                "it first or set a fresh ProjectConfiguration.iteration."
            )
        return out
    if output_dir is None:
        raise ValueError("output_dir required without automatic_checkpoint_naming")
    return output_dir

def _list_checkpoints(base: str) -> list[str]:
    """Complete (committed) checkpoints under ``base``, oldest first.

    The name match is the commit protocol's read side: an in-flight or
    crashed save only ever exists under ``checkpoint_<n>.tmp`` (see
    :mod:`~accelerate_tpu.checkpoint_async.commit`), which the fullmatch
    rejects — so restore never resumes from, and rotation never counts or
    deletes, an uncommitted directory.
    """
    entries = []
    for name in os.listdir(base):
        m = re.fullmatch(r"checkpoint_(\d+)", name)
        if m:
            entries.append((int(m.group(1)), os.path.join(base, name)))
    return [p for _, p in sorted(entries)]


def _commit_mod():
    """Lazy import of the commit-protocol module (checkpoint_async imports
    this module's helpers back, so the dependency stays call-time)."""
    from .checkpoint_async import commit

    return commit


def topology_metadata(accelerator) -> dict[str, Any]:
    """The save-time topology record stamped into the commit protocol
    (``topology.json``): everything a restore on a DIFFERENT fleet needs
    to validate the checkpoint and to explain a mismatch — world size,
    device count, mesh shape, and the process -> shard-file map.

    format_version 2 adds the slice layout: top-level ``num_slices`` and
    a per-process ``fault_domain`` (slice id, slice-major contiguous
    rank numbering). Purely additive — every reader uses ``.get``, so v1
    checkpoints load unchanged and v1 readers ignore the new fields.
    """
    from .dist_checkpoint import INDEX_FILE_PATTERN, SHARD_FILE_PATTERN
    from .parallel.mesh import fault_domain_of_rank, mesh_num_slices

    world = accelerator.num_processes
    num_devices = int(accelerator.state.num_devices)
    num_slices = mesh_num_slices(accelerator.state.mesh)
    if world % max(1, num_slices) != 0:
        num_slices = 1  # inconsistent env: don't stamp an unusable layout
    return {
        "format_version": 2,
        "world_size": world,
        "num_devices": num_devices,
        "devices_per_process": num_devices // max(1, world),
        "num_slices": num_slices,
        "mesh_shape": {k: int(v) for k, v in accelerator.state.mesh.shape.items()},
        "process_shard_files": {
            str(p): {
                "shard": SHARD_FILE_PATTERN.format(p),
                "index": INDEX_FILE_PATTERN.format(p),
                "fault_domain": fault_domain_of_rank(p, world, num_slices),
            }
            for p in range(world)
        },
        "step": accelerator.step,
    }


def _capture_host_state(accelerator, carry: Any = None) -> list[tuple[str, str, Any]]:
    """Snapshot the host-side small state as ``(filename, kind, payload)``
    triples (``kind`` in ``{"json", "pickle"}``), captured NOW so an async
    writer serializes exactly the state at save time, not whatever the
    objects mutate to while the background write runs. Shared files are
    main-process-only; the per-process RNG file is always captured."""
    files: list[tuple[str, str, Any]] = []
    if accelerator.is_main_process:
        for i, sched in enumerate(accelerator._schedulers):
            files.append(
                (f"{SCHEDULER_NAME}_{i}.json", "json", _jsonable(sched.state_dict()))
            )
        for i, dl in enumerate(accelerator._dataloaders):
            state = getattr(dl, "state_dict", lambda: None)()
            if state is not None:
                files.append((f"{SAMPLER_NAME}_{i}.json", "json", _jsonable(state)))
        for i, obj in enumerate(accelerator._custom_objects):
            files.append((f"{CUSTOM_STATE_NAME}_{i}.pkl", "pickle", obj.state_dict()))
        if carry is not None and "opt_step" in carry:
            # the carry's device counters are the source of truth
            accelerator.sync_from_carry(carry)
        meta = {
            "step": accelerator.step,
            "iteration": accelerator.project_configuration.iteration,
            "version": 1,
            "has_carry": carry is not None,
            "num_optimizers": len(accelerator._optimizers),
            "num_schedulers": len(accelerator._schedulers),
            "num_dataloaders": len(accelerator._dataloaders),
            "num_custom": len(accelerator._custom_objects),
        }
        files.append((METADATA_NAME, "json", meta))

    # --- per-process RNG (reference checkpointing.py:134-148) ---
    import random as _py_random

    rng = {
        "python": _py_random.getstate(),
        "numpy": np.random.get_state(),
        "keychain": accelerator.keys.state_dict(),
    }
    files.append((f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl", "pickle", rng))
    return files


def _write_host_state(files: list[tuple[str, str, Any]], output_dir: str) -> None:
    """Write captured host state; every file lands atomically."""
    for name, kind, payload in files:
        path = os.path.join(output_dir, name)
        if kind == "json":
            indent = 2 if name == METADATA_NAME else None
            _atomic_json_dump(payload, path, indent=indent)
        else:
            _atomic_pickle_dump(payload, path)

def save_accelerator_state(
    accelerator,
    output_dir: Optional[str] = None,
    carry: Any = None,
    params: Any = None,
    safe_serialization: bool = True,
    sharded: bool = True,
) -> str:
    """Serialize the entire training state (reference checkpointing.py:51).

    ``carry`` is the compiled-step carry from :meth:`Accelerator.init_carry`
    (params + opt state + counters [+ loss scale]); alternatively pass bare
    ``params``. Custom registered objects, schedulers, dataloader positions
    and host RNG are saved alongside, file-per-object like the reference.

    ``sharded=True`` (default) uses the distributed per-process format
    (:mod:`accelerate_tpu.dist_checkpoint`): each host writes only the
    shards it owns — the FSDP ``SHARDED_STATE_DICT`` capability (reference
    utils/fsdp_utils.py:60-215), required for models that do not fit one
    host's RAM. ``sharded=False`` falls back to a rank-0 single-file
    export (all-gathers everything to every host first).

    All files are written into ``<dir>.tmp`` and published by the atomic
    commit protocol (:mod:`accelerate_tpu.checkpoint_async.commit`): a
    crash at any point leaves only an invisible work dir, never a
    half-written checkpoint that restore would pick up. For zero-stall
    saves use :func:`accelerate_tpu.checkpoint_async.save_accelerator_state_async`,
    which shares every phase of this function but runs the
    serialization+IO on a background writer.
    """
    import time as _time

    t0 = _time.perf_counter()
    final_dir = _checkpoint_dir(accelerator, output_dir)
    commit = _commit_mod()
    work_dir = commit.work_dir_for(final_dir)
    if accelerator.is_main_process:
        commit.discard_work_dir(work_dir)  # stale tmp from a crashed run
    accelerator.wait_for_everyone()
    os.makedirs(work_dir, exist_ok=True)
    logger.info(f"Saving current state to {final_dir}")
    is_main = accelerator.is_main_process
    nbytes = 0

    # --- the array state (one pytree, possibly cross-host sharded) ---
    tree = carry if carry is not None else params
    if tree is None and accelerator._models:
        tree = accelerator._models[0]
    if tree is not None:
        if sharded:
            from .dist_checkpoint import snapshot_tree, write_snapshot

            nbytes += write_snapshot(snapshot_tree(tree), work_dir, fsync=True)
        else:
            named = flatten_tree(_to_host(tree))
            if is_main:
                arrays = {k: v for k, v in named.items() if _is_arraylike(v)}
                _save_named(
                    arrays,
                    os.path.join(
                        work_dir,
                        SAFE_WEIGHTS_NAME if safe_serialization else MODEL_NAME + ".bin",
                    ),
                    safe_serialization,
                )
                nbytes += sum(np.asarray(v).nbytes for v in arrays.values())

    # --- optimizer states not inside the carry (raw-loop usage) ---
    if carry is None:
        for i, opt in enumerate(accelerator._optimizers):
            if opt.opt_state is not None and is_main:
                named = flatten_tree(_to_host(opt.opt_state))
                arrays = {k: v for k, v in named.items() if _is_arraylike(v)}
                _save_named(
                    arrays, os.path.join(work_dir, f"{OPTIMIZER_NAME}_{i}.safetensors"), True
                )
                nbytes += sum(np.asarray(v).nbytes for v in arrays.values())

    # --- host-side small state (schedulers, samplers, custom, meta, RNG) ---
    _write_host_state(_capture_host_state(accelerator, carry), work_dir)

    accelerator.project_configuration.iteration += 1
    commit.commit(
        work_dir,
        final_dir,
        accelerator.process_index,
        accelerator.num_processes,
        topology=topology_metadata(accelerator),
    )
    accelerator.wait_for_everyone()
    telemetry = getattr(accelerator, "telemetry", None)
    if telemetry is not None:
        telemetry.record_checkpoint(
            step=accelerator.step,
            directory=final_dir,
            mode="sync",
            blocked_s=_time.perf_counter() - t0,
            background_s=0.0,
            bytes_written=nbytes,
        )
    return final_dir

def _topology_mismatch(saved: dict, accelerator) -> Optional[str]:
    """A one-line description of how the live fleet differs from the
    save-time topology, or None when they match. Mesh-shape-only changes
    on the same fleet (e.g. dp=2,fsdp=4 -> dp=4,fsdp=2) are NOT a
    mismatch: the template's shardings already drive that re-slicing and
    every per-host file is necessarily present."""
    cur_world = accelerator.num_processes
    cur_devices = int(accelerator.state.num_devices)
    diffs = []
    if int(saved.get("world_size", cur_world)) != cur_world:
        diffs.append(f"world size {saved['world_size']} -> {cur_world}")
    if int(saved.get("num_devices", cur_devices)) != cur_devices:
        diffs.append(f"device count {saved['num_devices']} -> {cur_devices}")
    return ", ".join(diffs) if diffs else None


def load_accelerator_state(
    accelerator,
    input_dir: Optional[str] = None,
    carry: Any = None,
    params: Any = None,
    allow_reshape: Optional[bool] = None,
) -> Any:
    """Restore state saved by :func:`save_accelerator_state` (reference
    checkpointing.py:152 / accelerator.py:3023). Pass the same-structured
    ``carry`` (or ``params``) as a template; returns it filled with
    checkpointed values, re-placed on the template's shardings.

    ``allow_reshape`` controls topology-independent restore. A checkpoint
    stamped with a different save-time topology (world size or device
    count) refuses to load by default — the error names both topologies.
    With ``allow_reshape=True`` the full chunk coverage across every
    per-host file is validated first, the array state is re-sliced onto
    the live shardings, and the non-sliceable host state follows explicit
    re-derivation rules:

    * **RNG**: every rank restores rank 0's saved streams, and the
      KeyChain folds in the NEW process index — deterministic and
      distinct per rank, but a different stream than an uninterrupted
      run (unavoidable when ranks appear or disappear);
    * **grad-accum remainder**: a carry saved mid-accumulation
      (``micro_step != 0``) resumes at the last optimizer-step boundary
      (the partial ``accum_grads`` sum is zeroed) because microbatch
      boundaries do not map across world sizes;
    * **data-loader cursor**: positions re-derive by samples seen, not
      batch index (see ``DataLoaderShard.load_state_dict``).

    ``allow_reshape=None`` (default) resolves from the
    ``ACCELERATE_TPU_ELASTIC`` env flag, so runs relaunched by the
    elastic supervisor reshape without every train script needing the
    kwarg."""
    if input_dir is None:
        pc = accelerator.project_configuration
        base = os.path.join(pc.project_dir or ".", "checkpoints")
        cks = _list_checkpoints(base)
        if not cks:
            raise FileNotFoundError(f"no checkpoints under {base}")
        input_dir = cks[-1]
    logger.info(f"Loading states from {input_dir}")

    meta = {}
    meta_path = os.path.join(input_dir, METADATA_NAME)
    if os.path.isfile(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)

    if allow_reshape is None:
        from .utils.constants import ENV_PREFIX
        from .utils.environment import parse_flag_from_env

        allow_reshape = parse_flag_from_env(ENV_PREFIX + "ELASTIC")
    from .checkpoint_async.commit import read_topology

    saved_topology = read_topology(input_dir)
    mismatch = (
        _topology_mismatch(saved_topology, accelerator)
        if saved_topology is not None
        else None
    )
    reshaped = mismatch is not None
    if reshaped and not allow_reshape:
        cur = topology_metadata(accelerator)
        raise ValueError(
            f"checkpoint {input_dir} was saved on a different topology "
            f"({mismatch}): saved world_size={saved_topology['world_size']} "
            f"num_devices={saved_topology.get('num_devices')} "
            f"mesh={saved_topology.get('mesh_shape')}, live "
            f"world_size={cur['world_size']} num_devices={cur['num_devices']} "
            f"mesh={cur['mesh_shape']}. Pass allow_reshape=True to "
            "load_state (or launch under --elastic) to re-slice the shards "
            "onto the live topology."
        )
    if reshaped:
        from .dist_checkpoint import is_sharded_checkpoint, validate_coverage

        if is_sharded_checkpoint(input_dir):
            stats = validate_coverage(input_dir)
            logger.warning(
                f"reshaping checkpoint {input_dir} ({mismatch}): "
                f"{stats['chunks']} chunks across {stats['files']} per-host "
                f"files fully cover all {stats['leaves']} leaves"
            )

    template = carry if carry is not None else params
    result = None
    if template is not None:
        from .dist_checkpoint import is_sharded_checkpoint, load_sharded_tree

        if is_sharded_checkpoint(input_dir):
            # strict=False: leaves absent from the file keep the template's
            # value (legacy merge semantics, e.g. a new loss_scale leaf)
            result = load_sharded_tree(template, input_dir, strict=False)
        else:
            named = load_model_weights(input_dir)
            # non-array leaves (counters saved as arrays) restore fine;
            # anything missing falls back to the template's current value.
            flat_template = flatten_tree(template)
            merged = {k: named.get(k, v) for k, v in flat_template.items()}
            result = unflatten_into(template, merged)

    if carry is None:
        for i, opt in enumerate(accelerator._optimizers):
            path = os.path.join(input_dir, f"{OPTIMIZER_NAME}_{i}.safetensors")
            if os.path.isfile(path) and opt.opt_state is not None:
                named = _load_named(path)
                opt.opt_state = unflatten_into(opt.opt_state, named)

    for i, sched in enumerate(accelerator._schedulers):
        path = os.path.join(input_dir, f"{SCHEDULER_NAME}_{i}.json")
        if os.path.isfile(path):
            with open(path) as f:
                sched.load_state_dict(json.load(f))
    for i, dl in enumerate(accelerator._dataloaders):
        path = os.path.join(input_dir, f"{SAMPLER_NAME}_{i}.json")
        if os.path.isfile(path) and hasattr(dl, "load_state_dict"):
            with open(path) as f:
                dl.load_state_dict(json.load(f))
    for i, obj in enumerate(accelerator._custom_objects):
        path = os.path.join(input_dir, f"{CUSTOM_STATE_NAME}_{i}.pkl")
        if os.path.isfile(path):
            with open(path, "rb") as f:
                obj.load_state_dict(pickle.load(f))

    rng_path = os.path.join(
        input_dir, f"{RNG_STATE_NAME}_{accelerator.process_index}.pkl"
    )
    if reshaped or not os.path.isfile(rng_path):
        # re-derivation rule: on a topology change a rank's own saved RNG
        # file may not exist (M>N) or may belong to a rank holding
        # different data shards (M<N), so EVERY rank restores rank 0's
        # streams and the keychain folds in the new process index below —
        # deterministic per (checkpoint, new rank), never rank-aliased.
        rng_path = os.path.join(input_dir, f"{RNG_STATE_NAME}_0.pkl")
    if os.path.isfile(rng_path):
        import random as _py_random

        with open(rng_path, "rb") as f:
            rng = pickle.load(f)
        _py_random.setstate(rng["python"])
        np.random.set_state(rng["numpy"])
        accelerator.keys.load_state_dict(rng["keychain"])
        if reshaped:
            from .utils.random import KeyChain

            accelerator.keys = KeyChain(
                accelerator.keys.fold_in(accelerator.process_index)
            )

    if reshaped and isinstance(result, dict) and "micro_step" in result:
        micro = int(np.asarray(jax.device_get(result["micro_step"])))
        if micro != 0:
            logger.warning(
                f"checkpoint was saved mid-accumulation (micro_step={micro}); "
                "microbatch boundaries do not map across world sizes, so the "
                "partial gradient sum is dropped and the run resumes at the "
                "last optimizer-step boundary"
            )
            def _zeros_like_sharded(x):
                z = jnp.zeros(x.shape, x.dtype)
                if isinstance(
                    getattr(x, "sharding", None), jax.sharding.NamedSharding
                ):
                    z = jax.device_put(z, x.sharding)
                return z

            result = dict(result)
            result["micro_step"] = _zeros_like_sharded(result["micro_step"])
            if "accum_grads" in result:
                result["accum_grads"] = jax.tree.map(
                    _zeros_like_sharded, result["accum_grads"]
                )

    if "step" in meta:
        accelerator.step = int(meta["step"])
    if carry is not None and isinstance(result, dict) and "opt_step" in result:
        accelerator.sync_from_carry(result)
    if "iteration" in meta:
        accelerator.project_configuration.iteration = int(meta["iteration"]) + 1
    return result


def _is_arraylike(v: Any) -> bool:
    return isinstance(v, (np.ndarray, jax.Array)) or np.isscalar(v)

def _jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.ndarray, jax.Array)):
        return np.asarray(obj).tolist()
    return obj
