"""Experiment tracking.

Parity: reference ``src/accelerate/tracking.py`` (1023 LoC) —
``GeneralTracker`` ABC :91 (``store_init_configuration`` :132, ``log`` :144,
``finish`` :157, ``@on_main_process`` :67), backends
TensorBoard :165 / WandB :276 / CometML :399 / Aim :480 / MLflow :579 /
ClearML :724 / DVCLive :876, registry ``LOGGER_TYPE_TO_CLASS`` :960 and
``filter_trackers`` :971.

TPU-native notes: logging is host-side and main-process-only exactly like
the reference; metric values may arrive as live ``jax.Array``s — we
``device_get`` scalars lazily so logging never forces a blocking sync inside
the step loop beyond the value actually logged. A zero-dependency
:class:`JSONLTracker` is first-class (the others gate on their libraries).
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Any, Optional, Union

import jax
import numpy as np

from .logging import get_logger
from .state import PartialState
from .utils.dataclasses import LoggerType
from .utils.imports import (
    is_aim_available,
    is_clearml_available,
    is_comet_ml_available,
    is_dvclive_available,
    is_mlflow_available,
    is_tensorboard_available,
    is_wandb_available,
)

logger = get_logger(__name__)

_available_trackers: list[LoggerType] = [LoggerType.JSONL]
if is_tensorboard_available():
    _available_trackers.append(LoggerType.TENSORBOARD)
if is_wandb_available():
    _available_trackers.append(LoggerType.WANDB)
if is_comet_ml_available():
    _available_trackers.append(LoggerType.COMETML)
if is_aim_available():
    _available_trackers.append(LoggerType.AIM)
if is_mlflow_available():
    _available_trackers.append(LoggerType.MLFLOW)
if is_clearml_available():
    _available_trackers.append(LoggerType.CLEARML)
if is_dvclive_available():
    _available_trackers.append(LoggerType.DVCLIVE)


def get_available_trackers() -> list[LoggerType]:
    """Reference tracking.py:87."""
    return list(_available_trackers)


def on_main_process(function):
    """Run the decorated tracker method on the main process only
    (reference tracking.py:67)."""

    @functools.wraps(function)
    def execute_on_main_process(self, *args, **kwargs):
        if getattr(self, "main_process_only", True):
            state = PartialState()
            if not state.is_main_process:
                return None
        return function(self, *args, **kwargs)

    return execute_on_main_process


def _scalarize(values: dict) -> dict:
    """Fetch jax scalars to python numbers; pass strings through."""
    out = {}
    for k, v in values.items():
        if isinstance(v, (jax.Array, np.ndarray)):
            v = np.asarray(v)
            out[k] = v.item() if v.ndim == 0 else v.tolist()
        elif isinstance(v, (np.integer, np.floating)):
            out[k] = v.item()
        else:
            out[k] = v
    return out


class GeneralTracker:
    """Tracker ABC (reference tracking.py:91). Subclasses set ``name`` and
    ``requires_logging_directory`` and implement ``store_init_configuration``
    and ``log``; ``tracker`` returns the underlying run object."""

    main_process_only = True
    name: str = "general"
    requires_logging_directory: bool = False

    def __init__(self, _blank: bool = False):
        if not _blank:
            for attr in ("name", "requires_logging_directory"):
                if getattr(self.__class__, attr, None) is None:
                    raise NotImplementedError(
                        f"Tracker {self.__class__.__name__} must set `{attr}`"
                    )

    @property
    def tracker(self):
        raise NotImplementedError

    def store_init_configuration(self, values: dict):
        raise NotImplementedError

    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        raise NotImplementedError

    def finish(self):
        pass


class JSONLTracker(GeneralTracker):
    """Zero-dependency file tracker: one JSON object per log call. The
    TPU-native default — greppable, rsyncable off a pod, no daemon."""

    name = "jsonl"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Union[str, os.PathLike] = "."):
        super().__init__()
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir or ".", run_name)
        os.makedirs(self.logging_dir, exist_ok=True)
        self._path = os.path.join(self.logging_dir, "metrics.jsonl")
        self._file = open(self._path, "a", buffering=1)
        logger.debug(f"Initialized JSONL tracker at {self._path}")

    @property
    def tracker(self):
        return self._file

    @on_main_process
    def store_init_configuration(self, values: dict):
        with open(os.path.join(self.logging_dir, "config.json"), "w") as f:
            json.dump(_scalarize(values), f, indent=2, default=str)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        record = {"_time": time.time()}
        if step is not None:
            record["_step"] = int(step)
        record.update(_scalarize(values))
        self._file.write(json.dumps(record, default=str) + "\n")

    @on_main_process
    def finish(self):
        if not self._file.closed:
            self._file.close()


class TensorBoardTracker(GeneralTracker):
    """Reference tracking.py:165."""

    name = "tensorboard"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: Union[str, os.PathLike] = ".",
                 **kwargs):
        super().__init__()
        try:
            from torch.utils import tensorboard
        except ImportError:
            import tensorboardX as tensorboard
        self.run_name = run_name
        self.logging_dir = os.path.join(logging_dir or ".", run_name)
        self.writer = tensorboard.SummaryWriter(self.logging_dir, **kwargs)
        logger.debug(f"Initialized TensorBoard project {run_name} at {self.logging_dir}")

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.add_hparams(_flatten_config(_scalarize(values)), metric_dict={})
        self.writer.flush()
        try:
            with open(os.path.join(self.logging_dir, "hparams.yml"), "w") as out:
                try:
                    import yaml

                    yaml.dump(_scalarize(values), out)
                except ImportError:
                    json.dump(_scalarize(values), out, default=str)
        except Exception:
            logger.error("Serialization to store hyperparameters failed")

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        values = _scalarize(values)
        for k, v in values.items():
            if isinstance(v, (int, float)):
                self.writer.add_scalar(k, v, global_step=step, **kwargs)
            elif isinstance(v, str):
                self.writer.add_text(k, v, global_step=step, **kwargs)
            elif isinstance(v, dict):
                self.writer.add_scalars(k, v, global_step=step, **kwargs)
        self.writer.flush()

    @on_main_process
    def finish(self):
        self.writer.close()


class WandBTracker(GeneralTracker):
    """Reference tracking.py:276."""

    name = "wandb"
    requires_logging_directory = False
    main_process_only = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        import wandb

        self.run_name = run_name
        self.run = wandb.init(project=self.run_name, **kwargs)
        logger.debug(f"Initialized WandB project {self.run_name}")

    @property
    def tracker(self):
        return self.run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import wandb

        wandb.config.update(_scalarize(values), allow_val_change=True)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        self.run.log(_scalarize(values), step=step, **kwargs)

    @on_main_process
    def log_table(self, table_name: str, columns: Optional[list] = None,
                  data: Optional[list] = None, dataframe: Any = None,
                  step: Optional[int] = None, **kwargs):
        import wandb

        values = {table_name: wandb.Table(columns=columns, data=data, dataframe=dataframe)}
        self.log(values, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.run.finish()


class MLflowTracker(GeneralTracker):
    """Reference tracking.py:579."""

    name = "mlflow"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, experiment_name: Optional[str] = None,
                 logging_dir: Optional[str] = None, run_id: Optional[str] = None,
                 tags: Optional[dict] = None, nested_run: bool = False,
                 run_name: Optional[str] = None, description: Optional[str] = None):
        super().__init__()
        import mlflow

        experiment_name = os.environ.get("MLFLOW_EXPERIMENT_NAME", experiment_name)
        run_id = os.environ.get("MLFLOW_RUN_ID", run_id)
        exps = mlflow.search_experiments(filter_string=f"name = '{experiment_name}'")
        if exps:
            experiment_id = exps[0].experiment_id
        else:
            experiment_id = mlflow.create_experiment(
                name=experiment_name, artifact_location=logging_dir, tags=tags
            )
        self.active_run = mlflow.start_run(
            run_id=run_id, experiment_id=experiment_id, run_name=run_name,
            nested=nested_run, tags=tags, description=description,
        )
        logger.debug(f"Initialized mlflow experiment {experiment_name}")

    @property
    def tracker(self):
        return self.active_run

    @on_main_process
    def store_init_configuration(self, values: dict):
        import mlflow

        for chunk in _chunk_dict(_scalarize(values), mlflow.utils.validation.MAX_PARAMS_TAGS_PER_BATCH):
            mlflow.log_params(chunk)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        import mlflow

        metrics = {k: v for k, v in _scalarize(values).items() if isinstance(v, (int, float))}
        mlflow.log_metrics(metrics, step=step)

    @on_main_process
    def finish(self):
        import mlflow

        mlflow.end_run()


class CometMLTracker(GeneralTracker):
    """Reference tracking.py:399."""

    name = "comet_ml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: str, **kwargs):
        super().__init__()
        from comet_ml import Experiment

        self.run_name = run_name
        self.writer = Experiment(project_name=run_name, **kwargs)
        logger.debug(f"Initialized CometML project {self.run_name}")

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer.log_parameters(_scalarize(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.writer.set_step(step)
        self.writer.log_others(_scalarize(values))

    @on_main_process
    def finish(self):
        self.writer.end()


class AimTracker(GeneralTracker):
    """Reference tracking.py:480."""

    name = "aim"
    requires_logging_directory = True

    @on_main_process
    def __init__(self, run_name: str, logging_dir: str = ".", **kwargs):
        super().__init__()
        from aim import Run

        self.run_name = run_name
        self.writer = Run(repo=logging_dir, **kwargs)
        self.writer.name = self.run_name

    @property
    def tracker(self):
        return self.writer

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.writer["hparams"] = _scalarize(values)

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in _scalarize(values).items():
            self.writer.track(v, name=k, step=step, **kwargs)

    @on_main_process
    def finish(self):
        self.writer.close()


class ClearMLTracker(GeneralTracker):
    """Reference tracking.py:724."""

    name = "clearml"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, **kwargs):
        super().__init__()
        from clearml import Task

        current = Task.current_task()
        self._initialized_externally = current is not None
        self.task = current or Task.init(
            project_name=kwargs.pop("project_name", run_name),
            task_name=kwargs.pop("task_name", run_name), **kwargs,
        )

    @property
    def tracker(self):
        return self.task

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.task.connect_configuration(_scalarize(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        for k, v in _scalarize(values).items():
            if isinstance(v, (int, float)) and step is None:
                self.task.get_logger().report_single_value(name=k, value=v, **kwargs)
            elif isinstance(v, (int, float)):
                title, _, series = k.partition("/")
                self.task.get_logger().report_scalar(
                    title=title, series=series or title, value=v, iteration=step, **kwargs
                )

    @on_main_process
    def finish(self):
        if not self._initialized_externally:
            self.task.close()


class DVCLiveTracker(GeneralTracker):
    """Reference tracking.py:876."""

    name = "dvclive"
    requires_logging_directory = False

    @on_main_process
    def __init__(self, run_name: Optional[str] = None, live: Any = None, **kwargs):
        super().__init__()
        from dvclive import Live

        self.live = live if live is not None else Live(**kwargs)

    @property
    def tracker(self):
        return self.live

    @on_main_process
    def store_init_configuration(self, values: dict):
        self.live.log_params(_scalarize(values))

    @on_main_process
    def log(self, values: dict, step: Optional[int] = None, **kwargs):
        if step is not None:
            self.live.step = step
        for k, v in _scalarize(values).items():
            if isinstance(v, (int, float)):
                self.live.log_metric(k, v, **kwargs)
        self.live.next_step()

    @on_main_process
    def finish(self):
        self.live.end()


LOGGER_TYPE_TO_CLASS = {
    "aim": AimTracker,
    "comet_ml": CometMLTracker,
    "mlflow": MLflowTracker,
    "tensorboard": TensorBoardTracker,
    "wandb": WandBTracker,
    "clearml": ClearMLTracker,
    "dvclive": DVCLiveTracker,
    "jsonl": JSONLTracker,
}


def filter_trackers(
    log_with: list,
    logging_dir: Optional[str] = None,
    project_name: str = "accelerate_tpu",
    config: Optional[dict] = None,
    init_kwargs: Optional[dict] = None,
) -> list[GeneralTracker]:
    """Instantiate requested-and-available trackers (reference :971)."""
    loggers: list[GeneralTracker] = []
    init_kwargs = init_kwargs or {}
    requested: list[Any] = []
    for item in log_with or []:
        if issubclass(type(item), GeneralTracker):
            loggers.append(item)
            continue
        item = LoggerType(str(item).lower())
        if item == LoggerType.ALL:
            requested = get_available_trackers()
            break
        requested.append(item)
    for ltype in requested:
        if ltype not in _available_trackers:
            logger.warning(f"Tried adding logger {ltype} but package is not installed")
            continue
        cls = LOGGER_TYPE_TO_CLASS[str(ltype)]
        kwargs = dict(init_kwargs.get(str(ltype), {}))
        if cls.requires_logging_directory:
            if logging_dir is None:
                logger.warning(
                    f"Logging with {ltype} requires a logging_dir; skipping"
                )
                continue
            kwargs.setdefault("logging_dir", logging_dir)
        tracker = cls(project_name, **kwargs)
        if config:
            tracker.store_init_configuration(config)
        loggers.append(tracker)
    return loggers


def telemetry_bridge(trackers: Any, prefix: str = "telemetry/"):
    """Bridge step telemetry into these trackers.

    Returns a :class:`~accelerate_tpu.telemetry.TrackerBridgeSink` that
    forwards every numeric field of each step record (step time, tokens/s,
    HBM peak, dataloader wait, loss, ...) to ``tracker.log`` under
    ``prefix`` — so any of the tracking backends doubles as a telemetry
    dashboard::

        accelerator.telemetry.add_sink(telemetry_bridge(accelerator))

    ``trackers``: a tracker list or anything exposing ``.trackers`` (the
    Accelerator itself — resolved lazily, so the bridge may be attached
    before ``init_trackers``).
    """
    # lazy import: tracking must stay importable without the telemetry
    # package and vice versa (telemetry.sinks duck-types trackers)
    from .telemetry import TrackerBridgeSink

    return TrackerBridgeSink(trackers, prefix=prefix)


def _flatten_config(values: dict, prefix: str = "") -> dict:
    out = {}
    for k, v in values.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten_config(v, prefix=f"{key}/"))
        elif isinstance(v, (int, float, str, bool)):
            out[key] = v
        else:
            out[key] = str(v)
    return out


def _chunk_dict(d: dict, size: int):
    items = list(d.items())
    for i in range(0, len(items), size):
        yield dict(items[i : i + size])
