"""Local SGD: train replicas independently, average parameters every K steps.

Parity: reference ``local_sgd.py:19-102`` — ``LocalSGD(accelerator, model,
local_sgd_steps, enabled)`` wraps the training loop, suppresses the DDP
gradient all-reduce inside the window (``no_sync``) and calls a manual
parameter ``all_reduce`` mean every ``local_sgd_steps`` steps.

TPU-native redesign: under GSPMD there is no grad-hook to suppress — cross-
replica sync is implied by array shardings. Independent local training is
expressed in one of two ways:

* **multi-process** (one trainer per host, the reference's setting): keep
  params host-local (not globally sharded); each process steps its own
  copy, and :meth:`LocalSGD.step` performs the periodic cross-process
  parameter mean (``utils.operations.reduce``) — exactly the reference's
  ``_sync_and_avg_model_params``.
* **single-process SPMD**: give each data-parallel group its own weights by
  stacking params on a leading ``dp``-sharded replica dim
  (:func:`replicate_params`) and training with a vmapped loss; the periodic
  :func:`average_replicas` mean collapses the stacked dim — XLA lowers it
  to an all-reduce over the ``dp`` axis of the mesh.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .logging import get_logger

logger = get_logger(__name__)


class LocalSGD:
    """Context manager for periodic parameter averaging (reference :19).

    Usage (multi-process)::

        with LocalSGD(accelerator, local_sgd_steps=8) as lsgd:
            for batch in loader:
                carry, _ = step(carry, batch)
                carry = lsgd.step(carry)

    ``step`` must be called once per optimizer step with either the train
    carry (its ``"params"`` — and, so stale moments do not undo the
    averaging, ``"opt_state"`` — are averaged) or a bare param tree; it
    returns the same structure, averaged on sync steps. On ``__exit__`` a
    final average runs unless the step count already landed on a boundary
    (reference :78 syncs on leaving the context).
    """

    def __init__(
        self,
        accelerator,
        local_sgd_steps: int = 8,
        enabled: bool = True,
    ):
        if local_sgd_steps < 1:
            raise ValueError(f"local_sgd_steps must be >= 1, got {local_sgd_steps}")
        self.accelerator = accelerator
        self.local_sgd_steps = local_sgd_steps
        self.enabled = enabled
        self.num_steps = 0
        self._last_tree: Any = None

    def __enter__(self) -> "LocalSGD":
        self.num_steps = 0
        if self.enabled and self.accelerator.num_processes == 1:
            logger.debug(
                "LocalSGD on a single process averages over the in-process "
                "replica dim only (see replicate_params)"
            )
        return self

    def __exit__(self, *exc):
        # final flush so replicas agree when the loop length is not a
        # multiple of local_sgd_steps (reference :78). Only dict carries can
        # be updated in place; any other container must be flushed by the
        # caller (``carry = lsgd.flush(carry)``) — warn instead of silently
        # leaving replicas diverged.
        if (
            self.enabled
            and exc[0] is None
            and self._last_tree is not None
            and self.num_steps % self.local_sgd_steps != 0
        ):
            if isinstance(self._last_tree, dict):
                logger.debug("LocalSGD: final parameter average on exit")
                averaged = self._average(self._last_tree)
                _copy_into(self._last_tree, averaged)
            else:
                logger.warning(
                    "LocalSGD exited mid-window with a non-dict tree; the "
                    "exit flush cannot update it in place — call "
                    "`tree = local_sgd.flush(tree)` before leaving the "
                    "context or replicas stay diverged."
                )
        return False

    def step(self, tree: Any) -> Any:
        """Advance the step counter; every ``local_sgd_steps``-th call
        returns the cross-replica parameter average of ``tree``."""
        if not self.enabled:
            return tree
        self.num_steps += 1
        self._last_tree = tree
        if self.num_steps % self.local_sgd_steps != 0:
            return tree
        out = self._average(tree)
        self._last_tree = out
        return out

    def flush(self, tree: Any) -> Any:
        """Force an average now regardless of the window position — returns
        the synced tree (use before leaving the context with non-dict
        trees, or at eval boundaries)."""
        if not self.enabled:
            return tree
        out = self._average(tree)
        self._last_tree = out
        self.num_steps = 0
        return out

    def _average(self, tree: Any) -> Any:
        from .utils.operations import reduce

        if jax.process_count() == 1:
            # cross-PROCESS mean of one process is the identity; skipping it
            # also avoids flooding XLA:CPU's collective rendezvous with
            # hundreds of small per-leaf eager programs between queued train
            # steps (observed deadlock-abort on the virtual test mesh). The
            # in-process replica-dim pattern averages via average_replicas.
            return tree
        if isinstance(tree, dict) and "params" in tree:
            out = dict(tree)
            out["params"] = reduce(tree["params"], "mean")
            if "opt_state" in tree:
                out["opt_state"] = _average_float_leaves(tree["opt_state"])
            return out
        return reduce(tree, "mean")


def _average_float_leaves(tree: Any) -> Any:
    """Cross-process mean of floating leaves only (Adam moments); integer
    leaves (step counts) pass through untouched."""
    from .utils.operations import reduce

    return jax.tree.map(
        lambda x: reduce(x, "mean")
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)
        else x,
        tree,
    )


def _copy_into(dst: Any, src: Any) -> None:
    """Best-effort in-place update for the exit-flush (dict carries)."""
    if isinstance(dst, dict) and isinstance(src, dict):
        for k in src:
            dst[k] = src[k]


# ---------------------------------------------------------------------- #
# single-process SPMD expression: a dp-sharded replica dim
# ---------------------------------------------------------------------- #
def replicate_params(
    params: Any, mesh, num_replicas: Optional[int] = None
) -> Any:
    """Stack ``num_replicas`` copies of ``params`` on a new leading dim
    sharded over the ``dp`` mesh axis: each data-parallel group now owns an
    *independent* copy (train it with a vmapped loss), which is the SPMD
    form of "no gradient sync"."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .utils.constants import MESH_AXIS_DATA

    n = num_replicas or mesh.shape[MESH_AXIS_DATA]
    if n % mesh.shape[MESH_AXIS_DATA]:
        raise ValueError(
            f"num_replicas {n} must be a multiple of dp={mesh.shape[MESH_AXIS_DATA]}"
        )

    def _one(leaf):
        stacked = jnp.broadcast_to(leaf[None], (n,) + leaf.shape)
        spec = P(MESH_AXIS_DATA, *([None] * leaf.ndim))
        return jax.device_put(stacked, NamedSharding(mesh, spec))

    return jax.tree.map(_one, params)


def average_replicas(params: Any) -> Any:
    """Collapse the leading replica dim by mean — lowered by XLA to an
    all-reduce over the ``dp`` axis when the dim is dp-sharded."""
    return jax.tree.map(lambda leaf: jnp.mean(leaf, axis=0), params)
