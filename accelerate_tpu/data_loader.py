"""Data pipeline: shard host data across processes, land it in HBM as
globally-sharded arrays, prefetch ahead of the step.

Parity: reference ``src/accelerate/data_loader.py`` (1149 LoC):
``SeedableRandomSampler``:67, ``BatchSamplerShard``:100,
``IterableDatasetShard``:256, ``DataLoaderShard``:391 (one-batch-lookahead
iter :445-476), ``MpDeviceLoaderWrapper``:521, ``DataLoaderDispatcher``:562,
``prepare_data_loader``:797, ``skip_first_batches``:1082.

TPU-native redesign:

* Batches are **global jax.Arrays** with a ``NamedSharding`` over the data
  axes of the mesh — on multi-host, each process contributes its local
  shard via ``jax.make_array_from_process_local_data`` and XLA sees ONE
  logical batch; there is no per-rank tensor juggling above this module.
* Device placement is double-buffered by a background prefetch thread (the
  seat of torch-xla's ``MpDeviceLoader`` per-core prefetch :521), so the
  H2D copy of batch N+1 overlaps step N.
* XLA needs static shapes: the uneven tail batch is padded (and recorded in
  ``remainder``) instead of shipped ragged; ``gather_for_metrics`` uses the
  remainder to drop the padding — the fixed-shape answer to the reference's
  ``even_batches``/``join_uneven_inputs`` machinery.
"""

from __future__ import annotations

import math
import threading
import time
import queue as queue_mod
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import numpy as np

from .logging import get_logger
from .parallel.sharding import batch_sharding
from .state import AcceleratorState, GradientState
from .utils.dataclasses import DataLoaderConfiguration
from .utils.operations import broadcast_object_list, find_batch_size, recursively_apply

logger = get_logger(__name__)


def _to_numpy(batch: Any) -> Any:
    """Convert a host batch (torch tensors / lists / scalars) to numpy."""

    def _is_convertible(x):
        if isinstance(x, np.ndarray):
            return True
        # torch tensor without importing torch eagerly
        return type(x).__module__.startswith("torch") and hasattr(x, "numpy")

    def _conv(x):
        if isinstance(x, np.ndarray):
            return x
        return x.detach().cpu().numpy() if hasattr(x, "detach") else np.asarray(x)

    return recursively_apply(_conv, batch, test_type=_is_convertible)


class SeedableRandomSampler:
    """Deterministic epoch-seeded permutation sampler (reference
    data_loader.py:67): every process computes the identical shuffle from
    (seed, epoch) — no RNG-state broadcast needed, unlike the reference."""

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.length = data_source_len
        self.seed = seed
        self.epoch = epoch

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return self.length

    def __iter__(self) -> Iterator[int]:
        rng = np.random.default_rng(self.seed + self.epoch)
        yield from rng.permutation(self.length).tolist()


class RandomSampler:
    """Non-seedable shuffle drawing from the process-global numpy RNG
    (reference RandomSampler path when use_seedable_sampler=False); identical
    shuffles across processes then rely on synchronize_rng_states."""

    def __init__(self, data_source_len: int):
        self.length = data_source_len

    def set_epoch(self, epoch: int) -> None:
        pass

    def __len__(self) -> int:
        return self.length

    def __iter__(self) -> Iterator[int]:
        yield from np.random.permutation(self.length).tolist()


class SequentialSampler:
    def __init__(self, data_source_len: int):
        self.length = data_source_len

    def set_epoch(self, epoch: int) -> None:
        pass

    def __len__(self) -> int:
        return self.length

    def __iter__(self) -> Iterator[int]:
        yield from range(self.length)


class BatchSamplerShard:
    """Yield this process's slice of each global batch of indices
    (reference data_loader.py:100).

    ``even_batches=True`` wraps around to complete the tail batch
    (reference _iter_with_split:186 wraparound); ``False`` yields the short
    tail — DataLoaderShard then pads it for XLA and records the remainder.
    """

    def __init__(
        self,
        sampler,
        batch_size: int,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches and batch_size % num_processes != 0:
            raise ValueError(
                f"batch_size {batch_size} must be divisible by num_processes "
                f"{num_processes} when split_batches=True"
            )
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches

    @property
    def global_batch_size(self) -> int:
        return (
            self.batch_size
            if self.split_batches
            else self.batch_size * self.num_processes
        )

    @property
    def local_batch_size(self) -> int:
        return self.global_batch_size // self.num_processes

    def __len__(self) -> int:
        n = len(self.sampler)
        if self.drop_last:
            return n // self.global_batch_size
        return math.ceil(n / self.global_batch_size)

    def set_epoch(self, epoch: int) -> None:
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __iter__(self) -> Iterator[tuple[list[int], int]]:
        """Yields (local_indices, global_valid_count) pairs."""
        indices = list(self.sampler)
        gbs = self.global_batch_size
        for start in range(0, len(indices), gbs):
            batch = indices[start : start + gbs]
            if len(batch) < gbs:
                if self.drop_last:
                    return
                valid = len(batch)
                if self.even_batches:
                    # wrap around the dataset to fill (reference :186-207)
                    while len(batch) < gbs:
                        batch += indices[: gbs - len(batch)]
                else:
                    # short tail: repeat last index to keep shapes static;
                    # remainder tracking drops the padding in metrics.
                    batch = batch + [batch[-1]] * (gbs - len(batch))
                local = batch[
                    self.process_index * self.local_batch_size : (self.process_index + 1)
                    * self.local_batch_size
                ]
                yield local, valid
            else:
                local = batch[
                    self.process_index * self.local_batch_size : (self.process_index + 1)
                    * self.local_batch_size
                ]
                yield local, gbs


class IterableDatasetShard:
    """Shard an iterable (no len / no random access) across processes
    (reference data_loader.py:256): collect global batches from the stream,
    each process keeps its slice; tail padded + remainder reported."""

    def __init__(
        self,
        iterable: Iterable,
        batch_size: int,
        num_processes: int = 1,
        process_index: int = 0,
        drop_last: bool = False,
        even_batches: bool = True,
    ):
        self.iterable = iterable
        self.batch_size = batch_size
        self.num_processes = num_processes
        self.process_index = process_index
        self.drop_last = drop_last
        self.even_batches = even_batches

    def __iter__(self) -> Iterator[tuple[list[Any], int]]:
        gbs = self.batch_size * self.num_processes
        buffer: list[Any] = []
        first_batch: Optional[list[Any]] = None
        for item in self.iterable:
            buffer.append(item)
            if len(buffer) == gbs:
                if first_batch is None:
                    first_batch = list(buffer)
                yield buffer[
                    self.process_index * self.batch_size : (self.process_index + 1)
                    * self.batch_size
                ], gbs
                buffer = []
        if buffer and not self.drop_last:
            valid = len(buffer)
            pad_src = buffer if not self.even_batches else (buffer + (first_batch or buffer))
            while len(buffer) < gbs:
                buffer.append(pad_src[len(buffer) % len(pad_src)] if self.even_batches else buffer[-1])
            yield buffer[
                self.process_index * self.batch_size : (self.process_index + 1)
                * self.batch_size
            ], valid


def _sharding_data_degree(sharding) -> int:
    """Number of shards the batch dim is split into under ``sharding``."""
    spec0 = sharding.spec[0] if len(sharding.spec) else None
    if spec0 is None:
        return 1
    axes = spec0 if isinstance(spec0, tuple) else (spec0,)
    degree = 1
    for a in axes:
        degree *= sharding.mesh.shape[a]
    return degree


def _stack_superbatches(
    source: Iterator[tuple[Any, int]], k: int
) -> Iterator[tuple[Any, int]]:
    """Collate every ``k`` consecutive microbatches into ONE stacked
    ``[k, micro, ...]`` host batch — the input contract of the fused
    gradient-accumulation step (``unified_step(fused_accumulation=True)``),
    which ``lax.scan``s over the leading axis instead of being dispatched
    ``k`` times.

    A partial final group is padded by repeating its last microbatch so
    the stacked shape stays static for XLA; ``valid`` carries the TRUE
    global sample count summed across the k slots, so remainder tracking
    and loss masking can drop the padding.
    """
    group: list[Any] = []
    valid_total = 0
    for host_batch, valid in source:
        group.append(_to_numpy(host_batch))
        valid_total += valid
        if len(group) == k:
            yield _stack_group(group), valid_total
            group, valid_total = [], 0
    if group:
        # pad-and-mask: repeat the last microbatch to fill the stack
        while len(group) < k:
            group.append(group[-1])
        yield _stack_group(group), valid_total


def _stack_group(group: list[Any]) -> Any:
    return jax.tree.map(lambda *xs: np.stack(xs), *group)


def _default_collate(items: list[Any]) -> Any:
    """Stack a list of samples into a batch pytree."""
    first = items[0]
    if isinstance(first, dict):
        return {k: _default_collate([it[k] for it in items]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(
            _default_collate([it[i] for it in items]) for i in range(len(first))
        )
    return np.stack([np.asarray(it) for it in items])


class DataLoaderStateMixin:
    """begin/end hooks wiring GradientState (reference data_loader.py:355)."""

    def begin(self):
        self.end_of_dataloader = False
        self.remainder = -1
        GradientState()._add_dataloader(self)

    def end(self):
        GradientState()._remove_dataloader(self)


class DataLoaderShard(DataLoaderStateMixin):
    """The prepared training dataloader: yields globally-sharded device
    batches with background prefetch (reference data_loader.py:391 +
    MpDeviceLoaderWrapper:521 in one object)."""

    def __init__(
        self,
        batch_iter_factory: Callable[[], Iterator[tuple[Any, int]]],
        num_batches: Optional[int],
        sharding,
        global_batch_size: int,
        prefetch_size: int = 2,
        rng_synchronizer: Optional[Callable[[], None]] = None,
        sampler=None,
        superbatch: int = 1,
        _skip_batches: int = 0,
    ):
        self._factory = batch_iter_factory
        self._num_batches = num_batches
        self.sharding = sharding
        self.global_batch_size = global_batch_size
        # superbatch=K: stack K consecutive microbatches into one
        # [K, micro, ...] device batch for the fused-accumulation step.
        # The K axis is replicated; the batch axis (now axis 1) keeps the
        # data sharding. global_batch_size stays the per-MICROBATCH size.
        self.superbatch = max(1, int(superbatch))
        self.prefetch_size = max(1, prefetch_size)
        self._rng_synchronizer = rng_synchronizer
        self.sampler = sampler
        self.epoch = 0
        self._skip_batches = _skip_batches
        self._batches_yielded = 0  # position within the current epoch
        self.end_of_dataloader = False
        self.remainder = -1
        # set by Accelerator.prepare_data_loader: a StepTelemetry that gets
        # told how long the loop blocked waiting for each batch, so step
        # records separate input starvation from compute
        self.telemetry = None

    def _timed_get(self, q: "queue_mod.Queue") -> Any:
        """q.get() that reports blocking time to the telemetry collector.

        The producer thread prefetches, so in a healthy pipeline the queue
        is non-empty and this is ~0; sustained dataloader_wait_s means the
        input pipeline — not the TPU — is the bottleneck."""
        tel = self.telemetry
        if tel is None or not tel.enabled:
            return q.get()
        t0 = time.perf_counter()
        item = q.get()
        tel.record_dataloader_wait(time.perf_counter() - t0, source="shard")
        return item

    @property
    def total_batch_size(self) -> int:
        return self.global_batch_size

    def _stacked_sharding(self):
        """Sharding of a [K, micro, ...] superbatch: leading K axis
        replicated (every device scans all K slots), batch axis keeps the
        data-parallel split — GSPMD then propagates it through lax.scan."""
        return jax.sharding.NamedSharding(
            self.sharding.mesh,
            jax.sharding.PartitionSpec(None, *tuple(self.sharding.spec)),
        )

    def batch_spec(self) -> Any:
        """Abstract spec of one global device batch: a pytree of
        ``jax.ShapeDtypeStruct`` with the shardings :meth:`__iter__` would
        commit — the AOT-warmup contract (``accelerator.warmup``). Every
        batch is padded to one fixed shape, so the first batch's spec is
        THE spec. In superbatch mode the spec gains the leading stacked
        ``K`` axis (the shape the fused step is compiled for).

        Collates one host batch from a fresh iterator to read the shapes
        (no device transfer, no training-iterator state touched)."""
        source = self._factory()
        try:
            host_batch, _valid = next(iter(source))
        except StopIteration:
            raise ValueError("empty dataloader: no batch to derive a spec from")
        finally:
            close = getattr(source, "close", None)
            if close is not None:
                close()
        host_batch = _to_numpy(host_batch)
        num_processes = jax.process_count()
        data_degree = _sharding_data_degree(self.sharding)
        k = self.superbatch

        def _spec(x):
            # mirror _device_put's placement decisions exactly; the factory
            # yields microbatches, so in superbatch mode prepend the K axis
            x = np.asarray(x)
            if x.ndim == 0 or (x.shape[0] * num_processes) % data_degree != 0:
                replicated = jax.sharding.NamedSharding(
                    self.sharding.mesh, jax.sharding.PartitionSpec()
                )
                shape = (k,) + x.shape if k > 1 else x.shape
                return jax.ShapeDtypeStruct(shape, x.dtype, sharding=replicated)
            if k > 1:
                global_shape = (k, x.shape[0] * num_processes) + x.shape[1:]
                return jax.ShapeDtypeStruct(
                    global_shape, x.dtype, sharding=self._stacked_sharding()
                )
            global_shape = (x.shape[0] * num_processes,) + x.shape[1:]
            return jax.ShapeDtypeStruct(global_shape, x.dtype, sharding=self.sharding)

        return recursively_apply(
            _spec, host_batch, test_type=lambda x: isinstance(x, np.ndarray)
        )

    def __len__(self) -> int:
        if self._num_batches is None:
            raise TypeError("this dataloader has no length")
        n = self._num_batches
        if self.superbatch > 1:
            # the factory counts microbatches; we yield stacked superbatches
            n = math.ceil(n / self.superbatch)
        return max(0, n - self._skip_batches)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch
        if self.sampler is not None and hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def state_dict(self) -> dict:
        """Checkpointable cursor: epoch + intra-epoch position, plus the
        global batch size the position was counted under so a restore on a
        different topology can re-derive it by samples seen."""
        return {
            "epoch": self.epoch,
            "batches_yielded": self._batches_yielded,
            "global_batch_size": self.global_batch_size * self.superbatch,
        }

    def load_state_dict(self, state: dict) -> None:
        """Restore the cursor. Same global batch size: skip exactly the
        yielded batches. Different (a reshaped restore whose per-process
        count changed the effective global batch): re-derive the position
        from SAMPLES seen — ``batches * saved_gbs // live_gbs`` — rounded
        DOWN to a whole live batch, so no sample is skipped unseen (a few
        may repeat; the conservative side of the trade)."""
        self.set_epoch(int(state.get("epoch", 0)))
        seen = int(state.get("batches_yielded", 0))
        saved_gbs = int(state.get("global_batch_size", 0) or 0)
        live_gbs = self.global_batch_size * self.superbatch
        if saved_gbs and live_gbs and saved_gbs != live_gbs:
            samples = seen * saved_gbs
            seen = samples // live_gbs
            logger.warning(
                "dataloader cursor re-derived for a changed global batch "
                "size (%d -> %d): %d samples seen -> resume at batch %d",
                saved_gbs,
                live_gbs,
                samples,
                seen,
            )
        self._skip_batches = seen
        self._batches_yielded = seen

    def _device_put(self, host_batch: Any, valid: int) -> Any:
        """Host numpy pytree -> global sharded jax.Array pytree.

        In superbatch mode ``host_batch`` arrives already stacked
        ``[K, micro, ...]`` (the producer ran :func:`_stack_superbatches`),
        so the batch dim is axis 1 and the K axis is replicated."""
        num_processes = jax.process_count()
        data_degree = _sharding_data_degree(self.sharding)
        batch_axis = 1 if self.superbatch > 1 else 0

        def _make(x):
            x = np.asarray(x)
            sharding = self.sharding
            if (
                x.ndim <= batch_axis
                or (x.shape[batch_axis] * num_processes) % data_degree != 0
            ):
                # batch not divisible over the data axes: replicate (correct,
                # just not parallel) rather than crash mid-epoch.
                logger.warning_once(
                    "batch dim %s not divisible by data-parallel degree %s; "
                    "replicating this input",
                    x.shape[batch_axis] if x.ndim > batch_axis else 0,
                    data_degree,
                )
                sharding = jax.sharding.NamedSharding(
                    self.sharding.mesh, jax.sharding.PartitionSpec()
                )
                return jax.device_put(x, sharding)
            if batch_axis == 1:
                sharding = self._stacked_sharding()
                if num_processes > 1:
                    global_shape = (
                        x.shape[0],
                        x.shape[1] * num_processes,
                    ) + x.shape[2:]
                    try:
                        return jax.make_array_from_process_local_data(
                            sharding, x, global_shape
                        )
                    except TypeError:  # older jax: no global_shape arg
                        return jax.make_array_from_process_local_data(sharding, x)
                return jax.device_put(x, sharding)
            if num_processes > 1:
                return jax.make_array_from_process_local_data(sharding, x)
            return jax.device_put(x, sharding)

        batch = recursively_apply(
            _make, host_batch, test_type=lambda x: isinstance(x, np.ndarray)
        )
        return batch

    def __iter__(self) -> Iterator[Any]:
        if self._rng_synchronizer is not None:
            self._rng_synchronizer()
        self.begin()
        q: queue_mod.Queue = queue_mod.Queue(maxsize=self.prefetch_size)
        stop = object()
        cancelled = threading.Event()
        try:
            source = self._factory()
            if self.superbatch > 1:
                # the generator is consumed by the producer thread, so the
                # K-way stacking (host collate) happens off the step loop
                source = _stack_superbatches(source, self.superbatch)

            def _put(item) -> bool:
                """put that gives up when the consumer is gone (break/GC) —
                otherwise the producer thread would block forever on a full
                queue and pin prefetched device batches."""
                while not cancelled.is_set():
                    try:
                        q.put(item, timeout=0.2)
                        return True
                    except queue_mod.Full:
                        continue
                return False

            def _producer():
                try:
                    skipped = 0
                    for host_batch, valid in source:
                        if cancelled.is_set():
                            return
                        if skipped < self._skip_batches:
                            skipped += 1
                            continue
                        # host work only in this thread: collate/convert.
                        # The device_put happens on the consumer thread —
                        # concurrent jax dispatch from two threads can wedge
                        # XLA:CPU collective rendezvous, and on TPU
                        # device_put is async so the consumer-side put still
                        # overlaps H2D with the running step.
                        host_batch = _to_numpy(host_batch)
                        if not _put((host_batch, valid)):
                            return
                    _put(stop)
                except BaseException as e:  # surface producer errors
                    _put(e)

            thread = threading.Thread(target=_producer, daemon=True)
            thread.start()

            # skipped batches count as consumed positions in the cursor
            self._batches_yielded = self._skip_batches
            current = self._timed_get(q)
            if isinstance(current, BaseException):
                raise current
            while current is not stop:
                nxt = self._timed_get(q)
                if isinstance(nxt, BaseException):
                    raise nxt
                host_batch, valid = current
                batch = self._device_put(host_batch, valid)
                if self.global_batch_size == 0:
                    # iterable-of-batches path: learn the batch size from the
                    # first batch so the tail's remainder is detected
                    self.global_batch_size = valid // self.superbatch
                # a full superbatch carries K microbatches' worth of samples
                gbs = self.global_batch_size * self.superbatch
                if nxt is stop:
                    # one-batch lookahead: mark last batch before yielding it
                    # (reference data_loader.py:445-476)
                    self.end_of_dataloader = True
                    self.remainder = valid if valid != gbs else 0
                yield batch
                self._batches_yielded += 1
                current = nxt
        finally:
            cancelled.set()
            # drain so a blocked producer can observe the cancel promptly
            try:
                while True:
                    q.get_nowait()
            except queue_mod.Empty:
                pass
            self.end()
            self._skip_batches = 0
            if self.end_of_dataloader:
                self._batches_yielded = 0  # full epoch consumed


class DataLoaderDispatcher(DataLoaderShard):
    """Process 0 reads the dataset and broadcasts each global batch to all
    processes (reference data_loader.py:562) — for datasets only rank 0 can
    see. On TPU the broadcast is a host-level object collective; prefer
    DataLoaderShard when every host can read its shard."""

    def __iter__(self) -> Iterator[Any]:
        if jax.process_count() == 1:
            yield from super().__iter__()
            return
        self.begin()
        try:
            is_main = jax.process_index() == 0
            source = self._factory() if is_main else None
            if source is not None and self.superbatch > 1:
                # stack before broadcast so every process receives the
                # ready-made [K, micro, ...] superbatch
                source = _stack_superbatches(source, self.superbatch)
            skipped = 0

            def _next_payload():
                nonlocal skipped
                if is_main:
                    while True:
                        try:
                            host_batch, valid = next(source)  # type: ignore[arg-type]
                        except StopIteration:
                            payload = [None, 0, True]
                            break
                        if skipped < self._skip_batches:
                            skipped += 1
                            continue
                        payload = [_to_numpy(host_batch), valid, False]
                        break
                else:
                    payload = [None, 0, True]
                return broadcast_object_list(payload, from_process=0)

            def _next_payload_timed():
                # no prefetch thread on this path: the whole read+broadcast
                # blocks the loop, so all of it is dataloader wait
                tel = self.telemetry
                if tel is None or not tel.enabled:
                    return _next_payload()
                t0 = time.perf_counter()
                payload = _next_payload()
                tel.record_dataloader_wait(
                    time.perf_counter() - t0, source="dispatcher"
                )
                return payload

            def _to_batch(payload):
                host_batch, valid, _ = payload
                num = jax.process_count()
                idx = jax.process_index()

                def _slice(x):
                    # superbatch payloads carry the batch dim at axis 1
                    axis = 1 if self.superbatch > 1 and x.ndim > 1 else 0
                    local = x.shape[axis] // num
                    if axis == 1:
                        return x[:, idx * local : (idx + 1) * local]
                    return x[idx * local : (idx + 1) * local]

                local_batch = recursively_apply(
                    _slice, host_batch, test_type=lambda x: isinstance(x, np.ndarray)
                )
                return self._device_put(local_batch, valid), valid

            # one-payload lookahead so the last batch is marked before yield
            self._batches_yielded = self._skip_batches
            current = _next_payload_timed()
            while not current[2]:
                nxt = _next_payload_timed()
                batch, valid = _to_batch(current)
                if nxt[2]:
                    self.end_of_dataloader = True
                    full = self.global_batch_size * self.superbatch
                    self.remainder = valid if valid != full else 0
                yield batch
                self._batches_yielded += 1
                current = nxt
        finally:
            self.end()
            self._skip_batches = 0
            if self.end_of_dataloader:
                self._batches_yielded = 0  # full epoch consumed


def prepare_data_loader(
    dataloader: Any,
    state: Optional[AcceleratorState] = None,
    config: Optional[DataLoaderConfiguration] = None,
    seed: int = 0,
    skip_batches: int = 0,
    superbatch: int = 1,
) -> DataLoaderShard:
    """Turn a host dataloader into a DataLoaderShard (reference
    data_loader.py:797 decision tree).

    Accepts:
    * our :class:`DataLoader` (or anything exposing ``dataset``,
      ``batch_size``, ``shuffle``/``sampler``, ``drop_last``, ``collate_fn``)
      — includes torch.utils.data.DataLoader;
    * a bare iterable of already-batched pytrees (treated as an iterable
      dataset of batches on every process).

    The incoming ``batch_size`` is the **per-process** batch; the prepared
    loader yields the global batch (``batch_size * num_processes``) as one
    sharded array (``split_batches=True``: the incoming batch is already the
    global batch and is split).

    ``superbatch=K`` (K > 1) puts the loader in stacked mode for fused
    gradient accumulation: each yielded device batch stacks K consecutive
    microbatches as ``[K, micro, ...]`` (K axis replicated, batch axis
    data-sharded); a partial final group is padded by repeating its last
    microbatch with the true sample count recorded in ``remainder``.
    """
    state = state or AcceleratorState()
    config = config or getattr(state, "dataloader_config", None) or DataLoaderConfiguration()
    mesh = state.mesh
    sharding = batch_sharding(mesh)
    num_processes = state.num_processes
    process_index = state.process_index

    dataset = getattr(dataloader, "dataset", None)
    batch_size = getattr(dataloader, "batch_size", None)

    if dataset is not None and batch_size is not None and hasattr(dataset, "__len__"):
        # map-style dataset: shard by sampler
        collate = getattr(dataloader, "collate_fn", None) or _default_collate
        shuffle = _loader_shuffles(dataloader)
        if not shuffle:
            sampler = SequentialSampler(len(dataset))
        elif config.use_seedable_sampler:
            sampler = SeedableRandomSampler(len(dataset), seed=seed)
        else:
            sampler = RandomSampler(len(dataset))
        drop_last = bool(getattr(dataloader, "drop_last", False) or config.drop_last)
        shard = BatchSamplerShard(
            sampler,
            batch_size,
            drop_last=drop_last,
            num_processes=num_processes,
            process_index=process_index,
            split_batches=config.split_batches,
            even_batches=config.even_batches,
        )

        dispatching = bool(config.dispatch_batches) and num_processes > 1
        # Dispatcher mode: ONLY rank 0 runs the factory and must produce the
        # whole GLOBAL batch (the dispatcher slices per process afterwards)
        # — a per-process shard here would get sliced twice, silently
        # dropping (num_processes-1)/num_processes of every batch.
        factory_shard = (
            BatchSamplerShard(
                sampler,
                shard.global_batch_size,
                drop_last=drop_last,
                num_processes=1,
                process_index=0,
                split_batches=False,
                even_batches=config.even_batches,
            )
            if dispatching
            else shard
        )

        def factory():
            for local_indices, valid in iter(factory_shard):
                items = [dataset[i] for i in local_indices]
                yield collate(items), valid

        global_bs = shard.global_batch_size
        data_degree = _sharding_data_degree(sharding)
        if global_bs % data_degree != 0:
            raise ValueError(
                f"global batch size {global_bs} (batch_size x num_processes) must be "
                f"divisible by the data-parallel device count {data_degree} so XLA can "
                f"shard the batch. Increase batch_size, or reduce the dp/fsdp mesh axes."
            )
        num_batches = len(factory_shard)
        cls = DataLoaderDispatcher if dispatching else DataLoaderShard
        batch_sampler = factory_shard
        out = cls(
            factory,
            num_batches,
            sharding,
            global_bs,
            prefetch_size=config.prefetch_size,
            sampler=sampler,
            superbatch=superbatch,
            _skip_batches=skip_batches,
        )
        # exposed for join_uneven_inputs: flipping .even_batches takes
        # effect on the next epoch's iter(factory_shard)
        out.batch_sampler = batch_sampler
        return out

    # iterable of pre-batched pytrees
    def factory():
        for batch in dataloader:
            batch = _to_numpy(batch)
            bs = find_batch_size(batch) or 0
            yield batch, bs

    try:
        num_batches = len(dataloader)
    except TypeError:
        num_batches = None
    return DataLoaderShard(
        factory,
        num_batches,
        sharding,
        global_batch_size=getattr(dataloader, "global_batch_size", 0) or 0,
        prefetch_size=config.prefetch_size,
        superbatch=superbatch,
        _skip_batches=skip_batches,
    )


def _loader_shuffles(dataloader: Any) -> bool:
    """Best-effort detection of shuffling on the incoming loader."""
    if getattr(dataloader, "shuffle", None) is not None:
        return bool(dataloader.shuffle)
    sampler = getattr(dataloader, "sampler", None)
    if sampler is not None:
        return type(sampler).__name__ in ("RandomSampler", "SeedableRandomSampler")
    return False


class DataLoader:
    """Minimal torch-free host dataloader: map-style dataset + batch/shuffle/
    collate. Exists so the framework has no torch dependency; torch loaders
    are also accepted by prepare_data_loader directly."""

    def __init__(
        self,
        dataset: Any,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Optional[Callable] = None,
        seed: int = 0,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn or _default_collate
        self.seed = seed
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self._epoch = epoch

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)

    def __iter__(self) -> Iterator[Any]:
        indices = (
            np.random.default_rng(self.seed + self._epoch).permutation(len(self.dataset))
            if self.shuffle
            else np.arange(len(self.dataset))
        )
        for start in range(0, len(indices), self.batch_size):
            chunk = indices[start : start + self.batch_size]
            if self.drop_last and len(chunk) < self.batch_size:
                return
            yield self.collate_fn([self.dataset[int(i)] for i in chunk])


def skip_first_batches(dataloader: DataLoaderShard, num_batches: int = 0):
    """Resume mid-epoch: a view of the loader that skips the first
    ``num_batches`` (reference data_loader.py:1082)."""
    if isinstance(dataloader, DataLoaderShard):
        dataloader._skip_batches = num_batches
        return dataloader
    raise TypeError(
        "skip_first_batches expects a loader returned by prepare()/prepare_data_loader()"
    )
