"""Rank-aware logging.

Parity: reference ``src/accelerate/logging.py`` — ``MultiProcessAdapter``:22
(`main_process_only`/`in_order` kwargs), ``get_logger``:85,
``warning_once``:74.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Optional


class MultiProcessAdapter(logging.LoggerAdapter):
    """Logs only on main process unless ``main_process_only=False`` is
    passed; ``in_order=True`` serializes output process by process."""

    @staticmethod
    def _should_log(main_process_only: bool) -> bool:
        from .state import PartialState

        state = PartialState()
        return not main_process_only or state.is_main_process

    def log(self, level, msg, *args, **kwargs):
        if os.environ.get("ACCELERATE_TPU_DISABLE_LOGGING", "false").lower() in (
            "1",
            "true",
        ):
            return
        from .state import PartialState

        main_process_only = kwargs.pop("main_process_only", True)
        in_order = kwargs.pop("in_order", False)
        kwargs.setdefault("stacklevel", 2)

        if self.isEnabledFor(level):
            if self._should_log(main_process_only):
                msg, kwargs = self.process(msg, kwargs)
                self.logger.log(level, msg, *args, **kwargs)
            elif in_order:
                state = PartialState()
                for i in range(state.num_processes):
                    if i == state.process_index:
                        msg, kwargs = self.process(msg, kwargs)
                        self.logger.log(level, msg, *args, **kwargs)
                    state.wait_for_everyone()

    @functools.lru_cache(None)
    def warning_once(self, *args, **kwargs):
        """Emit a given warning only once per process (reference :74)."""
        self.warning(*args, **kwargs)


def get_logger(name: str, log_level: Optional[str] = None) -> MultiProcessAdapter:
    """Reference logging.py:85."""
    logger = logging.getLogger(name)
    if log_level is None:
        log_level = os.environ.get("ACCELERATE_TPU_LOG_LEVEL", None)
    if log_level is not None:
        logger.setLevel(log_level.upper())
        logger.root.setLevel(log_level.upper())
    return MultiProcessAdapter(logger, {})
