"""Process/device state singletons.

Parity: reference ``src/accelerate/state.py`` — ``PartialState``:110,
``AcceleratorState``:805, ``GradientState``:1082, including the shared-dict
singleton trick (:78-107). TPU-native redesign: ``torch.distributed.
init_process_group`` / backend selection (:708-760) becomes
``jax.distributed.initialize`` (one process per host, single-controller
SPMD), and the device mesh — absent in the reference, where topology hides
inside NCCL process groups — is a first-class member here.
"""

from __future__ import annotations

import logging
import os
from contextlib import contextmanager
from functools import wraps
from typing import Any, Callable, Iterable, Optional

import jax

from .parallel.mesh import build_mesh, data_axes, mesh_axis_size
from .utils.constants import ENV_PREFIX
from .utils.dataclasses import (
    DataLoaderConfiguration,
    DistributedInitKwargs,
    DistributedType,
    GradientAccumulationPlugin,
    MixedPrecisionPolicy,
    ParallelismPlugin,
    PrecisionType,
)
from .utils.environment import parse_flag_from_env

logger = logging.getLogger(__name__)


def _maybe_init_distributed(kwargs: Optional[DistributedInitKwargs]) -> None:
    """Bring up the multi-process JAX runtime when the launcher asked for it.

    The launcher (commands/launch.py) sets ACCELERATE_TPU_NUM_PROCESSES /
    COORDINATOR_ADDRESS / PROCESS_ID; on GCE TPU pods jax.distributed can
    also self-discover from metadata. Idempotent.

    ORDER MATTERS: this must not touch any backend-initializing JAX API
    (jax.process_count(), jax.devices(), ...) before calling
    jax.distributed.initialize — doing so pins the single-process backend
    and makes initialize() raise unconditionally. All the pre-checks below
    are env/kwargs reads only.
    """
    num = kwargs.num_processes if kwargs and kwargs.num_processes else None
    if num is None:
        env = os.environ.get(ENV_PREFIX + "NUM_PROCESSES")
        num = int(env) if env else None
    coord = (kwargs.coordinator_address if kwargs else None) or os.environ.get(
        ENV_PREFIX + "COORDINATOR_ADDRESS"
    )
    if not coord and (num is None or num <= 1):
        return
    from jax._src import distributed as _jax_distributed

    if _jax_distributed.global_state.client is not None:
        return  # already initialized by someone else
    pid = kwargs.process_id if kwargs and kwargs.process_id is not None else None
    if pid is None:
        env = os.environ.get(ENV_PREFIX + "PROCESS_ID")
        pid = int(env) if env else None
    extra = {}
    if kwargs and kwargs.local_device_ids is not None:
        extra["local_device_ids"] = kwargs.local_device_ids
    if kwargs and kwargs.initialization_timeout is not None:
        extra["initialization_timeout"] = int(
            kwargs.initialization_timeout.total_seconds()
        )
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu" or (
        os.environ.get("JAX_PLATFORM_NAME", "").strip() == "cpu"
    ):
        # XLA:CPU has no native cross-process collectives ("Multiprocess
        # computations aren't implemented on the CPU backend"); the gloo
        # transport must be selected BEFORE initialize() or every
        # multi-process debug/elastic run dies at its first collective.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax: option absent, single-host paths still work
    try:
        jax.distributed.initialize(
            coordinator_address=coord, num_processes=num, process_id=pid, **extra
        )
    except Exception as e:  # single-process fallback
        logger.warning("jax.distributed.initialize skipped: %s", e)


class PartialState:
    """Singleton holding process topology + collective entry points
    (reference state.py:110). One instance per python process; in JAX's
    single-controller model one process drives all local devices, so the
    reference's per-GPU ranks map to (process_index, local devices)."""

    _shared_state: dict[str, Any] = {}

    def __init__(self, cpu: bool = False, **kwargs):
        self.__dict__ = self._shared_state
        if self.initialized:
            return
        init_kwargs = kwargs.get("init_kwargs")
        if cpu:
            # Force the CPU backend (reference semantics: cpu=True debugs on
            # CPU even on an accelerator host). Only possible before the XLA
            # backend initializes; best-effort otherwise.
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception:
                logger.warning("could not force CPU backend; it is already live")
        else:
            _maybe_init_distributed(init_kwargs)
        self.fork_launched = parse_flag_from_env("FORK_LAUNCHED", False)
        backend = jax.default_backend()
        self.backend = backend
        self.num_processes = jax.process_count()
        self.process_index = jax.process_index()
        self.local_process_index = int(
            os.environ.get(ENV_PREFIX + "LOCAL_PROCESS_INDEX", 0)
        )
        self.device = jax.local_devices()[0]
        self.num_devices = jax.device_count()
        self.num_local_devices = jax.local_device_count()
        if backend in ("tpu", "axon"):
            self.distributed_type = (
                DistributedType.MULTI_TPU
                if self.num_processes > 1
                else (DistributedType.TPU if self.num_devices > 1 else DistributedType.NO)
            )
        else:
            self.distributed_type = (
                DistributedType.MULTI_CPU
                if self.num_processes > 1
                else (DistributedType.CPU if self.num_devices > 1 else DistributedType.NO)
            )
        self.debug = parse_flag_from_env(ENV_PREFIX + "DEBUG_MODE")

    @property
    def initialized(self) -> bool:
        return "distributed_type" in self.__dict__

    @staticmethod
    def _reset_state():
        """Wipe the singleton (test isolation; reference state.py:105)."""
        PartialState._shared_state.clear()

    def __repr__(self) -> str:
        return (
            f"Distributed environment: {self.distributed_type}\n"
            f"Num processes: {self.num_processes}\n"
            f"Process index: {self.process_index}\n"
            f"Local devices: {self.num_local_devices} / {self.num_devices} global\n"
            f"Device: {self.device}\n"
        )

    # ------------------------------------------------------------------ #
    # process predicates
    # ------------------------------------------------------------------ #
    @property
    def use_distributed(self) -> bool:
        return self.num_processes > 1 or self.num_devices > 1

    @property
    def is_main_process(self) -> bool:
        return self.process_index == 0

    @property
    def is_local_main_process(self) -> bool:
        return self.local_process_index == 0

    @property
    def is_last_process(self) -> bool:
        return self.process_index == self.num_processes - 1

    # ------------------------------------------------------------------ #
    # process control
    # ------------------------------------------------------------------ #
    def wait_for_everyone(self) -> None:
        """Cross-process barrier (reference state.py:347). Single-process is
        a no-op; multi-process syncs all hosts via a tiny global collective."""
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("accelerate_tpu.wait_for_everyone")

    @contextmanager
    def main_process_first(self):
        """Main process runs the body before others (reference state.py:481)
        — e.g. dataset download/tokenization caches."""
        if not self.is_main_process:
            self.wait_for_everyone()
        yield
        if self.is_main_process:
            self.wait_for_everyone()

    @contextmanager
    def local_main_process_first(self):
        if not self.is_local_main_process:
            self.wait_for_everyone()
        yield
        if self.is_local_main_process:
            self.wait_for_everyone()

    def on_main_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_local_main_process(self, function: Callable) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.is_local_main_process:
                return function(*args, **kwargs)

        return wrapper

    def on_process(self, function: Callable, process_index: int = 0) -> Callable:
        @wraps(function)
        def wrapper(*args, **kwargs):
            if self.process_index == process_index:
                return function(*args, **kwargs)

        return wrapper

    @contextmanager
    def split_between_processes(
        self, inputs: Any, apply_padding: bool = False
    ):
        """Split a list/dict/tuple evenly across processes (reference
        state.py:392). With ``apply_padding`` the last items are repeated so
        every process gets the same count (for fixed-shape collectives)."""
        if self.num_processes == 1:
            yield inputs
            return
        if isinstance(inputs, dict):
            split = {}
            with self.split_between_processes(
                list(zip(*inputs.values())), apply_padding
            ) as rows:
                for i, key in enumerate(inputs.keys()):
                    split[key] = [row[i] for row in rows]
            yield split
            return
        length = len(inputs)
        num = self.num_processes
        base, extra = divmod(length, num)
        # first `extra` processes get one more element
        start = self.process_index * base + min(self.process_index, extra)
        end = start + base + (1 if self.process_index < extra else 0)
        chunk = inputs[start:end]
        if apply_padding and extra != 0:
            target = base + 1
            if len(chunk) < target and length:
                pad = inputs[-1:] * (target - len(chunk))
                chunk = list(chunk) + pad
        yield chunk

    def print(self, *args, **kwargs) -> None:
        """Print once (main process only) — reference state.py:561."""
        if self.is_main_process:
            print(*args, **kwargs)

    def destroy_process_group(self) -> None:
        if self.num_processes > 1:
            try:
                jax.distributed.shutdown()
            except Exception:
                pass

    @property
    def local_devices(self) -> list[jax.Device]:
        return jax.local_devices()


class AcceleratorState:
    """Full accelerator-level state: PartialState + precision + parallelism
    mesh + plugins (reference state.py:805)."""

    _shared_state: dict[str, Any] = {}

    def __init__(
        self,
        mixed_precision: Optional[str] = None,
        cpu: bool = False,
        parallelism_plugin: Optional[ParallelismPlugin] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        compile_plugin=None,
        **kwargs,
    ):
        self.__dict__ = self._shared_state
        if self.initialized:
            if mixed_precision is not None and str(mixed_precision) != str(
                self.mixed_precision
            ):
                logger.warning(
                    "AcceleratorState already initialized with mixed_precision=%s; "
                    "ignoring new value %s",
                    self.mixed_precision,
                    mixed_precision,
                )
            return
        self.partial_state = PartialState(cpu, **kwargs)
        self.gradient_accumulation_plugin = gradient_accumulation_plugin
        if mixed_precision is None:
            mixed_precision = os.environ.get(ENV_PREFIX + "MIXED_PRECISION", "no")
        self.mixed_precision = PrecisionType(str(mixed_precision))
        self.mixed_precision_policy = MixedPrecisionPolicy.from_precision(
            self.mixed_precision
        )
        self.parallelism_plugin = parallelism_plugin or ParallelismPlugin.pure_dp()
        self.dataloader_config = dataloader_config or DataLoaderConfiguration()
        # Persistent XLA compilation cache: activated here — the same
        # once-per-process seat that builds the mesh — so every jit in the
        # process (user code included, not just the unified step) reuses
        # compiles across restarts. No-op without a cache_dir (env:
        # ACCELERATE_TPU_COMPILE_CACHE).
        self.compile_plugin = compile_plugin
        self.compile_cache_dir = None
        if compile_plugin is not None:
            from .compilation import activate_persistent_cache

            self.compile_cache_dir = activate_persistent_cache(compile_plugin)
        self.mesh = build_mesh(self.parallelism_plugin)
        self.data_axis_names = data_axes(self.mesh)
        self.data_parallel_size = mesh_axis_size(self.mesh, *self.data_axis_names)

    def reform_mesh(self, devices: Optional[Iterable[jax.Device]] = None):
        """Rebuild the device mesh from an explicit device set (the elastic
        survivor path: after a relaunch at a smaller world size, or — in
        tests — to model a shrunken fleet on a device subset). ``-1`` auto
        axes in the parallelism plugin re-resolve against the new device
        count; fixed axes that no longer divide it raise, same as at init.
        Returns the new mesh; derived data-axis bookkeeping is refreshed."""
        devices = list(devices) if devices is not None else None
        self.mesh = build_mesh(self.parallelism_plugin, devices=devices)
        self.data_axis_names = data_axes(self.mesh)
        self.data_parallel_size = mesh_axis_size(self.mesh, *self.data_axis_names)
        return self.mesh

    @property
    def initialized(self) -> bool:
        return "partial_state" in self.__dict__

    @staticmethod
    def _reset_state(reset_partial_state: bool = False):
        AcceleratorState._shared_state.clear()
        if reset_partial_state:
            PartialState._reset_state()

    def __getattr__(self, name: str):
        # Delegate process topology to PartialState (reference state.py:1070).
        if name in ("partial_state", "initialized") or name.startswith("__"):
            raise AttributeError(name)
        ps = self.__dict__.get("partial_state")
        if ps is not None and hasattr(ps, name):
            return getattr(ps, name)
        raise AttributeError(
            f"'AcceleratorState' object has no attribute '{name}'"
        )

    def __repr__(self) -> str:
        return (
            repr(self.partial_state)
            + f"Mixed precision: {self.mixed_precision}\n"
            + f"Mesh: {dict(self.mesh.shape)}\n"
        )


class GradientState:
    """Gradient-accumulation bookkeeping shared between Accelerator,
    dataloaders and wrapped optimizer (reference state.py:1082).

    On TPU the *arithmetic* of accumulation runs inside the compiled step
    (carried grad buffer + lax.cond apply); this singleton tracks the
    host-side schedule — whether the *current* host step is an optimizer
    boundary — which gates scheduler stepping and `sync_gradients` parity
    semantics, plus dataloader end/remainder state for gather_for_metrics.
    """

    _shared_state: dict[str, Any] = {}

    def __init__(
        self, gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None
    ):
        self.__dict__ = self._shared_state
        if not self.initialized:
            self.sync_gradients = True
            self.active_dataloader = None
            self.dataloader_references: list[Any] = [None]
            self.plugin_kwargs = (
                gradient_accumulation_plugin.to_kwargs()
                if gradient_accumulation_plugin is not None
                else {}
            )
            self._num_steps = (
                gradient_accumulation_plugin.num_steps
                if gradient_accumulation_plugin is not None
                else 1
            )
        elif gradient_accumulation_plugin is not None:
            self.plugin_kwargs = gradient_accumulation_plugin.to_kwargs()
            self._num_steps = gradient_accumulation_plugin.num_steps

    @property
    def initialized(self) -> bool:
        return "sync_gradients" in self.__dict__

    @property
    def num_steps(self) -> int:
        return self._num_steps

    @num_steps.setter
    def num_steps(self, value: int):
        self._num_steps = value

    @property
    def sync_with_dataloader(self) -> bool:
        return self.plugin_kwargs.get("sync_with_dataloader", True)

    @property
    def adjust_scheduler(self) -> bool:
        return self.plugin_kwargs.get("adjust_scheduler", True)

    @property
    def sync_each_batch(self) -> bool:
        return self.plugin_kwargs.get("sync_each_batch", False)

    @property
    def fused(self) -> bool:
        """Whether accumulation runs fused: one compiled step per optimizer
        step, scanning over a stacked ``[num_steps, micro, ...]`` batch.

        Falls back to the ``ACCELERATE_TPU_FUSED_ACCUM`` env flag: the
        plugin's ``to_kwargs`` keeps only non-default fields, and with the
        env set a default-constructed plugin ALSO has fused=True, so the
        knob would otherwise vanish from ``plugin_kwargs``."""
        if "fused" in self.plugin_kwargs:
            return self.plugin_kwargs["fused"]
        return parse_flag_from_env(ENV_PREFIX + "FUSED_ACCUM")

    @property
    def end_of_dataloader(self) -> bool:
        return (
            self.active_dataloader is not None
            and getattr(self.active_dataloader, "end_of_dataloader", False)
        )

    @property
    def remainder(self) -> int:
        return (
            getattr(self.active_dataloader, "remainder", -1)
            if self.active_dataloader is not None
            else -1
        )

    @property
    def in_dataloader(self) -> bool:
        return self.active_dataloader is not None

    def _add_dataloader(self, dataloader):
        self.dataloader_references.append(dataloader)
        self.active_dataloader = dataloader

    def _remove_dataloader(self, dataloader):
        if dataloader in self.dataloader_references:
            self.dataloader_references.remove(dataloader)
        self.active_dataloader = self.dataloader_references[-1]

    @staticmethod
    def _reset_state():
        GradientState._shared_state.clear()

    def __repr__(self) -> str:
        return (
            f"Sync gradients: {self.sync_gradients}\n"
            f"Accumulation steps: {self.num_steps}\n"
            f"At end of dataloader: {self.end_of_dataloader}\n"
            f"Remainder: {self.remainder}\n"
        )
