"""`accelerate-tpu diagnose <dir>` — post-mortem report from a run's
flight-recorder dumps and heartbeat files.

Point it at the diagnostics dir (``DiagnosticsConfig.dir`` /
``Accelerator(diagnostics="<dir>")``) of a dead or hung job and it names
the rank that stopped first, the last committed checkpoint to restart
from, and where the wall-clock went (goodput/badput breakdown). Works on
a copied directory from any machine — no devices are initialized.
"""

from __future__ import annotations

import argparse
import json
import sys


def diagnose_command(args) -> None:
    from ..diagnostics.diagnose import build_report, format_report

    report = build_report(args.dir, stall_timeout_s=args.stall_timeout)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
    else:
        print(format_report(report))
    if report["num_dumps"] == 0 and report["num_heartbeats"] == 0:
        print(
            f"\nNo flight-recorder dumps or heartbeat files under {args.dir}.\n"
            "Enable them with Accelerator(diagnostics='<shared dir>') — every "
            "host must point at the same directory.",
            file=sys.stderr,
        )
        sys.exit(1)


def diagnose_command_parser(subparsers=None) -> argparse.ArgumentParser:
    help_ = "Post-mortem report from flight-recorder dumps + heartbeats"
    if subparsers is not None:
        parser = subparsers.add_parser("diagnose", help=help_)
    else:
        parser = argparse.ArgumentParser("accelerate-tpu diagnose")
    parser.add_argument(
        "dir", help="diagnostics directory (DiagnosticsConfig.dir)"
    )
    parser.add_argument(
        "--stall-timeout",
        type=float,
        default=300.0,
        help="heartbeats older than this many seconds count as stale",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the raw report as JSON"
    )
    if subparsers is not None:
        parser.set_defaults(func=diagnose_command)
    return parser
