"""`accelerate-tpu launch` — the distributed entry point.

Parity: reference ``commands/launch.py`` (1107 LoC: ~90 flags :135,
``simple_launcher`` :696, ``multi_gpu_launcher`` :708, ``tpu_launcher``
:796, ``tpu_pod_launcher`` :827, ``_validate_launch_command`` :906).

TPU-native collapse: JAX is single-controller-per-host SPMD, so there is no
per-core process spawning (the reference's ``xmp.spawn``) and no torchrun
rendezvous. Three modes remain:

* **single-host** — exec the script with the config's env transport;
* **multi-host pod** — same, plus ``jax.distributed`` coordinator env
  (each host runs one process; ``--machine_rank`` selects identity), with
  a ``--gcloud`` helper that prints/executes the pod-wide SSH fan-out
  (reference tpu_pod_launcher);
* **debug** — N local processes on the CPU backend with a localhost
  coordinator: the reference's gloo debug launcher, for testing
  multi-process semantics anywhere (SURVEY.md §4 pattern 2).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import Optional

from ..utils.constants import ENV_PREFIX
from .config import ClusterConfig, default_config_file


def launch_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser("launch", help="Launch a training script")
    else:
        parser = argparse.ArgumentParser("accelerate-tpu launch")
    parser.add_argument("--config_file", default=None)
    parser.add_argument("--num_machines", type=int, default=None,
                        help="Number of hosts (JAX processes)")
    parser.add_argument("--machine_rank", type=int, default=None,
                        help="This host's pod worker index; -1 = infer "
                        "from TPU_WORKER_ID / hostname (errors if neither "
                        "yields one)")
    parser.add_argument("--main_process_ip", default=None)
    parser.add_argument("--main_process_port", type=int, default=None)
    parser.add_argument("--mixed_precision", default=None,
                        choices=["no", "bf16", "fp16", "fp8"])
    parser.add_argument("--gradient_accumulation_steps", type=int, default=None)
    for axis in ("dp", "fsdp", "tp", "sp", "ep"):
        parser.add_argument(f"--{axis}_size", type=int, default=None,
                            help=f"{axis} mesh degree")
    parser.add_argument("--sharding_strategy", default=None)
    parser.add_argument("--debug_num_processes", type=int, default=None,
                        help="Spawn N local CPU processes (debug/test mode)")
    def _non_negative(val: str) -> int:
        n = int(val)
        if n < 0:
            raise argparse.ArgumentTypeError(
                "--max_restarts must be >= 0 (there is no 'infinite' mode)"
            )
        return n

    parser.add_argument("--max_restarts", type=_non_negative, default=0,
                        help="Supervised retry: relaunch a crashed training "
                        "script up to N times (pair with CheckpointManager "
                        "auto-resume; reference torchelastic max_restarts)")
    def _non_negative_f(val: str) -> float:
        x = float(val)
        if x < 0:
            raise argparse.ArgumentTypeError("--monitor_interval must be >= 0")
        return x

    parser.add_argument("--monitor_interval", type=_non_negative_f, default=5.0,
                        help="Seconds to wait before each relaunch "
                        "(reference torchelastic monitor_interval)")
    parser.add_argument("--elastic", action="store_true",
                        help="Elastic supervision: on a rank death, tear "
                        "down the survivors (SIGTERM -> final checkpoint "
                        "where reachable), re-form the world at the reduced "
                        "size and resume from the last committed checkpoint "
                        "(survivors relaunch with ACCELERATE_TPU_ELASTIC=1, "
                        "so load_state reshapes the N-host checkpoint onto "
                        "the M-host mesh). Pair with --debug_num_processes.")
    parser.add_argument("--min_processes", type=int, default=1,
                        help="Elastic floor: give up instead of re-forming "
                        "below this many survivors")
    parser.add_argument("--stall_timeout", type=float, default=60.0,
                        help="Elastic: seconds of heartbeat silence (after "
                        "a rank's first beat) that declare it dead")
    parser.add_argument("--grace_period", type=float, default=10.0,
                        help="Elastic: SIGTERM -> SIGKILL window at "
                        "survivor teardown")
    parser.add_argument("--heartbeat_dir", default=None,
                        help="Elastic: directory of heartbeat-rank*.json "
                        "files (enables heartbeat-based death detection; "
                        "exported to ranks as "
                        "ACCELERATE_TPU_ELASTIC_HEARTBEAT_DIR)")
    parser.add_argument("--num_slices", type=int, default=1,
                        help="Elastic: slice fault domains. Ranks are "
                        "assigned slice-major (N/num_slices per slice); a "
                        "death drops the victim's WHOLE slice in one "
                        "generation and survivors re-form as a "
                        "(num_slices-1)-slice hierarchical mesh. Each rank "
                        "sees ACCELERATE_TPU_NUM_SLICES + "
                        "ACCELERATE_TPU_FAULT_DOMAIN.")
    parser.add_argument("--gcloud", action="store_true",
                        help="Fan out to all pod workers via gcloud ssh")
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument("training_script", help="Script to launch")
    parser.add_argument("training_script_args", nargs=argparse.REMAINDER)
    if subparsers is not None:
        parser.set_defaults(func=launch_command)
    return parser


def _merge_config(args) -> ClusterConfig:
    """YAML config + CLI overrides (reference _validate_launch_command)."""
    try:
        cfg = ClusterConfig.load(args.config_file)
    except FileNotFoundError:
        cfg = ClusterConfig()
    for name in (
        "num_machines", "machine_rank", "main_process_ip", "main_process_port",
        "mixed_precision", "gradient_accumulation_steps", "sharding_strategy",
        "tpu_name", "tpu_zone",
    ):
        val = getattr(args, name, None)
        if val is not None:
            setattr(cfg, name, val)
    for axis in ("dp", "fsdp", "tp", "sp", "ep"):
        val = getattr(args, f"{axis}_size", None)
        if val is not None:
            setattr(cfg, f"{axis}_size", val)
    if getattr(args, "machine_rank", None) == -1:
        # explicit "infer on this worker" sentinel (the pod fan-out uses
        # it): derive from the TPU runtime env, raising loudly on failure
        cfg.machine_rank = infer_machine_rank()
    elif (
        cfg.num_machines > 1
        and getattr(args, "machine_rank", None) is None
        and any(v in os.environ for v in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"))
    ):
        # multi-host with no explicit rank but a TPU runtime present:
        # trust the runtime's worker id over the config-file default
        cfg.machine_rank = infer_machine_rank()
    return cfg


def simple_launcher(args, cfg: ClusterConfig) -> int:
    """Single host: exec the script with the env transport (reference :696).

    With ``--max_restarts N``, a crashed script is relaunched up to N
    times (reference passes torchelastic ``max_restarts``/
    ``monitor_interval``, launchers.py:226-239). The restarted run resumes
    from the latest complete checkpoint when the script uses
    :class:`~accelerate_tpu.fault_tolerance.CheckpointManager.restore_or_init`
    — together they form the supervised-elastic loop. The attempt index is
    exported as ``ACCELERATE_TPU_RESTART_COUNT``.
    """
    import time

    env = {**os.environ, **cfg.to_env()}
    if cfg.num_machines > 1:
        env[ENV_PREFIX + "NUM_PROCESSES"] = str(cfg.num_machines)
        env[ENV_PREFIX + "PROCESS_ID"] = str(cfg.machine_rank)
    cmd = [sys.executable, args.training_script, *args.training_script_args]
    max_restarts = getattr(args, "max_restarts", 0) or 0
    for attempt in range(max_restarts + 1):
        env[ENV_PREFIX + "RESTART_COUNT"] = str(attempt)
        rc = subprocess.call(cmd, env=env)
        if rc == 0:
            return 0
        if attempt < max_restarts:
            delay = getattr(args, "monitor_interval", 5.0)
            print(
                f"training script exited with {rc}; restart "
                f"{attempt + 1}/{max_restarts} in {delay}s",
                file=sys.stderr,
            )
            time.sleep(delay)
    return rc


def debug_launcher_command(args, cfg: ClusterConfig) -> int:
    """N local CPU processes with a localhost coordinator (reference
    launchers.py:263 debug_launcher, as a CLI mode)."""
    n = args.debug_num_processes
    port = cfg.main_process_port or 29512
    procs = []
    for rank in range(n):
        env = {
            **os.environ,
            **cfg.to_env(),
            "JAX_PLATFORMS": "cpu",
            ENV_PREFIX + "NUM_PROCESSES": str(n),
            ENV_PREFIX + "PROCESS_ID": str(rank),
            ENV_PREFIX + "COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        }
        procs.append(
            subprocess.Popen(
                [sys.executable, args.training_script, *args.training_script_args],
                env=env,
            )
        )
    rc = 0
    for p in procs:
        rc = p.wait() or rc
    return rc


def infer_machine_rank() -> int:
    """This host's pod worker index (reference derives it host-side,
    commands/launch.py:827-885).

    Priority: the TPU runtime's own worker id (``TPU_WORKER_ID``, set on
    every Cloud TPU VM; ``CLOUD_TPU_TASK_ID`` on older images) — then the
    ``-w-{i}`` hostname suffix GCE gives TPU VM workers. A bare trailing
    digit (e.g. a custom DNS name ``ml-node-7``) is NOT a worker index and
    raises: a silently wrong rank makes workers collide at coordinator
    init and hangs the whole pod.
    """
    import re
    import socket

    for var in ("TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"):
        val = os.environ.get(var)
        if val is not None and val.strip().isdigit():
            return int(val)
    hostname = socket.gethostname()
    m = re.search(r"-w-(\d+)$", hostname)
    if m:
        return int(m.group(1))
    raise RuntimeError(
        f"cannot derive --machine_rank: TPU_WORKER_ID/CLOUD_TPU_TASK_ID "
        f"unset and hostname {hostname!r} has no '-w-<index>' suffix — "
        "pass --machine_rank explicitly"
    )


def tpu_pod_launcher(args, cfg: ClusterConfig) -> int:
    """Fan the same launch out to every pod worker over gcloud ssh
    (reference tpu_pod_launcher :827 / tpu.py:90). Each worker derives its
    own rank host-side via :func:`infer_machine_rank` (TPU_WORKER_ID with
    an erroring hostname fallback — the r2 hostname regex produced an
    empty rank on non-standard names with no error)."""
    from .tpu import build_gcloud_ssh_command

    # NO per-worker restart flags: a single restarted worker cannot rejoin
    # a live jax.distributed job (the coordinator holds the original
    # generation's ranks) — one rejoining process would hang the pod.
    # Supervision happens HERE instead: the whole fan-out (every worker
    # together) is relaunched, so the coordinator re-forms cleanly.
    inner = (
        f"cd {os.getcwd()} && "
        f"accelerate-tpu launch --machine_rank -1 "
        f"{args.training_script} {' '.join(args.training_script_args)}"
    )
    cmd = build_gcloud_ssh_command(
        cfg.tpu_name or "tpu", inner, cfg.tpu_zone
    )
    print("Running:", " ".join(cmd))
    import time

    max_restarts = getattr(args, "max_restarts", 0) or 0
    for attempt in range(max_restarts + 1):
        rc = subprocess.call(cmd)
        if rc == 0:
            return 0
        if attempt < max_restarts:
            delay = getattr(args, "monitor_interval", 5.0)
            print(
                f"pod launch exited with {rc}; whole-pod restart "
                f"{attempt + 1}/{max_restarts} in {delay}s",
                file=sys.stderr,
            )
            time.sleep(delay)
    return rc


def launch_command(args) -> None:
    cfg = _merge_config(args)
    if getattr(args, "elastic", False):
        from .elastic import elastic_launcher_command

        rc = elastic_launcher_command(args, cfg)
    elif args.debug_num_processes:
        rc = debug_launcher_command(args, cfg)
    elif args.gcloud:
        rc = tpu_pod_launcher(args, cfg)
    else:
        rc = simple_launcher(args, cfg)
    if rc:
        sys.exit(rc)
