"""Elastic supervisor: re-form the world with the survivors.

The supervised-restart story (``--max_restarts``) relaunches the whole
fleet at the ORIGINAL world size — fine for transient crashes, useless
when a host is actually gone (preempted, hardware-failed): the relaunch
blocks at coordinator init waiting for a rank that will never come back.
This module is the actuation half of the PR-5 detection stack
(heartbeats + flight recorder + ``diagnose``):

1. run one *generation*: spawn ``world`` training processes wired
   through a fresh localhost ``jax.distributed`` coordinator;
2. declare a rank dead when its process exits nonzero, or when its
   heartbeat file (``telemetry/heartbeat.py``) goes stale mid-run;
3. tear down the remainder cleanly — SIGTERM so each survivor's
   :class:`~accelerate_tpu.fault_tolerance.CheckpointManager` attempts
   its final checkpoint where reachable (a survivor wedged in a
   collective against the dead rank cannot finish a *collective* save;
   the atomic commit protocol guarantees an unfinished attempt stays
   invisible, so restore falls back to the last committed cadence
   checkpoint), then SIGKILL whatever is still alive after the grace
   period;
4. recompute the healthy world (``world - dead``), renumber ranks
   ``0..M-1``, and relaunch the next generation at the reduced size.
   Relaunched processes see ``ACCELERATE_TPU_ELASTIC=1`` so
   ``restore_or_init``/``load_state`` default to ``allow_reshape``:
   the N-host checkpoint re-slices onto the M-host mesh
   (:mod:`~accelerate_tpu.dist_checkpoint` coverage-validated restore).

A generation whose every process exits 0 ends the run successfully;
fewer than ``min_processes`` survivors, or ``max_generations``
exhausted, ends it with a failure.

Scope: this supervisor drives LOCAL processes (one per rank, CPU
backend by default) — the ``--elastic`` mode of ``accelerate-tpu
launch`` pairs it with ``--debug_num_processes``, and it is the engine
of the elastic tests. On a real pod the same loop runs on the
controller with the spawn step replaced by the gcloud fan-out; the
generation/teardown/reshape contract is identical.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Any, Callable, Optional, Sequence

from ..logging import get_logger
from ..utils.constants import ENV_PREFIX

logger = get_logger(__name__)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class GenerationRecord:
    """What one generation did — the supervisor's auditable history."""

    generation: int
    world: int
    outcome: str  # "success" | "rank_death" | "below_min"
    dead_ranks: list[int]
    exit_codes: dict[int, Optional[int]]
    duration_s: float
    # hierarchical (multi-slice) runs: how many slices this generation
    # ran with, and which fault domains (slice ids) it lost
    num_slices: int = 1
    dead_domains: list[int] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class ElasticSupervisor:
    """Generation loop around a training command (see module docstring).

    ``cmd``: the training command, relaunched verbatim each generation.
    ``heartbeat_dir``: where ranks write ``heartbeat-rank{i}.json``; also
    receives the supervisor's ``elastic-events.jsonl``. Heartbeat-based
    death declaration needs it; exit-based declaration works without.
    ``stall_timeout_s``: silence after a rank's FIRST beat that declares
    it dead (never-beaten ranks are only caught by process exit — a rank
    may legitimately spend a long time importing/compiling before its
    first step). ``grace_period_s``: SIGTERM -> SIGKILL window at
    teardown. ``generation_hook(generation, world)`` runs before each
    spawn (tests use it to snapshot checkpoints between generations).
    ``cpu=True`` pins children to the CPU backend (the local debug
    topology); pass False when the child env already selects a platform.

    ``num_slices > 1`` turns on slice fault domains: ranks are assigned
    slice-major (ranks ``[s*P, (s+1)*P)`` form slice ``s``,
    ``P = num_processes / num_slices``), every rank's env carries its
    ``ACCELERATE_TPU_FAULT_DOMAIN`` + the generation's
    ``ACCELERATE_TPU_NUM_SLICES``, and a death declaration expands to
    the victim's WHOLE slice — the unit of failure on a DCN-linked pod
    is a slice, and re-forming at ``world - 1`` would land on a
    topology no hierarchical mesh can use. Survivors relaunch as a
    valid ``(num_slices - len(dead_domains))``-slice fleet in ONE
    generation.
    """

    def __init__(
        self,
        cmd: Sequence[str],
        num_processes: int,
        min_processes: int = 1,
        heartbeat_dir: Optional[str] = None,
        stall_timeout_s: float = 60.0,
        grace_period_s: float = 10.0,
        max_generations: int = 8,
        monitor_interval_s: float = 0.2,
        generation_timeout_s: Optional[float] = None,
        env: Optional[dict[str, str]] = None,
        cpu: bool = True,
        generation_hook: Optional[Callable[[int, int], None]] = None,
        num_slices: int = 1,
    ):
        if num_processes < 1:
            raise ValueError("num_processes must be >= 1")
        if not (1 <= min_processes <= num_processes):
            raise ValueError(
                f"min_processes must be in [1, num_processes]; got "
                f"{min_processes} with num_processes={num_processes}"
            )
        if num_slices < 1:
            raise ValueError("num_slices must be >= 1")
        if num_processes % num_slices != 0:
            raise ValueError(
                f"num_processes={num_processes} must be divisible by "
                f"num_slices={num_slices} (slice-major contiguous rank "
                "assignment needs equal-sized fault domains)"
            )
        self.cmd = list(cmd)
        self.num_processes = num_processes
        self.min_processes = min_processes
        self.num_slices = num_slices
        self.procs_per_slice = num_processes // num_slices
        self.heartbeat_dir = heartbeat_dir
        self.stall_timeout_s = stall_timeout_s
        self.grace_period_s = grace_period_s
        self.max_generations = max_generations
        self.monitor_interval_s = monitor_interval_s
        self.generation_timeout_s = generation_timeout_s
        self.env = dict(env or {})
        self.cpu = cpu
        self.generation_hook = generation_hook
        self.history: list[GenerationRecord] = []
        if heartbeat_dir:
            os.makedirs(heartbeat_dir, exist_ok=True)

    # ------------------------------------------------------------------ #
    def _fault_domain(self, rank: int) -> int:
        """Slice id of a rank (slice-major contiguous assignment). The
        slice width is fixed for the run — whole slices die, so every
        generation's world is a multiple of ``procs_per_slice``."""
        if self.num_slices <= 1:
            return 0
        return rank // self.procs_per_slice

    def _world_slices(self, world: int) -> int:
        """How many slices a generation's world spans."""
        if self.num_slices <= 1:
            return 1
        return max(1, world // self.procs_per_slice)

    def _expand_to_domains(
        self, dead: set[int], world: int
    ) -> tuple[set[int], list[int]]:
        """Expand a dead-rank set to every rank in the affected fault
        domains -> (expanded set, sorted dead domain ids). Identity when
        the run is single-slice."""
        if self.num_slices <= 1 or not dead:
            return set(dead), []
        domains = sorted({self._fault_domain(r) for r in dead})
        expanded = {
            r for r in range(world) if self._fault_domain(r) in domains
        }
        return expanded, domains

    def _child_env(self, rank: int, world: int, generation: int, port: int):
        env = {**os.environ, **self.env}
        if self.cpu:
            env["JAX_PLATFORMS"] = "cpu"
        env[ENV_PREFIX + "NUM_PROCESSES"] = str(world)
        env[ENV_PREFIX + "PROCESS_ID"] = str(rank)
        env[ENV_PREFIX + "NUM_SLICES"] = str(self._world_slices(world))
        env[ENV_PREFIX + "FAULT_DOMAIN"] = str(self._fault_domain(rank))
        env[ENV_PREFIX + "COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        env[ENV_PREFIX + "ELASTIC"] = "1"
        env[ENV_PREFIX + "ELASTIC_GENERATION"] = str(generation)
        env[ENV_PREFIX + "ELASTIC_MIN_PROCESSES"] = str(self.min_processes)
        env[ENV_PREFIX + "RESTART_COUNT"] = str(generation)
        # a heartbeat-declared death gets SIGABRT before SIGKILL so the
        # wedged rank's stack lands in its log — worthless without this
        env.setdefault("PYTHONFAULTHANDLER", "1")
        if self.heartbeat_dir:
            env[ENV_PREFIX + "ELASTIC_HEARTBEAT_DIR"] = self.heartbeat_dir
        return env

    def _child_stdio(self, rank: int, generation: int):
        """Per-rank log file under the heartbeat dir (post-mortems need
        each rank's own output, not an interleaved console)."""
        if not self.heartbeat_dir:
            return None
        path = os.path.join(
            self.heartbeat_dir, f"rank{rank}-gen{generation}.log"
        )
        return open(path, "ab")

    def _event(self, kind: str, **fields) -> None:
        record = {"event": kind, "time_unix": time.time(), **fields}
        logger.info(f"elastic: {kind} {fields}")
        if not self.heartbeat_dir:
            return
        try:
            path = os.path.join(self.heartbeat_dir, "elastic-events.jsonl")
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")
        except OSError:
            pass  # event log is observability, never a failure source

    # ------------------------------------------------------------------ #
    def run(self) -> int:
        """Run generations until success, exhaustion, or too few
        survivors. Returns a process exit code (0 = trained to
        completion)."""
        world = self.num_processes
        for generation in range(self.max_generations):
            if self.generation_hook is not None:
                self.generation_hook(generation, world)
            record = self._run_generation(generation, world)
            self.history.append(record)
            if record.outcome == "success":
                self._event(
                    "run_complete",
                    generation=generation,
                    generations=generation + 1,
                )
                return 0
            survivors = world - len(record.dead_ranks)
            if survivors < self.min_processes:
                record.outcome = "below_min"
                self._event(
                    "giving_up",
                    generation=generation,
                    survivors=survivors,
                    min_processes=self.min_processes,
                    dead_ranks=record.dead_ranks,
                    victim_ranks=record.dead_ranks,
                    fault_domains=record.dead_domains,
                )
                logger.error(
                    f"elastic: {survivors} survivor(s) after generation "
                    f"{generation} is below --min_processes="
                    f"{self.min_processes}; giving up"
                )
                return 1
            self._event(
                "reforming",
                generation=generation + 1,
                old_world=world,
                new_world=survivors,
                dead_ranks=record.dead_ranks,
                victim_ranks=record.dead_ranks,
                fault_domains=record.dead_domains,
                old_num_slices=record.num_slices,
                new_num_slices=self._world_slices(survivors),
            )
            world = survivors
        logger.error(
            f"elastic: exhausted max_generations={self.max_generations} "
            "without a clean finish"
        )
        return 1

    # ------------------------------------------------------------------ #
    def _run_generation(self, generation: int, world: int) -> GenerationRecord:
        t0 = time.monotonic()
        port = _free_port()
        num_slices = self._world_slices(world)
        self._event(
            "generation_start",
            generation=generation,
            world=world,
            port=port,
            num_slices=num_slices,
        )
        procs: dict[int, subprocess.Popen] = {}
        logs = []
        for rank in range(world):
            log = self._child_stdio(rank, generation)
            if log is not None:
                logs.append(log)
            procs[rank] = subprocess.Popen(
                self.cmd,
                env=self._child_env(rank, world, generation, port),
                stdout=log,
                stderr=subprocess.STDOUT if log is not None else None,
            )
        for log in logs:  # children hold their own copies now
            log.close()
        deadline = (
            time.monotonic() + self.generation_timeout_s
            if self.generation_timeout_s
            else None
        )
        dead: set[int] = set()
        while True:
            running = {r: p for r, p in procs.items() if p.poll() is None}
            dead = {
                r
                for r, p in procs.items()
                if p.poll() is not None and p.returncode != 0
            }
            if not dead and self.heartbeat_dir and self.stall_timeout_s:
                from ..telemetry.heartbeat import scan_heartbeats

                records = scan_heartbeats(
                    self.heartbeat_dir, stall_timeout_s=self.stall_timeout_s
                )
                stale = {
                    r: rec
                    for r, rec in records.items()
                    if rec.get("generation") == generation
                    and rec["stale"]
                    and r in running
                }
                if stale:
                    # when one rank wedges, EVERY rank goes silent within a
                    # step (they all block at the next collective) — so
                    # declare dead only the rank that went silent FIRST
                    # (oldest last beat: the straggler); the rest are
                    # survivors and re-form. A hung rank gets SIGKILL, not
                    # SIGTERM: it is wedged, the final-checkpoint contract
                    # cannot run anyway. On a multi-slice run, every stale
                    # rank sharing the straggler's fault domain is declared
                    # with it — a slice-level fault (power, DCN link) wedges
                    # the whole slice at once, and burning one generation
                    # per rank would re-form num_slices*P times.
                    victim = min(
                        stale, key=lambda r: stale[r].get("time_unix", 0.0)
                    )
                    victims = [victim]
                    if self.num_slices > 1:
                        domain = self._fault_domain(victim)
                        victims = sorted(
                            r
                            for r in stale
                            if self._fault_domain(r) == domain
                        )
                    self._event(
                        "heartbeat_death",
                        generation=generation,
                        rank=victim,
                        victim_ranks=victims,
                        fault_domain=self._fault_domain(victim),
                        fault_domains=[self._fault_domain(victim)],
                        last_step=stale[victim].get("step"),
                        age_s=stale[victim].get("age_s"),
                    )
                    # SIGABRT first: with PYTHONFAULTHANDLER each victim's
                    # wedged stack prints to its log before it dies
                    for v in victims:
                        self._kill(running[v], signal.SIGABRT)
                    for v in victims:
                        try:
                            running[v].wait(timeout=3)
                        except subprocess.TimeoutExpired:
                            self._kill(running[v], signal.SIGKILL)
                            running[v].wait()
                    dead.update(victims)
            if dead:
                victims = sorted(dead)
                dead, dead_domains = self._expand_to_domains(dead, world)
                if set(victims) != dead:
                    # whole-slice drop: the survivors of the victim's
                    # slice are healthy processes on a dead fault domain
                    self._event(
                        "slice_death",
                        generation=generation,
                        fault_domains=dead_domains,
                        victim_ranks=victims,
                        dropped_ranks=sorted(dead),
                    )
                self._event(
                    "rank_death",
                    generation=generation,
                    dead_ranks=sorted(dead),
                    victim_ranks=victims,
                    fault_domains=dead_domains,
                    exit_codes={
                        r: procs[r].returncode for r in sorted(dead)
                    },
                )
                self._teardown(
                    {r: p for r, p in procs.items() if p.poll() is None},
                    generation=generation,
                )
                break
            if not running:
                return GenerationRecord(
                    generation=generation,
                    world=world,
                    outcome="success",
                    dead_ranks=[],
                    exit_codes={r: p.returncode for r, p in procs.items()},
                    duration_s=time.monotonic() - t0,
                    num_slices=num_slices,
                )
            if deadline is not None and time.monotonic() > deadline:
                self._event(
                    "generation_timeout",
                    generation=generation,
                    running=sorted(running),
                )
                # nobody exited, nobody was declared dead: treat every
                # still-running rank as hung
                for p in running.values():
                    self._kill(p, signal.SIGKILL)
                for p in running.values():
                    p.wait()
                dead = set(running)
                break
            time.sleep(self.monitor_interval_s)
        # idempotent on the rank_death path, and folds the timeout path's
        # kill-everyone set onto whole fault domains too
        dead, dead_domains = self._expand_to_domains(dead, world)
        return GenerationRecord(
            generation=generation,
            world=world,
            outcome="rank_death",
            dead_ranks=sorted(dead),
            exit_codes={r: p.returncode for r, p in procs.items()},
            duration_s=time.monotonic() - t0,
            num_slices=num_slices,
            dead_domains=dead_domains,
        )

    # ------------------------------------------------------------------ #
    def _kill(self, proc: subprocess.Popen, sig: int) -> None:
        try:
            proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass

    def _teardown(
        self,
        survivors: dict[int, subprocess.Popen],
        generation: int = -1,
    ) -> None:
        """SIGTERM -> grace -> SIGKILL. The SIGTERM gives each survivor's
        CheckpointManager its final-checkpoint attempt; a survivor stuck
        in a collective against the dead rank never reaches the handler's
        next step() check, which is exactly what the grace SIGKILL is
        for. Any unfinished save stays an invisible ``.tmp`` work dir."""
        if not survivors:
            return
        for p in survivors.values():
            self._kill(p, signal.SIGTERM)
        deadline = time.monotonic() + self.grace_period_s
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in survivors.values()):
                break
            time.sleep(0.05)
        killed = []
        for rank, p in survivors.items():
            if p.poll() is None:
                killed.append(rank)
                self._kill(p, signal.SIGKILL)
        for p in survivors.values():
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        if killed:
            self._event(
                "teardown_sigkill", generation=generation, ranks=sorted(killed)
            )


def elastic_launcher_command(args, cfg) -> int:
    """``accelerate-tpu launch --elastic`` entry: wrap the training script
    in an :class:`ElasticSupervisor` over local processes."""
    n = args.debug_num_processes
    if not n:
        raise SystemExit(
            "--elastic drives local processes: pass --debug_num_processes N "
            "(on a pod, run this supervisor on the controller so the "
            "gcloud fan-out IS the spawn step)"
        )
    supervisor = ElasticSupervisor(
        cmd=[sys.executable, args.training_script, *args.training_script_args],
        num_processes=n,
        min_processes=args.min_processes,
        heartbeat_dir=args.heartbeat_dir,
        stall_timeout_s=args.stall_timeout,
        grace_period_s=args.grace_period,
        max_generations=args.max_restarts + 1 if args.max_restarts else 8,
        env=cfg.to_env(),
        num_slices=getattr(args, "num_slices", 1) or 1,
    )
    return supervisor.run()
