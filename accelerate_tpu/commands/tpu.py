"""`accelerate-tpu tpu-config` — run commands on every worker of a TPU pod.

Parity: reference ``commands/tpu.py`` (``tpu_command_launcher``:90 — wraps
``gcloud alpha compute tpus tpu-vm ssh --worker=all --command=...``, with
``--install_accelerate`` bootstrapping and ``--debug`` printing instead of
running). Same shape here: the pod's hosts are reached through gcloud ssh
fan-out; the framework itself is hostname-agnostic (jax.distributed does
the rendezvous once processes start).
"""

from __future__ import annotations

import argparse
import os
import subprocess
from typing import Optional

from .config import ClusterConfig, default_config_file

_DEFAULT_CMD = ["cd /usr/share"]


def build_gcloud_ssh_command(
    tpu_name: str, command: str, tpu_zone: Optional[str] = None
) -> list[str]:
    """The single gcloud pod fan-out invocation — shared by `tpu-config`
    and `launch --gcloud` so the two cannot drift."""
    out = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        "--worker", "all",
        "--command", command,
    ]
    if tpu_zone:
        out += ["--zone", tpu_zone]
    return out


def _resolve_tpu(args) -> tuple[str, Optional[str]]:
    """(tpu_name, tpu_zone) from CLI args with config-file fallback —
    shared by `tpu-config` and `provision` so resolution cannot drift."""
    cfg: Optional[ClusterConfig] = None
    config_path = args.config_file or default_config_file()
    if os.path.isfile(config_path):
        cfg = ClusterConfig.load(config_path)
    tpu_name = args.tpu_name or (cfg.tpu_name if cfg else None)
    tpu_zone = args.tpu_zone or (cfg.tpu_zone if cfg else None)
    if not tpu_name:
        raise ValueError(
            "no TPU name: pass --tpu_name or set tpu_name in the config "
            "(accelerate-tpu config)"
        )
    return tpu_name, tpu_zone


def build_pod_command(args) -> list[str]:
    """Assemble the gcloud fan-out command line (pure — testable without
    gcloud)."""
    tpu_name, tpu_zone = _resolve_tpu(args)

    commands = list(_DEFAULT_CMD)
    if args.install_accelerate:
        commands.append("pip install accelerate_tpu -U")
    for cmd in args.command or []:
        commands.append(cmd)
    if len(commands) == len(_DEFAULT_CMD):
        raise ValueError(
            "no command to run: pass --command (repeatable) and/or "
            "--install_accelerate"
        )
    joined = "; ".join(commands)
    return build_gcloud_ssh_command(tpu_name, joined, tpu_zone)


def tpu_command(args) -> None:
    cmd = build_pod_command(args)
    if args.debug:
        print(f"Running {' '.join(cmd)}")
        return
    print(f"Running {' '.join(cmd)} on every pod worker...")
    subprocess.run(cmd, check=True)
    print("Successfully run command on every pod worker")


def build_queued_resource_command(args) -> list[str]:
    """``gcloud compute tpus queued-resources create`` invocation — the
    managed-cloud job-submission seat (reference submits to SageMaker,
    commands/launch.py:886 / utils/launch.py:464; the TPU-native analog
    is a queued resource that provisions capacity and runs the training
    command when granted). Pure — testable without gcloud."""
    tpu_name, tpu_zone = _resolve_tpu(args)
    if not args.accelerator_type:
        raise ValueError("--accelerator_type is required (e.g. v5e-16)")
    out = [
        "gcloud", "compute", "tpus", "queued-resources", "create", tpu_name,
        "--node-id", tpu_name,
        "--accelerator-type", args.accelerator_type,
        "--runtime-version", args.runtime_version,
    ]
    if tpu_zone:
        out += ["--zone", tpu_zone]
    if args.spot:
        out += ["--spot"]
    if args.valid_until_duration:
        out += ["--valid-until-duration", args.valid_until_duration]
    if args.startup_command:
        # the queued resource runs this on every worker once granted —
        # typically an `accelerate-tpu launch ...` line
        out += ["--metadata", f"startup-script=#! /bin/bash\n{args.startup_command}"]
    return out


def provision_command(args) -> None:
    cmd = build_queued_resource_command(args)
    if args.debug:
        print(f"Running {' '.join(cmd)}")
        return
    # cmd[5] is the resolved name (args.tpu_name may be None when it came
    # from the config file)
    print(f"Submitting queued resource {cmd[5]}...")
    subprocess.run(cmd, check=True)
    print(
        "Queued resource submitted — capacity is granted asynchronously; "
        "check `gcloud compute tpus queued-resources list`"
    )


def provision_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser(
            "provision",
            help="Submit a TPU queued-resource request (managed-cloud "
            "job submission; runs a startup command when granted)",
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu provision")
    parser.add_argument("--config_file", default=None,
                        help="Launch config with tpu_name/tpu_zone")
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument("--accelerator_type", default=None,
                        help="e.g. v5e-16, v5p-8")
    parser.add_argument("--runtime_version", default="tpu-ubuntu2204-base")
    parser.add_argument("--spot", action="store_true",
                        help="Request preemptible (spot) capacity")
    parser.add_argument("--valid_until_duration", default=None,
                        help="Auto-cancel the request after e.g. 6h")
    parser.add_argument("--startup_command", default=None,
                        help="Command each worker runs once granted "
                        "(e.g. an accelerate-tpu launch line)")
    parser.add_argument("--debug", action="store_true",
                        help="Print the gcloud command instead of running it")
    if subparsers is not None:
        parser.set_defaults(func=provision_command)
    return parser


def tpu_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser(
            "tpu-config", help="Run commands on all TPU pod workers"
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu tpu-config")
    parser.add_argument("--config_file", default=None,
                        help="Launch config with tpu_name/tpu_zone")
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument(
        "--command", action="append",
        help="Command to run on every worker (repeatable)",
    )
    parser.add_argument(
        "--install_accelerate", action="store_true",
        help="Install/upgrade accelerate_tpu on every worker first",
    )
    parser.add_argument(
        "--debug", action="store_true",
        help="Print the gcloud command instead of running it",
    )
    if subparsers is not None:
        parser.set_defaults(func=tpu_command)
    return parser
