"""`accelerate-tpu tpu-config` — run commands on every worker of a TPU pod.

Parity: reference ``commands/tpu.py`` (``tpu_command_launcher``:90 — wraps
``gcloud alpha compute tpus tpu-vm ssh --worker=all --command=...``, with
``--install_accelerate`` bootstrapping and ``--debug`` printing instead of
running). Same shape here: the pod's hosts are reached through gcloud ssh
fan-out; the framework itself is hostname-agnostic (jax.distributed does
the rendezvous once processes start).
"""

from __future__ import annotations

import argparse
import os
import subprocess
from typing import Optional

from .config import ClusterConfig, default_config_file

_DEFAULT_CMD = ["cd /usr/share"]


def build_gcloud_ssh_command(
    tpu_name: str, command: str, tpu_zone: Optional[str] = None
) -> list[str]:
    """The single gcloud pod fan-out invocation — shared by `tpu-config`
    and `launch --gcloud` so the two cannot drift."""
    out = [
        "gcloud", "compute", "tpus", "tpu-vm", "ssh", tpu_name,
        "--worker", "all",
        "--command", command,
    ]
    if tpu_zone:
        out += ["--zone", tpu_zone]
    return out


def build_pod_command(args) -> list[str]:
    """Assemble the gcloud fan-out command line (pure — testable without
    gcloud)."""
    cfg: Optional[ClusterConfig] = None
    config_path = args.config_file or default_config_file()
    if os.path.isfile(config_path):
        cfg = ClusterConfig.load(config_path)
    tpu_name = args.tpu_name or (cfg.tpu_name if cfg else None)
    tpu_zone = args.tpu_zone or (cfg.tpu_zone if cfg else None)
    if not tpu_name:
        raise ValueError(
            "no TPU name: pass --tpu_name or set tpu_name in the config "
            "(accelerate-tpu config)"
        )

    commands = list(_DEFAULT_CMD)
    if args.install_accelerate:
        commands.append("pip install accelerate_tpu -U")
    for cmd in args.command or []:
        commands.append(cmd)
    if len(commands) == len(_DEFAULT_CMD):
        raise ValueError(
            "no command to run: pass --command (repeatable) and/or "
            "--install_accelerate"
        )
    joined = "; ".join(commands)
    return build_gcloud_ssh_command(tpu_name, joined, tpu_zone)


def tpu_command(args) -> None:
    cmd = build_pod_command(args)
    if args.debug:
        print(f"Running {' '.join(cmd)}")
        return
    print(f"Running {' '.join(cmd)} on every pod worker...")
    subprocess.run(cmd, check=True)
    print("Successfully run command on every pod worker")


def tpu_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser(
            "tpu-config", help="Run commands on all TPU pod workers"
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu tpu-config")
    parser.add_argument("--config_file", default=None,
                        help="Launch config with tpu_name/tpu_zone")
    parser.add_argument("--tpu_name", default=None)
    parser.add_argument("--tpu_zone", default=None)
    parser.add_argument(
        "--command", action="append",
        help="Command to run on every worker (repeatable)",
    )
    parser.add_argument(
        "--install_accelerate", action="store_true",
        help="Install/upgrade accelerate_tpu on every worker first",
    )
    parser.add_argument(
        "--debug", action="store_true",
        help="Print the gcloud command instead of running it",
    )
    if subparsers is not None:
        parser.set_defaults(func=tpu_command)
    return parser
