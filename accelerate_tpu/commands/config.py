"""`accelerate-tpu config` — questionnaire + YAML config file.

Parity: reference ``commands/config/`` (~1700 LoC: interactive questionnaire
``cluster.py:49-723``, ``ClusterConfig`` serialization ``config_args.py:244``,
``write_basic_config`` ``default.py:29``). The TPU build's question set
collapses to what matters here: topology (hosts/chips), the mesh degrees
(dp/fsdp/tp/sp/ep), precision, and gradient accumulation — DeepSpeed/FSDP/
Megatron engine pages have no equivalent because sharding replaced them.
"""

from __future__ import annotations

import argparse
import json
import os
from dataclasses import asdict, dataclass, field
from typing import Any, Optional

from ..utils.constants import DEFAULT_CONFIG_DIR, ENV_PREFIX
from ..utils.imports import is_yaml_available

default_config_dir = os.path.expanduser(DEFAULT_CONFIG_DIR)
default_yaml_config_file = os.path.join(default_config_dir, "default_config.yaml")
default_json_config_file = os.path.join(default_config_dir, "default_config.json")


def default_config_file() -> str:
    if os.path.isfile(default_yaml_config_file):
        return default_yaml_config_file
    return default_json_config_file


@dataclass
class ClusterConfig:
    """The saved launch configuration (reference config_args.py:244)."""

    compute_environment: str = "LOCAL_MACHINE"
    distributed_type: str = "TPU"
    num_processes: int = 1  # processes (hosts), not chips
    num_machines: int = 1
    machine_rank: int = 0
    main_process_ip: Optional[str] = None
    main_process_port: Optional[int] = None
    mixed_precision: str = "bf16"
    gradient_accumulation_steps: int = 1
    # mesh degrees
    dp_size: int = -1
    pp_size: int = 1
    fsdp_size: int = 1
    tp_size: int = 1
    sp_size: int = 1
    ep_size: int = 1
    num_micro_batches: int = 1
    sharding_strategy: str = "full_shard"
    # pod fan-out
    tpu_name: Optional[str] = None
    tpu_zone: Optional[str] = None
    downcast_bf16: bool = False

    def to_dict(self) -> dict:
        return {k: v for k, v in asdict(self).items() if v is not None}

    def save(self, path: Optional[str] = None) -> str:
        path = path or default_yaml_config_file
        os.makedirs(os.path.dirname(path), exist_ok=True)
        data = self.to_dict()
        if path.endswith((".yaml", ".yml")) and is_yaml_available():
            import yaml

            with open(path, "w") as f:
                yaml.safe_dump(data, f)
        else:
            with open(path, "w") as f:
                json.dump(data, f, indent=2)
        return path

    @classmethod
    def load(cls, path: Optional[str] = None) -> "ClusterConfig":
        path = path or default_config_file()
        if not os.path.isfile(path):
            raise FileNotFoundError(
                f"no config at {path}; run `accelerate-tpu config` first"
            )
        if path.endswith((".yaml", ".yml")):
            import yaml

            with open(path) as f:
                data = yaml.safe_load(f)
        else:
            with open(path) as f:
                data = json.load(f)
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in (data or {}).items() if k in known})

    def to_env(self) -> dict[str, str]:
        """The env-var transport into workers (reference launch.py env
        builders): every mesh degree and precision flag becomes
        ACCELERATE_TPU_*."""
        env = {
            ENV_PREFIX + "MIXED_PRECISION": self.mixed_precision,
            ENV_PREFIX + "GRADIENT_ACCUMULATION_STEPS": str(
                self.gradient_accumulation_steps
            ),
            ENV_PREFIX + "DP_SIZE": str(self.dp_size),
            ENV_PREFIX + "PP_SIZE": str(self.pp_size),
            ENV_PREFIX + "NUM_MICRO_BATCHES": str(self.num_micro_batches),
            ENV_PREFIX + "FSDP_SIZE": str(self.fsdp_size),
            ENV_PREFIX + "TP_SIZE": str(self.tp_size),
            ENV_PREFIX + "SP_SIZE": str(self.sp_size),
            ENV_PREFIX + "EP_SIZE": str(self.ep_size),
            ENV_PREFIX + "SHARDING_STRATEGY": self.sharding_strategy,
        }
        if self.num_machines > 1:
            env[ENV_PREFIX + "NUM_PROCESSES"] = str(self.num_machines)
            if self.main_process_ip:
                env[ENV_PREFIX + "COORDINATOR_ADDRESS"] = (
                    f"{self.main_process_ip}:{self.main_process_port or 8476}"
                )
        return env


def _ask(prompt: str, default: Any, cast=str, validate=None):
    """One free-form question; re-asks until ``cast``+``validate`` accept
    (reference _ask_field commands/config/config_utils.py:41)."""
    while True:
        raw = input(f"{prompt} [{default}]: ").strip()
        try:
            value = cast(raw) if raw else default
        except (TypeError, ValueError):
            print(f"  invalid value {raw!r}, try again")
            continue
        if validate is not None and not validate(value):
            print(f"  {value!r} not allowed here, try again")
            continue
        return value


def _ask_options(prompt: str, options: list[str], default_index: int = 0) -> str:
    """Numbered-menu question (reference _ask_options + the arrow-key menu
    commands/config/menu/selection_menu.py — numbered input works over ssh
    and in dumb terminals, which is where TPU pods are configured)."""
    print(prompt)
    for i, opt in enumerate(options):
        print(f"  [{i}] {opt}")
    idx = _ask(
        "choice", default_index, int, validate=lambda v: 0 <= v < len(options)
    )
    return options[idx]


def get_user_input() -> ClusterConfig:
    """Interactive questionnaire (reference cluster.py:49)."""
    print("accelerate_tpu configuration")
    print("----------------------------")
    cfg = ClusterConfig()
    env = _ask_options(
        "Where will the job run?",
        ["LOCAL_MACHINE", "TPU_POD (gcloud fan-out)"],
    )
    cfg.compute_environment = "TPU_POD" if env.startswith("TPU_POD") else env
    if cfg.compute_environment == "TPU_POD":
        cfg.tpu_name = _ask("TPU pod name (gcloud)?", "", str) or None
        cfg.tpu_zone = _ask("TPU zone?", "", str) or None
    cfg.num_machines = _ask(
        "How many hosts (machines)?", 1, int, validate=lambda v: v >= 1
    )
    if cfg.num_machines > 1:
        cfg.machine_rank = _ask(
            "Rank of this machine?", 0, int,
            validate=lambda v: 0 <= v < cfg.num_machines,
        )
        cfg.main_process_ip = _ask("Coordinator (rank 0) IP?", "", str) or None
        cfg.main_process_port = _ask("Coordinator port?", 8476, int)
    cfg.mixed_precision = _ask_options(
        "Mixed precision?", ["bf16", "no", "fp16", "fp8"], 0
    )
    cfg.gradient_accumulation_steps = _ask(
        "Gradient accumulation steps?", 1, int, validate=lambda v: v >= 1
    )
    deg = lambda v: v == -1 or v >= 1  # noqa: E731
    cfg.fsdp_size = _ask(
        "FSDP (parameter-sharding) degree (1=off, -1=all)?", 1, int, deg
    )
    if cfg.fsdp_size != 1:
        cfg.sharding_strategy = _ask_options(
            "Sharding strategy?",
            ["full_shard", "shard_grad_op", "shard_opt", "hybrid_shard"],
        )
    cfg.tp_size = _ask("Tensor-parallel degree?", 1, int, deg)
    cfg.sp_size = _ask("Sequence-parallel (ring attention) degree?", 1, int, deg)
    cfg.ep_size = _ask("Expert-parallel degree (MoE)?", 1, int, deg)
    cfg.pp_size = _ask("Pipeline-parallel degree?", 1, int, deg)
    if cfg.pp_size != 1:
        # -1 (auto) included: microbatches must cover whatever pp resolves
        # to, or validate_pipeline_plugin rejects the launch
        floor = cfg.pp_size if cfg.pp_size > 1 else 2
        cfg.num_micro_batches = _ask(
            f"Pipeline microbatches (>= pipeline degree, >= {floor})?",
            max(floor, 2), int, validate=lambda v: v >= floor,
        )
    cfg.dp_size = _ask("Data-parallel degree (-1 = remaining chips)?", -1, int, deg)
    return cfg


def config_command(args) -> None:
    if getattr(args, "default", False):
        path = write_basic_config(save_location=args.config_file)
    else:
        cfg = get_user_input()
        path = cfg.save(args.config_file)
    print(f"Configuration saved at {path}")


def write_basic_config(
    mixed_precision: str = "bf16", save_location: Optional[str] = None
) -> str:
    """Non-interactive default config (reference default.py:29)."""
    cfg = ClusterConfig(mixed_precision=mixed_precision)
    return cfg.save(save_location)


def config_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser("config", help="Create the launch config")
    else:
        parser = argparse.ArgumentParser("accelerate-tpu config")
    parser.add_argument("--config_file", default=None, help="Where to save")
    parser.add_argument(
        "--default", action="store_true",
        help="Write the defaults without asking questions",
    )
    if subparsers is not None:
        parser.set_defaults(func=config_command)
    return parser
