"""`accelerate-tpu merge-weights` — consolidate a sharded checkpoint.

Parity: reference ``commands/merge.py`` (-> ``merge_fsdp_weights``
utils/fsdp_utils.py:242). Our checkpoints are safetensors shards + index;
merging = stream every shard into one file (or re-shard at a new size).
"""

from __future__ import annotations

import argparse
import os


def merge_command(args) -> None:
    from ..checkpointing import load_model_weights, shard_checkpoint, _save_named
    from ..utils.constants import SAFE_WEIGHTS_INDEX_NAME, SAFE_WEIGHTS_NAME
    import json

    from ..dist_checkpoint import is_sharded_checkpoint, load_full_named

    if is_sharded_checkpoint(args.checkpoint_dir):
        named = load_full_named(args.checkpoint_dir)
    else:
        named = load_model_weights(args.checkpoint_dir)
    os.makedirs(args.output_dir, exist_ok=True)
    shards, index = shard_checkpoint(named, args.max_shard_size)
    if index is None:
        _save_named(shards[0], os.path.join(args.output_dir, SAFE_WEIGHTS_NAME))
    else:
        stem, ext = os.path.splitext(SAFE_WEIGHTS_NAME)
        for i, shard in enumerate(shards):
            _save_named(
                shard,
                os.path.join(
                    args.output_dir, f"{stem}-{i + 1:05d}-of-{len(shards):05d}{ext}"
                ),
            )
        with open(os.path.join(args.output_dir, SAFE_WEIGHTS_INDEX_NAME), "w") as f:
            json.dump(index, f, indent=2, sort_keys=True)
    print(f"Merged {len(named)} tensors into {args.output_dir}")


def merge_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser(
            "merge-weights", help="Consolidate a sharded checkpoint"
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu merge-weights")
    parser.add_argument("checkpoint_dir")
    parser.add_argument("output_dir")
    parser.add_argument("--max_shard_size", default="1000GB",
                        help="Use e.g. 5GB to re-shard instead of merging")
    if subparsers is not None:
        parser.set_defaults(func=merge_command)
    return parser
