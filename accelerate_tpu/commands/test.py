"""`accelerate-tpu test` — config sanity check (reference commands/test.py:
runs a bundled script under the launcher and reports success)."""

from __future__ import annotations

import argparse
import os
import subprocess
import sys


def test_command(args) -> None:
    from ..test_utils import scripts

    script = os.path.join(os.path.dirname(scripts.__file__), "test_script.py")
    cmd = [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
           "launch"]
    if args.config_file:
        cmd += ["--config_file", args.config_file]
    cmd += [script]
    result = subprocess.run(cmd)
    if result.returncode == 0:
        print("Test is a success! You are ready for your distributed training!")
    sys.exit(result.returncode)


def test_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser("test", help="Validate the saved config")
    else:
        parser = argparse.ArgumentParser("accelerate-tpu test")
    parser.add_argument("--config_file", default=None)
    if subparsers is not None:
        parser.set_defaults(func=test_command)
    return parser
