"""CLI subcommands (reference src/accelerate/commands/)."""
