"""`accelerate-tpu estimate-memory` — model memory calculator.

Parity: reference ``commands/estimate.py`` (309 LoC): meta-device model from
a Hub config (``create_empty_model`` :63), training usage ≈ Adam 4x param
bytes (``estimate_training_usage`` :215), ascii table (:139). Here the
abstract init is ``jax.eval_shape`` (truly zero-alloc) and the training
column reflects this framework's actual layout: fp32 master + 2 AdamW
moments + bf16 compute cast (+ optional fp32 accum buffer).
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _human(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024:
            return f"{n:.2f} {unit}"
        n /= 1024
    return f"{n:.2f} PB"


def estimate_activation_bytes(
    cfg, batch_size: int, seq_len: int, remat: Optional[str], dtype: str
) -> dict:
    """Activation memory for one train step — the term users get wrong when
    budgeting HBM (the reference documents params-only as its assumption;
    here activations are first-class because remat changes them 10x).

    Model: per layer, the saved residuals depend on the remat policy —
    "full" keeps only each layer's input; "dots" (the bench default) keeps
    matmul outputs (qkv/o projections, gate/up/down); None keeps those plus
    the elementwise intermediates. The lm-head logits (+fp32 softmax) are
    counted separately: at large vocab they dominate and remat cannot
    remove them.
    """
    h, f, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    qkv = (cfg.num_heads + 2 * cfg.num_kv_heads) * cfg.head_dim
    itemsize = jnp.dtype(dtype).itemsize
    if remat == "full":
        per_layer = h
    elif remat == "dots":
        per_layer = 2 * h + qkv + 2 * f
    else:
        per_layer = 2 * h + qkv + 3 * f + 2 * h
    tokens = batch_size * seq_len
    layer_bytes = tokens * per_layer * L * itemsize
    # logits in compute dtype + the fp32 softmax/loss intermediates
    logits_bytes = tokens * cfg.vocab_size * (itemsize + 4)
    return {
        "activation_bytes": int(layer_bytes),
        "logits_bytes": int(logits_bytes),
    }


def estimate_from_config(preset_or_json: str, dtype: str = "bfloat16",
                         grad_accum: bool = False, batch_size: int = 8,
                         seq_len: int = 2048,
                         remat: Optional[str] = "dots") -> dict:
    from ..models import TransformerConfig, causal_model_for

    presets = {
        "tiny": TransformerConfig.tiny,
        "gpt2": TransformerConfig.gpt2,
        "llama3-8b": TransformerConfig.llama3_8b,
        "llama3-70b": TransformerConfig.llama3_70b,
        "qwen2-7b": TransformerConfig.qwen2_7b,
        "mixtral-8x7b": TransformerConfig.mixtral_8x7b,
    }
    if preset_or_json in presets:
        cfg = presets[preset_or_json]()
    elif preset_or_json.endswith(".json"):
        with open(preset_or_json) as f:
            raw = json.load(f)
        # accept HF transformers config field names too
        mapped = {
            "vocab_size": raw.get("vocab_size", 32000),
            "hidden_size": raw.get("hidden_size", 4096),
            "intermediate_size": raw.get("intermediate_size", 11008),
            "num_layers": raw.get("num_hidden_layers", raw.get("num_layers", 32)),
            "num_heads": raw.get("num_attention_heads", raw.get("num_heads", 32)),
            "num_kv_heads": raw.get("num_key_value_heads"),
            "max_seq_len": raw.get("max_position_embeddings", 4096),
        }
        cfg = TransformerConfig(**mapped)
    else:
        raise ValueError(
            f"unknown preset {preset_or_json!r}; options: {sorted(presets)} "
            "or a config.json path"
        )
    # arch-dispatched (gpt2 preset -> GPT2LM): the byte estimate must
    # count the parameters of the model that will actually run
    model = causal_model_for(cfg)
    abstract = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(abstract))
    itemsize = jnp.dtype(dtype).itemsize
    inference = n_params * itemsize
    # training: fp32 master + 2 AdamW moments (fp32) + compute-dtype cast
    train = n_params * (4 + 8 + itemsize + (4 if grad_accum else 0))
    acts = estimate_activation_bytes(cfg, batch_size, seq_len, remat, dtype)
    return {
        "params": n_params,
        "largest_layer": max(
            int(np.prod(l.shape)) * itemsize for l in jax.tree.leaves(abstract)
        ),
        "inference_bytes": inference,
        "training_bytes": train,
        "training_total_bytes": (
            train + acts["activation_bytes"] + acts["logits_bytes"]
        ),
        **acts,
        "batch_size": batch_size,
        "seq_len": seq_len,
        "remat": remat,
        "dtype": dtype,
    }


def estimate_command(args) -> None:
    for dtype in args.dtypes:
        info = estimate_from_config(
            args.model_name, dtype, args.grad_accum,
            batch_size=args.batch_size, seq_len=args.seq_len,
            remat=None if args.remat == "none" else args.remat,
        )
        print(
            f"{args.model_name} [{dtype}]: {info['params'] / 1e9:.2f}B params | "
            f"inference {_human(info['inference_bytes'])} | "
            f"training state (AdamW) {_human(info['training_bytes'])} | "
            f"activations@B{args.batch_size}xS{args.seq_len} "
            f"{_human(info['activation_bytes'] + info['logits_bytes'])} "
            f"(remat={info['remat']}) | "
            f"training total {_human(info['training_total_bytes'])} | "
            f"largest layer {_human(info['largest_layer'])}"
        )


def estimate_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser(
            "estimate-memory", help="Estimate model memory usage"
        )
    else:
        parser = argparse.ArgumentParser("accelerate-tpu estimate-memory")
    parser.add_argument("model_name", help="Preset name or config.json path")
    parser.add_argument("--dtypes", nargs="+", default=["bfloat16", "float32"])
    parser.add_argument("--grad_accum", action="store_true")
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--seq_len", type=int, default=2048)
    parser.add_argument("--remat", choices=["none", "dots", "full"],
                        default="dots",
                        help="Remat policy assumed for the activation term")
    if subparsers is not None:
        parser.set_defaults(func=estimate_command)
    return parser
