"""CLI front door: `accelerate-tpu <command>` (reference
commands/accelerate_cli.py:27 registers the subcommand zoo)."""

from __future__ import annotations

import argparse
import sys

from .config import config_command_parser
from .diagnose import diagnose_command_parser
from .env import env_command_parser
from .estimate import estimate_command_parser
from .launch import launch_command_parser
from .merge import merge_command_parser
from .test import test_command_parser
from .tpu import provision_command_parser, tpu_command_parser


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(
        "accelerate-tpu",
        usage="accelerate-tpu <command> [<args>]",
        allow_abbrev=False,
    )
    subparsers = parser.add_subparsers(dest="command")
    config_command_parser(subparsers)
    diagnose_command_parser(subparsers)
    launch_command_parser(subparsers)
    env_command_parser(subparsers)
    estimate_command_parser(subparsers)
    merge_command_parser(subparsers)
    test_command_parser(subparsers)
    tpu_command_parser(subparsers)
    provision_command_parser(subparsers)

    args = parser.parse_args(argv)
    if not hasattr(args, "func"):
        parser.print_help()
        sys.exit(1)
    args.func(args)


if __name__ == "__main__":
    main()
