"""`accelerate-tpu env` — bug-report environment dump (reference
commands/env.py:47)."""

from __future__ import annotations

import argparse
import os
import platform


def env_command(args) -> None:
    import jax

    import accelerate_tpu

    info = {
        "accelerate_tpu version": accelerate_tpu.__version__,
        "Platform": platform.platform(),
        "Python version": platform.python_version(),
        "JAX version": jax.__version__,
        "JAX backend": jax.default_backend(),
        "Devices": str(jax.devices()),
        "Process count": jax.process_count(),
    }
    try:
        import flax
        import optax

        info["Flax version"] = flax.__version__
        info["Optax version"] = getattr(optax, "__version__", "?")
    except ImportError:
        pass
    from .config import default_config_file

    path = default_config_file()
    info["Config file"] = path if os.path.isfile(path) else f"{path} (not found)"
    accel_env = {
        k: v for k, v in os.environ.items() if k.startswith("ACCELERATE_TPU_")
    }
    print("\nCopy-and-paste the text below in your GitHub issue\n")
    for k, v in info.items():
        print(f"- `{k}`: {v}")
    if accel_env:
        print("- Environment:")
        for k, v in sorted(accel_env.items()):
            print(f"    - {k}={v}")


def env_command_parser(subparsers=None) -> argparse.ArgumentParser:
    if subparsers is not None:
        parser = subparsers.add_parser("env", help="Print environment info")
        parser.set_defaults(func=env_command)
        return parser
    return argparse.ArgumentParser("accelerate-tpu env")
