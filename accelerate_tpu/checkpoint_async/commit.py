"""Atomic commit protocol for checkpoint directories.

A checkpoint is either COMMITTED or invisible. Writers (sync or async,
any number of hosts on a shared filesystem — the same assumption
:mod:`~accelerate_tpu.dist_checkpoint` already makes) follow:

1. every host writes its files into ``<final>.tmp/`` — the work dir;
2. each host fsyncs its files and drops a ``done_{proc:05d}`` marker;
3. hosts barrier on the markers (a filesystem poll, NOT a jax collective
   — commit may run on a background thread where collectives are unsafe);
4. host 0 writes the ``COMMITTED`` marker inside the work dir, fsyncs,
   and executes ONE ``os.rename(work, final)``.

Readers (``_list_checkpoints`` / ``restore_or_init`` / ``load_state``)
only match ``checkpoint_<n>`` names, so a ``.tmp`` work dir — the only
on-disk state a crash at any point before step 4's rename can leave —
is never listed, never restored from, and never counted or deleted by
rotation. The rename is atomic on POSIX: a reader sees either no
directory or a complete one carrying ``COMMITTED``.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Optional

from ..logging import get_logger

logger = get_logger(__name__)

TMP_SUFFIX = ".tmp"
COMMITTED_MARKER = "COMMITTED"
DONE_MARKER_PATTERN = "done_{:05d}"
TOPOLOGY_FILE = "topology.json"


def work_dir_for(final_dir: str) -> str:
    """The uncommitted work dir a save targets before the commit rename."""
    return os.path.normpath(final_dir) + TMP_SUFFIX


def is_work_dir(path: str) -> bool:
    return os.path.normpath(path).endswith(TMP_SUFFIX)


def is_committed(path: str) -> bool:
    """True when ``path`` carries the COMMITTED marker. Checkpoints written
    before the commit protocol existed lack the marker but were also never
    renamed into place, so completeness checks must pair this with the
    ``.tmp``-name exclusion rather than require the marker outright."""
    return os.path.isfile(os.path.join(path, COMMITTED_MARKER))


def _fsync_path(path: str) -> None:
    """fsync a file (or directory entry) by fd; best-effort on filesystems
    that reject directory fsync (e.g. some network mounts)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_marker(directory: str, name: str) -> str:
    """Durably create ``directory/name`` (empty marker file): write, fsync
    the file, fsync the directory so the entry itself survives a crash."""
    path = os.path.join(directory, name)
    with open(path, "w") as f:
        f.flush()
        os.fsync(f.fileno())
    _fsync_path(directory)
    return path


def write_topology(work_dir: str, topology: dict[str, Any]) -> str:
    """Durably write the save-time topology record into the work dir.

    The record travels WITH the commit protocol (written before the
    COMMITTED marker, visible only after the rename) so a committed
    checkpoint always either carries a complete topology file or — for
    checkpoints from before this field existed — none at all.
    """
    path = os.path.join(work_dir, TOPOLOGY_FILE)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(topology, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_path(work_dir)
    return path


def read_topology(checkpoint_dir: str) -> Optional[dict[str, Any]]:
    """The topology record a committed checkpoint was saved under, or
    ``None`` for pre-topology checkpoints (they load unchanged as long as
    the live topology matches — ``allow_reshape`` cannot validate them)."""
    path = os.path.join(checkpoint_dir, TOPOLOGY_FILE)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def mark_done(work_dir: str, process_index: int) -> str:
    """This host's shard files are written + fsynced; publish the fact."""
    return write_marker(work_dir, DONE_MARKER_PATTERN.format(process_index))


def wait_for_done_markers(
    work_dir: str,
    world: int,
    timeout_s: float = 600.0,
    poll_s: float = 0.05,
) -> None:
    """Block until every host's done marker exists (trivial when world==1).

    The work dir VANISHING counts as the barrier passing: process 0
    renames it to the final directory the instant it sees the last
    marker, so another host whose scan loses that race (markers written,
    rename already done) would otherwise poll a nonexistent directory
    until the timeout — observed as a multi-host run wedging right after
    a cadence save commits."""
    deadline = time.monotonic() + timeout_s
    missing = list(range(world))
    while missing:
        if not os.path.isdir(work_dir):
            # renamed away by process 0 => every marker existed
            return
        missing = [
            p
            for p in missing
            if not os.path.isfile(
                os.path.join(work_dir, DONE_MARKER_PATTERN.format(p))
            )
        ]
        if not missing:
            return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"checkpoint commit barrier timed out after {timeout_s}s: "
                f"missing done markers from processes {missing} in {work_dir}"
            )
        time.sleep(poll_s)


def commit(
    work_dir: str,
    final_dir: str,
    process_index: int = 0,
    world: int = 1,
    timeout_s: float = 600.0,
    topology: Optional[dict[str, Any]] = None,
) -> str:
    """Run steps 2-4 of the protocol for this host; returns ``final_dir``.

    Process 0 performs the rename; other processes return once the final
    directory is visible (so a caller may read it back immediately).
    ``topology`` (written by process 0, after the done-marker barrier so
    it reflects a save every host finished) stamps the save-time world
    size / mesh shape / shard-file map for topology-independent restore."""
    mark_done(work_dir, process_index)
    wait_for_done_markers(work_dir, world, timeout_s=timeout_s)
    if process_index == 0:
        if topology is not None:
            write_topology(work_dir, topology)
        write_marker(work_dir, COMMITTED_MARKER)
        if os.path.isdir(final_dir):
            # explicit-output_dir overwrite: swap the old dir aside first so
            # the rename still lands atomically (the .old name matches no
            # checkpoint pattern, so a crash here leaves it invisible)
            backup = f"{final_dir}.old.{os.getpid()}"
            os.rename(final_dir, backup)
            os.rename(work_dir, final_dir)
            shutil.rmtree(backup, ignore_errors=True)
        else:
            os.rename(work_dir, final_dir)
        _fsync_path(os.path.dirname(os.path.normpath(final_dir)) or ".")
        logger.info(f"committed checkpoint {final_dir}")
    else:
        deadline = time.monotonic() + timeout_s
        while not os.path.isdir(final_dir):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"process {process_index}: {final_dir} did not appear "
                    f"within {timeout_s}s of the commit barrier"
                )
            time.sleep(0.05)
    return final_dir


def discard_work_dir(work_dir: str) -> None:
    """Remove an uncommitted work dir (stale tmp from a crashed run, or
    cleanup after a failed background write). Never called on a committed
    (renamed) directory."""
    if is_work_dir(work_dir):
        shutil.rmtree(work_dir, ignore_errors=True)
