"""The bounded background checkpoint writer.

The train-loop-side cost of an async save is ONLY the device->host
snapshot (one batched ``jax.device_get`` of this process's addressable
shards — no rank-0 allgather, no serialization, no disk). The snapshot is
handed to a single daemon writer thread that serializes, writes, fsyncs,
and runs the atomic commit protocol, all while the next training steps
execute on device.

Backpressure: the job queue is bounded (``max_pending``, default 1). If
saves arrive faster than disk drains them, ``submit`` blocks the train
loop until a slot frees — checkpoints are never silently dropped and
host RAM holds at most ``max_pending + 1`` snapshots. The blocked time
(snapshot + any queue wait) and the hidden background time are reported
separately through telemetry as ``kind="checkpoint"`` records.

The writer thread performs NO jax calls — device access is complete by
the time a job is enqueued — so it is safe next to collectives running
on the main thread. Background failures are captured and re-raised on
the next ``submit``/``wait`` (a checkpointing subsystem that fails
silently is worse than a slow one).
"""

from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from typing import Any, Optional

import numpy as np

from ..logging import get_logger
from . import commit as commit_mod

logger = get_logger(__name__)

_STOP = object()


@dataclasses.dataclass
class CheckpointJob:
    """Everything one async save needs after the snapshot: host-resident
    array chunks, captured host state, and the commit coordinates."""

    final_dir: str
    work_dir: str
    shard_snapshot: Any  # dist_checkpoint.ShardSnapshot | None
    host_files: list  # [(filename, kind, payload)] from _capture_host_state
    named_files: list  # [(filename, named_dict, safe)] raw-loop opt states
    process_index: int
    world: int
    step: Optional[int]
    blocked_s: float  # snapshot + queue-wait seconds (filled by the caller)
    barrier_timeout_s: float = 600.0
    # save-time topology record (checkpointing.topology_metadata), captured
    # at submit time — the commit stamps it into the checkpoint so restore
    # can validate/reshape even if the fleet changes while the write runs
    topology: Optional[dict] = None


class AsyncCheckpointer:
    """Owns the writer thread and the in-flight bookkeeping.

    One instance serializes its saves: jobs run in submission order on a
    single thread, so two async saves can never interleave writes or
    commit out of order. ``wait()`` drains everything in flight (the
    preemption contract: drain, then write the final checkpoint
    synchronously); ``close()`` drains and stops the thread.
    """

    def __init__(
        self,
        telemetry: Any = None,
        max_pending: int = 1,
        barrier_timeout_s: float = 600.0,
    ):
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self.telemetry = telemetry
        self.max_pending = max_pending
        self.barrier_timeout_s = barrier_timeout_s
        self._queue: queue.Queue = queue.Queue(maxsize=max_pending)
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()
        self._pending = 0  # jobs submitted and not yet finished
        self._idle = threading.Event()
        self._idle.set()
        self.saves_completed = 0

    # ------------------------------------------------------------------ #
    @property
    def in_flight(self) -> bool:
        """True while any submitted save has not finished writing."""
        return not self._idle.is_set()

    def _raise_pending_error(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError(
                "a background checkpoint write failed; the checkpoint was "
                "NOT committed (its .tmp work dir was discarded)"
            ) from err

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="ckpt-writer", daemon=True
            )
            self._thread.start()

    def submit(self, job: CheckpointJob) -> float:
        """Enqueue a job; blocks only when ``max_pending`` saves are already
        queued (backpressure). The queue-wait seconds are folded into
        ``job.blocked_s`` BEFORE the job is enqueued (the writer thread
        reads the job afterwards, so it must not be mutated post-put) and
        also returned."""
        self._raise_pending_error()
        self._ensure_thread()
        wait_s = 0.0
        if self._queue.full():
            # single producer: once not-full, the put below cannot block
            t0 = time.perf_counter()
            while self._queue.full():
                time.sleep(0.005)
            wait_s = time.perf_counter() - t0
            job.blocked_s += wait_s
        with self._lock:
            self._pending += 1
            self._idle.clear()
        self._queue.put(job)
        return wait_s

    def wait(self, timeout_s: Optional[float] = None) -> None:
        """Drain: block until every submitted save has committed (or
        failed — failures re-raise here)."""
        if not self._idle.wait(timeout=timeout_s):
            raise TimeoutError(
                f"async checkpoint drain did not finish within {timeout_s}s"
            )
        self._raise_pending_error()

    def close(self) -> None:
        """Drain and stop the writer thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            self.wait()
            self._queue.put(_STOP)
            self._thread.join()
        self._thread = None
        self._raise_pending_error()

    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        while True:
            job = self._queue.get()
            if job is _STOP:
                return
            try:
                self._write(job)
            except BaseException as exc:  # noqa: BLE001 — captured, re-raised
                logger.warning(
                    f"background checkpoint write for {job.final_dir} "
                    f"failed: {exc!r}"
                )
                commit_mod.discard_work_dir(job.work_dir)
                with self._lock:
                    self._error = exc
            finally:
                with self._lock:
                    self._pending -= 1
                    if self._pending == 0:
                        self._idle.set()

    def _write(self, job: CheckpointJob) -> None:
        from .. import dist_checkpoint
        from ..checkpointing import _save_named, _write_host_state

        t0 = time.perf_counter()
        os.makedirs(job.work_dir, exist_ok=True)
        nbytes = 0
        if job.shard_snapshot is not None:
            nbytes += dist_checkpoint.write_snapshot(
                job.shard_snapshot, job.work_dir, fsync=True
            )
        for fname, named, safe in job.named_files:
            _save_named(named, os.path.join(job.work_dir, fname), safe)
            nbytes += sum(np.asarray(v).nbytes for v in named.values())
        _write_host_state(job.host_files, job.work_dir)
        commit_mod.commit(
            job.work_dir,
            job.final_dir,
            job.process_index,
            job.world,
            timeout_s=job.barrier_timeout_s,
            topology=job.topology,
        )
        background_s = time.perf_counter() - t0
        self.saves_completed += 1
        if self.telemetry is not None:
            self.telemetry.record_checkpoint(
                step=job.step,
                directory=job.final_dir,
                mode="async",
                blocked_s=job.blocked_s,
                background_s=background_s,
                bytes_written=nbytes,
            )


def save_accelerator_state_async(
    accelerator,
    checkpointer: AsyncCheckpointer,
    output_dir: Optional[str] = None,
    carry: Any = None,
    params: Any = None,
) -> str:
    """Zero-stall counterpart of
    :func:`accelerate_tpu.checkpointing.save_accelerator_state`.

    The synchronous section is only: directory resolution/rotation, the
    batched device->host snapshot of this process's shards, and the host
    small-state capture. Serialization, disk IO, fsync and the commit all
    happen on the writer thread — by the time the checkpoint is visible
    on disk the train loop is several steps ahead. Returns the FINAL
    directory the save will commit to (it does not exist yet when this
    returns; call ``checkpointer.wait()`` to block on durability).
    """
    from ..checkpointing import (
        _capture_host_state,
        _checkpoint_dir,
        _is_arraylike,
        _to_host,
        flatten_tree,
        topology_metadata,
    )
    from ..dist_checkpoint import snapshot_tree

    t0 = time.perf_counter()
    checkpointer._raise_pending_error()
    final_dir = _checkpoint_dir(accelerator, output_dir)
    work_dir = commit_mod.work_dir_for(final_dir)
    if accelerator.is_main_process:
        commit_mod.discard_work_dir(work_dir)  # stale tmp from a crashed run
    accelerator.wait_for_everyone()
    logger.info(f"Async-saving current state to {final_dir}")

    tree = carry if carry is not None else params
    if tree is None and accelerator._models:
        tree = accelerator._models[0]
    snapshot = snapshot_tree(tree) if tree is not None else None

    named_files = []
    if carry is None:
        from ..utils.constants import OPTIMIZER_NAME

        for i, opt in enumerate(accelerator._optimizers):
            if opt.opt_state is not None and accelerator.is_main_process:
                named = flatten_tree(_to_host(opt.opt_state))
                arrays = {k: v for k, v in named.items() if _is_arraylike(v)}
                named_files.append(
                    (f"{OPTIMIZER_NAME}_{i}.safetensors", arrays, True)
                )

    host_files = _capture_host_state(accelerator, carry)
    accelerator.project_configuration.iteration += 1

    job = CheckpointJob(
        final_dir=final_dir,
        work_dir=work_dir,
        shard_snapshot=snapshot,
        host_files=host_files,
        named_files=named_files,
        process_index=accelerator.process_index,
        world=accelerator.num_processes,
        step=accelerator.step,
        blocked_s=0.0,
        barrier_timeout_s=checkpointer.barrier_timeout_s,
        topology=topology_metadata(accelerator),
    )
    job.blocked_s = time.perf_counter() - t0
    queue_wait = checkpointer.submit(job)
    if queue_wait > 0.01:
        logger.info(
            f"async checkpoint backpressure: waited {queue_wait:.2f}s for "
            "the previous save to drain (disk slower than the cadence)"
        )
    return final_dir
