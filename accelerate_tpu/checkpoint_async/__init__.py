"""Async distributed checkpointing: zero-stall saves with per-host
sharded writes and an atomic commit protocol.

The synchronous save path blocks every host for device->host transfer +
serialization + disk IO; this subsystem shrinks the train-loop cost of a
save to ONE batched ``jax.device_get`` of this process's own shards and
hides everything else behind the next training steps (the same move the
compilation subsystem made for compile cost: pay it off the hot path).
CheckFreq (FAST '21) and Orbax's async checkpointing proved the shape:
snapshot fast, persist in the background, commit atomically.

Layers:

* :mod:`.commit` — the atomic commit protocol (``<dir>.tmp`` work dirs,
  per-host ``done_*`` markers, a filesystem barrier, one rename +
  ``COMMITTED``). Shared by the sync path too: no save, sync or async,
  can leave a torn checkpoint.
* :mod:`.writer` — :class:`AsyncCheckpointer` (the bounded background
  writer thread) and :func:`save_accelerator_state_async` (the
  snapshot-then-enqueue counterpart of ``save_accelerator_state``).

Entry points: ``CheckpointManager(..., async_saves=True)`` for managed
loops, ``accelerator.save_state(..., block=False)`` for direct use.
"""

from .commit import (
    COMMITTED_MARKER,
    TMP_SUFFIX,
    is_committed,
    work_dir_for,
)
from .writer import AsyncCheckpointer, CheckpointJob, save_accelerator_state_async

__all__ = [
    "AsyncCheckpointer",
    "CheckpointJob",
    "save_accelerator_state_async",
    "COMMITTED_MARKER",
    "TMP_SUFFIX",
    "is_committed",
    "work_dir_for",
]
