"""Fleet serving: the multi-replica router and its placement policies.

The router is pure host policy, so most of this file runs on FAKE
engines and a fake clock — placement decisions, staleness tolerance,
session spill, kill/drain accounting and the chaos handlers are all
exact, deterministic assertions with no jax in the loop. The real-engine
tests at the bottom pin the engine-side satellites (drain semantics, the
bounded chain-key digest, the scrape endpoint's draining/prefix routes
and stop-during-scrape behavior) and one small end-to-end fleet: three
live engines behind prefix-affinity routing, warm hits strictly better
than round-robin, one compiled decode per replica.
"""

import json
import threading
import urllib.error
import urllib.request
from collections import deque
from types import SimpleNamespace

import pytest

from accelerate_tpu.router import (
    FleetRouter,
    InProcessReplica,
    ReplicaSnapshot,
    load_score,
    make_policy,
)
from accelerate_tpu.serving.block_pool import BlockPool, prefix_keys
from accelerate_tpu.telemetry.http_exporter import MetricsHTTPExporter
from accelerate_tpu.test_utils.fault_injection import (
    FaultInjector,
    FaultSpec,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt

    def advance(self, dt: float) -> None:
        self.t += dt

    def __call__(self) -> float:
        return self.t


class FakeReq:
    """Shape-compatible with scheduler.Request where the router's
    re-queue path reads it."""

    def __init__(self, prompt, rid, adapter=None, max_new_tokens=4):
        self.prompt = list(prompt)
        self.request_id = rid
        self.adapter = adapter
        self.max_new_tokens = max_new_tokens
        self.temperature = 0.0
        self.eos_token_id = None
        self.priority = 0


class FakeEngine:
    """A no-jax engine exposing exactly the duck surface the replica
    handle reads: one queued request completes per step, and completion
    'publishes' the request's chain keys so prefix_digest reflects what
    the replica has cached (real rolling-hash math via prefix_keys)."""

    block_size = 4

    def __init__(self, fingerprint="fake-fp", gauges=None):
        self.scheduler = SimpleNamespace(queue=deque(), slots=[])
        self._swapped_reqs = []
        self.gauges = dict(gauges or {})
        self.fingerprint = fingerprint
        self.keys = set()
        self.warm_hits = 0
        self.finished = {}
        self._draining = False
        self._n = 0

    def add_request(self, prompt, max_new_tokens=32, temperature=0.0,
                    eos_token_id=None, request_id="", adapter=None,
                    priority=0):
        rid = request_id or f"fake-{self._n}"
        self._n += 1
        keys = prefix_keys(self.fingerprint, adapter, prompt, self.block_size)
        if keys and keys[0].hex() in self.keys:
            self.warm_hits += 1
        self.scheduler.queue.append(
            FakeReq(prompt, rid, adapter, max_new_tokens)
        )
        return rid

    def step(self):
        if self.scheduler.queue:
            req = self.scheduler.queue.popleft()
            for k in prefix_keys(
                self.fingerprint, req.adapter, req.prompt, self.block_size
            ):
                self.keys.add(k.hex())
            self.finished[req.request_id] = [1]
        return []

    @property
    def has_work(self):
        return bool(self.scheduler.queue)

    def _gauge_fields(self):
        g = {
            "queue_depth": len(self.scheduler.queue),
            "slots_active": 0,
            "slot_occupancy": 0.0,
            "pool_utilization": 0.0,
            "tokens_in_flight": 0,
        }
        g.update(self.gauges)
        return g

    def prefix_digest(self, max_entries=512):
        entries = sorted(self.keys)[:max_entries]
        return {
            "block_size": self.block_size,
            "entries": entries,
            "fingerprint": self.fingerprint,
            "total": len(self.keys),
            "truncated": len(self.keys) > len(entries),
        }

    def drain(self):
        self._draining = True
        out = list(self.scheduler.queue)
        self.scheduler.queue.clear()
        return out

    @property
    def draining(self):
        return self._draining

    def health(self):
        return {
            "ok": True,
            "state": "draining" if self._draining else "serving",
        }

    def result(self, rid):
        return self.finished.get(rid)

    def shed_reason(self, rid):
        return None


def _fleet(n=3, policy="least_loaded", clock=None, gauges=None, **kw):
    clock = clock or FakeClock()
    engines = [FakeEngine(gauges=(gauges or {}).get(i)) for i in range(n)]
    reps = [InProcessReplica(f"r{i}", e) for i, e in enumerate(engines)]
    router = FleetRouter(reps, policy=policy, now=clock, **kw)
    return router, engines, clock


def _drain_fleet(router, clock, budget=200):
    for _ in range(budget):
        if not router.has_work:
            return
        router.step()
        clock.tick(0.1)
    raise AssertionError("fleet did not drain")


# ---------------------------------------------------------------------- #
# placement policies
# ---------------------------------------------------------------------- #
def test_least_loaded_picks_idle_replica_under_skew():
    router, engines, _ = _fleet(
        3, gauges={0: {"queue_depth": 5}, 2: {"queue_depth": 3}}
    )
    for _ in range(4):
        router.add_request([1, 2, 3])
    # every request lands on the idle replica... which then carries its
    # own queue into the next snapshot — after 4 sends r1 has depth 4,
    # so the 5th prefers r2 (depth 3)
    assert router.routed_by_replica["r1"] == 4
    router.add_request([1, 2, 3])
    assert router.routed_by_replica["r2"] == 1


def test_make_policy_resolution_and_load_score():
    assert make_policy("round_robin").name == "round_robin"
    assert make_policy("prefix_affinity", load_penalty=2.0).load_penalty == 2.0
    with pytest.raises(ValueError):
        make_policy("power_of_two")  # not (yet) a policy
    snap = ReplicaSnapshot(queue_depth=3, slots_active=2,
                           pool_utilization=0.5)
    assert load_score(snap) == 5.5


def test_round_robin_cycles_registration_order():
    router, _, _ = _fleet(3, policy="round_robin")
    picks = [router.select([1, 2, 3]) for _ in range(6)]
    assert picks == ["r0", "r1", "r2", "r0", "r1", "r2"]


def test_round_robin_skips_dead_and_draining():
    router, _, _ = _fleet(3, policy="round_robin")
    router.drain("r1")
    picks = [router.select([1]) for _ in range(4)]
    assert picks == ["r0", "r2", "r0", "r2"]


def _templated_trace(n_cohorts=4, per_cohort=6, prefix_blocks=2, bs=4):
    # 4 cohorts over a 3-replica fleet: the cohort cycle and the RR
    # cycle are coprime, so round-robin genuinely scatters each cohort
    trace = []
    for i in range(n_cohorts * per_cohort):
        c = i % n_cohorts
        prefix = [100 + c] * (prefix_blocks * bs)
        trace.append(prefix + [200 + i, 201 + i, 1 + c])
    return trace


def test_prefix_affinity_beats_round_robin_on_warm_hits():
    def run(policy):
        router, engines, clock = _fleet(3, policy=policy)
        for prompt in _templated_trace():
            router.add_request(prompt)
            _drain_fleet(router, clock)
        return sum(e.warm_hits for e in engines)

    rr, affinity = run("round_robin"), run("prefix_affinity")
    # each cohort's chain lives on ONE replica under affinity: every
    # request after the cohort's first is warm
    assert affinity == 4 * 6 - 4
    assert affinity > rr


def test_prefix_affinity_degrades_to_least_loaded_when_cold():
    router, engines, _ = _fleet(
        3, policy="prefix_affinity", gauges={0: {"queue_depth": 9}}
    )
    assert router.select([7, 7, 7, 7]) == "r1"  # no overlap anywhere


def test_affinity_load_penalty_overrides_overlap():
    """A warm replica buried under queue must lose to an idle cold one
    once the penalty outweighs the overlap."""
    router, engines, clock = _fleet(
        2, policy="prefix_affinity", load_penalty=8.0
    )
    prompt = [5] * 12
    router.add_request(prompt)
    _drain_fleet(router, clock)
    assert router.select(prompt) == "r0"  # warm, idle: affinity wins
    engines[0].gauges["queue_depth"] = 50  # 50*8 penalty >> 11 overlap
    clock.tick(1.0)  # age out cached snapshot + digest
    assert router.select(prompt) == "r1"


# ---------------------------------------------------------------------- #
# session affinity
# ---------------------------------------------------------------------- #
def test_session_affinity_pins_and_spills_on_drain():
    router, _, _ = _fleet(3, session_affinity=True)
    first = router.select([1, 2], session_id="alice")
    assert all(
        router.select([i], session_id="alice") == first for i in range(5)
    )
    router.drain(first)
    second = router.select([9], session_id="alice")
    assert second != first
    assert router.session_spills_total == 1
    # the spill RE-PINS: later requests stick to the new home
    assert router.select([10], session_id="alice") == second
    assert router.session_spills_total == 1


def test_session_map_is_bounded():
    router, _, _ = _fleet(2, session_affinity=True, max_sessions=8)
    for i in range(50):
        router.select([1], session_id=f"s{i}")
    assert len(router._sessions) == 8
    assert router.router_summary()["sessions_tracked"] == 8


# ---------------------------------------------------------------------- #
# staleness tolerance
# ---------------------------------------------------------------------- #
def test_stale_gauge_snapshots_never_wedge_admission():
    router, engines, clock = _fleet(2)
    router.add_request([1, 2, 3])  # healthy snapshot cached for both

    def boom():
        raise ConnectionError("scrape died")

    engines[0]._gauge_fields = boom
    engines[1]._gauge_fields = boom
    clock.tick(1.0)  # age the cache out
    for _ in range(3):
        router.add_request([4, 5, 6])  # must not raise
    assert router.stale_snapshot_routes_total >= 2
    assert router.routed_total == 4


def test_snapshotless_replica_routes_optimistically():
    """A replica that has NEVER produced a snapshot still takes traffic
    (zero-load default) instead of blocking the fleet."""
    router, engines, _ = _fleet(1)

    def boom():
        raise ConnectionError("never scraped")

    engines[0]._gauge_fields = boom
    assert router.select([1, 2]) == "r0"
    assert router.stale_snapshot_routes_total == 1


def test_digest_fetch_failure_degrades_to_load_routing():
    router, engines, _ = _fleet(2, policy="prefix_affinity")

    def boom(_max):
        raise ConnectionError("no digest")

    for e in engines:
        e.prefix_digest = boom
    assert router.select([1, 2, 3, 4]) in ("r0", "r1")  # no raise


# ---------------------------------------------------------------------- #
# lifecycle: drain / kill / health ejection / slow
# ---------------------------------------------------------------------- #
def test_drain_requeues_unadmitted_onto_survivors():
    router, engines, _ = _fleet(2)
    for _ in range(3):
        router.add_request([1, 2])
    # least-loaded: r0, r1, then the tie goes to r0 again
    assert router.routed_by_replica == {"r0": 2, "r1": 1}
    out = router.drain("r0")
    assert out == {"replica": "r0", "requeued": 2, "lost": 0}
    assert not engines[0].scheduler.queue
    assert len(engines[1].scheduler.queue) == 3
    assert router.requests_requeued == 2
    assert router.router_summary()["replicas_alive"] == 2  # draining != dead


def test_kill_requeues_queue_and_counts_seated_as_lost():
    router, engines, _ = _fleet(2)
    victim = engines[0]
    victim.scheduler.queue.extend(
        FakeReq([1, 2, 3], f"q{i}") for i in range(3)
    )
    victim.scheduler.slots = [
        SimpleNamespace(busy=True), SimpleNamespace(busy=True),
        SimpleNamespace(busy=False),
    ]
    out = router.kill("r0")
    assert out == {"replica": "r0", "requeued": 3, "lost": 2}
    assert len(engines[1].scheduler.queue) == 3  # landed on the survivor
    assert router.requests_lost == 2
    assert router.rerouted_total == 3
    summary = router.router_summary()
    assert summary["replicas_alive"] == 1
    assert summary["ejections_total"] == 1
    # idempotent: a second kill must not double-count
    assert router.kill("r0") == {"replica": "r0", "requeued": 0, "lost": 0}


def test_kill_with_no_survivor_counts_queue_as_lost():
    router, engines, _ = _fleet(1)
    engines[0].scheduler.queue.append(FakeReq([1], "q0"))
    out = router.kill("r0")
    assert out["requeued"] == 0 and out["lost"] == 1
    with pytest.raises(RuntimeError):
        router.add_request([1, 2])


def test_healthz_ejection_on_step():
    router, engines, clock = _fleet(2)
    engines[0].scheduler.queue.append(FakeReq([1, 2], "q0"))
    engines[0].health = lambda: {"ok": False, "state": "dead"}
    router.step()
    assert router.router_summary()["replicas_alive"] == 1
    assert not router.replica("r0").alive
    assert router.requests_requeued == 1
    while router.has_work:  # the rescued request finishes on r1
        router.step()
    assert router.result("q0") == [1]


def test_replica_slow_skips_steps_until_deadline():
    router, engines, clock = _fleet(2)
    router.add_request([1, 2])  # -> r0 (tie-break)
    router.slow("r0", 5.0)
    router.step()
    assert engines[0].scheduler.queue  # frozen: took no step
    clock.tick(6.0)
    router.step()
    assert not engines[0].scheduler.queue  # thawed


def test_trace_counts_merge_keeps_dead_replicas():
    router, engines, _ = _fleet(2)
    for e in engines:
        e.trace_counts = lambda: {"decode": 1, "prefill": 2}
    assert router.trace_counts() == {"decode": 2, "prefill": 4}
    router.kill("r0")
    assert router.trace_counts() == {"decode": 2, "prefill": 4}


def test_result_resolves_through_placement_map():
    router, engines, clock = _fleet(2)
    rid = router.add_request([1, 2, 3], request_id="want-this")
    _drain_fleet(router, clock)
    assert rid == "want-this"
    assert router.result(rid) == [1]
    assert router.result("never-submitted") is None


# ---------------------------------------------------------------------- #
# fault grammar + chaos handlers
# ---------------------------------------------------------------------- #
def test_fault_spec_replica_field_round_trips():
    spec = FaultSpec.parse("replica_kill@0:replica=1")
    assert spec.action == "replica_kill" and spec.replica == 1
    assert FaultSpec.parse(spec.render()) == spec
    slow = FaultSpec.parse("replica_slow@2:replica=0:secs=3")
    assert slow.stall_secs == 3.0 and slow.replica == 0
    assert FaultSpec.parse(slow.render()) == slow


def test_fault_spec_replica_field_rejected_elsewhere():
    with pytest.raises(ValueError):
        FaultSpec.parse("stall_decode@0:replica=1")
    with pytest.raises(ValueError):
        FaultSpec.parse("replica_kill@0:secs=2")  # kill is not timed


def test_chaos_replica_kill_fires_against_fleet():
    from accelerate_tpu.loadgen.chaos import ChaosAdapter

    router, engines, clock = _fleet(2)
    engines[1].scheduler.queue.append(FakeReq([1, 2], "q0"))
    injector = FaultInjector([], rank=0, generation=0)
    chaos = ChaosAdapter(router, injector, clock)
    injector.specs = [FaultSpec.parse("replica_kill@0:replica=1")]
    injector.maybe_fire(0)
    assert router.router_summary()["replicas_alive"] == 1
    (event,) = [e for e in chaos.events if e["action"] == "replica_kill"]
    assert event["replica"] == "r1"
    assert event["requeued"] == 1 and event["lost"] == 0


def test_chaos_replica_slow_fires_against_fleet():
    from accelerate_tpu.loadgen.chaos import ChaosAdapter

    router, engines, clock = _fleet(2)
    injector = FaultInjector([], rank=0, generation=0)
    chaos = ChaosAdapter(router, injector, clock)
    injector.specs = [FaultSpec.parse("replica_slow@0:replica=0:secs=4")]
    injector.maybe_fire(0)
    (event,) = chaos.events
    assert event["action"] == "replica_slow"
    assert event["replica"] == "r0" and event["secs"] == 4.0
    router.add_request([1, 2])  # ties still place on r0...
    router.step()
    assert engines[0].scheduler.queue  # ...but r0 is frozen: no step
    clock.tick(5.0)
    router.step()
    assert not engines[0].scheduler.queue


def test_chaos_replica_actions_skip_single_engine():
    from accelerate_tpu.loadgen.chaos import ChaosAdapter

    clock = FakeClock()
    engine = FakeEngine()
    injector = FaultInjector([], rank=0, generation=0)
    chaos = ChaosAdapter(engine, injector, clock)
    injector.specs = [FaultSpec.parse("replica_kill@0:replica=0")]
    injector.maybe_fire(0)
    assert chaos.events[0]["skipped"] == "not_a_fleet"
    assert engine.has_work is False  # untouched


def test_chaos_replica_out_of_range_skips():
    from accelerate_tpu.loadgen.chaos import ChaosAdapter

    router, _, clock = _fleet(2)
    injector = FaultInjector([], rank=0, generation=0)
    chaos = ChaosAdapter(router, injector, clock)
    injector.specs = [FaultSpec.parse("replica_kill@0:replica=7")]
    injector.maybe_fire(0)
    assert chaos.events[0]["skipped"] == "replica_out_of_range"
    assert router.router_summary()["replicas_alive"] == 2


# ---------------------------------------------------------------------- #
# the chain-key digest (BlockPool, host-only)
# ---------------------------------------------------------------------- #
def test_cached_chain_digest_is_bounded_and_token_free():
    pool = BlockPool(num_blocks=32, block_size=4)
    keys = prefix_keys("fp", None, list(range(1, 41)), 4)  # 10 full blocks
    blocks = pool.allocate(len(keys))
    for b, k in zip(blocks, keys):
        pool.publish(b, k)
    digest = pool.cached_chain_digest(max_entries=4)
    assert len(digest["entries"]) == 4
    assert digest["total"] == 10 and digest["truncated"]
    assert all(
        isinstance(e, str) and len(e) == 64 and int(e, 16) >= 0
        for e in digest["entries"]
    )
    full = pool.cached_chain_digest(max_entries=100)
    assert len(full["entries"]) == 10 and not full["truncated"]
    assert set(full["entries"]) == {k.hex() for k in keys}


def test_cached_chain_digest_prefers_live_then_mru():
    pool = BlockPool(num_blocks=32, block_size=4)
    keys = prefix_keys("fp", None, list(range(1, 25)), 4)  # 6 blocks
    blocks = pool.allocate(len(keys))
    for b, k in zip(blocks, keys):
        pool.publish(b, k)
    pool.free(blocks[3:])  # retire 3 chains into the cached LRU
    digest = pool.cached_chain_digest(max_entries=3)
    assert digest["entries"] == [k.hex() for k in keys[:3]]  # live first


# ---------------------------------------------------------------------- #
# scrape endpoint: dict healthz, /debug/prefix, stop-during-scrape
# ---------------------------------------------------------------------- #
def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read().decode())


def test_healthz_serves_dict_body_with_state():
    state = {"ok": True, "state": "serving"}
    exporter = MetricsHTTPExporter(health_fn=lambda: dict(state)).start()
    try:
        code, body = _get(exporter.url + "/healthz")
        assert (code, body) == (200, {"ok": True, "state": "serving"})
        state["state"] = "draining"
        code, body = _get(exporter.url + "/healthz")
        assert (code, body) == (200, {"ok": True, "state": "draining"})
        state.update(ok=False, state="dead")
        code, body = _get(exporter.url + "/healthz")
        assert (code, body) == (503, {"ok": False, "state": "dead"})
    finally:
        exporter.stop()


def test_debug_prefix_route():
    digest = {"block_size": 4, "entries": ["ab" * 32], "total": 1,
              "truncated": False}
    exporter = MetricsHTTPExporter(prefix_fn=lambda: digest).start()
    try:
        code, body = _get(exporter.url + "/debug/prefix")
        assert code == 200 and body == digest
    finally:
        exporter.stop()
    bare = MetricsHTTPExporter().start()
    try:
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bare.url + "/debug/prefix", timeout=5)
    finally:
        bare.stop()


def test_stop_during_active_scrape_completes_the_scrape():
    entered, release = threading.Event(), threading.Event()

    def slow_state():
        entered.set()
        assert release.wait(5.0)
        return {"fine": True}

    exporter = MetricsHTTPExporter(state_fn=slow_state).start()
    results = []
    scraper = threading.Thread(
        target=lambda: results.append(_get(exporter.url + "/debug/state"))
    )
    scraper.start()
    assert entered.wait(5.0)
    stopper = threading.Thread(target=exporter.stop)
    stopper.start()
    release.set()  # let the in-flight handler finish under stop()
    scraper.join(timeout=10.0)
    stopper.join(timeout=10.0)
    assert results == [(200, {"fine": True})]


# ---------------------------------------------------------------------- #
# real engines: drain semantics + an end-to-end fleet
# ---------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, model, params


def test_engine_drain_stops_admission_and_finishes_seats(tiny_model):
    from accelerate_tpu.serving import ServingEngine

    _, model, params = tiny_model
    engine = ServingEngine(model, params, max_slots=1, block_size=4, seed=0)
    rids = [
        engine.add_request(list(range(1, 6)), max_new_tokens=3)
        for _ in range(3)
    ]
    engine.step()  # seats the first request
    harvested = engine.drain()
    assert [r.request_id for r in harvested] == rids[1:]
    assert engine.health() == {"ok": True, "state": "draining"}
    late = engine.add_request([1, 2, 3], max_new_tokens=2)
    assert engine.shed_reason(late) == "draining"
    assert engine.scheduler.shed_counts["draining"] == 1
    while engine.has_work:  # the seated request still finishes
        engine.step()
    assert engine.result(rids[0]) is not None
    engine.undrain()
    assert engine.health()["state"] == "serving"
    ok = engine.add_request([1, 2, 3], max_new_tokens=2)
    while engine.has_work:
        engine.step()
    assert engine.result(ok) is not None


def test_engine_prefix_digest_scoped_and_bounded(tiny_model):
    from accelerate_tpu.serving import ServingEngine

    _, model, params = tiny_model
    engine = ServingEngine(
        model, params, max_slots=2, block_size=4, seed=0,
        prefix_cache=True, model_fingerprint="digest-test",
    )
    engine.add_request(list(range(1, 14)), max_new_tokens=2)
    while engine.has_work:
        engine.step()
    digest = engine.prefix_digest()
    assert digest["enabled"] and digest["fingerprint"] == "digest-test"
    expected = prefix_keys("digest-test", None, list(range(1, 14)), 4)
    assert set(digest["entries"]) >= {k.hex() for k in expected}


def test_fleet_e2e_affinity_beats_round_robin(tiny_model):
    """Three REAL engines on a shared fake clock: prefix-affinity must
    concentrate each cohort's chain on one replica (strictly more cache
    hits than round-robin), outputs must match across policies, and
    every replica must hold ONE compiled decode program."""
    from accelerate_tpu.serving import ServingEngine

    _, model, params = tiny_model
    cohorts = [[10 + c] * 8 for c in range(2)]
    prompts = [
        cohorts[i % 2] + [30 + i, 31 + i] for i in range(8)
    ]

    def run(policy):
        clock = FakeClock()
        engines = [
            ServingEngine(
                model, params, max_slots=2, block_size=4, seed=0,
                prefix_cache=True, model_fingerprint="fleet-e2e",
                now=clock,
            )
            for _ in range(3)
        ]
        router = FleetRouter(
            [InProcessReplica(f"r{i}", e) for i, e in enumerate(engines)],
            policy=policy, now=clock,
        )
        rids = []
        for p in prompts:
            rids.append(router.add_request(list(p), max_new_tokens=3))
            _drain_fleet(router, clock, budget=500)
        outs = [router.result(r) for r in rids]
        hits = sum(e.prefix_cache.stats()["hits"] for e in engines)
        decodes = [e.trace_counts().get("decode", 0) for e in engines]
        return outs, hits, decodes

    rr_outs, rr_hits, rr_decodes = run("round_robin")
    af_outs, af_hits, af_decodes = run("prefix_affinity")
    assert af_outs == rr_outs  # placement changes WHERE, never WHAT
    assert all(o is not None for o in af_outs)
    assert af_hits > rr_hits
    assert af_hits == len(prompts) - 2  # all but each cohort's opener
    # zero decode retraces on every replica: one compiled decode each
    # (0 allowed only for a replica that never decoded)
    assert all(d <= 1 for d in af_decodes + rr_decodes)


# ---------------------------------------------------------------------- #
# export_trace: one merged Perfetto timeline for the whole fleet
# ---------------------------------------------------------------------- #
def test_export_trace_merges_replica_rows_and_transfer_ledger(tmp_path):
    """Per-replica span logs and the KV hand-off ledger land in ONE
    Chrome-trace JSON: a named process row per replica, a kv-transfer
    row, and every slice referenced to the fleet's shared time origin
    (so a prefill -> transfer -> decode hand-off reads left-to-right)."""
    from accelerate_tpu.serving.spans import SpanLog

    router, engines, clock = _fleet(n=2)
    # give the fakes real span logs with one finished request each,
    # deliberately offset so the shared origin is r0's submit (t=2.0)
    for i, eng in enumerate(engines):
        log = SpanLog()
        t0 = 2.0 + i
        log.on_submit(f"req-{i}", t0, prompt_tokens=8)
        log.on_admit(f"req-{i}", t0 + 0.1)
        log.on_prefill(f"req-{i}", t0 + 0.1)
        log.on_first_token(f"req-{i}", t0 + 0.3)
        log.on_finish(f"req-{i}", t0 + 0.5, new_tokens=4)
        eng.span_log = log
    # the hand-off ledger shape _deliver()/_drop_record() retain
    router._transfer_trace.append({
        "request_id": "req-0", "src": "r0", "dst": "r1",
        "state": "delivered", "started_at": 2.4, "done_at": 2.6,
        "bytes": 4096, "blocks": 2,
    })
    router._transfer_trace.append({
        "request_id": "req-x", "src": "r0", "dst": None,
        "state": "dropped", "reason": "dst_dead", "started_at": 3.0,
        "done_at": 3.0, "bytes": 0, "blocks": 0,
    })

    path = router.export_trace(str(tmp_path / "fleet.json"))
    with open(path) as f:
        payload = json.load(f)
    events = payload["traceEvents"]

    rows = {
        e["args"]["name"]: e["pid"]
        for e in events if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert set(rows) == {"r0", "r1", "kv-transfer"}
    assert len(set(rows.values())) == 3  # distinct pids, distinct rows

    slices = [e for e in events if e["ph"] == "X"]
    by_name = {e["name"]: e for e in slices}
    xfer = by_name["transfer:r0->r1"]
    assert xfer["pid"] == rows["kv-transfer"]
    assert xfer["args"]["bytes"] == 4096 and xfer["args"]["blocks"] == 2
    drop = by_name["transfer-drop:dst_dead"]
    assert drop["dur"] == 0.0
    # shared origin: earliest submit (2.0) maps to ts=0, the transfer
    # start 0.4s later lands at 400000us on the SAME clock
    assert min(e["ts"] for e in slices) == 0.0
    assert xfer["ts"] == pytest.approx(0.4e6)
    # replica phase slices made it over via spans_to_chrome_trace
    assert {"queue", "prefill", "decode"} <= {
        e["name"] for e in slices if e["pid"] in (rows["r0"], rows["r1"])
    }


def test_export_trace_empty_fleet_writes_valid_json(tmp_path):
    router, engines, clock = _fleet(n=2)  # fakes expose no span_log
    path = router.export_trace(str(tmp_path / "empty.json"))
    with open(path) as f:
        payload = json.load(f)
    assert payload["traceEvents"] == []
