"""Sharding X-ray tests: HLO collective parsing, ring bytes estimates,
contract derivation (NO_SHARD vs ZeRO-2 vs hierarchical multi-slice),
the mis-pinned-sharding violation path end to end, KV-gather bytes
sanity vs analytic sizes, and the ROADMAP (a) execution: every captured
serving program (decode, >= 2 prefill buckets, >= 1 verify width, COW)
audited on a 4-device CPU mesh under both ``fsdp`` and ``tensor``
weight layouts with zero involuntary reshards asserted.

All CPU-runnable on the virtual 8-device backend the conftest forces.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from accelerate_tpu.parallel.sharding import (
    collective_contract_for_params,
    collective_contract_for_train,
    mesh_axes_of_params,
)
from accelerate_tpu.profiling import (
    CONTRACT_ZERO,
    ProgramRegistry,
    audit_compiled,
    parse_hlo_collectives,
    parse_replica_groups,
    summarize_audits,
)
from accelerate_tpu.profiling.hlo_audit import (
    RESHARD_COPY,
    estimate_bytes_moved,
)
from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy


# ---------------------------------------------------------------------- #
# parsing units: both replica_groups formats XLA prints
# ---------------------------------------------------------------------- #
def test_parse_replica_groups_literal_and_iota():
    # literal braces (all-reduce / reduce-scatter print this)
    assert parse_replica_groups("replica_groups={{0,1,2,3},{4,5,6,7}}") == [
        [0, 1, 2, 3], [4, 5, 6, 7],
    ]
    # iota shorthand (all-gather prints this)
    assert parse_replica_groups("replica_groups=[2,4]<=[8]") == [
        [0, 1, 2, 3], [4, 5, 6, 7],
    ]
    # iota with a transpose: groups stride across the device order
    assert parse_replica_groups("replica_groups=[4,2]<=[2,4]T(1,0)") == [
        [0, 4], [1, 5], [2, 6], [3, 7],
    ]
    assert parse_replica_groups("no groups here") is None


def test_parse_hlo_collectives_counts_and_skips_done_halves():
    text = """
  %ag = f32[8,16]{1,0} all-gather(f32[2,16]{1,0} %p0), channel_id=1, replica_groups=[2,4]<=[8], dimensions={0}, use_global_device_ids=true
  %ar-start = f32[4]{0} all-reduce-start(f32[4]{0} %p1), channel_id=2, replica_groups={{0,1,2,3,4,5,6,7}}, to_apply=%add
  %ar-done = f32[4]{0} all-reduce-done(f32[4]{0} %ar-start)
"""
    ops = parse_hlo_collectives(text, num_devices=8, num_slices=1)
    assert [op.kind for op in ops] == ["all-gather", "all-reduce"]
    assert ops[0].group_size == 4
    assert ops[1].group_size == 8
    assert ops[1].is_async
    # async pairs fold into ONE op: the -done half is not double-counted
    assert len(ops) == 2


def test_ring_bytes_estimates_are_analytic():
    # ring schedules: all-gather moves result*(g-1)/g, reduce-scatter
    # operand*(g-1)/g, all-reduce 2*operand*(g-1)/g
    assert estimate_bytes_moved("all-gather", 0, 1024, 4) == 768
    assert estimate_bytes_moved("reduce-scatter", 1024, 0, 4) == 768
    assert estimate_bytes_moved("all-reduce", 1024, 1024, 4) == 1536
    assert estimate_bytes_moved("collective-permute", 512, 512, 2) == 512
    # degenerate single-member group moves nothing
    assert estimate_bytes_moved("all-gather", 0, 1024, 1) == 0


# ---------------------------------------------------------------------- #
# contract derivation: NO_SHARD vs ZeRO-2 vs hierarchical multi-slice
# ---------------------------------------------------------------------- #
def test_contract_no_shard_is_all_reduce_only():
    plugin = ParallelismPlugin(
        dp_size=8, fsdp_size=1, sharding_strategy=ShardingStrategy.NO_SHARD,
    )
    c = collective_contract_for_train(plugin, mesh=None)
    assert c.permits("all-reduce")
    assert not c.permits("reduce-scatter")
    assert not c.permits("all-gather")
    assert not c.permits("all-to-all")


def test_contract_zero2_allows_scatter_and_gather():
    plugin = ParallelismPlugin(
        dp_size=2, fsdp_size=4,
        sharding_strategy=ShardingStrategy.SHARD_GRAD_OP,
    )
    c = collective_contract_for_train(plugin, mesh=None)
    assert c.permits("reduce-scatter")
    assert c.permits("all-gather")
    assert c.permits("all-reduce")
    assert c.permits(RESHARD_COPY)  # shard_map bodies cross the boundary
    assert not c.permits("all-to-all")


def test_contract_hierarchical_multislice(monkeypatch):
    # > 1 slice: the hierarchical scatter -> cross-slice reduce ->
    # gather path is expected regardless of the sharding strategy
    from accelerate_tpu.parallel.mesh import NUM_SLICES_ENV, build_mesh

    monkeypatch.setenv(NUM_SLICES_ENV, "2")
    mesh = build_mesh(
        ParallelismPlugin(
            dp_size=2, fsdp_size=4,
            sharding_strategy=ShardingStrategy.NO_SHARD,
            min_weight_size=1,
        )
    )
    c = collective_contract_for_train(
        ParallelismPlugin(sharding_strategy=ShardingStrategy.NO_SHARD),
        mesh,
    )
    assert c.permits("reduce-scatter")
    assert c.permits("all-gather")
    assert c.permits("all-reduce")
    assert "slices=2" in c.origin


def test_params_contract_replicated_is_zero():
    params = {"w": jnp.ones((4, 4))}
    assert mesh_axes_of_params(params) == set()
    c = collective_contract_for_params(params)
    assert c.allowed == frozenset()
    assert c.origin == "serve:replicated"


def _mesh(axis: str, n: int = 4) -> Mesh:
    return Mesh(np.array(jax.devices()[:n]), (axis,))


def test_params_contract_follows_actual_leaf_sharding():
    mesh = _mesh("fsdp")
    w = jax.device_put(
        jnp.ones((8, 16)), NamedSharding(mesh, P("fsdp", None)),
    )
    params = {"w": w, "b": jnp.ones((16,))}
    assert mesh_axes_of_params(params) == {"fsdp"}
    c = collective_contract_for_params(params)
    assert c.permits("all-gather")
    assert c.permits("all-reduce")
    assert not c.permits("all-to-all")
    assert not c.permits("collective-permute")


# ---------------------------------------------------------------------- #
# the mis-pinned sharding fixture: provably trips sharding_violation
# ---------------------------------------------------------------------- #
def _mis_pinned_compiled(mesh):
    """A program whose sharding is mis-pinned: an fsdp-sharded weight is
    constrained replicated mid-computation, forcing the compiler to emit
    an involuntary all-gather on what should be a collective-free op."""
    sharded = NamedSharding(mesh, P("fsdp", None))
    replicated = NamedSharding(mesh, P())

    def f(w):
        return jax.lax.with_sharding_constraint(w * 2.0, replicated)

    spec = jax.ShapeDtypeStruct((8, 16), jnp.float32, sharding=sharded)
    return jax.jit(f).lower(spec).compile()


def test_mis_pinned_sharding_trips_violation():
    mesh = _mesh("fsdp")
    audit = audit_compiled(
        "mis_pinned", _mis_pinned_compiled(mesh), contract=CONTRACT_ZERO,
    )
    assert audit.by_kind == {"all-gather": 1}
    assert not audit.clean
    assert len(audit.violations) == 1
    v = audit.violations[0]
    assert v["op_kind"] == "all-gather"
    assert v["op"]  # the offending HLO op is named
    assert v["fabric"] == "ici"
    # exact ring estimate: result is 8*16*4 = 512B, gathered over g=4
    assert v["bytes_moved"] == 512 * 3 // 4


def test_violation_routes_to_sharding_violation_anomaly():
    from accelerate_tpu.diagnostics.anomaly import AnomalyDetector
    from accelerate_tpu.diagnostics.config import DiagnosticsConfig

    mesh = _mesh("fsdp")
    audit = audit_compiled(
        "mis_pinned", _mis_pinned_compiled(mesh), contract=CONTRACT_ZERO,
    )
    det = AnomalyDetector(DiagnosticsConfig())
    out = det.observe_audit(audit.to_record())
    assert len(out) == 1
    anom = out[0]
    assert anom["anomaly_type"] == "sharding_violation"
    assert anom["program"] == "mis_pinned"
    assert anom["op_kind"] == "all-gather"
    assert anom["op"] in anom["ops"]
    # the full audit record travels with the alarm
    assert anom["record"]["violations"] == audit.violations
    # clean audits never fire
    clean = audit_compiled(
        "clean", _mis_pinned_compiled(mesh),
        contract=collective_contract_for_params(
            {"w": jax.device_put(
                jnp.ones((8, 16)), NamedSharding(mesh, P("fsdp", None)),
            )},
        ),
    )
    assert clean.clean
    assert det.observe_audit(clean.to_record()) == []


# ---------------------------------------------------------------------- #
# bytes-estimate sanity vs analytic KV-gather sizes
# ---------------------------------------------------------------------- #
def test_kv_gather_bytes_match_analytic():
    # a KV-pool-shaped tensor (blocks, block_size, kv_heads, head_dim)
    # sharded over fsdp then gathered: the audited bytes must equal the
    # analytic ring all-gather volume result*(g-1)/g exactly
    mesh = _mesh("fsdp")
    shape = (16, 8, 4, 32)
    kv_bytes = int(np.prod(shape)) * 4  # f32
    sharded = NamedSharding(mesh, P("fsdp"))
    replicated = NamedSharding(mesh, P())

    def gather(kv):
        # a real op first: a bare identity constraint collapses to a
        # single-device program and audits (correctly) as empty
        return jax.lax.with_sharding_constraint(kv * 2.0, replicated)

    spec = jax.ShapeDtypeStruct(shape, jnp.float32, sharding=sharded)
    compiled = jax.jit(gather).lower(spec).compile()
    audit = audit_compiled("kv_gather", compiled)
    assert audit.by_kind == {"all-gather": 1}
    (op,) = audit.collectives
    assert op.result_bytes == kv_bytes
    assert op.bytes_moved == kv_bytes * 3 // 4
    assert audit.ici_bytes == kv_bytes * 3 // 4
    assert audit.dcn_bytes == 0


def test_summarize_audits_rolls_up_programs():
    mesh = _mesh("fsdp")
    compiled = _mis_pinned_compiled(mesh)
    a1 = audit_compiled("p1", compiled, contract=CONTRACT_ZERO)
    a2 = audit_compiled("p2", compiled)  # no contract: nothing violates
    s = summarize_audits([a1, a2])
    assert s["num_programs_audited"] == 2
    assert s["collectives_total"] == 2
    assert s["violations_total"] == 1
    assert s["violations"][0]["program"] == "p1"
    assert s["ici_bytes_total"] == 2 * (512 * 3 // 4)
    assert s["dcn_bytes_total"] == 0
    assert set(s["programs"]) == {"p1", "p2"}


# ---------------------------------------------------------------------- #
# ROADMAP (a): every serving program audited under fsdp/tensor layouts
# ---------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def tiny_serving_model():
    from accelerate_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, model, params


def _shard_params(params, mesh, axis):
    """Shard every leaf whose leading dim tiles over the mesh axis;
    replicate the rest (min-weight-size idiom, but explicit)."""
    size = mesh.shape[axis]

    def place(leaf):
        if leaf.ndim >= 1 and leaf.shape[0] % size == 0:
            spec = P(axis, *([None] * (leaf.ndim - 1)))
        else:
            spec = P()
        return jax.device_put(leaf, NamedSharding(mesh, spec))

    return jax.tree.map(place, params)


def _audited_engine(model, params, axis):
    """Build a weight-sharded engine, run enough traffic to trace >= 2
    prefill buckets, the decode program, >= 1 verify width and the COW
    path, then audit every captured program. Returns (engine, audits)."""
    from accelerate_tpu.serving import ServingEngine
    from accelerate_tpu.serving.speculation import SpecConfig

    mesh = _mesh(axis)
    sharded = _shard_params(params, mesh, axis)
    engine = ServingEngine(
        model, sharded, max_slots=2, block_size=8, seed=0,
        spec_decode=SpecConfig(k=2),
    )
    # two prompt lengths -> two pow2 prefill buckets; the repetitive
    # prompts make the n-gram proposer fire, tracing a verify width
    engine.add_request([7, 8] * 3, max_new_tokens=6)
    engine.add_request([1, 2, 3] * 5, max_new_tokens=6)
    for _ in engine.stream():
        pass
    assert engine.trace_counts()["verify"] >= 1
    registry = ProgramRegistry()
    audits = engine.audit_programs(registry, emit=False)
    return engine, audits


@pytest.mark.parametrize("axis", ["fsdp", "tp"])
def test_all_serving_programs_audit_clean_under_weight_sharding(
    tiny_serving_model, axis,
):
    _, model, params = tiny_serving_model
    engine, audits = _audited_engine(model, params, axis)
    labels = set(audits)
    assert "serve_decode" in labels
    assert "serve_cow" in labels
    assert sum(1 for l in labels if l.startswith("serve_prefill_b")) >= 2
    assert sum(1 for l in labels if l.startswith("serve_verify_w")) >= 1
    for label, audit in audits.items():
        # the contract came from the actual leaf shardings
        assert audit.contract.origin == f"serve:{axis}"
        # zero involuntary reshards: every collective the compiler
        # emitted is explained by the weight layout — any finding names
        # the offending HLO op in the assertion message
        assert audit.clean, (
            f"{label}: involuntary reshards {audit.violations}"
        )
        # single slice: nothing may cross DCN
        assert audit.dcn_bytes == 0
        for op in audit.collectives:
            assert op.fabric == "ici"
            assert op.group_size <= 4


def test_replicated_serving_programs_have_zero_collectives(
    tiny_serving_model,
):
    # pure replicated serving (the common single-host engine): the
    # decode/verify/COW/prefill programs expect — and get — ZERO
    # cross-device collectives
    from accelerate_tpu.serving import ServingEngine

    _, model, params = tiny_serving_model
    engine = ServingEngine(model, params, max_slots=2, block_size=8)
    engine.add_request([1, 2, 3], max_new_tokens=2)
    for _ in engine.stream():
        pass
    registry = ProgramRegistry()
    audits = engine.audit_programs(registry, emit=False)
    assert audits
    for label, audit in audits.items():
        assert audit.contract.allowed == frozenset()
        assert audit.collectives == [], (
            f"{label}: unexpected collectives {audit.by_kind}"
        )
        assert audit.clean
    # the registry roll-up is reachable for soak reports / BENCH records
    summary = engine.audit_summary(registry)
    assert summary["num_programs_audited"] == len(audits)
    assert summary["violations_total"] == 0


def test_audit_smoke_decode_and_verify_clean_under_fsdp(
    tiny_serving_model,
):
    """The `make audit-smoke` assertion: paged decode + spec verify
    compile collective-clean under fsdp weight sharding on a 4-device
    CPU mesh (the CPU-feasible half of ROADMAP (a))."""
    _, model, params = tiny_serving_model
    engine, audits = _audited_engine(model, params, "fsdp")
    decode = audits["serve_decode"]
    verifies = [a for l, a in audits.items() if l.startswith("serve_verify_w")]
    assert verifies
    for audit in [decode] + verifies:
        assert audit.clean, (
            f"{audit.label}: involuntary reshards {audit.violations}"
        )
        assert audit.dcn_bytes == 0
