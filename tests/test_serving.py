"""Serving engine tests: paged KV cache + continuous batching.

Three layers, matching the subsystem's split: the host-side block
allocator (pure policy, no jax), the paged attention math (must equal
the dense cache path — paging is layout, not math), and the engine's
step loop (admit/evict scheduling, EOS slot refill, and the
zero-retrace-after-warmup contract the trace counters pin).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import CausalLM, TransformerConfig
from accelerate_tpu.models.generation import generate
from accelerate_tpu.ops.attention import (
    PagedKVState,
    paged_attention,
    paged_update,
    xla_attention,
)
from accelerate_tpu.serving import (
    BlockPool,
    ContinuousScheduler,
    Request,
    ServingEngine,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return cfg, model, params


# ---------------------------------------------------------------------- #
# block pool
# ---------------------------------------------------------------------- #
def test_block_pool_never_hands_out_garbage_block():
    pool = BlockPool(num_blocks=8, block_size=4)
    blocks = pool.allocate(7)  # everything allocatable
    assert 0 not in blocks
    assert sorted(blocks) == list(range(1, 8))
    assert pool.num_free == 0


def test_block_pool_alloc_free_roundtrip_and_reuse():
    pool = BlockPool(num_blocks=10, block_size=4)
    a = pool.allocate(3)
    b = pool.allocate(2)
    assert pool.num_allocated == 5 and pool.num_free == 4
    pool.free(a)
    # freed blocks are immediately reusable; the pool never leaks
    c = pool.allocate(4)
    assert set(c) & set(a)  # reuse really happened
    assert pool.num_allocated == 6
    pool.free(b)
    pool.free(c)
    assert pool.num_free == 9 and pool.num_allocated == 0
    assert pool.stats()["utilization"] == 0.0


def test_block_pool_fragmentation_is_free():
    """Block indirection means non-contiguous free blocks are as good as
    contiguous ones: free every other allocation and a full-size request
    still fits."""
    pool = BlockPool(num_blocks=17, block_size=4)
    held = [pool.allocate(2) for _ in range(8)]
    for blocks in held[::2]:
        pool.free(blocks)
    assert pool.num_free == 8
    assert pool.can_allocate(8)
    scattered = pool.allocate(8)  # interleaved ids, not a contiguous run
    assert len(set(scattered)) == 8
    assert pool.num_free == 0


def test_block_pool_rejects_double_free_and_exhaustion():
    pool = BlockPool(num_blocks=4, block_size=2)
    blocks = pool.allocate(2)
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.allocate(2)
    pool.free(blocks)
    with pytest.raises(ValueError, match="not allocated"):
        pool.free(blocks)
    with pytest.raises(ValueError, match="num_blocks"):
        BlockPool(num_blocks=1, block_size=2)


def test_blocks_for_tokens_sizing_formula():
    pool = BlockPool(num_blocks=8, block_size=16)
    assert pool.blocks_for_tokens(0) == 0
    assert pool.blocks_for_tokens(1) == 1
    assert pool.blocks_for_tokens(16) == 1
    assert pool.blocks_for_tokens(17) == 2
    assert pool.blocks_for_tokens(33) == 3


# ---------------------------------------------------------------------- #
# paged attention numerics
# ---------------------------------------------------------------------- #
def test_paged_attention_matches_dense_attention():
    """Writing K/V through the block table and attending through the
    gathered pool must reproduce plain causal attention bit-for-near-bit:
    paging is an addressing scheme, not an approximation."""
    rng = np.random.default_rng(0)
    heads, head_dim, block_size, num_blocks = 4, 16, 8, 12
    seq = 21  # deliberately not a multiple of block_size
    max_table = 4
    q = jnp.asarray(rng.standard_normal((1, seq, heads, head_dim)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, seq, heads, head_dim)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, seq, heads, head_dim)), jnp.float32)

    key_pool = jnp.zeros((num_blocks, block_size, heads, head_dim), jnp.float32)
    value_pool = jnp.zeros_like(key_pool)
    state = PagedKVState(
        block_table=jnp.asarray([[5, 2, 9, 7]], jnp.int32),  # scattered
        cache_len=jnp.zeros((1,), jnp.int32),
        lengths=jnp.asarray([seq], jnp.int32),
        num_blocks=num_blocks,
        block_size=block_size,
    )
    key_pool, value_pool = paged_update(key_pool, value_pool, k, v, state)
    paged = paged_attention(q, key_pool, value_pool, state)

    dense = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(paged[:, :seq]), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_paged_update_routes_padding_to_garbage_block():
    """Rows past ``lengths`` (bucket padding) must land in block 0 and
    leave every real block untouched."""
    heads, head_dim, block_size, num_blocks = 2, 4, 4, 6
    key_pool = jnp.zeros((num_blocks, block_size, heads, head_dim), jnp.float32)
    value_pool = jnp.zeros_like(key_pool)
    k = jnp.ones((1, 8, heads, head_dim), jnp.float32)
    state = PagedKVState(
        block_table=jnp.asarray([[3, 0, 0]], jnp.int32),
        cache_len=jnp.zeros((1,), jnp.int32),
        lengths=jnp.asarray([3], jnp.int32),  # only 3 of the 8 rows valid
        num_blocks=num_blocks,
        block_size=block_size,
    )
    key_pool, _ = paged_update(key_pool, value_pool, k, k, state)
    out = np.asarray(key_pool)
    assert out[3, :3].sum() > 0          # the 3 valid rows landed
    assert out[3, 3:].sum() == 0          # nothing past the valid length
    assert out[[1, 2, 4, 5]].sum() == 0   # no other block touched
    # garbage block absorbed the padding writes — that is its job
    assert out[0].sum() > 0


def test_paged_generate_matches_dense_generate(tiny_model):
    """Engine greedy decode == the dense-cache ``generate`` path, token
    for token, across mixed prompt lengths and slot churn."""
    cfg, model, params = tiny_model
    rng = np.random.default_rng(0)
    engine = ServingEngine(model, params, max_slots=2, block_size=8)
    for p_len in (3, 8, 13):
        prompt = jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, p_len)), jnp.int32
        )
        want = generate(model, params, prompt, max_new_tokens=6)
        got = engine.generate(prompt, max_new_tokens=6)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------- #
# scheduler (fake clock)
# ---------------------------------------------------------------------- #
class FakeClock:
    def __init__(self):
        self.t = 0.0

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt

    def __call__(self) -> float:
        return self.t


def test_scheduler_admits_in_fifo_order_within_capacity():
    clock = FakeClock()
    pool = BlockPool(num_blocks=9, block_size=4)  # 8 allocatable
    sched = ContinuousScheduler(max_slots=2, pool=pool, now=clock)
    ids = [
        sched.submit(Request(prompt=[1] * 4, max_new_tokens=4))
        for _ in range(3)
    ]
    clock.tick()
    admitted = sched.admit()  # 2 slots, 2 blocks each -> first two fit
    assert [s.request.request_id for s in admitted] == ids[:2]
    assert all(s.admit_time == 1.0 for s in admitted)
    assert all(s.request.submit_time == 0.0 for s in admitted)
    assert len(sched.queue) == 1
    assert sched.admit() == []  # no free seat for the third
    # retire one: its seat AND blocks refill the head of the queue
    clock.tick()
    sched.release(admitted[0])
    refill = sched.admit()
    assert [s.request.request_id for s in refill] == [ids[2]]
    assert refill[0].admit_time == 2.0


def test_scheduler_head_of_queue_blocks_until_pool_can_fund_it():
    """Strict FIFO: a big head request that doesn't fit must wait for
    blocks, and must NOT be overtaken by a small later request."""
    clock = FakeClock()
    pool = BlockPool(num_blocks=7, block_size=4)  # 6 allocatable
    sched = ContinuousScheduler(max_slots=3, pool=pool, now=clock)
    big = sched.submit(Request(prompt=[1] * 16, max_new_tokens=4))  # 5 blocks
    (slot,) = sched.admit()
    assert slot.request.request_id == big
    big2 = sched.submit(Request(prompt=[1] * 8, max_new_tokens=4))  # 3 blocks
    small = sched.submit(Request(prompt=[1] * 2, max_new_tokens=2))  # 1 block
    assert sched.admit() == []  # 1 block free < 3: head stalls, small waits
    sched.release(slot)
    admitted = sched.admit()  # both fit now, in order
    assert [s.request.request_id for s in admitted] == [big2, small]


def test_scheduler_rejects_request_larger_than_pool():
    pool = BlockPool(num_blocks=4, block_size=4)  # 12 tokens max
    sched = ContinuousScheduler(max_slots=1, pool=pool)
    with pytest.raises(ValueError, match="allocatable blocks"):
        sched.submit(Request(prompt=[1] * 16, max_new_tokens=8))


def test_engine_queue_and_latency_accounting_with_fake_clock(tiny_model):
    """With max_slots=1 the second request waits a full generation in the
    queue; the injectable clock makes queue_s/e2e_s exact."""
    cfg, model, params = tiny_model
    clock = FakeClock()
    engine = ServingEngine(
        model, params, max_slots=1, block_size=8, now=clock
    )
    r1 = engine.add_request([1, 2, 3], max_new_tokens=3)
    r2 = engine.add_request([4, 5], max_new_tokens=2)
    while engine.has_work:
        engine.step()
        clock.tick()
    recs = {r["request_id"]: r for r in engine.stats.requests}
    assert recs[r1]["queue_s"] == 0.0
    # r1 holds the only slot for its whole generation; r2's queue time is
    # the ticks that elapsed before its admission
    assert recs[r2]["queue_s"] > 0.0
    assert recs[r2]["e2e_s"] >= recs[r2]["queue_s"]
    assert recs[r1]["new_tokens"] == 3 and recs[r2]["new_tokens"] == 2


# ---------------------------------------------------------------------- #
# engine: EOS refill + zero retrace
# ---------------------------------------------------------------------- #
def test_eos_slot_refill_completes_all_requests(tiny_model):
    """EOS-finished slots must free mid-flight and their seats refill
    from the queue: more requests than slots all complete, short ones
    never wait out a long neighbour's budget."""
    cfg, model, params = tiny_model
    rng = np.random.default_rng(2)
    engine = ServingEngine(model, params, max_slots=2, block_size=8)
    # discover what greedy emits first for this prompt, use it as EOS so
    # the request finishes on its first decode step
    probe = rng.integers(0, cfg.vocab_size, (4,)).tolist()
    eid = engine.add_request(probe, max_new_tokens=2)
    for _ in engine.stream():
        pass
    eos = engine.result(eid)[1]

    ids = []
    budgets = {}
    for i in range(5):
        prompt = rng.integers(0, cfg.vocab_size, (3 + i,)).tolist()
        n = 8 if i % 2 else 3
        rid = engine.add_request(
            probe if i == 2 else prompt, max_new_tokens=n,
            eos_token_id=eos if i == 2 else None,
        )
        ids.append(rid)
        budgets[rid] = n
    events = list(engine.stream())
    for rid in ids:
        out = engine.result(rid)
        assert out is not None
        if rid == ids[2]:
            assert out[-1] == eos and len(out) <= budgets[rid]
        else:
            assert len(out) == budgets[rid]
    assert sum(e.done for e in events) == 5
    # every seat emptied, every block returned
    assert engine.pool.stats()["allocated"] == 0
    assert not engine.scheduler.has_work


def test_zero_decode_retrace_after_warmup(tiny_model):
    """The decode step must compile exactly ONCE: admissions, evictions,
    mixed depths and temperatures are all traced data. Prefill stays
    within the power-of-two bucket budget."""
    import math

    cfg, model, params = tiny_model
    rng = np.random.default_rng(3)
    engine = ServingEngine(model, params, max_slots=3, block_size=8)
    # warmup: one short request compiles one bucket + the decode step
    engine.add_request([1, 2, 3], max_new_tokens=2)
    for _ in engine.stream():
        pass
    assert engine.trace_counts()["decode"] == 1
    # storm: mixed lengths, budgets, temperatures, churn through slots
    for i in range(8):
        prompt = rng.integers(0, cfg.vocab_size, (2 + 3 * i % 17,)).tolist()
        engine.add_request(
            prompt, max_new_tokens=1 + i % 5, temperature=0.5 * (i % 2)
        )
    for _ in engine.stream():
        pass
    counts = engine.trace_counts()
    assert counts["decode"] == 1, "decode step retraced after warmup"
    assert counts["prefill"] <= int(math.log2(cfg.max_seq_len))
