"""Flash-attention kernel correctness vs the XLA reference path.

On CPU the Pallas kernel runs under the Mosaic interpreter
(``force_tpu_interpret_mode``) — same kernel code, exact semantics — so CI
covers it without a chip; on a real TPU the same tests exercise the
compiled kernel.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.attention import xla_attention
from accelerate_tpu.ops.flash_attention import (
    flash_attention,
    kernel_interpret_mode as _kernel_mode,
)


def _qkv(B=1, S=256, H=4, Hkv=2, D=64, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), dtype)
    return q, k, v


def _assert_grads_close(got, want, atol=2e-2):
    """Compare grad triples normalized by the reference's max magnitude."""
    for a, b in zip(got, want):
        scale = float(jnp.max(jnp.abs(b))) + 1e-6
        np.testing.assert_allclose(
            np.asarray(a) / scale, np.asarray(b) / scale, atol=atol
        )


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_xla(causal):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=causal)
    with _kernel_mode():
        out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), atol=5e-3, rtol=1e-2
    )


def test_backward_matches_xla():
    q, k, v = _qkv(S=256)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, block_q=128, block_k=128) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    with _kernel_mode():  # backward kernels run here too
        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    _assert_grads_close(g1, g2)


@pytest.mark.parametrize("padded", [False, True])
def test_fused_backward_matches_xla(padded):
    """The single-pass fused backward (kept as the measured record of the
    r5 attempt — 26x slower on-chip, see the FUSED_BWD comment block)
    must stay numerically correct: dq/dk/dv vs the dense oracle, GQA and
    kv-length padding included."""
    import accelerate_tpu.ops.flash_attention as fa

    q, k, v = _qkv(S=256)
    lengths = jnp.asarray([160], jnp.int32) if padded else None

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64,
                kv_lengths=lengths,
            ) ** 2
        )

    def loss_ref(q, k, v):
        from accelerate_tpu.ops.attention import lengths_to_mask

        mask = lengths_to_mask(lengths, k.shape[1]) if padded else None
        return jnp.sum(xla_attention(q, k, v, causal=True, mask=mask) ** 2)

    old = fa.FUSED_BWD
    fa.FUSED_BWD = True
    try:
        with _kernel_mode():
            g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa.FUSED_BWD = old
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    _assert_grads_close(g1, g2)


@pytest.mark.parametrize("window", [1, 7, 64, 200, 1000])
def test_sliding_window_forward_matches_xla(window):
    """The banded causal mask (Mistral/Qwen2 sliding window, r5): the
    flash kernel's band — including block skipping below it — must match
    the dense banded oracle at windows crossing every block-geometry
    case (sub-block, block-straddling, larger-than-seq)."""
    q, k, v = _qkv(S=256)
    ref = xla_attention(q, k, v, causal=True, window=window)
    with _kernel_mode():
        out = flash_attention(
            q, k, v, causal=True, block_q=64, block_k=64, window=window
        )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), atol=5e-3, rtol=1e-2
    )


@pytest.mark.parametrize("window", [7, 100])
def test_sliding_window_backward_matches_xla(window):
    """Band gradients: dq/dk/dv through both backward kernels (with their
    own block-skip predicates) vs the dense banded oracle."""
    q, k, v = _qkv(S=256)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=True, block_q=64, block_k=64, window=window
            ) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, window=window) ** 2)

    with _kernel_mode():
        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    _assert_grads_close(g1, g2)


def test_sliding_window_decode_alignment():
    """Decode: a short query block end-aligned on a long kv context sees
    exactly the last `window` keys at its global position."""
    rng = np.random.default_rng(3)
    S, Skv, W = 8, 128, 16
    q = jnp.asarray(rng.normal(size=(1, S, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, Skv, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, Skv, 2, 64)), jnp.float32)
    ref = xla_attention(q, k, v, causal=True, window=W)
    with _kernel_mode():
        out = flash_attention(
            q, k, v, causal=True, block_q=8, block_k=64, window=W
        )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), atol=5e-3, rtol=1e-2
    )


@pytest.mark.parametrize("causal", [True, False])
def test_kv_lengths_padding_matches_xla(causal):
    """Ragged right-padded batches: the flash kernel's per-row kv-length
    mask must agree with the dense key-mask oracle (VERDICT r2 missing #2
    'done' criterion). Lengths deliberately straddle block boundaries,
    include a full row and a tiny prefix."""
    from accelerate_tpu.ops.attention import lengths_to_mask

    q, k, v = _qkv(B=4, S=256, seed=3)
    lengths = jnp.asarray([256, 133, 7, 64], jnp.int32)
    ref = xla_attention(
        q, k, v, causal=causal, mask=lengths_to_mask(lengths, 256)
    )
    with _kernel_mode():
        out = flash_attention(
            q, k, v, causal=causal, kv_lengths=lengths,
            block_q=128, block_k=128,
        )
    # only rows with >= 1 visible key are comparable; with causal +
    # padding both paths zero/garbage the same *valid* region, so compare
    # the full tensor — the oracle defines it everywhere
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(out), atol=5e-3, rtol=1e-2
    )


@pytest.mark.parametrize("causal", [True, False])
def test_kv_lengths_backward_matches_xla(causal):
    """Gradients through the padding-masked kernel equal the dense-mask
    oracle, including zero grads for padded-out keys/values."""
    from accelerate_tpu.ops.attention import lengths_to_mask

    q, k, v = _qkv(B=3, S=256, seed=4)
    lengths = jnp.asarray([256, 160, 40], jnp.int32)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(
                q, k, v, causal=causal, kv_lengths=lengths,
                block_q=128, block_k=128,
            ) ** 2
        )

    def loss_ref(q, k, v):
        return jnp.sum(
            xla_attention(
                q, k, v, causal=causal, mask=lengths_to_mask(lengths, 256)
            ) ** 2
        )

    with _kernel_mode():
        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    # padded-out kv positions must get exactly zero grad (k: (B,S,Hkv,D))
    np.testing.assert_array_equal(
        np.asarray(g1[1][1, 160:]), np.zeros_like(np.asarray(g1[1][1, 160:]))
    )
    _assert_grads_close(g1, g2)


def test_kv_lengths_zero_row():
    """A fully-padded row (length 0) yields zero output, not NaN."""
    q, k, v = _qkv(B=2, S=128, seed=5)
    lengths = jnp.asarray([128, 0], jnp.int32)
    with _kernel_mode():
        out = flash_attention(
            q, k, v, causal=False, kv_lengths=lengths,
            block_q=128, block_k=128,
        )
    out = np.asarray(out)
    assert np.all(np.isfinite(out))
    np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))


def test_mha_no_gqa():
    q, k, v = _qkv(H=4, Hkv=4)
    ref = xla_attention(q, k, v, causal=True)
    with _kernel_mode():
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=5e-3, rtol=1e-2)


def test_decode_alignment_q_shorter_than_kv():
    """causal with q_len < kv_len must end-align the diagonal (a short query
    block sees the full preceding context), like make_causal_mask."""
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.normal(size=(1, 128, 4, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 512, 4, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 512, 4, 64)), jnp.float32)
    ref = xla_attention(q, k, v, causal=True)
    with _kernel_mode():
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=5e-3, rtol=1e-2)


def test_rejects_indivisible_seq():
    q, k, v = _qkv(S=192)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_q=128, block_k=128)


def test_causal_q_longer_than_kv_masked_rows_zero_grads():
    """ADVICE r1: with q_len > kv_len the first q_len-kv_len rows are fully
    masked; their forward output is zero and their gradients must be zero
    too (the backward previously fabricated p=1 for them)."""
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 64, 2, 64)), jnp.float32)
    n_masked = 128 - 64

    with _kernel_mode():
        out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        np.testing.assert_allclose(np.asarray(out[:, :n_masked]), 0.0)

        def loss(q, k, v):
            o = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
            return jnp.sum(o ** 2)

        dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # masked query rows: exactly zero gradient
    np.testing.assert_allclose(np.asarray(dq[:, :n_masked]), 0.0)
    assert np.isfinite(np.asarray(dq)).all()

    # valid region must agree with the XLA oracle on the equivalent
    # end-aligned problem (q2 = last 64 queries, same kv)
    q2 = q[:, n_masked:]

    def loss_ref(q2, k, v):
        return jnp.sum(xla_attention(q2, k, v, causal=True) ** 2)

    dq2, dk2, dv2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q2, k, v)
    np.testing.assert_allclose(
        np.asarray(dq[:, n_masked:]), np.asarray(dq2), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk2), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv2), rtol=2e-4, atol=2e-4)


def test_forward_masked_rows_inside_visible_block():
    """When the diagonal crosses mid-block (block_q > kv deficit), fully
    masked rows share a VISIBLE block with valid rows; their forward output
    must still be zero, not mean-of-v (review finding on the fwd kernel)."""
    rng = np.random.default_rng(5)
    q = jnp.asarray(rng.normal(size=(1, 128, 2, 64)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 64, 2, 64)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(1, 64, 2, 64)), jnp.float32)
    with _kernel_mode():
        # block_q=128 covers masked rows 0..63 AND valid rows 64..127
        out = flash_attention(q, k, v, causal=True, block_q=128, block_k=64)
    np.testing.assert_allclose(np.asarray(out[:, :64]), 0.0)
    ref = xla_attention(q[:, 64:], k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out[:, 64:]), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_fit_block_and_nonpow2_seq():
    """S=1536 (multiple of 512, not 1024) must still run flash with an
    adapted block (review finding: raising defaults broke such lengths)."""
    from accelerate_tpu.ops.flash_attention import MIN_BLOCK, fit_block

    assert fit_block(1536, 1024) == 512
    assert fit_block(1024, 1024) == 1024
    assert fit_block(64, 1024) == 64  # short seqs are their own block
    assert fit_block(192, 128) == 64
    assert fit_block(128, 64) == 64  # explicit small block still honored
    # unaligned seqs (not a multiple of the 8-row sublane) must fall back
    # to dense rather than hand Pallas a misaligned block
    assert fit_block(100, 1024) is None
    assert fit_block(20, 1024) is None
    assert fit_block(1001, 512) is None  # odd seq > preferred: no block
    assert fit_block(24, 1024) == 24  # aligned short seq is its own block

    q, k, v = _qkv(S=384)  # 384 = 3*128: needs the adaptive step-down
    ref = xla_attention(q, k, v, causal=True)
    with _kernel_mode():
        out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )
