"""End-to-end Accelerator tests — the port of the reference's training_check
(test_utils/scripts/test_script.py:420: single- vs multi-process training
must produce identical weights) and grad-sync suite (test_sync.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import (
    Accelerator,
    AcceleratedOptimizer,
    AcceleratedScheduler,
    DataLoader,
    ParallelismPlugin,
)


class RegressionDataset:
    """Reference test_utils/training.py RegressionDataset."""

    def __init__(self, n=64, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(n, 1)).astype(np.float32)
        self.y = (2.0 * self.x[:, 0] + 3.0 + 0.05 * rng.normal(size=n)).astype(
            np.float32
        )

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


def loss_fn(params, batch):
    pred = batch["x"][:, 0] * params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def numpy_reference_sgd(dataset, lr, steps, batch_size):
    """Closed-form full-batch SGD in numpy — the ground truth."""
    w, b = 0.0, 0.0
    x, y = dataset.x[:, 0], dataset.y
    for s in range(steps):
        lo = (s * batch_size) % len(x)
        bx, by = x[lo : lo + batch_size], y[lo : lo + batch_size]
        pred = w * bx + b
        err = pred - by
        gw = np.mean(2 * err * bx)
        gb = np.mean(2 * err)
        w -= lr * gw
        b -= lr * gb
    return w, b


def test_training_check_dp_matches_numpy():
    """8-way DP training must produce the same weights as the numpy
    single-device reference (the SPMD analogue of single-vs-multi)."""
    accelerator = Accelerator()
    ds = RegressionDataset(64)
    loader = DataLoader(ds, batch_size=16, shuffle=False)
    params = {"w": jnp.asarray(0.0), "b": jnp.asarray(0.0)}
    params, opt, prepared = accelerator.prepare(params, optax.sgd(0.1), loader)
    step_fn = accelerator.unified_step(loss_fn, opt)
    carry = accelerator.init_carry(params, opt)
    steps = 0
    for epoch in range(2):
        prepared.set_epoch(epoch)
        for batch in prepared:
            carry, metrics = step_fn(carry, batch)
            steps += 1
    w_ref, b_ref = numpy_reference_sgd(ds, 0.1, steps, 16)
    np.testing.assert_allclose(float(carry["params"]["w"]), w_ref, rtol=1e-4)
    np.testing.assert_allclose(float(carry["params"]["b"]), b_ref, rtol=1e-4)
    assert int(carry["opt_step"]) == steps


def test_gradient_accumulation_equivalence():
    """accum=2 over half-batches == one step over the full batch
    (reference test_sync.py:113 test_distributed_sync)."""
    ds = RegressionDataset(32)

    def run(accum_steps, batch_size):
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        acc = Accelerator(gradient_accumulation_steps=accum_steps)
        loader = DataLoader(ds, batch_size=batch_size, shuffle=False)
        params = {"w": jnp.asarray(0.0), "b": jnp.asarray(0.0)}
        params, opt, prepared = acc.prepare(params, optax.sgd(0.1), loader)
        step = acc.unified_step(loss_fn, opt)
        carry = acc.init_carry(params, opt)
        for batch in prepared:
            carry, _ = step(carry, batch)
        return float(carry["params"]["w"]), float(carry["params"]["b"]), int(
            carry["opt_step"]
        )

    w2, b2, n2 = run(accum_steps=2, batch_size=8)
    w1, b1, n1 = run(accum_steps=1, batch_size=16)
    assert n2 == n1  # same number of optimizer steps
    np.testing.assert_allclose(w2, w1, rtol=1e-5)
    np.testing.assert_allclose(b2, b1, rtol=1e-5)


def test_fsdp_sharding_matches_dp():
    """FULL_SHARD over fsdp axis must produce identical training results to
    pure DP — sharding is layout, not math."""
    ds = RegressionDataset(32)

    def run(plugin):
        from accelerate_tpu.state import AcceleratorState, GradientState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        acc = Accelerator(parallelism_plugin=plugin)
        loader = DataLoader(ds, batch_size=16, shuffle=False)
        # big enough param to shard: a (8,) vector weight
        params = {"w": jnp.zeros((8,)), "b": jnp.asarray(0.0)}

        def vec_loss(p, batch):
            pred = batch["x"] @ p["w"][:1] + p["b"]
            return jnp.mean((pred - batch["y"]) ** 2)

        params, opt, prepared = acc.prepare(params, optax.sgd(0.05), loader)
        step = acc.unified_step(vec_loss, opt)
        carry = acc.init_carry(params, opt)
        for batch in prepared:
            carry, _ = step(carry, batch)
        return np.asarray(carry["params"]["w"])

    w_dp = run(ParallelismPlugin.pure_dp())
    w_fsdp = run(
        ParallelismPlugin(dp_size=2, fsdp_size=4, min_weight_size=1)
    )
    np.testing.assert_allclose(w_fsdp, w_dp, rtol=1e-5)


def test_fp16_loss_scaling_step():
    from accelerate_tpu import MixedPrecisionPolicy

    policy = MixedPrecisionPolicy.from_precision("fp16")
    policy.loss_scale_init = 2.0**8  # keep fp16 backward finite for the toy
    accelerator = Accelerator(
        mixed_precision="fp16", mixed_precision_policy=policy
    )
    ds = RegressionDataset(16)
    loader = DataLoader(ds, batch_size=16, shuffle=False)
    params = {"w": jnp.asarray(0.0), "b": jnp.asarray(0.0)}
    params, opt, prepared = accelerator.prepare(params, optax.sgd(0.01), loader)
    step = accelerator.unified_step(loss_fn, opt)
    carry = accelerator.init_carry(params, opt)
    assert "loss_scale" in carry
    for batch in prepared:
        carry, metrics = step(carry, batch)
    assert bool(metrics["grads_finite"])
    assert float(carry["params"]["w"]) != 0.0


def test_bf16_step_and_param_dtype():
    accelerator = Accelerator(mixed_precision="bf16")
    ds = RegressionDataset(16)
    loader = DataLoader(ds, batch_size=16, shuffle=False)
    params = {"w": jnp.asarray(0.0), "b": jnp.asarray(0.0)}
    params, opt, prepared = accelerator.prepare(params, optax.sgd(0.01), loader)
    step = accelerator.unified_step(loss_fn, opt)
    carry = accelerator.init_carry(params, opt)
    for batch in prepared:
        carry, metrics = step(carry, batch)
    # master params stay fp32
    assert carry["params"]["w"].dtype == jnp.float32


def test_clip_grad_norm():
    accelerator = Accelerator()
    grads = {"a": jnp.full((4,), 10.0)}
    clipped, norm = accelerator.clip_grad_norm_(grads, max_norm=1.0)
    assert float(norm) == pytest.approx(20.0)
    clipped_norm = float(optax_global_norm(clipped))
    assert clipped_norm == pytest.approx(1.0, rel=1e-4)


def optax_global_norm(tree):
    import optax

    return optax.global_norm(tree)


def test_clip_inside_unified_step():
    accelerator = Accelerator()
    ds = RegressionDataset(16)
    loader = DataLoader(ds, batch_size=16, shuffle=False)
    params = {"w": jnp.asarray(100.0), "b": jnp.asarray(0.0)}  # huge grads
    params, opt, prepared = accelerator.prepare(params, optax.sgd(0.01), loader)
    step = accelerator.unified_step(loss_fn, opt, max_grad_norm=1.0)
    carry = accelerator.init_carry(params, opt)
    for batch in prepared:
        carry, metrics = step(carry, batch)
    # un-clipped grad norm reported, but applied update was clipped:
    # |delta| <= lr * max_norm
    assert abs(float(carry["params"]["w"]) - 100.0) <= 0.01 + 1e-6


def test_prepare_dispatch_and_scheduler():
    accelerator = Accelerator()
    ds = RegressionDataset(16)
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    params = {"w": jnp.asarray(0.0)}
    sched_fn = optax.linear_schedule(1.0, 0.0, 10)
    p, opt, l, sched = accelerator.prepare(params, optax.sgd(0.1), loader, sched_fn)
    assert isinstance(opt, AcceleratedOptimizer)
    assert isinstance(sched, AcceleratedScheduler)
    assert opt.opt_state is not None
    sched.step()
    assert sched.step_count == 1


def test_gather_for_metrics_drops_padding():
    accelerator = Accelerator()
    ds = RegressionDataset(12)  # 12 samples, batch 8 -> tail valid 4
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    prepared = accelerator.prepare(loader)
    seen = []
    for batch in prepared:
        out = accelerator.gather_for_metrics(batch["y"])
        seen.append(np.asarray(out))
    total = np.concatenate(seen)
    assert total.shape[0] == 12  # padding dropped
    np.testing.assert_allclose(total, ds.y, rtol=1e-6)


def test_gather_for_metrics_scalar_and_error_semantics(monkeypatch):
    """VERDICT r2 weak #3: no blanket error swallowing. Scalar (0-d) leaves
    pass through un-truncated with a warning (they carry no duplicated tail
    samples; reference returns data here, accelerator.py:2420-2422), while
    genuine slice failures on batch-dim leaves propagate instead of
    silently corrupting eval metrics."""
    accelerator = Accelerator()
    ds = RegressionDataset(12)  # 12 samples, batch 8 -> tail remainder 4
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    prepared = accelerator.prepare(loader)
    for _ in prepared:
        # a scalar metric gather must keep working on the remainder batch
        out = accelerator.gather_for_metrics(jnp.asarray(5.0))
        assert float(out) == 5.0

    class _Exploding(np.ndarray):
        def __getitem__(self, item):
            raise RuntimeError("slice failed")

    bad = np.zeros((8,)).view(_Exploding)
    import accelerate_tpu.accelerator as accel_mod
    from accelerate_tpu.state import GradientState

    monkeypatch.setattr(accel_mod, "gather", lambda t: bad)

    class _FakeLoader:
        end_of_dataloader = True
        remainder = 4

    gs = GradientState()
    monkeypatch.setattr(gs, "active_dataloader", _FakeLoader())
    with pytest.raises(RuntimeError, match="slice failed"):
        accelerator.gather_for_metrics(np.zeros((8,)))


def test_accumulate_context_and_step_counter():
    accelerator = Accelerator(gradient_accumulation_steps=2)
    with accelerator.accumulate():
        assert not accelerator.sync_gradients
    with accelerator.accumulate():
        assert accelerator.sync_gradients
    assert accelerator.step == 2


def test_trigger_roundtrip():
    accelerator = Accelerator()
    assert not accelerator.check_trigger()
    accelerator.set_trigger()
    assert accelerator.check_trigger()
    assert not accelerator.check_trigger()


def test_get_state_dict_full_host_copy():
    """Reference accelerator.get_state_dict: full de-sharded named dict."""
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=1, fsdp_size=8, min_weight_size=1
        )
    )
    params = acc.prepare({"layer": {"kernel": jnp.arange(64.0).reshape(8, 8)}})
    sd = acc.get_state_dict(params)
    assert set(sd) == {"layer//kernel"}
    np.testing.assert_allclose(
        np.asarray(sd["layer//kernel"]), np.arange(64.0).reshape(8, 8)
    )


def test_memory_utils_shim_warns():
    import importlib
    import warnings

    import accelerate_tpu.memory_utils as mu

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        importlib.reload(mu)
    assert any(issubclass(x.category, FutureWarning) for x in w)
    assert hasattr(mu, "find_executable_batch_size")
