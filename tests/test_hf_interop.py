"""HF-checkpoint interop: real torch/transformers checkpoints load into
the native stacked layout with matching logits, and native params export
back into checkpoints transformers can consume.

This is the round-3 answer to VERDICT r2 missing #1 — the reference's
core capability of running *real* pretrained weights
(load_checkpoint_in_model utils/modeling.py:1608,
load_checkpoint_and_dispatch big_modeling.py:499).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    init_empty_weights,
    load_checkpoint_and_dispatch,
)
from accelerate_tpu.models import CausalLM
from accelerate_tpu.models.config import TransformerConfig
from accelerate_tpu.utils.hf_interop import (
    infer_config_from_hf,
    is_hf_checkpoint,
    save_hf_checkpoint,
)

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

_TINY = dict(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=176,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA: 2 query heads per kv head
    max_seq_len=64,
    rope_theta=500000.0,
    rms_norm_eps=1e-5,
)


_LLAMA31_ROPE_SCALING = {
    "rope_type": "llama3",
    "factor": 8.0,
    "low_freq_factor": 1.0,
    "high_freq_factor": 4.0,
    "original_max_position_embeddings": 32,
}


def _save_hf_llama(tmp_path, tie=False, dtype=None, seed=0, rope_scaling=None):
    cfg = transformers.LlamaConfig(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=_TINY["num_layers"],
        num_attention_heads=_TINY["num_heads"],
        num_key_value_heads=_TINY["num_kv_heads"],
        max_position_embeddings=_TINY["max_seq_len"],
        rope_theta=_TINY["rope_theta"],
        rope_scaling=rope_scaling,
        rms_norm_eps=_TINY["rms_norm_eps"],
        tie_word_embeddings=tie,
        attention_dropout=0.0,
    )
    torch.manual_seed(seed)
    model = transformers.LlamaForCausalLM(cfg).eval()
    if dtype is not None:
        model = model.to(dtype)
    path = str(tmp_path / "hf_llama")
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def _torch_logits(model, ids: np.ndarray) -> np.ndarray:
    with torch.no_grad():
        return model(torch.from_numpy(ids)).logits.float().numpy()


def _native_logits(config, params, ids: np.ndarray) -> np.ndarray:
    model = CausalLM(config)
    return np.asarray(
        model.apply({"params": params}, jnp.asarray(ids)), dtype=np.float32
    )


def _abstract(config):
    model = CausalLM(config)
    return init_empty_weights(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )


_IDS = np.array([[3, 17, 91, 4, 200, 11, 7, 42, 9, 128, 55, 250]], dtype=np.int32)


def test_llama_checkpoint_logits_match_torch(tmp_path):
    """An HF-layout Llama checkpoint (GQA, untied) produces the same
    logits through the native stacked model as through transformers."""
    hf_model, path = _save_hf_llama(tmp_path)
    assert is_hf_checkpoint(path)
    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.num_kv_heads == 2 and not config.tie_embeddings
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_tied_llama_checkpoint_reties_lm_head(tmp_path):
    """tie_word_embeddings checkpoints omit lm_head.weight; the loader
    re-ties from the embedding and logits still match."""
    hf_model, path = _save_hf_llama(tmp_path, tie=True)
    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.tie_embeddings
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    assert "lm_head" not in params  # native tied layout has no lm_head
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_mixtral_checkpoint_logits_match_torch(tmp_path):
    """Mixtral expert weights (experts.{e}.w1/w2/w3) stack onto the
    (L, E, ...) expert-parallel layout; dense dispatch is the exact-math
    oracle for the top-k routed forward."""
    cfg = transformers.MixtralConfig(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=_TINY["max_seq_len"],
        rope_theta=_TINY["rope_theta"],
        rms_norm_eps=_TINY["rms_norm_eps"],
        router_jitter_noise=0.0,
        attention_dropout=0.0,
    )
    torch.manual_seed(1)
    hf_model = transformers.MixtralForCausalLM(cfg).eval()
    path = str(tmp_path / "hf_mixtral")
    hf_model.save_pretrained(path, safe_serialization=True)

    config = infer_config_from_hf(path, attention_impl="xla", moe_dispatch="dense")
    assert config.num_experts == 4
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=5e-4, atol=5e-4)


def test_gspmd_and_device_map_paths_identical(tmp_path):
    """The same HF checkpoint through the GSPMD sharded load and the cpu
    device_map path yields bitwise-identical WEIGHTS (VERDICT r2 'done'
    criterion for interop); forward logits agree to float32 noise — exact
    bitwise logit equality across different shardings is impossible in
    principle (sharded matmuls change the reduction order)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin

    _, path = _save_hf_llama(tmp_path)
    config = infer_config_from_hf(path, attention_impl="xla")
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(fsdp_size=8, min_weight_size=16)
    )
    sharded = load_checkpoint_and_dispatch(
        _abstract(config), path, mesh=acc.mesh,
        plugin=acc.state.parallelism_plugin,
    )
    host = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    flat_host = {
        str(p): l for p, l in jax.tree_util.tree_leaves_with_path(host)
    }
    for p, a in jax.tree_util.tree_leaves_with_path(sharded):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(flat_host[str(p)])
        )
    logits_sharded = _native_logits(config, sharded, _IDS)
    logits_host = _native_logits(config, host, _IDS)
    np.testing.assert_allclose(logits_sharded, logits_host, rtol=1e-5, atol=1e-6)


def test_save_hf_checkpoint_loads_in_transformers(tmp_path):
    """Native params export to an HF-layout checkpoint that transformers
    loads directly, with matching logits (the reverse interop)."""
    config = TransformerConfig(**_TINY, attention_impl="xla")
    model = CausalLM(config)
    params = model.init(
        jax.random.PRNGKey(2), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    out = str(tmp_path / "export")
    save_hf_checkpoint(params, config, out)
    assert os.path.isfile(os.path.join(out, "model.safetensors"))
    assert json.load(open(os.path.join(out, "config.json")))["model_type"] == "llama"

    hf_model = transformers.LlamaForCausalLM.from_pretrained(out).eval()
    theirs = _torch_logits(hf_model, _IDS)
    ours = _native_logits(config, params, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_hf_round_trip_native_identity(tmp_path):
    """native -> HF file -> native round-trip is exact (bitwise)."""
    config = TransformerConfig(**_TINY, attention_impl="xla")
    model = CausalLM(config)
    params = model.init(
        jax.random.PRNGKey(3), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    out = str(tmp_path / "rt")
    save_hf_checkpoint(params, config, out)
    reloaded = load_checkpoint_and_dispatch(
        _abstract(config), out, device_map={"": "cpu"}
    )
    flat_a = jax.tree_util.tree_leaves_with_path(params)
    flat_b = {str(p): l for p, l in jax.tree_util.tree_leaves_with_path(reloaded)}
    for p, a in flat_a:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(flat_b[str(p)]))


def test_lookalike_arch_rejected(tmp_path):
    """Architectures sharing the model.layers.* key convention but holding
    parameters the mapping would drop (qkv biases etc.) must fail loudly,
    not load garbage (code-review r3 finding)."""
    from safetensors.numpy import save_file

    _, path = _save_hf_llama(tmp_path)
    config = infer_config_from_hf(path, attention_impl="xla")

    # 1) unknown model_type in config.json -> infer_config_from_hf raises
    # (qwen2 AND gemma moved to SUPPORTED in round 4; phi3 stays a
    # lookalike — fused qkv_proj the mapping would drop)
    cfg_path = os.path.join(path, "config.json")
    hf_cfg = json.load(open(cfg_path))
    hf_cfg["model_type"] = "phi3"
    json.dump(hf_cfg, open(cfg_path, "w"))
    with pytest.raises(ValueError, match="model_type"):
        infer_config_from_hf(path)
    hf_cfg["model_type"] = "llama"
    json.dump(hf_cfg, open(cfg_path, "w"))

    # 1b) rope_scaling types the native rope does NOT implement (yarn,
    # longrope, ...) must be rejected, not silently produce diverging
    # logits (llama3/linear ARE implemented — tested below)
    hf_cfg["rope_scaling"] = {"rope_type": "yarn", "factor": 8.0}
    json.dump(hf_cfg, open(cfg_path, "w"))
    with pytest.raises(ValueError, match="rope_scaling"):
        infer_config_from_hf(path)
    del hf_cfg["rope_scaling"]
    json.dump(hf_cfg, open(cfg_path, "w"))

    # 2) extra tensors the mapping never consumes -> load raises
    extra = os.path.join(path, "model.safetensors")
    from safetensors import safe_open

    with safe_open(extra, framework="numpy") as f:
        named = {k: f.get_tensor(k) for k in f.keys()}
    named["model.layers.0.self_attn.q_proj.bias"] = np.zeros(
        (_TINY["hidden_size"],), np.float32
    )
    save_file(named, extra)
    with pytest.raises(ValueError, match="not consumed"):
        load_checkpoint_and_dispatch(
            _abstract(config), path, device_map={"": "cpu"}, config=config,
            hf_format=True,
        )


def test_llama31_rope_scaled_checkpoint_logits_match_torch(tmp_path):
    """A Llama-3.1-style checkpoint (rope_scaling rope_type="llama3")
    loads with the scaled rope applied and logits still match transformers
    — closing VERDICT r3 missing #1 (previously these checkpoints were
    rejected; most currently-shipping Llama weights are 3.1+)."""
    hf_model, path = _save_hf_llama(
        tmp_path, seed=6, rope_scaling=_LLAMA31_ROPE_SCALING
    )

    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.rope_scaling is not None
    assert config.rope_scaling.get("rope_type") == "llama3"
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    # the scaling must actually change the forward (guard against a
    # silently-ignored rope_scaling reproducing the old bug class)
    import dataclasses

    plain = dataclasses.replace(config, rope_scaling=None)
    unscaled = _native_logits(plain, params, _IDS)
    assert np.abs(unscaled - theirs).max() > np.abs(ours - theirs).max()


def test_llama31_rope_scaled_generation_matches_torch_greedy(tmp_path):
    """The KV-cache decode path applies rope scaling too (prefill AND the
    per-token steps go through the scaled frequencies): greedy generation
    must reproduce transformers'."""
    from accelerate_tpu.models.generation import generate

    hf_model, path = _save_hf_llama(
        tmp_path, seed=11, rope_scaling=_LLAMA31_ROPE_SCALING
    )

    config = infer_config_from_hf(path, attention_impl="xla")
    model = CausalLM(config)
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    prompt = jnp.asarray(_IDS[:, :8])
    ours = generate(model, params, prompt, max_new_tokens=6)
    with torch.no_grad():
        theirs = hf_model.generate(
            torch.from_numpy(np.asarray(prompt).copy()),
            max_new_tokens=6, do_sample=False,
        )
    # guard the comparison alignment: an early HF eos stop would silently
    # shift the [-6:] window onto prompt tokens (review finding)
    assert theirs.shape[1] == prompt.shape[1] + 6, theirs.shape
    assert np.asarray(ours)[0, -6:].tolist() == theirs[0, -6:].tolist()


def test_linear_rope_scaling_matches_torch(tmp_path):
    """Position-interpolation ("linear") rope scaling also logits-matches
    transformers."""
    cfg = transformers.LlamaConfig(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=_TINY["num_layers"],
        num_attention_heads=_TINY["num_heads"],
        num_key_value_heads=_TINY["num_kv_heads"],
        max_position_embeddings=_TINY["max_seq_len"],
        rope_theta=_TINY["rope_theta"],
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        rms_norm_eps=_TINY["rms_norm_eps"],
        attention_dropout=0.0,
    )
    torch.manual_seed(7)
    hf_model = transformers.LlamaForCausalLM(cfg).eval()
    path = str(tmp_path / "hf_llama_linear")
    hf_model.save_pretrained(path, safe_serialization=True)

    config = infer_config_from_hf(path, attention_impl="xla")
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_sharded_hf_checkpoint_with_index(tmp_path):
    """Multi-file HF checkpoints (index json + shards) assemble correctly."""
    config = TransformerConfig(**_TINY, attention_impl="xla")
    model = CausalLM(config)
    params = model.init(
        jax.random.PRNGKey(4), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    out = str(tmp_path / "sharded")
    save_hf_checkpoint(params, config, out, max_shard_size=64 * 1024)
    assert os.path.isfile(os.path.join(out, "model.safetensors.index.json"))
    reloaded = load_checkpoint_and_dispatch(
        _abstract(config), out, device_map={"": "cpu"}
    )
    ours = _native_logits(config, reloaded, _IDS)
    ref = _native_logits(config, params, _IDS)
    np.testing.assert_array_equal(ours, ref)


def test_bf16_checkpoint_loads(tmp_path):
    """Real hub snapshots ship bf16 — the whole assembly path (transpose,
    stack, contiguous copies) must work on ml_dtypes bf16 numpy arrays and
    match torch's bf16 forward."""
    hf_model, path = _save_hf_llama(tmp_path, dtype=torch.bfloat16, seed=5)
    config = infer_config_from_hf(path, attention_impl="xla", dtype="bfloat16")
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    # loaded leaves keep the checkpoint dtype
    leaf = jax.tree_util.tree_leaves(params)[0]
    assert jnp.asarray(leaf).dtype == jnp.bfloat16
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    # bf16 end-to-end: coarser tolerance than the fp32 tests
    np.testing.assert_allclose(ours, theirs, rtol=0.1, atol=0.12)
    # and the argmax token predictions should essentially agree
    agree = np.mean(ours.argmax(-1) == theirs.argmax(-1))
    assert agree > 0.9, agree


def test_hf_load_onto_tp_fsdp_mesh(tmp_path):
    """HF weights stream onto a tp x fsdp mesh: the embedding lands on its
    (vocab=(tp,zero)) layout, projections pick up tp, and the forward
    still matches torch."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy

    from accelerate_tpu.parallel.sharding import get_logical_specs, unbox_params

    hf_model, path = _save_hf_llama(tmp_path)
    config = infer_config_from_hf(path, attention_impl="xla")
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=2, fsdp_size=2, tp_size=2, min_weight_size=16,
            sharding_strategy=ShardingStrategy.FULL_SHARD,
        )
    )
    abstract = _abstract(config)
    # logical specs come from the BOXED tree; the loaded tree is unboxed
    params = load_checkpoint_and_dispatch(
        unbox_params(abstract), path, mesh=acc.mesh,
        plugin=acc.state.parallelism_plugin,
        logical_specs=get_logical_specs(abstract),
    )
    embed_spec = params["embed"]["embedding"].sharding.spec
    flat = jax.tree.leaves(tuple(embed_spec))
    assert "tp" in flat and "fsdp" in flat, embed_spec  # vocab carries both
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=5e-4, atol=5e-4)


# ---------------------------------------------------------------------- #
# classic-arch interop: GPT-2 (VERDICT r3 missing #3)
# ---------------------------------------------------------------------- #
def _save_hf_gpt2(tmp_path, seed=8):
    cfg = transformers.GPT2Config(
        vocab_size=_TINY["vocab_size"],
        n_embd=64,
        n_inner=None,  # 4*n_embd
        n_layer=2,
        n_head=4,
        n_positions=64,
        layer_norm_epsilon=1e-5,
        activation_function="gelu_new",
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(seed)
    model = transformers.GPT2LMHeadModel(cfg).eval()
    path = str(tmp_path / "hf_gpt2")
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def test_gpt2_checkpoint_logits_match_torch(tmp_path):
    """A real HF GPT-2 checkpoint (learned positions, LayerNorm, biases,
    fused c_attn, GELU) loads into the faithful GPT2LM with logits
    matching transformers — the classic-arch boundary decision: GPT-2 IS
    supported; BERT/T5 remain documented exclusions."""
    from accelerate_tpu.models import GPT2LM, causal_model_for

    hf_model, path = _save_hf_gpt2(tmp_path)
    assert is_hf_checkpoint(path)
    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.arch == "gpt2" and config.tie_embeddings
    model = causal_model_for(config)
    assert isinstance(model, GPT2LM)
    abstract = init_empty_weights(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    params = load_checkpoint_and_dispatch(
        abstract, path, device_map={"": "cpu"}, config=config,
    )
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(_IDS)), dtype=np.float32
    )
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_gpt2_unprefixed_hub_layout_loads(tmp_path):
    """The canonical hub gpt2/gpt2-medium/... safetensors store the BASE
    model's keys unprefixed (``wte.weight``, ``h.0.attn.c_attn.weight``) —
    transformers re-prefixes them via ``base_model_prefix`` at load. A
    checkpoint rewritten to that layout must detect as HF and load with
    identical logits (ADVICE r4 medium)."""
    from safetensors import safe_open
    from safetensors.numpy import save_file

    from accelerate_tpu.models import causal_model_for

    hf_model, path = _save_hf_gpt2(tmp_path)
    # rewrite to the hub's unprefixed base-model layout
    src = os.path.join(path, "model.safetensors")
    with safe_open(src, framework="numpy") as f:
        tensors = {
            k.removeprefix("transformer."): f.get_tensor(k) for k in f.keys()
        }
    assert any(k.startswith("h.0.") for k in tensors), "rewrite had no effect"
    unpref = str(tmp_path / "hf_gpt2_unprefixed")
    os.makedirs(unpref)
    save_file(tensors, os.path.join(unpref, "model.safetensors"))
    with open(os.path.join(path, "config.json")) as f:
        cfg_json = f.read()
    with open(os.path.join(unpref, "config.json"), "w") as f:
        f.write(cfg_json)

    assert is_hf_checkpoint(unpref)
    config = infer_config_from_hf(unpref, attention_impl="xla")
    model = causal_model_for(config)
    abstract = init_empty_weights(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    params = load_checkpoint_and_dispatch(
        abstract, unpref, device_map={"": "cpu"}, config=config,
    )
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(_IDS)), dtype=np.float32
    )
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_gpt2_generate_matches_torch_greedy(tmp_path):
    """The GPT-2 KV-cache decode path (wpe position counter + per-layer
    cache) reproduces transformers' greedy generation."""
    from accelerate_tpu.models import causal_model_for
    from accelerate_tpu.models.generation import generate

    hf_model, path = _save_hf_gpt2(tmp_path, seed=9)
    config = infer_config_from_hf(path, attention_impl="xla")
    model = causal_model_for(config)
    abstract = init_empty_weights(
        lambda: model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    params = load_checkpoint_and_dispatch(
        abstract, path, device_map={"": "cpu"}, config=config,
    )
    prompt = jnp.asarray(_IDS[:, :8])
    ours = generate(model, params, prompt, max_new_tokens=6)
    with torch.no_grad():
        theirs = hf_model.generate(
            torch.from_numpy(np.asarray(prompt).copy()),
            max_new_tokens=6, do_sample=False,
        )
    assert np.asarray(ours)[0, -6:].tolist() == theirs[0, -6:].tolist()


def test_gpt2_export_loads_in_transformers(tmp_path):
    """Native GPT2LM params export to an HF checkpoint transformers loads
    with matching logits (reverse interop, arch-dispatched plan)."""
    from accelerate_tpu.models import GPT2LM
    from accelerate_tpu.models.config import TransformerConfig

    config = TransformerConfig.gpt2(
        vocab_size=_TINY["vocab_size"], hidden_size=64, intermediate_size=256,
        num_layers=2, num_heads=4, max_seq_len=64, attention_impl="xla",
    )
    model = GPT2LM(config)
    params = model.init(
        jax.random.PRNGKey(10), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    out = str(tmp_path / "gpt2_export")
    save_hf_checkpoint(params, config, out)
    assert json.load(open(os.path.join(out, "config.json")))["model_type"] == "gpt2"
    hf_model = transformers.GPT2LMHeadModel.from_pretrained(out).eval()
    theirs = _torch_logits(hf_model, _IDS)
    ours = np.asarray(
        model.apply({"params": params}, jnp.asarray(_IDS)), dtype=np.float32
    )
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_gpt2_attention_math_variants_rejected(tmp_path):
    """GPT-2 variants with identical tensor layouts but different
    attention math (scale_attn_by_inverse_layer_idx etc.) must fail at
    config time, not silently diverge (code-review r4 finding)."""
    _, path = _save_hf_gpt2(tmp_path)
    cfg_path = os.path.join(path, "config.json")
    hf_cfg = json.load(open(cfg_path))
    hf_cfg["scale_attn_by_inverse_layer_idx"] = True
    json.dump(hf_cfg, open(cfg_path, "w"))
    with pytest.raises(ValueError, match="attention math"):
        infer_config_from_hf(path)


def _save_hf_qwen2(tmp_path, seed=12, **cfg_kw):
    cfg_kw.setdefault("use_sliding_window", False)
    cfg = transformers.Qwen2Config(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=_TINY["num_layers"],
        num_attention_heads=_TINY["num_heads"],
        num_key_value_heads=_TINY["num_kv_heads"],
        max_position_embeddings=_TINY["max_seq_len"],
        rope_theta=_TINY["rope_theta"],
        rms_norm_eps=_TINY["rms_norm_eps"],
        tie_word_embeddings=False,
        attention_dropout=0.0,
        **cfg_kw,
    )
    torch.manual_seed(seed)
    model = transformers.Qwen2ForCausalLM(cfg).eval()
    path = str(tmp_path / "hf_qwen2")
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def test_qwen2_checkpoint_logits_match_torch(tmp_path):
    """Qwen2 (Llama layout + q/k/v biases) loads through the qkv_bias
    mapping with logits matching transformers — round 4 moves the family
    from rejected-lookalike to supported."""
    hf_model, path = _save_hf_qwen2(tmp_path)

    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.qkv_bias
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    # the bias leaves really exist and carry the checkpoint values
    assert "bias" in params["layers"]["attn"]["q_proj"]
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)

    # round-trip: native export declares model_type qwen2 and transformers
    # loads it back with the biases intact
    out = str(tmp_path / "qwen2_export")
    save_hf_checkpoint(params, config, out)
    assert json.load(open(os.path.join(out, "config.json")))["model_type"] == "qwen2"
    hf2 = transformers.Qwen2ForCausalLM.from_pretrained(out).eval()
    np.testing.assert_allclose(
        _torch_logits(hf2, _IDS), theirs, rtol=2e-4, atol=2e-4
    )


def test_qwen2_sliding_window_logits_match_torch(tmp_path):
    """use_sliding_window=true with every layer sliding
    (max_window_layers=0) loads with the banded causal mask active —
    logits match transformers AND differ from the full-causal run, so a
    loader silently dropping the band cannot pass (r5: the r4 rejection
    flipped to support)."""
    hf_model, path = _save_hf_qwen2(
        tmp_path, seed=13, use_sliding_window=True, sliding_window=4,
        max_window_layers=0,
    )
    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.sliding_window == 4 and config.qkv_bias
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    # the band is live: full-causal logits must differ beyond tolerance
    import dataclasses

    full = _native_logits(
        dataclasses.replace(config, sliding_window=None), params, _IDS
    )
    assert float(np.max(np.abs(full - ours))) > 1e-2

    # round-trip: the export re-declares use_sliding_window with every
    # layer sliding, and infer_config_from_hf reads the band back
    out = str(tmp_path / "qwen2_sw_export")
    save_hf_checkpoint(params, config, out)
    cfg_json = json.load(open(os.path.join(out, "config.json")))
    assert cfg_json["use_sliding_window"] and cfg_json["sliding_window"] == 4
    assert infer_config_from_hf(out).sliding_window == 4


def test_qwen2_mixed_window_layers_load(tmp_path):
    """A genuine per-layer sliding/full mix rides the layer scan as
    ``layer_windows`` (r5: the traced per-layer band) — logits must match
    transformers, which applies the window only to the sliding layers."""
    hf_model, path = _save_hf_qwen2(
        tmp_path, seed=14, use_sliding_window=True, sliding_window=4,
        max_window_layers=1,  # layer 0 full, layer 1 sliding
    )
    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.sliding_window is None
    assert config.layer_windows == (None, 4)
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    # the mix is live: all-full logits must differ beyond tolerance
    import dataclasses

    full = _native_logits(
        dataclasses.replace(config, layer_windows=(None, None)), params, _IDS
    )
    assert float(np.max(np.abs(full - ours))) > 1e-2


def test_sliding_layers_with_null_window_rejected(tmp_path):
    """layer_types declaring sliding_attention layers while config
    sliding_window is null must fail loudly instead of silently loading
    as full attention (the load-or-reject-loudly policy for
    semantics-changing fields)."""

    def _write(name, cfg):
        d = tmp_path / name
        d.mkdir()
        (d / "config.json").write_text(json.dumps(cfg))
        return str(d)

    base = dict(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=2,
        num_attention_heads=_TINY["num_heads"],
        num_key_value_heads=_TINY["num_kv_heads"],
        max_position_embeddings=_TINY["max_seq_len"],
    )
    qwen = _write("qwen2_null_window", {
        **base,
        "model_type": "qwen2",
        "use_sliding_window": True,
        "sliding_window": None,
        "layer_types": ["full_attention", "sliding_attention"],
    })
    with pytest.raises(ValueError, match="sliding_window is null"):
        infer_config_from_hf(qwen)

    # gemma2's default pattern alternates sliding/full, so an explicit
    # null window is the same contradiction
    gemma2 = _write("gemma2_null_window", {
        **base,
        "model_type": "gemma2",
        "sliding_window": None,
    })
    with pytest.raises(ValueError, match="sliding_window is null"):
        infer_config_from_hf(gemma2)


def _save_hf_mistral(tmp_path, seed=15, **cfg_kw):
    cfg = transformers.MistralConfig(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=_TINY["num_layers"],
        num_attention_heads=_TINY["num_heads"],
        num_key_value_heads=_TINY["num_kv_heads"],
        max_position_embeddings=_TINY["max_seq_len"],
        rope_theta=_TINY["rope_theta"],
        rms_norm_eps=_TINY["rms_norm_eps"],
        tie_word_embeddings=False,
        attention_dropout=0.0,
        **cfg_kw,
    )
    torch.manual_seed(seed)
    model = transformers.MistralForCausalLM(cfg).eval()
    path = str(tmp_path / "hf_mistral")
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def test_mistral_sliding_window_logits_match_torch(tmp_path):
    """Mistral (the Llama layout + an every-layer sliding window) loads
    with the band active and logits matching transformers — the family
    the r4 matrix listed as unsupported."""
    hf_model, path = _save_hf_mistral(tmp_path, sliding_window=4)
    assert is_hf_checkpoint(path)
    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.sliding_window == 4 and not config.qkv_bias
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)
    # the band is live (see qwen2 test)
    import dataclasses

    full = _native_logits(
        dataclasses.replace(config, sliding_window=None), params, _IDS
    )
    assert float(np.max(np.abs(full - ours))) > 1e-2

    # round-trip: a windowed Llama-layout export IS model_type mistral
    out = str(tmp_path / "mistral_export")
    save_hf_checkpoint(params, config, out)
    assert json.load(open(os.path.join(out, "config.json")))["model_type"] == "mistral"
    hf2 = transformers.MistralForCausalLM.from_pretrained(out).eval()
    np.testing.assert_allclose(
        _torch_logits(hf2, _IDS), theirs, rtol=2e-4, atol=2e-4
    )


def _save_hf_gemma2(tmp_path, seed=21, **cfg_kw):
    cfg = transformers.Gemma2Config(
        vocab_size=_TINY["vocab_size"],
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=16,
        # decoupled from head_dim (16) so the scale switch is observable;
        # production caps (50/30) are deep in tanh's linear region at toy
        # scale, so tiny caps keep the soft-capping itself observable too
        query_pre_attn_scalar=32.0,
        sliding_window=4,
        attn_logit_softcapping=1.0,
        final_logit_softcapping=5.0,
        max_position_embeddings=64,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        hidden_activation="gelu_pytorch_tanh",
        attention_dropout=0.0,
        **cfg_kw,
    )
    torch.manual_seed(seed)
    # transformers' default sdpa path SILENTLY DROPS the attention
    # softcap (sdpa_attention_forward has no softcap kwarg) — eager is
    # the faithful Gemma-2 math this port implements
    cfg._attn_implementation = "eager"
    model = transformers.Gemma2ForCausalLM(cfg).eval()
    # default-init scores are ~1e-3: every cap/scale switch would be
    # numerically invisible and the logits match would prove nothing
    # about them. Amplify q/k and the tied embedding so scores/logits sit
    # in the caps' ACTIVE region — big enough to bend, not so big that
    # tanh saturates every logit to the cap and greedy argmax becomes a
    # float-noise coin flip between tied tokens.
    with torch.no_grad():
        for layer in model.model.layers:
            layer.self_attn.q_proj.weight *= 10.0
            layer.self_attn.k_proj.weight *= 10.0
        model.model.embed_tokens.weight *= 4.0
    path = str(tmp_path / "hf_gemma2")
    model.save_pretrained(path, safe_serialization=True)
    return model, path


def test_gemma2_checkpoint_logits_match_torch(tmp_path):
    """Gemma-2 (r5: the family the r4 matrix rejected) loads with ALL its
    math live — 4 offset-norms per block, query_pre_attn_scalar scale,
    attn + final tanh soft-capping, and the alternating sliding/full
    layer pattern riding the scan as a traced per-layer window — with
    logits matching transformers."""
    from accelerate_tpu.models import causal_model_for

    hf_model, path = _save_hf_gemma2(tmp_path)
    assert is_hf_checkpoint(path)
    config = infer_config_from_hf(path, attention_impl="xla")
    # default Gemma-2 pattern: layer 0 sliding, layer 1 full
    assert config.layer_windows == (4, None)
    assert config.post_norms and config.attn_softcap == 1.0
    assert config.query_pre_attn_scalar == 32.0 and config.tie_embeddings
    model = causal_model_for(config)
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}, config=config,
    )
    assert "post_attn_norm" in params["layers"]
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)
    # every switch is live: turning each one off must move the logits
    import dataclasses

    for off in (
        {"layer_windows": (None, None)},
        {"attn_softcap": None},
        {"final_softcap": None},
        {"query_pre_attn_scalar": None},
    ):
        perturbed = _native_logits(
            dataclasses.replace(config, **off), params, _IDS
        )
        assert float(np.max(np.abs(perturbed - ours))) > 1e-3, off

    # export round-trip: transformers loads the native save as gemma2
    out = str(tmp_path / "gemma2_export")
    save_hf_checkpoint(params, config, out)
    cfg_json = json.load(open(os.path.join(out, "config.json")))
    assert cfg_json["model_type"] == "gemma2"
    assert cfg_json["layer_types"] == ["sliding_attention", "full_attention"]
    hf2 = transformers.Gemma2ForCausalLM.from_pretrained(
        out, attn_implementation="eager"
    ).eval()
    np.testing.assert_allclose(
        _torch_logits(hf2, _IDS), theirs, rtol=3e-4, atol=3e-4
    )


def test_gemma2_generate_matches_torch_greedy(tmp_path):
    """The KV-cache decode path under per-layer windows + soft-capping
    reproduces transformers' greedy generation token-for-token."""
    from accelerate_tpu.models import causal_model_for
    from accelerate_tpu.models.generation import generate

    hf_model, path = _save_hf_gemma2(tmp_path, seed=22)
    config = infer_config_from_hf(path, attention_impl="xla")
    model = causal_model_for(config)
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}, config=config,
    )
    prompt = jnp.asarray(_IDS[:, :8])
    ours = generate(model, params, prompt, max_new_tokens=8)
    with torch.no_grad():
        theirs = hf_model.generate(
            torch.from_numpy(np.asarray(prompt).copy()),
            max_new_tokens=8, do_sample=False,
        )
    assert np.asarray(ours)[0, -8:].tolist() == theirs[0, -8:].tolist()


def test_mistral_generate_matches_torch_greedy(tmp_path):
    """The KV-cache decode path anchors the band at the GLOBAL decode
    position (not the cache buffer end): greedy generation past the
    window must reproduce transformers token-for-token."""
    from accelerate_tpu.models import causal_model_for
    from accelerate_tpu.models.generation import generate

    hf_model, path = _save_hf_mistral(tmp_path, seed=16, sliding_window=4)
    config = infer_config_from_hf(path, attention_impl="xla")
    model = causal_model_for(config)
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}, config=config,
    )
    prompt = jnp.asarray(_IDS[:, :8])
    ours = generate(model, params, prompt, max_new_tokens=8)
    with torch.no_grad():
        theirs = hf_model.generate(
            torch.from_numpy(np.asarray(prompt).copy()),
            max_new_tokens=8, do_sample=False,
        )
    assert np.asarray(ours)[0, -8:].tolist() == theirs[0, -8:].tolist()


def test_unrepresentable_export_combos_rejected():
    """Switch combinations no HF model_type represents must fail at
    export-dispatch time, before any shard is written (code-review r4):
    partial Gemma switch sets, gemma+qkv_bias, moe+gemma, untied gemma."""
    from accelerate_tpu.utils.hf_interop import _export_arch

    ok = TransformerConfig(**_TINY, attention_impl="xla")
    assert _export_arch(ok) == ("LlamaForCausalLM", "llama")
    gemma = TransformerConfig(
        **_TINY, attention_impl="xla", norm_offset=True,
        mlp_activation="gelu_tanh", embed_scale=True, tie_embeddings=True,
    )
    assert _export_arch(gemma) == ("GemmaForCausalLM", "gemma")
    import dataclasses

    with pytest.raises(ValueError, match="partial Gemma"):
        _export_arch(dataclasses.replace(gemma, embed_scale=False))
    with pytest.raises(ValueError, match="combination"):
        _export_arch(dataclasses.replace(gemma, qkv_bias=True))
    with pytest.raises(ValueError, match="combination"):
        _export_arch(dataclasses.replace(
            gemma, num_experts=4, moe_dispatch="dense"))
    with pytest.raises(ValueError, match="tied"):
        _export_arch(dataclasses.replace(gemma, tie_embeddings=False))


def test_moe_with_qkv_bias_export_rejected(tmp_path):
    """num_experts>0 + qkv_bias=True matches no HF model_type; a
    mixtral-labeled export would silently drop the biases in transformers
    — save must fail loudly (code-review r4 finding)."""
    config = TransformerConfig(
        **_TINY, attention_impl="xla", num_experts=4, num_experts_per_tok=2,
        qkv_bias=True, moe_dispatch="dense",
    )
    from accelerate_tpu.models import CausalLM as _CausalLM

    model = _CausalLM(config)
    params = model.init(
        jax.random.PRNGKey(14), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    with pytest.raises(ValueError, match="combination"):
        save_hf_checkpoint(params, config, str(tmp_path / "bad"))


def test_gemma_checkpoint_logits_match_torch(tmp_path):
    """Gemma v1 (Llama key layout; offset RMSNorm, tanh-GELU gate,
    sqrt(h)-scaled embeddings, explicit head_dim, tied heads) loads with
    logits matching transformers, and exports back as model_type gemma."""
    cfg = transformers.GemmaConfig(
        vocab_size=_TINY["vocab_size"],
        hidden_size=_TINY["hidden_size"],
        intermediate_size=_TINY["intermediate_size"],
        num_hidden_layers=_TINY["num_layers"],
        num_attention_heads=_TINY["num_heads"],
        num_key_value_heads=_TINY["num_kv_heads"],
        head_dim=32,  # DECOUPLED: != hidden/num_heads (= 16) like real Gemma
        max_position_embeddings=_TINY["max_seq_len"],
        rope_theta=10000.0,
        rms_norm_eps=_TINY["rms_norm_eps"],
        attention_dropout=0.0,
    )
    torch.manual_seed(15)
    hf_model = transformers.GemmaForCausalLM(cfg).eval()
    path = str(tmp_path / "hf_gemma")
    hf_model.save_pretrained(path, safe_serialization=True)

    config = infer_config_from_hf(path, attention_impl="xla")
    assert config.norm_offset and config.embed_scale
    assert config.mlp_activation == "gelu_tanh" and config.tie_embeddings
    assert config.head_dim == 32
    params = load_checkpoint_and_dispatch(
        _abstract(config), path, device_map={"": "cpu"}
    )
    ours = _native_logits(config, params, _IDS)
    theirs = _torch_logits(hf_model, _IDS)
    np.testing.assert_allclose(ours, theirs, rtol=3e-4, atol=3e-4)

    out = str(tmp_path / "gemma_export")
    save_hf_checkpoint(params, config, out)
    assert json.load(open(os.path.join(out, "config.json")))["model_type"] == "gemma"
    hf2 = transformers.GemmaForCausalLM.from_pretrained(out).eval()
    np.testing.assert_allclose(
        _torch_logits(hf2, _IDS), theirs, rtol=3e-4, atol=3e-4
    )


def test_gemma3_rejected(tmp_path):
    """Gemma-3 qk-norms / dual rope bases are not implemented —
    model_type gemma3 must be rejected at config time, before any tensor
    loads (Gemma-2 loads since r5)."""
    _, path = _save_hf_llama(tmp_path)
    cfg_path = os.path.join(path, "config.json")
    hf_cfg = json.load(open(cfg_path))
    hf_cfg["model_type"] = "gemma3"
    json.dump(hf_cfg, open(cfg_path, "w"))
    with pytest.raises(ValueError, match="gemma3"):
        infer_config_from_hf(path)
