"""Tests for pytree utilities + host-level collectives (reference
test_utils/scripts/test_ops.py + tests/test_utils.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.utils import (
    broadcast,
    broadcast_object_list,
    concatenate,
    convert_to_fp32,
    find_batch_size,
    gather,
    gather_object,
    get_data_structure,
    initialize_tensors,
    pad_across_processes,
    pad_input_tensors,
    recursively_apply,
    reduce,
    send_to_device,
    slice_tensors,
)


def test_recursively_apply_nested():
    data = {"a": jnp.ones(3), "b": [jnp.zeros(2), "keep"], "c": (1, jnp.ones(1))}
    out = recursively_apply(lambda x: x + 1, data)
    assert float(out["a"][0]) == 2.0
    assert out["b"][1] == "keep"
    assert out["c"][0] == 1


def test_send_to_device():
    batch = {"x": np.ones((4, 2), dtype=np.float32), "y": np.zeros(4)}
    out = send_to_device(batch, jax.devices()[0])
    assert isinstance(out["x"], jax.Array)
    assert out["x"].devices() == {jax.devices()[0]}


def test_send_to_device_skip_keys():
    batch = {"x": np.ones(2), "meta": np.zeros(2)}
    out = send_to_device(batch, jax.devices()[0], skip_keys=["meta"])
    assert isinstance(out["meta"], np.ndarray)


def test_data_structure_roundtrip():
    data = {"a": jnp.ones((2, 3)), "b": [jnp.zeros(5, dtype=jnp.int32)]}
    structure = get_data_structure(data)
    empty = initialize_tensors(structure)
    assert empty["a"].shape == (2, 3)
    assert empty["b"][0].dtype == jnp.int32


def test_find_batch_size():
    assert find_batch_size({"x": jnp.ones((7, 2))}) == 7
    assert find_batch_size({"x": 3}) is None


def test_slice_concat():
    data = {"x": jnp.arange(10)}
    sliced = slice_tensors(data, slice(0, 4))
    assert sliced["x"].shape == (4,)
    merged = concatenate([sliced, sliced])
    assert merged["x"].shape == (8,)


def test_convert_to_fp32():
    data = {"a": jnp.ones(2, dtype=jnp.bfloat16), "b": jnp.ones(2, dtype=jnp.int32)}
    out = convert_to_fp32(data)
    assert out["a"].dtype == jnp.float32
    assert out["b"].dtype == jnp.int32


def test_gather_single_process_sharded_array():
    """gather on a globally-sharded array returns the full array."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from accelerate_tpu import AcceleratorState

    state = AcceleratorState()
    x = jax.device_put(
        jnp.arange(16.0).reshape(8, 2), NamedSharding(state.mesh, P("dp", None))
    )
    gathered = gather(x)
    assert gathered.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(gathered), np.arange(16.0).reshape(8, 2))


def test_gather_object_single():
    assert gather_object({"k": 1}) == [{"k": 1}]


def test_broadcast_single():
    x = {"a": jnp.ones(3)}
    out = broadcast(x)
    np.testing.assert_allclose(np.asarray(out["a"]), 1.0)


def test_broadcast_object_list_single():
    objs = ["a", 1]
    assert broadcast_object_list(objs) == ["a", 1]


def test_reduce_single():
    out = reduce(jnp.ones(3), "sum")
    np.testing.assert_allclose(np.asarray(out), 1.0)


def test_pad_input_tensors():
    batch = {"x": jnp.ones((5, 2))}
    out = pad_input_tensors(batch, batch_size=5, num_processes=4)
    assert out["x"].shape == (8, 2)
    # padded rows repeat the last row
    np.testing.assert_allclose(
        np.asarray(out["x"][5:]), np.tile(np.asarray(out["x"][4:5]), (3, 1))
    )


def test_pad_across_processes_single_noop():
    x = jnp.ones((3, 2))
    assert pad_across_processes(x) is x


def test_tqdm_wrapper_main_process_only():
    from accelerate_tpu.utils.tqdm import tqdm

    bar = tqdm(range(3), main_process_only=True)
    assert list(bar) == [0, 1, 2]


def test_compare_versions():
    from accelerate_tpu.utils.versions import compare_versions, is_jax_version

    assert compare_versions("jax", ">=", "0.4")
    assert not compare_versions("jax", "<", "0.4")
    assert is_jax_version(">", "0.1")
    import pytest as _pytest

    with _pytest.raises(ValueError):
        compare_versions("jax", "~=", "1.0")


def test_join_uneven_inputs_overrides_even_batches():
    import jax.numpy as jnp

    from accelerate_tpu import Accelerator, DataLoader

    class DS:
        def __len__(self):
            return 20  # not divisible by global batch

        def __getitem__(self, i):
            return {"x": jnp.ones((2,)) * i}

    acc = Accelerator()
    dl = acc.prepare_data_loader(DataLoader(DS(), batch_size=8))
    assert dl.batch_sampler.even_batches is True
    with acc.join_uneven_inputs([None], even_batches=False):
        assert dl.batch_sampler.even_batches is False
    assert dl.batch_sampler.even_batches is True
