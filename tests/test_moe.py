"""Sparse MoE dispatch tests (VERDICT r1 next#10): the capacity schedule
must match the dense oracle exactly when nothing drops, degrade gracefully
under tight capacity, and train under expert-parallel sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import CausalLM, TransformerConfig
from accelerate_tpu.ops.moe import (
    expert_capacity,
    load_balancing_loss,
    moe_dispatch_combine,
    no_drop_capacity_factor,
    ragged_ep_supported,
)

# the ragged EP schedule needs jax's partial-manual shard_map mode
# (axis_names); on older jax the library refuses with NotImplementedError
# and auto dispatch resolves to capacity instead
requires_ragged_ep = pytest.mark.skipif(
    not ragged_ep_supported(),
    reason="jax shard_map partial-manual mode unavailable",
)


def _router(T, E, K, seed=0):
    logits = jax.random.normal(jax.random.PRNGKey(seed), (T, E))
    weights, sel = jax.lax.top_k(jax.nn.softmax(logits, -1), K)
    weights = weights / jnp.sum(weights, -1, keepdims=True)
    return logits, sel, weights


def _dense_oracle(x, sel, weights, experts_fn_single, E):
    """Every expert computes every token; weighted combine (exact math)."""
    T, h = x.shape
    outs = jnp.stack([experts_fn_single(e, x) for e in range(E)])  # (E,T,h)
    combine = jnp.zeros((T, E)).at[
        jnp.arange(T)[:, None], sel
    ].add(weights)
    return jnp.einsum("eth,te->th", outs, combine)


def test_capacity_matches_dense_when_nothing_drops():
    T, h, E, K = 64, 16, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(1), (T, h))
    _, sel, weights = _router(T, E, K)
    w = jax.random.normal(jax.random.PRNGKey(2), (E, h, h)) / np.sqrt(h)

    def experts_fn(buf):  # (E,C,h)
        return jnp.tanh(jnp.einsum("ech,ehf->ecf", buf, w))

    out = moe_dispatch_combine(
        x, sel, weights, experts_fn, E,
        capacity_factor=no_drop_capacity_factor(E, K),
    )
    ref = _dense_oracle(
        x, sel, weights, lambda e, t: jnp.tanh(t @ w[e]), E
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-6)


def test_capacity_factor_bounds_flops_and_drops():
    """With capacity below the no-drop bound, overflow tokens contribute
    zero for that expert choice — never another expert's output."""
    T, h, E, K = 32, 8, 2, 1
    x = jnp.ones((T, h))
    # route EVERY token to expert 0
    sel = jnp.zeros((T, 1), jnp.int32)
    weights = jnp.ones((T, 1))

    def experts_fn(buf):
        return buf + 1.0  # expert adds 1

    out = moe_dispatch_combine(
        x, sel, weights, experts_fn, E, capacity=8
    )
    # first 8 tokens got the expert (1+1=2), the rest dropped to 0
    np.testing.assert_allclose(np.asarray(out[:8]), 2.0)
    np.testing.assert_allclose(np.asarray(out[8:]), 0.0)


def test_expert_capacity_alignment():
    c = expert_capacity(1024, 8, 2, 1.0)
    assert c == 256 and c % 8 == 0
    assert expert_capacity(4, 64, 1, 1.0) == 8  # floor of 8


def test_load_balancing_loss_uniform_is_one():
    """Uniform routing gives loss ~= 1 (the Switch normalisation), worse
    balance gives more."""
    T, E, K = 512, 4, 1
    logits = jnp.zeros((T, E))
    sel = jnp.asarray(np.random.default_rng(0).integers(0, E, (T, K)))
    loss = load_balancing_loss(logits, sel, E)
    np.testing.assert_allclose(float(loss), 1.0, atol=0.05)
    # all tokens to one expert: density=(1,0,0,0), prob uniform -> still 1;
    # skew the router too and the loss exceeds 1
    hot = jnp.zeros((T, E)).at[:, 0].set(5.0)
    sel_hot = jnp.zeros((T, K), jnp.int32)
    assert float(load_balancing_loss(hot, sel_hot, E)) > 2.0


def test_moe_model_capacity_vs_dense_forward():
    """Full model equivalence: same params, capacity dispatch at the
    no-drop factor == dense dispatch."""
    E, K = 4, 2
    kw = dict(num_experts=E, num_experts_per_tok=K, dtype="float32")
    cfg_dense = TransformerConfig.tiny(moe_dispatch="dense", **kw)
    cfg_cap = TransformerConfig.tiny(
        moe_dispatch="capacity",
        moe_capacity_factor=no_drop_capacity_factor(E, K),
        **kw,
    )
    model_d, model_c = CausalLM(cfg_dense), CausalLM(cfg_cap)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg_dense.vocab_size, (2, 32)),
        jnp.int32,
    )
    params = model_d.init(jax.random.PRNGKey(0), ids)["params"]
    out_d = model_d.apply({"params": params}, ids)
    out_c = model_c.apply({"params": params}, ids)
    np.testing.assert_allclose(
        np.asarray(out_c), np.asarray(out_d), rtol=5e-5, atol=5e-5
    )


def test_moe_capacity_grads_flow():
    """Router and expert weights both receive gradients through the sparse
    dispatch (top_k + scatter must not sever the graph)."""
    E, K = 4, 2
    cfg = TransformerConfig.tiny(
        num_experts=E, num_experts_per_tok=K, moe_dispatch="capacity"
    )
    model = CausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), ids)["params"]

    def loss(p):
        return jnp.mean(model.apply({"params": p}, ids) ** 2)

    grads = jax.grad(loss)(params)
    flat = {
        "//".join(str(getattr(k, "key", k)) for k in path): g
        for path, g in jax.tree_util.tree_flatten_with_path(grads)[0]
    }
    expert_grads = [v for k, v in flat.items() if "gate_proj" in k]
    router_grads = [v for k, v in flat.items() if "router" in k]
    assert expert_grads and router_grads
    assert any(float(jnp.abs(g).sum()) > 0 for g in expert_grads)
    assert any(float(jnp.abs(g).sum()) > 0 for g in router_grads)


def test_ragged_matches_dense_oracle():
    """moe_ragged computes every selected token-expert pair with no
    padding and no drops — it must match the dense dispatch exactly
    (same math, sparse cost). Forward AND gradients."""
    import dataclasses

    from accelerate_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig.tiny(
        num_experts=4, num_experts_per_tok=2, moe_dispatch="dense"
    )
    model_dense = CausalLM(cfg)
    model_ragged = CausalLM(dataclasses.replace(cfg, moe_dispatch="ragged"))
    params = model_dense.init_params(jax.random.PRNGKey(0), 2, 32)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)), jnp.int32
    )

    out_d = model_dense.apply({"params": params}, ids)
    out_r = model_ragged.apply({"params": params}, ids)
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(out_d), rtol=2e-5, atol=2e-5
    )

    def loss(m):
        def fn(p):
            logits = m.apply({"params": p}, ids)
            return jnp.mean(logits.astype(jnp.float32) ** 2)
        return fn

    g_d = jax.grad(loss(model_dense))(params)
    g_r = jax.grad(loss(model_ragged))(params)
    for a, b in zip(jax.tree.leaves(g_r), jax.tree.leaves(g_d)):
        scale = float(jnp.max(jnp.abs(b))) + 1e-8
        np.testing.assert_allclose(
            np.asarray(a) / scale, np.asarray(b) / scale, atol=5e-5
        )


@requires_ragged_ep
def test_ragged_ep_matches_dense_oracle():
    """moe_ragged_ep (shard-capacity ragged schedule over an ep=2 mesh)
    matches the dense oracle exactly when the window covers everything
    (capacity_factor >= ep => no shard can overflow) — forward AND
    gradients through the nested shard_map (VERDICT r3 weak #2: this
    lifts the ragged-dispatch ep>1 restriction)."""
    import dataclasses

    from accelerate_tpu import Accelerator
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=4, ep_size=2,
            sharding_strategy=ShardingStrategy.NO_SHARD,
        )
    )
    assert acc.mesh.shape["ep"] == 2

    cfg = TransformerConfig.tiny(
        num_experts=4, num_experts_per_tok=2, moe_dispatch="dense",
    )
    model_dense = CausalLM(cfg)
    model_ragged = CausalLM(dataclasses.replace(
        cfg, moe_dispatch="ragged",
        moe_capacity_factor=2.0,  # == ep: full coverage, zero drops
    ))
    params = model_dense.init_params(jax.random.PRNGKey(0), 2, 32)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)), jnp.int32
    )

    out_d = model_dense.apply({"params": params}, ids)
    out_r = jax.jit(
        lambda p, i: model_ragged.apply({"params": p}, i)
    )(params, ids)
    np.testing.assert_allclose(
        np.asarray(out_r), np.asarray(out_d), rtol=2e-5, atol=2e-5
    )

    def loss(m):
        def fn(p):
            logits = m.apply({"params": p}, ids)
            return jnp.mean(logits.astype(jnp.float32) ** 2)
        return fn

    g_d = jax.grad(loss(model_dense))(params)
    g_r = jax.jit(jax.grad(loss(model_ragged)))(params)
    for a, b in zip(jax.tree.leaves(g_r), jax.tree.leaves(g_d)):
        scale = float(jnp.max(jnp.abs(b))) + 1e-8
        np.testing.assert_allclose(
            np.asarray(a) / scale, np.asarray(b) / scale, atol=5e-5
        )
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


@requires_ragged_ep
def test_auto_dispatch_resolves_to_ragged_under_ep():
    """moe_dispatch="auto" routes through the shard-capacity ragged EP
    schedule when the mesh has ep>1 — the r5 default flip, backed by the
    measured drop-rate/collective-bytes evidence in moe_ragged_ep's
    docstring: auto output must equal explicit "ragged", not "capacity",
    under routing where the two schedules measurably differ."""
    import dataclasses

    from accelerate_tpu import Accelerator
    from accelerate_tpu.models import CausalLM, TransformerConfig
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=4, ep_size=2,
            sharding_strategy=ShardingStrategy.NO_SHARD,
        )
    )
    cfg = TransformerConfig.tiny(
        num_experts=4, num_experts_per_tok=2, moe_dispatch="auto",
        # tight factor: capacity (per-expert C) and shard-capacity
        # (per-shard window) drop DIFFERENT token-choices under skew, so
        # a capacity-resolved auto could not pass the equality below
        moe_capacity_factor=1.0,
    )
    params = CausalLM(cfg).init_params(jax.random.PRNGKey(0), 2, 32)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)), jnp.int32
    )
    out_auto = jax.jit(
        lambda p, i: CausalLM(cfg).apply({"params": p}, i)
    )(params, ids)
    cfg_r = dataclasses.replace(cfg, moe_dispatch="ragged")
    out_ragged = jax.jit(
        lambda p, i: CausalLM(cfg_r).apply({"params": p}, i)
    )(params, ids)
    np.testing.assert_allclose(
        np.asarray(out_auto), np.asarray(out_ragged), rtol=1e-6, atol=1e-6
    )
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()


@requires_ragged_ep
def test_ragged_ep_shard_capacity_drops_overflow():
    """With a tight window (capacity_factor < needed) overflow rows drop
    to zero contribution — graceful degradation, not corruption."""
    from accelerate_tpu.ops.moe import moe_ragged_ep
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=4, ep_size=2,
            sharding_strategy=ShardingStrategy.NO_SHARD,
        )
    )
    T, h, f, E, K = 64, 16, 32, 4, 2
    x = jax.random.normal(jax.random.PRNGKey(1), (T, h))
    # adversarial routing: EVERY token picks experts 0 and 1 (both owned
    # by shard 0) — shard 0's region is all T*K rows, far past its window
    sel = jnp.zeros((T, K), jnp.int32).at[:, 1].set(1)
    weights = jnp.full((T, K), 0.5)
    wg = jax.random.normal(jax.random.PRNGKey(2), (E, h, f)) / np.sqrt(h)
    wu = jax.random.normal(jax.random.PRNGKey(3), (E, h, f)) / np.sqrt(h)
    wd = jax.random.normal(jax.random.PRNGKey(4), (E, f, h)) / np.sqrt(f)

    out = jax.jit(
        lambda *a: moe_ragged_ep(
            *a, mesh=acc.mesh, capacity_factor=1.0
        )
    )(x, sel, weights, wg, wu, wd)
    out = np.asarray(out)
    # shard 0's region is all T*K rows but its window covers only the
    # first half — in sorted (stable) order that is exactly every
    # token's expert-0 pair. Every expert-1 pair drops: the result is
    # precisely 0.5 * expert0(x), not corruption.
    exp0 = (jax.nn.silu(x @ wg[0]) * (x @ wu[0])) @ wd[0]
    np.testing.assert_allclose(
        out, 0.5 * np.asarray(exp0), rtol=1e-5, atol=1e-5
    )
    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
