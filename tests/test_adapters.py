"""Multi-tenant adapters: LoRA/QLoRA training + batched multi-adapter
serving (accelerate_tpu/adapters/).

The contracts under test: a fresh adapter (B = 0) is bitwise-invisible;
the frozen base takes identically-zero gradients (stop_gradient, not
just unoptimized); the optimizer carry holds ONLY adapter leaves;
adapter checkpoints are tiny committed artifacts; and the serving side
decodes N tenants in ONE batch through ONE compiled decode program —
per-tenant outputs bitwise equal to single-tenant references, zero
retraces as adapters churn.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.adapters import (
    AdapterRegistry,
    LoraConfig,
    adapter_dir,
    adapter_num_bytes,
    adapter_num_params,
    assert_adapter_only,
    build_lora_state,
    init_adapter,
    list_adapters,
    load_adapter,
    lora_loss_fn,
    save_adapter,
    target_shapes,
)
from accelerate_tpu.adapters.runtime import (
    A_KEY,
    B_KEY,
    lora_delta,
    pad_rank,
    stack_adapter,
)
from accelerate_tpu.models import CausalLM, TransformerConfig

_CFG = TransformerConfig.tiny()
_LCFG = LoraConfig(rank=4, alpha=8.0, target_modules=("q_proj", "v_proj"))


@pytest.fixture(scope="module")
def tiny():
    model = CausalLM(_CFG)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _ids(batch=2, seq=16, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, _CFG.vocab_size, (batch, seq)),
        jnp.int32,
    )


def _rand_adapter(seed, lcfg=_LCFG, cfg=_CFG):
    """An adapter with NONZERO B (init_adapter's B=0 contract makes fresh
    adapters invisible; tenant-distinguishing tests need visible ones)."""
    ad = init_adapter(jax.random.PRNGKey(seed), cfg, lcfg)
    return {
        t: {
            A_KEY: pair[A_KEY],
            B_KEY: 0.05 * jax.random.normal(
                jax.random.PRNGKey(seed * 977 + i), pair[B_KEY].shape
            ),
        }
        for i, (t, pair) in enumerate(sorted(ad.items()))
    }


# --------------------------------------------------------------------- #
# config + layout
# --------------------------------------------------------------------- #
def test_lora_config_validation_and_round_trip():
    with pytest.raises(ValueError):
        LoraConfig(rank=0)
    with pytest.raises(ValueError):
        LoraConfig(dropout=1.0)
    with pytest.raises(ValueError):
        LoraConfig(target_modules=("qproj",))
    with pytest.raises(ValueError):
        LoraConfig(target_modules=())
    cfg = LoraConfig(rank=16, alpha=32.0, target_modules=["q_proj"])
    assert cfg.scaling == 2.0
    assert LoraConfig.from_dict(cfg.to_dict()) == cfg


def test_init_adapter_injection_layout():
    lcfg = LoraConfig(rank=4, target_modules=(
        "q_proj", "k_proj", "o_proj", "gate_proj", "down_proj"
    ))
    ad = init_adapter(jax.random.PRNGKey(0), _CFG, lcfg)
    shapes = target_shapes(_CFG)
    L = _CFG.num_layers
    assert set(ad) == set(lcfg.target_modules)
    for t in lcfg.target_modules:
        in_dim, out_dim = shapes[t]
        assert ad[t][A_KEY].shape == (L, in_dim, 4)
        assert ad[t][B_KEY].shape == (L, 4, out_dim)
        # B = 0 is the init contract: delta exactly zero at birth
        assert not np.any(np.asarray(ad[t][B_KEY]))
    # k/v project to the KV width under GQA, q to the full head width
    assert shapes["q_proj"][1] == _CFG.num_heads * _CFG.head_dim
    assert shapes["k_proj"][1] == _CFG.num_kv_heads * _CFG.head_dim
    assert adapter_num_params(_CFG, lcfg) == sum(
        int(np.prod(l.shape)) for l in jax.tree.leaves(ad)
    )


def test_fresh_adapter_is_bitwise_invisible(tiny):
    model, params = tiny
    ids = _ids()
    ref = model.apply({"params": params}, ids)
    state = build_lora_state(
        init_adapter(jax.random.PRNGKey(1), _CFG, _LCFG), _LCFG, ids.shape[0]
    )
    out = model.apply({"params": params}, ids, lora=state)
    assert np.array_equal(np.asarray(ref), np.asarray(out))
    # and a trained (nonzero-B) adapter IS visible
    state2 = build_lora_state(_rand_adapter(7), _LCFG, ids.shape[0])
    out2 = model.apply({"params": params}, ids, lora=state2)
    assert not np.array_equal(np.asarray(ref), np.asarray(out2))


def test_per_slot_indexing_parity():
    """The gathered-stack math: each batch row reads ONLY its own slot's
    adapter — a mixed batch equals per-row single-adapter computations."""
    rng = np.random.default_rng(0)
    in_dim, out_dim, r, L = 8, 6, 4, 1
    pairs = [
        {
            A_KEY: jnp.asarray(rng.normal(size=(L, in_dim, r)), jnp.float32),
            B_KEY: jnp.asarray(rng.normal(size=(L, r, out_dim)), jnp.float32),
        }
        for _ in range(3)
    ]
    # stack rows: [identity, pair0, pair1, pair2]
    zero = jax.tree.map(jnp.zeros_like, pairs[0])
    stacked = jax.tree.map(
        lambda *ls: jnp.stack(ls, axis=1)[0], zero, *pairs
    )  # (rows, in, r) / (rows, r, out) for layer 0
    x = jnp.asarray(rng.normal(size=(4, 5, in_dim)), jnp.float32)
    slot_ids = jnp.asarray([2, 0, 3, 1], jnp.int32)
    scales = jnp.asarray([2.0, 1.5, 0.5, 1.0], jnp.float32)
    mixed = lora_delta(x, stacked, slot_ids, scales)
    for row in range(4):
        single = lora_delta(
            x[row:row + 1], stacked, slot_ids[row:row + 1], scales
        )
        assert np.array_equal(np.asarray(mixed[row]), np.asarray(single[0]))
    # row 0 is the identity: delta exactly zero
    assert not np.any(np.asarray(mixed[1]))


def test_rank_padding_is_exact():
    """Zero-padding a rank-2 adapter to r_max=8 changes nothing: the
    padded columns of A meet the padded rows of B at 0*0."""
    rng = np.random.default_rng(1)
    # stack-row layout: (rows, in, r) / (rows, r, out), one row
    a = jnp.asarray(rng.normal(size=(1, 8, 2)), jnp.float32)
    b = jnp.asarray(rng.normal(size=(1, 2, 6)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(1, 3, 8)), jnp.float32)
    slot = jnp.zeros((1,), jnp.int32)
    scale = jnp.ones((1,), jnp.float32)
    small = lora_delta(x, {A_KEY: a, B_KEY: b}, slot, scale)
    padded = lora_delta(
        x,
        {
            A_KEY: pad_rank(a, axis=2, r_max=8),
            B_KEY: pad_rank(b, axis=1, r_max=8),
        },
        slot, scale,
    )
    assert np.array_equal(np.asarray(small), np.asarray(padded))
    with pytest.raises(ValueError):
        pad_rank(a, axis=2, r_max=1)


# --------------------------------------------------------------------- #
# training: frozen base, adapter-only carry
# --------------------------------------------------------------------- #
def test_frozen_base_gradients_identically_zero(tiny):
    model, params = tiny
    from accelerate_tpu.utils.quantization import (
        QuantizationConfig,
        quantize_params,
    )

    qbase = quantize_params(
        params, QuantizationConfig(load_in_8bit=True, min_weight_size=256)
    )
    adapter = _rand_adapter(3)
    batch = {"input_ids": _ids()}

    base_grads = jax.grad(
        lambda b: lora_loss_fn(model, b, _LCFG)(adapter, batch)
    )(params)
    # identically zero — stop_gradient, not merely small
    for path, leaf in jax.tree_util.tree_flatten_with_path(base_grads)[0]:
        assert not np.any(np.asarray(leaf)), path

    # the quantized base path: adapter grads exist and are finite
    ad_grads = jax.grad(
        lora_loss_fn(model, qbase, _LCFG, compute_dtype=jnp.float32)
    )(adapter, batch)
    leaves = jax.tree.leaves(ad_grads)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    # B is nonzero here, so BOTH a and b grads flow
    assert any(np.any(np.asarray(l)) for l in leaves)


def test_qlora_int8_loss_close_to_fp32(tiny):
    model, params = tiny
    from accelerate_tpu.utils.quantization import (
        QuantizationConfig,
        quantize_params,
    )

    adapter = _rand_adapter(4)
    batch = {"input_ids": _ids()}
    fp = float(lora_loss_fn(model, params, _LCFG)(adapter, batch))
    qbase = quantize_params(
        params, QuantizationConfig(load_in_8bit=True, min_weight_size=256)
    )
    q = float(
        lora_loss_fn(model, qbase, _LCFG, compute_dtype=jnp.float32)(
            adapter, batch
        )
    )
    assert abs(q - fp) / fp < 0.05, (q, fp)


@pytest.mark.parametrize("optimizer", ["adamw", "fused_adamw"])
def test_unified_step_adapter_only_carry(optimizer):
    """The tentpole training contract: ONLY adapter leaves in the carry,
    threading the existing unified_step (fused_adamw epilogue applies or
    declines without error), loss decreasing over an int8 frozen base.

    The adapter tree must be the LAST tree prepared before init_carry —
    prepare() re-infers shardings per call and unified_step pins the
    carry to the most recent set.
    """
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.benchmarks.measure import _reset_state
    from accelerate_tpu.utils.quantization import (
        QuantizationConfig,
        quantize_params,
    )

    _reset_state()
    model = CausalLM(_CFG)
    acc = Accelerator(mixed_precision="bf16")
    base = acc.prepare(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    qbase = quantize_params(base, QuantizationConfig(load_in_8bit=True))
    adapter = acc.prepare(init_adapter(jax.random.PRNGKey(1), _CFG, _LCFG))
    assert_adapter_only(adapter, _LCFG)
    if optimizer == "fused_adamw":
        from accelerate_tpu.ops.fused import fused_adamw

        opt = acc.prepare(fused_adamw(1e-3))
    else:
        opt = acc.prepare(optax.adamw(1e-3))
    carry = acc.init_carry(adapter, opt)
    assert_adapter_only(carry["params"], _LCFG)
    step = acc.unified_step(
        lora_loss_fn(model, qbase, _LCFG, compute_dtype=jnp.bfloat16),
        max_grad_norm=1.0,
    )
    batch = {"input_ids": _ids(seed=2)}
    losses = []
    for _ in range(5):
        carry, metrics = step(carry, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert_adapter_only(carry["params"], _LCFG)
    with pytest.raises(AssertionError):
        assert_adapter_only({"q_proj": {}, "extra": {}}, _LCFG)
    _reset_state()


# --------------------------------------------------------------------- #
# checkpoints: tiny committed artifacts
# --------------------------------------------------------------------- #
def test_adapter_save_restore_round_trip(tiny, tmp_path):
    _, params = tiny
    from accelerate_tpu.checkpointing import save_model_weights

    adapter = _rand_adapter(5)
    base_dir = str(tmp_path)
    path = save_adapter(base_dir, "tenant-a", adapter, _LCFG)
    assert os.path.basename(path) == "adapter_tenant-a"
    assert not os.path.exists(path + ".tmp")  # work dir committed away
    loaded, lcfg2 = load_adapter(path)
    assert lcfg2 == _LCFG
    for t in _LCFG.target_modules:
        for k in (A_KEY, B_KEY):
            assert np.array_equal(
                np.asarray(adapter[t][k]), np.asarray(loaded[t][k])
            ), (t, k)
    assert list_adapters(base_dir) == {"tenant-a": path}
    with pytest.raises(ValueError):
        save_adapter(base_dir, "a/b", adapter, _LCFG)

    # acceptance: committed adapter bytes <= 2% of the base checkpoint at
    # rank 16. Adapter bytes grow LINEARLY in hidden while the base grows
    # quadratically, so the check runs at a width where the ratio is
    # representative (at hidden=128 even the tiny base is only ~2.6 MB
    # and the constant-factor config json dominates).
    cfg = TransformerConfig.tiny(hidden_size=512)
    wide = CausalLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    base_ckpt = str(tmp_path / "base")
    save_model_weights(wide, base_ckpt)

    def du(d):
        return sum(
            os.path.getsize(os.path.join(r, f))
            for r, _, fs in os.walk(d) for f in fs
        )

    lcfg16 = LoraConfig(rank=16, target_modules=("q_proj", "v_proj"))
    path16 = save_adapter(
        base_dir, "rank16", init_adapter(jax.random.PRNGKey(2), cfg, lcfg16),
        lcfg16,
    )
    assert du(path16) <= 0.02 * du(base_ckpt), (du(path16), du(base_ckpt))


def test_load_adapter_requires_commit(tmp_path):
    from accelerate_tpu.checkpoint_async.commit import work_dir_for

    final = adapter_dir(str(tmp_path), "ghost")
    os.makedirs(work_dir_for(final))  # in-flight save, never committed
    with pytest.raises(FileNotFoundError):
        load_adapter(final)
    assert list_adapters(str(tmp_path)) == {}


# --------------------------------------------------------------------- #
# registry: residency, refcounts, LRU
# --------------------------------------------------------------------- #
def test_registry_load_evict_refcount_lru():
    reg = AdapterRegistry(
        _CFG, capacity=2, max_rank=4, target_modules=_LCFG.target_modules
    )
    a, b, c = (_rand_adapter(s) for s in (10, 11, 12))
    reg.load("a", a, _LCFG)
    reg.load("b", b, _LCFG)
    assert reg.resident("a") and reg.resident("b")
    assert reg.resident(None)  # base model is always resident (row 0)
    assert reg.slot_of(None) == 0
    assert sorted(reg.resident_names()) == ["a", "b"]
    assert reg.slot_of("a") != reg.slot_of("b") != 0

    reg.acquire("a")
    with pytest.raises(RuntimeError):
        reg.evict("a")  # in-flight requests pin it
    # full + "a" pinned: LRU evicts "b" (refcount 0)
    reg.load("c", c, _LCFG)
    assert not reg.resident("b") and reg.resident("c")
    assert reg.evict_total == 1

    reg.acquire("c")
    with pytest.raises(RuntimeError):
        reg.load("d", _rand_adapter(13), _LCFG)  # every slot pinned
    reg.release("a")
    reg.release("c")
    reg.evict("c")
    assert not reg.resident("c")
    assert reg.hbm_bytes() > 0


def test_registry_validates_rank_targets_shapes():
    reg = AdapterRegistry(
        _CFG, capacity=2, max_rank=4, target_modules=("q_proj", "v_proj")
    )
    with pytest.raises(ValueError):
        reg.load("r", _rand_adapter(1, LoraConfig(rank=8)),
                 LoraConfig(rank=8))  # rank > max_rank
    wide = LoraConfig(rank=4, target_modules=("q_proj", "o_proj"))
    with pytest.raises(ValueError):
        reg.load("t", _rand_adapter(1, wide), wide)  # o_proj not in registry
    bad = _rand_adapter(1)
    bad["q_proj"][A_KEY] = bad["q_proj"][A_KEY][:, :8, :]
    with pytest.raises(ValueError):
        reg.load("s", bad, _LCFG)  # leaf shape vs model layout
    # a rank-2 adapter zero-pads into the rank-4 stacks
    l2 = LoraConfig(rank=2, target_modules=_LCFG.target_modules)
    reg.load("small", _rand_adapter(2, l2), l2)
    assert reg.resident("small")


# --------------------------------------------------------------------- #
# serving: admission gating, multi-tenant parity, zero retraces
# --------------------------------------------------------------------- #
def _engine(tiny, capacity=4, **kw):
    from accelerate_tpu.serving import ServingEngine

    model, params = tiny
    reg = AdapterRegistry(
        _CFG, capacity=capacity, max_rank=_LCFG.rank,
        target_modules=_LCFG.target_modules,
    )
    kw.setdefault("max_slots", 4)
    kw.setdefault("block_size", 8)
    return ServingEngine(model, params, adapters=reg, **kw), reg


def _serve(engine, reqs, seed=0):
    """reqs: list of (adapter_name | None); returns {rid: tokens}."""
    rng = np.random.default_rng(seed)
    rids = [
        engine.add_request(
            rng.integers(1, 50, size=4 + i).tolist(),
            max_new_tokens=6, adapter=name,
        )
        for i, name in enumerate(reqs)
    ]
    for _ in engine.stream():
        pass
    return {rid: engine.result(rid) for rid in rids}


def test_scheduler_gates_admission_on_residency(tiny):
    engine, reg = _engine(tiny)
    rid = engine.add_request([1, 2, 3], max_new_tokens=4, adapter="t0")
    engine.step()
    # not resident: the request stays queued, attributed visibly
    assert engine.result(rid) is None
    assert engine.scheduler.blocked_reasons["adapter_not_resident"] >= 1
    assert (
        engine._gauge_fields()["admission_blocked_adapter_not_resident_total"]
        >= 1
    )
    reg.load("t0", _rand_adapter(20), _LCFG)
    for _ in engine.stream():
        pass
    assert engine.result(rid) is not None
    # naming an adapter without a registry is a loud error
    from accelerate_tpu.serving import ServingEngine

    model, params = tiny
    bare = ServingEngine(model, params, max_slots=2, block_size=8)
    with pytest.raises(ValueError):
        bare.add_request([1, 2], adapter="t0")


def test_multi_adapter_batch_bitwise_matches_single_tenant(tiny):
    """THE serving acceptance: >= 3 distinct adapters + the base in ONE
    batch; each tenant's tokens equal a single-tenant reference run."""
    adapters = {f"t{i}": _rand_adapter(30 + i) for i in range(3)}

    engine, reg = _engine(tiny)
    for name, ad in adapters.items():
        reg.load(name, ad, _LCFG)
    mixed = _serve(engine, ["t0", "t1", "t2", None], seed=7)
    assert engine.trace_counts()["decode"] == 1

    # one single-tenant reference engine per adapter, same prompts
    for i, name in enumerate(["t0", "t1", "t2", None]):
        ref_engine, ref_reg = _engine(tiny)
        if name is not None:
            ref_reg.load(name, adapters[name], _LCFG)
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, 50, size=4 + j).tolist() for j in range(4)]
        rid = ref_engine.add_request(
            prompts[i], max_new_tokens=6, adapter=name
        )
        for _ in ref_engine.stream():
            pass
        assert ref_engine.result(rid) == list(mixed.values())[i], name
    # distinct adapters really decode differently (B is nonzero)
    outs = list(mixed.values())
    assert len({tuple(o) for o in outs}) > 1


def test_zero_decode_retraces_across_adapter_churn(tiny):
    engine, reg = _engine(tiny)
    reg.load("t0", _rand_adapter(40), _LCFG)
    _serve(engine, ["t0", None])  # warmup compiles prefill + decode
    warm = dict(engine.trace_counts())
    for i in (1, 2, 3):
        reg.load(f"t{i}", _rand_adapter(40 + i), _LCFG)
    _serve(engine, ["t1", "t2", "t3", None], seed=1)
    reg.load("t4", _rand_adapter(44), _LCFG)  # LRU-evicts a cold tenant
    _serve(engine, ["t4", "t1"], seed=2)
    assert engine.trace_counts()["decode"] == warm["decode"] == 1
    assert reg.load_total == 5 and reg.evict_total >= 1


def test_serve_telemetry_carries_adapter_id(tiny):
    from accelerate_tpu.telemetry import (
        PrometheusTextSink,
        StepTelemetry,
        TelemetryConfig,
    )

    tel = StepTelemetry(TelemetryConfig())
    sink = PrometheusTextSink(path=None)
    tel.add_sink(sink)
    engine, reg = _engine(tiny, telemetry=tel, gauge_interval=1)
    reg.load("t0", _rand_adapter(50), _LCFG)
    _serve(engine, ["t0", None])
    records = [r for r in tel.records if r.get("kind") == "serve"]
    assert {r["adapter_id"] for r in records} == {"t0", None}
    spans = {s.request_id: s for s in engine.span_log.closed}
    assert sorted(
        (s.adapter_id for s in spans.values()), key=lambda a: a or ""
    ) == [None, "t0"]
    text = sink.render()
    assert (
        'accelerate_tpu_serve_requests_total{adapter="t0"} 1' in text
    ), text
    assert (
        'accelerate_tpu_serve_requests_total{adapter="none"} 1' in text
    ), text
    assert (
        'accelerate_tpu_serve_adapters_resident{label="serve"} 1.0' in text
    ), text
    tel.close()


# --------------------------------------------------------------------- #
# interop + end-to-end
# --------------------------------------------------------------------- #
def test_peft_export_layout_map():
    from accelerate_tpu.utils.hf_interop import adapter_to_peft, peft_to_adapter

    lcfg = LoraConfig(rank=4, target_modules=("q_proj", "gate_proj"))
    ad = init_adapter(jax.random.PRNGKey(0), _CFG, lcfg)
    sd = adapter_to_peft(ad, lcfg, _CFG)
    L = _CFG.num_layers
    assert len(sd) == 2 * 2 * L
    h, q_dim = target_shapes(_CFG)["q_proj"]
    f = _CFG.intermediate_size
    # PEFT/torch layouts: lora_A (r, in), lora_B (out, r); attention
    # modules under self_attn, MLP modules under mlp
    k = "base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight"
    assert sd[k].shape == (4, h)
    k = "base_model.model.model.layers.1.self_attn.q_proj.lora_B.weight"
    assert sd[k].shape == (q_dim, 4)
    k = "base_model.model.model.layers.0.mlp.gate_proj.lora_A.weight"
    assert sd[k].shape == (4, h)
    assert sd[
        "base_model.model.model.layers.1.mlp.gate_proj.lora_B.weight"
    ].shape == (f, 4)
    # torch layout is the TRANSPOSE of the native leaf, layer-sliced
    assert np.array_equal(
        sd["base_model.model.model.layers.0.self_attn.q_proj.lora_A.weight"],
        np.asarray(ad["q_proj"][A_KEY][0]).T,
    )
    back = peft_to_adapter(sd, lcfg, _CFG)
    for t in lcfg.target_modules:
        for key in (A_KEY, B_KEY):
            assert np.array_equal(np.asarray(ad[t][key]), back[t][key])


@pytest.mark.slow
def test_lora_smoke_end_to_end(tiny, tmp_path):
    """The `make lora-smoke` path: train an adapter through unified_step,
    commit its checkpoint, load it into an engine next to a second
    adapter, and decode token-for-token equal to a single-tenant
    reference engine serving the same trained adapter."""
    import optax

    from accelerate_tpu import Accelerator
    from accelerate_tpu.benchmarks.measure import _reset_state

    model, params = tiny
    _reset_state()
    acc = Accelerator(mixed_precision="bf16")
    base = acc.prepare(
        model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
            "params"
        ]
    )
    adapter = acc.prepare(init_adapter(jax.random.PRNGKey(1), _CFG, _LCFG))
    opt = acc.prepare(optax.adamw(1e-2))
    carry = acc.init_carry(adapter, opt)
    step = acc.unified_step(lora_loss_fn(model, base, _LCFG))
    batch = {"input_ids": _ids(seed=3)}
    first = last = None
    for _ in range(8):
        carry, metrics = step(carry, batch)
        last = float(metrics["loss"])
        first = first if first is not None else last
    assert last < first
    trained = jax.tree.map(np.asarray, carry["params"])
    path = save_adapter(str(tmp_path), "trained", trained, _LCFG)
    _reset_state()

    loaded, lcfg = load_adapter(path)
    engine, reg = _engine(tiny)
    reg.load("trained", loaded, lcfg)
    reg.load("other", _rand_adapter(60), _LCFG)
    mixed = _serve(engine, ["trained", "other", None], seed=9)
    assert engine.trace_counts()["decode"] == 1

    ref_engine, ref_reg = _engine(tiny)
    ref_reg.load("trained", loaded, lcfg)
    rng = np.random.default_rng(9)
    prompt = rng.integers(1, 50, size=4).tolist()
    rid = ref_engine.add_request(prompt, max_new_tokens=6, adapter="trained")
    for _ in ref_engine.stream():
        pass
    assert ref_engine.result(rid) == list(mixed.values())[0]
