"""Failure-detection / recovery tests (SURVEY §5.3): cadence checkpoints,
SIGTERM preemption -> final checkpoint + stop, auto-resume."""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, ProjectConfiguration
from accelerate_tpu.fault_tolerance import CheckpointManager


def _setup(tmp_path, accum=1):
    pc = ProjectConfiguration(
        project_dir=str(tmp_path), automatic_checkpoint_naming=True,
        total_limit=3,
    )
    acc = Accelerator(project_config=pc)
    params = acc.prepare({"w": jnp.zeros((4, 4))})
    opt = acc.prepare(optax.sgd(0.1))
    carry = acc.init_carry(params, opt)
    step = acc.unified_step(lambda p, b: jnp.mean((p["w"] - b["t"]) ** 2))
    batch = {"t": jnp.ones((4, 4))}
    return acc, carry, step, batch


def test_cadence_checkpoints_and_rotation(tmp_path):
    acc, carry, step, batch = _setup(tmp_path)
    with CheckpointManager(acc, every_n_steps=2, handle_signals=False) as mgr:
        saved = []
        for _ in range(6):
            carry, _ = step(carry, batch)
            out = mgr.step(carry)
            if out:
                saved.append(out)
    assert len(saved) == 3  # steps 2, 4, 6
    base = tmp_path / "checkpoints"
    assert sorted(os.listdir(base)) == [
        "checkpoint_0", "checkpoint_1", "checkpoint_2"
    ]


def test_preemption_signal_forces_checkpoint_and_stop(tmp_path):
    acc, carry, step, batch = _setup(tmp_path)
    with CheckpointManager(acc, every_n_steps=1000) as mgr:
        carry, _ = step(carry, batch)
        assert mgr.step(carry) is None  # far from cadence
        os.kill(os.getpid(), signal.SIGTERM)  # simulated eviction notice
        assert mgr.preempted
        carry, _ = step(carry, batch)
        out = mgr.step(carry)
        assert out is not None and mgr.should_stop


def test_auto_resume_continues_from_checkpoint(tmp_path):
    acc, carry, step, batch = _setup(tmp_path)
    with CheckpointManager(acc, every_n_steps=2, handle_signals=False) as mgr:
        for _ in range(4):
            carry, _ = step(carry, batch)
            mgr.step(carry)
    w_at_4 = np.asarray(carry["params"]["w"]).copy()

    # "restart": fresh singletons, fresh accelerator, zeroed carry
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    pc = ProjectConfiguration(
        project_dir=str(tmp_path), automatic_checkpoint_naming=True
    )
    acc2 = Accelerator(project_config=pc)
    params2 = acc2.prepare({"w": jnp.zeros((4, 4))})
    opt2 = acc2.prepare(optax.sgd(0.1))
    carry2 = acc2.init_carry(params2, opt2)
    with CheckpointManager(acc2, every_n_steps=2, handle_signals=False) as mgr2:
        carry2, resumed = mgr2.restore_or_init(carry2)
    assert resumed
    assert acc2.step == 4
    np.testing.assert_allclose(
        np.asarray(carry2["params"]["w"]), w_at_4, rtol=1e-6
    )
    assert int(np.asarray(carry2["opt_step"])) == 4


def test_preemption_drains_inflight_async_save_then_writes_final(
    tmp_path, monkeypatch
):
    """SIGTERM while a background save is still writing: the manager must
    drain it (its commit cannot race the final checkpoint's rotation),
    then write the final checkpoint synchronously — and restore resumes
    from the FINAL checkpoint, not the drained cadence save."""
    import time

    from accelerate_tpu import dist_checkpoint
    from accelerate_tpu.checkpoint_async import commit as commit_mod

    acc, carry, step, batch = _setup(tmp_path)
    real_write = dist_checkpoint.write_snapshot

    def slow_write(snap, out_dir, fsync=False):
        time.sleep(0.3)
        return real_write(snap, out_dir, fsync=fsync)

    monkeypatch.setattr(dist_checkpoint, "write_snapshot", slow_write)
    with CheckpointManager(
        acc, every_n_steps=2, async_saves=True
    ) as mgr:
        for _ in range(2):
            carry, _ = step(carry, batch)
            mgr.step(carry)  # step 2: async save now in flight (0.3s write)
        os.kill(os.getpid(), signal.SIGTERM)
        assert mgr.preempted
        carry, _ = step(carry, batch)
        out = mgr.step(carry)  # drain -> final sync checkpoint
        assert out is not None and mgr.should_stop
        assert not mgr.in_flight
    base = tmp_path / "checkpoints"
    assert sorted(os.listdir(base)) == ["checkpoint_0", "checkpoint_1"]
    for name in os.listdir(base):
        assert commit_mod.is_committed(str(base / name))

    # restart: the FINAL (preemption) checkpoint is what resumes
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    pc = ProjectConfiguration(
        project_dir=str(tmp_path), automatic_checkpoint_naming=True
    )
    acc2 = Accelerator(project_config=pc)
    params2 = acc2.prepare({"w": jnp.zeros((4, 4))})
    opt2 = acc2.prepare(optax.sgd(0.1))
    carry2 = acc2.init_carry(params2, opt2)
    with CheckpointManager(acc2, handle_signals=False) as mgr2:
        carry2, resumed = mgr2.restore_or_init(carry2)
    assert resumed and acc2.step == 3
    np.testing.assert_allclose(
        np.asarray(carry2["params"]["w"]),
        np.asarray(carry["params"]["w"]), rtol=1e-6,
    )


def test_restore_or_init_without_checkpoints(tmp_path):
    acc, carry, step, batch = _setup(tmp_path)
    with CheckpointManager(acc, handle_signals=False) as mgr:
        out, resumed = mgr.restore_or_init(carry)
    assert not resumed and out is carry


def test_rejects_bad_cadence(tmp_path):
    acc, *_ = _setup(tmp_path)
    with pytest.raises(ValueError):
        CheckpointManager(acc, every_n_steps=0)


def test_requires_automatic_naming():
    """Misconfiguration must fail at construction, not at the first
    (possibly preemption-triggered) save (review finding)."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    acc = Accelerator()  # default: no automatic naming
    with pytest.raises(ValueError, match="automatic checkpoint naming"):
        CheckpointManager(acc)


def _fresh_run(tmp_path):
    """A restarted process: reset singletons, rebuild the same model."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    return _setup(tmp_path)


def test_restore_falls_back_when_newest_checkpoint_corrupt(tmp_path):
    """A crash mid-write (or bit rot) on the newest checkpoint must not
    strand the run: restore falls back to the next-newest committed one."""
    import glob

    acc, carry, step, batch = _setup(tmp_path)
    with CheckpointManager(acc, every_n_steps=2, handle_signals=False) as mgr:
        for i in range(4):
            carry, _ = step(carry, batch)
            mgr.step(carry)
            if i == 1:
                w_at_2 = np.asarray(carry["params"]["w"]).copy()
    # corrupt the newest (checkpoint_1, step 4): lose its shard file
    for shard in glob.glob(
        str(tmp_path / "checkpoints" / "checkpoint_1" / "state_shard_*")
    ):
        os.remove(shard)

    acc2, carry2, _, _ = _fresh_run(tmp_path)
    with CheckpointManager(acc2, handle_signals=False) as mgr2:
        carry2, resumed = mgr2.restore_or_init(carry2)
    assert resumed
    assert acc2.step == 2  # checkpoint_0, not the corrupt checkpoint_1
    np.testing.assert_array_equal(np.asarray(carry2["params"]["w"]), w_at_2)


def test_restore_raises_when_every_checkpoint_corrupt(tmp_path):
    import glob

    acc, carry, step, batch = _setup(tmp_path)
    with CheckpointManager(acc, every_n_steps=2, handle_signals=False) as mgr:
        for _ in range(4):
            carry, _ = step(carry, batch)
            mgr.step(carry)
    for shard in glob.glob(
        str(tmp_path / "checkpoints" / "*" / "state_shard_*")
    ):
        os.remove(shard)
    acc2, carry2, _, _ = _fresh_run(tmp_path)
    with CheckpointManager(acc2, handle_signals=False) as mgr2:
        with pytest.raises(RuntimeError, match="every checkpoint"):
            mgr2.restore_or_init(carry2)


def test_sigint_opt_in_gets_preemption_semantics(tmp_path):
    """signals=(SIGTERM, SIGINT) gives Ctrl-C the durable-stop contract;
    WITHOUT the knob SIGINT keeps its normal KeyboardInterrupt handler."""
    acc, carry, step, batch = _setup(tmp_path)
    default_int = signal.getsignal(signal.SIGINT)
    with CheckpointManager(acc, every_n_steps=1000) as mgr:
        # default manager: SIGINT untouched, SIGTERM claimed
        assert signal.getsignal(signal.SIGINT) is default_int
        assert signal.getsignal(signal.SIGTERM) == mgr._on_preemption
    with CheckpointManager(
        acc, every_n_steps=1000,
        signals=(signal.SIGTERM, signal.SIGINT),
    ) as mgr:
        assert signal.getsignal(signal.SIGINT) == mgr._on_preemption
        carry, _ = step(carry, batch)
        os.kill(os.getpid(), signal.SIGINT)  # no KeyboardInterrupt raised
        assert mgr.preempted
        carry, _ = step(carry, batch)
        out = mgr.step(carry)
        assert out is not None and mgr.should_stop
    # handlers restored on close
    assert signal.getsignal(signal.SIGINT) is default_int


def test_close_is_idempotent_and_restores_handlers(tmp_path):
    acc, *_ = _setup(tmp_path)
    prev = signal.getsignal(signal.SIGTERM)
    mgr = CheckpointManager(acc, every_n_steps=1000)
    assert signal.getsignal(signal.SIGTERM) == mgr._on_preemption
    mgr.close()
    assert signal.getsignal(signal.SIGTERM) is prev
    mgr.close()  # second close (e.g. the atexit hook after __exit__): no-op
    assert signal.getsignal(signal.SIGTERM) is prev


def test_close_does_not_clobber_newer_handler(tmp_path):
    """Closing an OLD manager while a newer one owns the signal must leave
    the newer handler installed (un-install only your own handler)."""
    acc, *_ = _setup(tmp_path)
    prev = signal.getsignal(signal.SIGTERM)
    m1 = CheckpointManager(acc, every_n_steps=1000)
    m2 = CheckpointManager(acc, every_n_steps=1000)
    assert signal.getsignal(signal.SIGTERM) == m2._on_preemption
    m1.close()
    assert signal.getsignal(signal.SIGTERM) == m2._on_preemption
    m2.close()
    signal.signal(signal.SIGTERM, prev)  # unwind the nested install
