"""Distributed sharded-checkpoint tests (VERDICT r1 missing #5 / next #6).

Models the reference FSDP ``SHARDED_STATE_DICT`` capability
(utils/fsdp_utils.py:60-215): per-rank shard writes, restore onto the live
sharding, merge/export to a single file.
"""

import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Accelerator, ParallelismPlugin
from accelerate_tpu.checkpointing import flatten_tree
from accelerate_tpu.dist_checkpoint import (
    is_sharded_checkpoint,
    load_full_named,
    load_sharded_tree,
    save_sharded_tree,
)


def _sharded_params(acc):
    params = {
        "kernel": jnp.arange(256.0, dtype=jnp.float32).reshape(16, 16),
        "bias": jnp.arange(16.0, dtype=jnp.bfloat16),
        "counter": jnp.asarray(7, jnp.int32),
    }
    return acc.prepare(params)


def _zero_template(tree):
    """Zeros with the same shardings — proves restore fills real data."""
    return jax.tree.map(
        lambda x: jax.device_put(jnp.zeros(x.shape, x.dtype), x.sharding), tree
    )


def test_sharded_roundtrip_fsdp(tmp_path):
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=2, fsdp_size=4, min_weight_size=1
        )
    )
    params = _sharded_params(acc)
    before = jax.tree.map(np.asarray, params)
    out = str(tmp_path / "ck")
    save_sharded_tree(params, out)
    assert is_sharded_checkpoint(out)

    # fsdp=4 sharding => 4 distinct chunks per sharded leaf, written once
    # each (dp replicas do NOT duplicate data on disk)
    with open(os.path.join(out, "state_index_00000.json")) as f:
        manifest = json.load(f)
    assert len(manifest["kernel"]["chunks"]) == 4
    assert manifest["kernel"]["shape"] == [16, 16]
    assert manifest["bias"]["dtype"] == "bfloat16"

    restored = load_sharded_tree(_zero_template(params), out)
    for k in before:
        np.testing.assert_array_equal(np.asarray(restored[k]), before[k])
        assert restored[k].sharding == params[k].sharding
        assert restored[k].dtype == params[k].dtype


def test_sharded_restore_onto_different_sharding(tmp_path):
    """Saved under one layout, restored onto another — re-sharding on load
    is the capability dist_cp needs planner machinery for."""
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=1, fsdp_size=8, min_weight_size=1
        )
    )
    params = _sharded_params(acc)
    before = jax.tree.map(np.asarray, params)
    out = str(tmp_path / "ck")
    save_sharded_tree(params, out)

    # new template: replicated everywhere (e.g. resuming onto fewer chips)
    mesh = acc.mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    template = jax.tree.map(
        lambda x: jax.device_put(
            jnp.zeros(x.shape, x.dtype), NamedSharding(mesh, P())
        ),
        params,
    )
    restored = load_sharded_tree(template, out)
    for k in before:
        np.testing.assert_array_equal(np.asarray(restored[k]), before[k])
        assert restored[k].sharding.is_fully_replicated


def test_load_full_named_and_merge_cli(tmp_path):
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=1, fsdp_size=8, min_weight_size=1
        )
    )
    params = _sharded_params(acc)
    out = str(tmp_path / "ck")
    save_sharded_tree(params, out)

    named = load_full_named(out)
    np.testing.assert_array_equal(
        named["kernel"], np.asarray(params["kernel"])
    )

    # merge CLI consolidates the distributed format into one safetensors
    import subprocess
    import sys

    merged = str(tmp_path / "merged")
    res = subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli",
         "merge-weights", out, merged],
        capture_output=True, text=True,
    )
    assert res.returncode == 0, res.stderr
    from accelerate_tpu.checkpointing import load_model_weights

    re_named = load_model_weights(merged)
    np.testing.assert_array_equal(
        re_named["kernel"], np.asarray(params["kernel"])
    )


def test_save_state_uses_sharded_format(tmp_path):
    """Accelerator.save_state defaults to the distributed format — no
    model.safetensors full dump (the r1 scaling flaw)."""
    import optax

    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=2, fsdp_size=4, min_weight_size=1
        )
    )
    params = acc.prepare(
        {
            "kernel": jnp.arange(256.0, dtype=jnp.float32).reshape(16, 16),
            "bias": jnp.arange(16.0, dtype=jnp.float32),
        }
    )
    opt = acc.prepare(optax.adam(1e-2))
    carry = acc.init_carry(params, opt)
    step = acc.unified_step(lambda p, b: jnp.mean(p["kernel"] ** 2))
    carry, _ = step(carry, {"x": jnp.ones((8, 1))})
    out = acc.save_state(str(tmp_path / "ck"), carry=carry)
    assert is_sharded_checkpoint(out)
    assert not os.path.exists(os.path.join(out, "model.safetensors"))

    restored = acc.load_state(out, carry=_zero_template_carry(carry))
    for a, b in zip(jax.tree.leaves(carry), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _zero_template_carry(carry):
    def _zero(x):
        if isinstance(x.sharding, jax.sharding.NamedSharding):
            return jax.device_put(jnp.zeros(x.shape, x.dtype), x.sharding)
        return jnp.zeros(x.shape, x.dtype)

    return jax.tree.map(_zero, carry)


def test_incomplete_checkpoint_fails_loudly(tmp_path):
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=1, fsdp_size=8, min_weight_size=1
        )
    )
    params = _sharded_params(acc)
    out = str(tmp_path / "ck")
    save_sharded_tree(params, out)
    # simulate a lost host: drop half the kernel's chunks from the manifest
    idx_path = os.path.join(out, "state_index_00000.json")
    with open(idx_path) as f:
        manifest = json.load(f)
    manifest["kernel"]["chunks"] = manifest["kernel"]["chunks"][:4]
    with open(idx_path, "w") as f:
        json.dump(manifest, f)
    with pytest.raises(ValueError, match="incomplete|cover"):
        load_full_named(out)


def test_nonstrict_load_keeps_template_extras(tmp_path):
    """Resuming into a run whose carry grew a new leaf (e.g. loss_scale)
    must keep the template's value, not KeyError (legacy merge semantics)."""
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=1, fsdp_size=8, min_weight_size=1
        )
    )
    params = acc.prepare({"kernel": jnp.ones((16, 16))})
    out = str(tmp_path / "ck")
    save_sharded_tree(params, out)
    template = {
        "kernel": jax.device_put(
            jnp.zeros((16, 16)), params["kernel"].sharding
        ),
        "loss_scale": jnp.asarray(2.0**15),
    }
    with pytest.raises(KeyError):
        load_sharded_tree(template, out, strict=True)
    restored = load_sharded_tree(template, out, strict=False)
    np.testing.assert_array_equal(np.asarray(restored["kernel"]), 1.0)
    assert float(restored["loss_scale"]) == 2.0**15


def test_save_skips_non_tensor_leaves(tmp_path):
    tree = {"kernel": jnp.ones((4, 4)), "note": "hello", "none": None}
    out = str(tmp_path / "ck")
    save_sharded_tree(tree, out)
    named = load_full_named(out)
    assert set(named) == {"kernel"}


# ---------------------------------------------------------------------- #
# topology-independent restore: N -> M -> N round trips over device
# subsets (each mesh size stands in for a different fleet size)
# ---------------------------------------------------------------------- #
def _mesh_over(n):
    from accelerate_tpu.parallel.mesh import build_mesh

    return build_mesh(
        ParallelismPlugin(dp_size=1, fsdp_size=n, min_weight_size=1),
        devices=jax.devices()[:n],
    )


def _train_like_tree():
    """Params + adam-moment-like leaves; dim 24 divides every world size
    tested (1, 2, 4, 8), like real elastic checkpoints must."""
    kernel = np.arange(24.0 * 8).reshape(24, 8).astype(np.float32)
    bias = np.arange(24.0, dtype=np.float32)
    return {
        "params": {"kernel": kernel, "bias": bias},
        "mu": {"kernel": kernel * 0.1, "bias": bias * 0.1},
        "nu": {"kernel": kernel**2, "bias": bias**2},
        "count": np.asarray(3, np.int32),
    }


def _place(tree_np, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        arr = jnp.asarray(x)
        spec = P("fsdp") if arr.ndim >= 1 else P()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree.map(put, tree_np)


@pytest.mark.parametrize("m", [2, 1, 8])
def test_restore_across_world_sizes(tmp_path, m):
    """Save on a 4-way mesh, restore bitwise onto m-way (both m < 4 and
    m > 4): the re-slicing must be exact regardless of direction."""
    source = _train_like_tree()
    out = str(tmp_path / "ck")
    save_sharded_tree(_place(source, _mesh_over(4)), out)

    template = jax.tree.map(jnp.zeros_like, _place(source, _mesh_over(m)))
    restored = load_sharded_tree(template, out)
    for (path, a), b in zip(
        jax.tree_util.tree_flatten_with_path(restored)[0],
        jax.tree.leaves(source),
    ):
        np.testing.assert_array_equal(np.asarray(a), b, err_msg=str(path))
    # the full de-sharded view agrees too
    named = load_full_named(out)
    np.testing.assert_array_equal(named["params//kernel"], source["params"]["kernel"])


def test_round_trip_n_m_n_is_bitwise(tmp_path):
    """N -> M -> N: shrink onto 2 devices, re-save from there, grow back
    onto 4 — the twice-resliced state is bitwise the original."""
    source = _train_like_tree()
    out4 = str(tmp_path / "ck4")
    save_sharded_tree(_place(source, _mesh_over(4)), out4)

    mesh2 = _mesh_over(2)
    on2 = load_sharded_tree(
        jax.tree.map(jnp.zeros_like, _place(source, mesh2)), out4
    )
    out2 = str(tmp_path / "ck2")
    save_sharded_tree(on2, out2)

    back_on4 = load_sharded_tree(
        jax.tree.map(jnp.zeros_like, _place(source, _mesh_over(4))), out2
    )
    for a, b in zip(jax.tree.leaves(back_on4), jax.tree.leaves(source)):
        np.testing.assert_array_equal(np.asarray(a), b)


# ---------------------------------------------------------------------- #
# coverage validation: the reshape-time proof that the per-host files
# assemble into a complete checkpoint
# ---------------------------------------------------------------------- #
def _saved_checkpoint(tmp_path):
    out = str(tmp_path / "ck")
    save_sharded_tree(_place(_train_like_tree(), _mesh_over(4)), out)
    return out


def _edit_index(out, fn):
    idx = os.path.join(out, "state_index_00000.json")
    with open(idx) as f:
        manifest = json.load(f)
    fn(manifest)
    with open(idx, "w") as f:
        json.dump(manifest, f)


def test_validate_coverage_accepts_complete_checkpoint(tmp_path):
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out = _saved_checkpoint(tmp_path)
    stats = validate_coverage(out)
    assert stats["leaves"] == 7
    assert stats["files"] == 1
    # each 1d+ leaf contributes one chunk per fsdp shard
    assert stats["chunks"] >= 6 * 4 + 1


def test_validate_coverage_rejects_missing_chunk(tmp_path):
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out = _saved_checkpoint(tmp_path)
    _edit_index(out, lambda m: m["params//kernel"]["chunks"].pop(1))
    with pytest.raises(ValueError, match="params//kernel.*not covered"):
        validate_coverage(out)


def test_validate_coverage_rejects_overlapping_chunks(tmp_path):
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out = _saved_checkpoint(tmp_path)
    _edit_index(
        out,
        lambda m: m["params//kernel"]["chunks"].append(
            dict(m["params//kernel"]["chunks"][0])
        ),
    )
    with pytest.raises(ValueError, match="overlapping"):
        validate_coverage(out)


def test_validate_coverage_rejects_missing_shard_file(tmp_path):
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out = _saved_checkpoint(tmp_path)
    shard = glob.glob(os.path.join(out, "state_shard_*.safetensors"))[0]
    os.rename(shard, shard + ".lost")
    with pytest.raises(FileNotFoundError, match=os.path.basename(shard)):
        validate_coverage(out)


# ---------------------------------------------------------------------- #
# hierarchical (slice-major) process -> shard maps: a dropped slice must
# fail coverage loudly, never restore a silently-torn checkpoint
# ---------------------------------------------------------------------- #
def _hierarchical_checkpoint(tmp_path, world=4):
    """Synthetic per-process files with slice-major rank numbering: proc p
    owns row-block p of one (8, 8) leaf, and with 2 procs per slice the
    contiguous proc pairs (0,1) and (2,3) are the two fault domains."""
    from accelerate_tpu.dist_checkpoint import ShardSnapshot, write_snapshot

    out = str(tmp_path / "ck")
    full = np.arange(64.0, dtype=np.float32).reshape(8, 8)
    rows = 8 // world
    for p in range(world):
        lo = p * rows
        fname = f"state_shard_{p:05d}.safetensors"
        snap = ShardSnapshot(
            tensors={f"w@{p}": np.ascontiguousarray(full[lo:lo + rows])},
            manifest={
                "w": {
                    "shape": [8, 8],
                    "dtype": "float32",
                    "chunks": [
                        {
                            "file": fname,
                            "stored": f"w@{p}",
                            "offset": [lo, 0],
                            "shape": [rows, 8],
                        }
                    ],
                }
            },
            process_index=p,
        )
        write_snapshot(snap, out)
    return out, full


def test_validate_coverage_accepts_hierarchical_process_map(tmp_path):
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out, full = _hierarchical_checkpoint(tmp_path)
    stats = validate_coverage(out)
    assert stats == {"leaves": 1, "chunks": 4, "files": 4}
    # the slice-major map assembles back into the global leaf
    np.testing.assert_array_equal(load_full_named(out)["w"], full)


def test_validate_coverage_rejects_dropped_slice_gap(tmp_path):
    """Losing a whole slice (procs 2,3: index AND shard files gone) is a
    row-region gap — coverage must name the leaf and refuse."""
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out, _ = _hierarchical_checkpoint(tmp_path)
    for p in (2, 3):
        os.remove(os.path.join(out, f"state_index_{p:05d}.json"))
        os.remove(os.path.join(out, f"state_shard_{p:05d}.safetensors"))
    with pytest.raises(ValueError, match="'w'.*not covered"):
        validate_coverage(out)


def test_validate_coverage_rejects_dropped_slice_shards_only(tmp_path):
    """The slice's manifests survived but its shard data did not (indexes
    on shared storage, shards local): every missing file is named."""
    from accelerate_tpu.dist_checkpoint import validate_coverage

    out, _ = _hierarchical_checkpoint(tmp_path)
    for p in (2, 3):
        os.remove(os.path.join(out, f"state_shard_{p:05d}.safetensors"))
    with pytest.raises(FileNotFoundError) as exc:
        validate_coverage(out)
    assert "state_shard_00002.safetensors" in str(exc.value)
    assert "state_shard_00003.safetensors" in str(exc.value)
