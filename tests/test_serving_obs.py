"""Serving observability plane tests: spans, gauges, SLO burn-rate,
scrape endpoint, and observable overload shedding.

Three layers again, matching the subsystem split: pure host arithmetic
first (fake-clock scheduler shedding, burn-rate windows, span ordering,
Prometheus text — no jax), then the engine wiring (records actually
flow, zero-retrace preserved, bounded memory), then the end-to-end
overload smoke (slow-marked: engine under synthetic overload → live
/metrics scrape → flight dump → `diagnose` names shed counts and SLO
attainment).
"""

import json
import urllib.request

import numpy as np
import pytest

from accelerate_tpu.serving import (
    BlockPool,
    ContinuousScheduler,
    Request,
    SLOConfig,
    SloTracker,
    SpanLog,
    spans_to_chrome_trace,
    write_chrome_trace,
)
from accelerate_tpu.serving.telemetry import ServeStats
from accelerate_tpu.telemetry import MetricsHTTPExporter, PrometheusTextSink


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def tick(self, dt=1.0):
        self.t += dt

    def __call__(self):
        return self.t


# --------------------------------------------------------------------- #
# scheduler shedding (pure host, fake clock)
# --------------------------------------------------------------------- #
class TestSchedulerShedding:
    def _sched(self, clock, **kw):
        pool = BlockPool(num_blocks=9, block_size=8)
        return ContinuousScheduler(2, pool, now=clock, **kw)

    def test_queue_bound_tail_drops(self):
        clock = FakeClock()
        sched = self._sched(clock, max_queue=2)
        reqs = [Request(prompt=[1, 2], max_new_tokens=4) for _ in range(4)]
        for r in reqs:
            sched.submit(r)
        # first two queued, the rest tail-dropped with a reason
        assert len(sched.queue) == 2
        assert [r.shed_reason for r in reqs] == [
            None, None, "queue_full", "queue_full",
        ]
        assert sched.shed_counts["queue_full"] == 2
        # those waiting kept their place (FIFO fairness)
        assert list(sched.queue) == reqs[:2]

    def test_queue_deadline_sheds_expired_head(self):
        clock = FakeClock()
        sched = self._sched(clock, max_queue_delay_s=5.0)
        old = Request(prompt=[1], max_new_tokens=2)
        sched.submit(old)
        clock.tick(4.0)
        fresh = Request(prompt=[2], max_new_tokens=2)
        sched.submit(fresh)
        assert sched.shed_expired() == []  # nothing expired yet
        clock.tick(2.0)  # old is 6s deep, fresh only 2s
        shed = sched.shed_expired()
        assert [r.request_id for r in shed] == [old.request_id]
        assert old.shed_reason == "queue_deadline"
        assert list(sched.queue) == [fresh]
        assert sched.shed_counts["queue_deadline"] == 1

    def test_admit_attributes_blocked_reason(self):
        clock = FakeClock()
        pool = BlockPool(num_blocks=9, block_size=8)
        sched = ContinuousScheduler(1, pool, now=clock)
        # one slot: second queued request blocks on no_free_slot
        for _ in range(2):
            sched.submit(Request(prompt=[1] * 4, max_new_tokens=4))
        sched.admit()
        assert sched.blocked_reasons["no_free_slot"] == 1
        assert sched.blocked_reasons["pool_exhausted"] == 0
        # big request on a 2-slot scheduler: a seat is free but the pool
        # can't fund it -> pool_exhausted
        sched2 = ContinuousScheduler(2, pool, now=clock)
        sched2.submit(Request(prompt=[1] * 30, max_new_tokens=30))
        sched2.admit()
        assert sched2.blocked_reasons["pool_exhausted"] == 1

    def test_unbounded_by_default(self):
        clock = FakeClock()
        sched = self._sched(clock)
        for _ in range(100):
            sched.submit(Request(prompt=[1], max_new_tokens=2))
        assert len(sched.queue) == 100
        assert sched.shed_expired() == []


# --------------------------------------------------------------------- #
# SLO multi-window burn-rate arithmetic (fake clock)
# --------------------------------------------------------------------- #
class TestSloTracker:
    CFG = dict(
        ttft_objective_s=0.1, e2e_objective_s=1.0, target=0.9,
        fast_window_s=10.0, slow_window_s=100.0, burn_threshold=1.0,
        min_requests=2,
    )

    def test_burn_rate_arithmetic(self):
        t = SloTracker(SLOConfig(**self.CFG))
        # 10 requests, 2 miss ttft -> error rate 0.2, budget 0.1 -> burn 2.0
        for i in range(10):
            ttft = 0.5 if i < 2 else 0.05
            t.observe(float(i), ttft, 0.5)
        snap = t.snapshot(9.0)
        assert snap["ttft_burn_fast"] == pytest.approx(2.0)
        assert snap["ttft_burn_slow"] == pytest.approx(2.0)
        assert snap["e2e_burn_fast"] == 0.0
        assert snap["ttft_attainment"] == pytest.approx(0.8)
        assert snap["breach"] and snap["breached_objectives"] == ["ttft"]

    def test_multi_window_and_gate(self):
        # a burst of misses burns the fast window but not the slow one:
        # multi-window AND must hold the alarm
        t = SloTracker(SLOConfig(**self.CFG))
        for i in range(90):  # long healthy history
            t.observe(float(i), 0.05, 0.5)
        for i in range(3):  # short burst of ttft misses at the end
            t.observe(90.0 + i, 0.5, 0.5)
        snap = t.snapshot(93.0)
        assert snap["ttft_burn_fast"] >= 1.0  # fast window is burning
        assert snap["ttft_burn_slow"] < 1.0   # diluted over the slow window
        assert not snap["breach"]

    def test_min_requests_gate(self):
        t = SloTracker(SLOConfig(**self.CFG))
        t.observe(0.0, 99.0, 99.0)  # one total miss
        snap = t.snapshot(0.0)
        assert snap["ttft_burn_fast"] > 1.0
        assert not snap["breach"]  # 1 request < min_requests

    def test_events_age_out_lifetime_persists(self):
        t = SloTracker(SLOConfig(**self.CFG))
        for i in range(5):
            t.observe(float(i), 99.0, 99.0)  # all miss
        snap = t.snapshot(500.0)  # far beyond the slow window
        assert snap["requests_slow_window"] == 0
        assert snap["ttft_burn_slow"] == 0.0
        assert snap["requests_total"] == 5
        assert snap["ttft_attainment"] == 0.0  # lifetime remembers

    def test_none_latency_counts_as_miss(self):
        t = SloTracker(SLOConfig(**self.CFG))
        t.observe(0.0, None, None)
        assert t.met_total == {"ttft": 0, "e2e": 0}


# --------------------------------------------------------------------- #
# spans: ordering invariant + Perfetto round-trip
# --------------------------------------------------------------------- #
class TestSpans:
    def _finished_span(self, log, rid="r0"):
        log.on_submit(rid, 1.0, prompt_tokens=4)
        log.on_admit(rid, 2.0)
        log.on_prefill(rid, 2.5)
        log.on_first_token(rid, 3.0)
        return log.on_finish(rid, 5.0, new_tokens=8)

    def test_ordering_invariant_and_durations(self):
        log = SpanLog()
        span = self._finished_span(log)
        assert (
            span.submit_t <= span.admit_t <= span.prefill_start_t
            <= span.first_token_t <= span.finish_t
        )
        rec = span.to_record()
        assert rec["queue_s"] == pytest.approx(1.0)
        assert rec["prefill_s"] == pytest.approx(0.5)
        assert rec["decode_s"] == pytest.approx(2.0)
        assert rec["e2e_s"] == pytest.approx(4.0)
        assert rec["state"] == "finished"

    def test_shed_span_is_terminal_with_reason(self):
        log = SpanLog()
        log.on_submit("r1", 1.0)
        span = log.on_shed("r1", 3.0, "queue_full")
        assert span.terminal and span.state == "shed"
        rec = span.to_record()
        assert rec["shed_reason"] == "queue_full"
        assert rec["first_token_t"] is None and rec["decode_s"] is None
        assert rec["e2e_s"] == pytest.approx(2.0)  # time in system pre-shed
        assert log.summary()["spans_shed"] == 1

    def test_ring_bounds_closed_spans(self):
        log = SpanLog(maxlen=3)
        for i in range(6):
            log.on_submit(f"r{i}", float(i))
            log.on_finish(f"r{i}", float(i) + 1.0, 1)
        assert len(log.closed) == 3
        assert [s.request_id for s in log.closed] == ["r3", "r4", "r5"]

    def test_perfetto_round_trip(self, tmp_path):
        log = SpanLog()
        self._finished_span(log, "good")
        log.on_submit("bad", 1.5)
        log.on_shed("bad", 4.0, "queue_deadline")
        path = str(tmp_path / "trace.json")
        write_chrome_trace(path, log.closed)
        with open(path) as f:
            payload = json.load(f)
        events = payload["traceEvents"]
        slices = [e for e in events if e["ph"] == "X"]
        names = {e["name"] for e in slices}
        assert {"queue", "prefill", "decode", "shed:queue_deadline"} <= names
        # Chrome-trace contract: complete events carry non-negative
        # microsecond ts/dur, and metadata names the request rows
        assert all(e["ts"] >= 0 and e["dur"] >= 0 for e in slices)
        metas = [e for e in events if e["ph"] == "M"]
        assert {m["args"]["name"] for m in metas} == {"good", "bad"}
        queue = next(e for e in slices if e["name"] == "queue")
        assert queue["dur"] == pytest.approx(1.0 * 1e6)

    def test_chrome_trace_time_origin(self):
        log = SpanLog()
        self._finished_span(log)
        payload = spans_to_chrome_trace(log.closed)
        first = min(
            e["ts"] for e in payload["traceEvents"] if e["ph"] == "X"
        )
        assert first == 0.0  # traces start at the earliest submit


# --------------------------------------------------------------------- #
# bounded ServeStats (the unbounded-memory satellite)
# --------------------------------------------------------------------- #
class TestServeStatsBounded:
    def test_window_bounds_percentiles_totals_cumulative(self):
        stats = ServeStats(window=4)
        for i in range(10):
            stats.add({"prompt_tokens": 1, "new_tokens": 2, "ttft_s": float(i)})
        assert len(stats.requests) == 4  # window
        s = stats.summary()
        assert s["requests"] == 10  # lifetime counter survives eviction
        assert s["new_tokens"] == 20
        assert s["ttft_s_p50"] == pytest.approx(7.5)  # over [6, 7, 8, 9]
        assert len(stats) == 10

    def test_shed_counts_in_summary(self):
        stats = ServeStats()
        stats.add_shed("queue_full")
        stats.add_shed("queue_full")
        stats.add_shed("queue_deadline")
        s = stats.summary()
        assert s["shed_total"] == 3
        assert s["shed_queue_full"] == 2
        assert s["shed_queue_deadline"] == 1


# --------------------------------------------------------------------- #
# Prometheus sink: new kinds + render()
# --------------------------------------------------------------------- #
class TestPrometheusServingKinds:
    def test_gauge_shed_slo_lines(self):
        sink = PrometheusTextSink(path=None)  # in-memory only
        sink.emit({"kind": "serve_gauge", "label": "serve",
                   "queue_depth": 7, "slot_occupancy": 0.75, "time_unix": 1.0})
        sink.emit({"kind": "shed", "reason": "queue_full", "request_id": "r"})
        sink.emit({"kind": "shed", "reason": "queue_full", "request_id": "r2"})
        sink.emit({"kind": "slo", "breach": True, "max_burn_rate": 3.5,
                   "breached_objectives": ["ttft"], "time_unix": 1.0})
        text = sink.render()
        assert 'accelerate_tpu_serve_queue_depth{label="serve"} 7.0' in text
        assert "# TYPE accelerate_tpu_serve_shed_total counter" in text
        assert 'accelerate_tpu_serve_shed_total{reason="queue_full"} 2.0' in text
        assert 'accelerate_tpu_slo_breach{label="serve"} 1.0' in text
        assert "accelerate_tpu_slo_max_burn_rate" in text
        # non-numeric fields (the objectives list) never leak into lines
        assert "breached_objectives" not in text

    def test_span_records_are_not_gauges(self):
        sink = PrometheusTextSink(path=None)
        sink.emit({"kind": "span", "request_id": "r", "submit_t": 1.0})
        assert sink.render() == "\n"

    def test_path_none_never_touches_disk(self, tmp_path, monkeypatch):
        monkeypatch.chdir(tmp_path)
        sink = PrometheusTextSink(path=None)
        sink.emit({"kind": "serve_gauge", "queue_depth": 1})
        sink.close()
        assert list(tmp_path.iterdir()) == []


# --------------------------------------------------------------------- #
# HTTP exporter: ephemeral-port scrape
# --------------------------------------------------------------------- #
class TestHTTPExporter:
    def test_scrape_metrics_healthz_state(self):
        sink = PrometheusTextSink(path=None)
        # label escaping must survive the full render->HTTP round trip
        sink.emit({"kind": "serve_gauge", "label": 'we"ird\\lab\nel',
                   "queue_depth": 3})
        ex = MetricsHTTPExporter(
            metrics_fn=sink.render,
            state_fn=lambda: {"requests": 5},
            port=0,  # ephemeral: parallel tests can't collide
        )
        with ex:
            assert ex.port != 0
            base = f"http://127.0.0.1:{ex.port}"
            body = urllib.request.urlopen(f"{base}/metrics", timeout=5)
            assert body.headers["Content-Type"].startswith("text/plain")
            text = body.read().decode()
            assert (
                'accelerate_tpu_serve_queue_depth{label="we\\"ird\\\\lab\\nel"} 3.0'
                in text
            )
            health = json.loads(
                urllib.request.urlopen(f"{base}/healthz", timeout=5).read()
            )
            assert health == {"ok": True}
            state = json.loads(
                urllib.request.urlopen(
                    f"{base}/debug/state", timeout=5
                ).read()
            )
            assert state == {"requests": 5}
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(f"{base}/nope", timeout=5)
            assert err.value.code == 404

    def test_failing_callback_is_a_500_not_a_crash(self):
        def boom():
            raise RuntimeError("sink exploded")

        with MetricsHTTPExporter(metrics_fn=boom, port=0) as ex:
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{ex.port}/metrics", timeout=5
                )
            assert err.value.code == 500
            # server survives: next route still answers
            health = urllib.request.urlopen(
                f"http://127.0.0.1:{ex.port}/healthz", timeout=5
            )
            assert health.status == 200

    def test_stop_is_idempotent(self):
        ex = MetricsHTTPExporter(port=0).start()
        ex.stop()
        ex.stop()


# --------------------------------------------------------------------- #
# engine wiring (jax; tiny model)
# --------------------------------------------------------------------- #
@pytest.fixture(scope="module")
def tiny_model():
    import jax
    import jax.numpy as jnp

    from accelerate_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return cfg, model, params


def _overloaded_engine(tiny_model, telemetry=None, **kw):
    from accelerate_tpu.serving import ServingEngine

    _, model, params = tiny_model
    clock = FakeClock()
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 8)
    engine = ServingEngine(
        model, params, telemetry=telemetry, now=clock, **kw
    )
    return engine, clock


class TestEngineObservability:
    def test_engine_sheds_on_queue_bound_with_terminal_span(self, tiny_model):
        engine, _ = _overloaded_engine(tiny_model, max_queue=2)
        rng = np.random.default_rng(0)
        rids = [
            engine.add_request(rng.integers(1, 50, size=4), max_new_tokens=4)
            for _ in range(6)
        ]
        # admission happens on step(), so only max_queue=2 requests fit
        # at submit time; the other 4 tail-drop immediately
        shed = [r for r in rids if engine.shed_reason(r) == "queue_full"]
        assert len(shed) == 4
        for _ in engine.stream():
            pass
        # every request is terminal: finished with a result or shed
        for rid in rids:
            assert (engine.result(rid) is not None) ^ (
                engine.shed_reason(rid) is not None
            )
        assert engine.summary()["shed_queue_full"] == 4
        spans = {s.request_id: s for s in engine.span_log.closed}
        assert all(spans[r].state == "shed" for r in shed)
        assert engine.trace_counts()["decode"] == 1  # zero-retrace holds

    def test_engine_sheds_on_queue_deadline(self, tiny_model):
        engine, clock = _overloaded_engine(
            tiny_model, max_slots=1, max_queue_delay_s=0.5
        )
        rng = np.random.default_rng(1)
        rids = [
            engine.add_request(rng.integers(1, 50, size=4), max_new_tokens=8)
            for _ in range(3)
        ]
        engine.step()  # admits rid0; rid1/rid2 wait
        clock.tick(1.0)  # both queued requests blow the 0.5s deadline
        engine.step()
        assert engine.shed_reason(rids[1]) == "queue_deadline"
        assert engine.shed_reason(rids[2]) == "queue_deadline"
        for _ in engine.stream():
            pass
        assert engine.result(rids[0]) is not None
        assert engine.summary()["shed_queue_deadline"] == 2

    def test_records_flow_and_span_ordering(self, tiny_model):
        from accelerate_tpu.serving import SLOConfig
        from accelerate_tpu.telemetry import StepTelemetry

        tel = StepTelemetry(True)
        engine, _ = _overloaded_engine(
            tiny_model, telemetry=tel,
            slo=SLOConfig(interval_steps=2, min_requests=1),
            gauge_interval=1,
        )
        rng = np.random.default_rng(2)
        for _ in range(3):
            engine.add_request(rng.integers(1, 50, size=5), max_new_tokens=4)
        for _ in engine.stream():
            pass
        kinds = {r.get("kind") for r in tel.records}
        assert {"serve", "span", "serve_gauge", "slo"} <= kinds
        for rec in tel.records:
            if rec.get("kind") != "span":
                continue
            assert rec["state"] == "finished"
            assert (
                rec["submit_t"] <= rec["admit_t"] <= rec["prefill_start_t"]
                <= rec["first_token_t"] <= rec["finish_t"]
            )
        gauge = next(
            r for r in tel.records if r.get("kind") == "serve_gauge"
        )
        assert {"queue_depth", "slot_occupancy", "pool_utilization",
                "tokens_in_flight"} <= set(gauge)
        tel.close()

    def test_result_fifo_eviction(self, tiny_model):
        engine, _ = _overloaded_engine(tiny_model, max_retained_results=2)
        rng = np.random.default_rng(3)
        rids = [
            engine.add_request(rng.integers(1, 50, size=4), max_new_tokens=2)
            for _ in range(4)
        ]
        for _ in engine.stream():
            pass
        retained = [r for r in rids if engine.result(r) is not None]
        assert len(retained) == 2  # oldest two evicted, newest two kept
        assert engine.result(rids[0]) is None

    def test_export_trace_after_serving(self, tiny_model, tmp_path):
        engine, _ = _overloaded_engine(tiny_model)
        rng = np.random.default_rng(4)
        engine.add_request(rng.integers(1, 50, size=4), max_new_tokens=3)
        for _ in engine.stream():
            pass
        path = str(tmp_path / "serve_trace.json")
        engine.export_trace(path)
        with open(path) as f:
            payload = json.load(f)
        assert {e["name"] for e in payload["traceEvents"]
                if e["ph"] == "X"} >= {"queue", "prefill", "decode"}

    def test_slo_breach_routes_to_anomaly(self, tiny_model, tmp_path):
        from accelerate_tpu.serving import SLOConfig, ServingEngine
        from accelerate_tpu.telemetry import StepTelemetry, TelemetryConfig

        tel = StepTelemetry(TelemetryConfig(diagnostics=str(tmp_path)))
        _, model, params = tiny_model
        # impossible objective + REAL clock (a frozen fake clock yields
        # 0s latencies, which trivially meet any objective)
        engine = ServingEngine(
            model, params, max_slots=2, block_size=8, telemetry=tel,
            slo=SLOConfig(
                ttft_objective_s=1e-9, e2e_objective_s=1e-9,
                interval_steps=1, min_requests=1,
            ),
        )
        rng = np.random.default_rng(5)
        for _ in range(2):
            engine.add_request(rng.integers(1, 50, size=4), max_new_tokens=2)
        for _ in engine.stream():
            pass
        anomalies = [
            r for r in tel.records if r.get("kind") == "anomaly"
        ]
        assert any(a["anomaly_type"] == "slo_breach" for a in anomalies)
        tel.close()


# --------------------------------------------------------------------- #
# diagnose: the serving section
# --------------------------------------------------------------------- #
class TestDiagnoseServing:
    def _dump(self, tmp_path, records):
        payload = {
            "process_index": 0, "reason": "test", "time_unix": 1.0,
            "dumps": 1, "last_step": None, "records": records, "events": [],
        }
        with open(tmp_path / "flightrec-rank0.json", "w") as f:
            json.dump(payload, f)

    def test_report_names_shed_and_slo(self, tmp_path):
        from accelerate_tpu.diagnostics import build_report, format_report

        self._dump(tmp_path, [
            {"kind": "shed", "reason": "queue_full", "request_id": "a"},
            {"kind": "serve_gauge", "queue_depth": 4, "slots_active": 2,
             "slot_occupancy": 1.0, "pool_utilization": 0.8,
             "engine_steps": 10, "tokens_in_flight": 30,
             "queue_age_p95_s": 0.2,
             "admission_blocked_no_free_slot_total": 7,
             "admission_blocked_pool_exhausted_total": 0,
             "shed_queue_full_total": 3, "shed_queue_deadline_total": 1},
            {"kind": "slo", "target": 0.99, "ttft_attainment": 0.97,
             "e2e_attainment": 0.999, "ttft_objective_s": 0.5,
             "e2e_objective_s": 5.0, "max_burn_rate": 3.0, "breach": True},
        ])
        report = build_report(str(tmp_path))
        serving = report["serving"][0]
        assert serving["shed_queue_full_total"] == 3
        assert serving["shed_queue_deadline_total"] == 1
        assert serving["slo_ttft_attainment"] == 0.97
        assert serving["slo_breach"] is True
        text = format_report(report)
        assert "queue_full=3" in text
        assert "queue_deadline=1" in text
        assert "ttft=97.00%" in text
        assert "BREACH" in text
        assert "no_free_slot=7" in text

    def test_training_only_dump_has_no_serving_section(self, tmp_path):
        from accelerate_tpu.diagnostics import build_report, format_report

        self._dump(tmp_path, [{"kind": "step", "step": 1}])
        report = build_report(str(tmp_path))
        assert report["serving"] == {}
        assert "Serving" not in format_report(report)


# --------------------------------------------------------------------- #
# end-to-end overload smoke (make serve-obs-smoke)
# --------------------------------------------------------------------- #
@pytest.mark.slow
def test_overload_smoke_end_to_end(tiny_model, tmp_path):
    """Engine under synthetic overload with the full plane attached:
    every request completes or sheds (no unbounded queue), /metrics
    serves live gauges MID-RUN, export_trace round-trips, and
    `accelerate-tpu diagnose` names shed counts and SLO attainment."""
    from accelerate_tpu.diagnostics import build_report, format_report
    from accelerate_tpu.serving import SLOConfig, ServingEngine
    from accelerate_tpu.telemetry import (
        PrometheusTextSink,
        StepTelemetry,
        TelemetryConfig,
    )

    _, model, params = tiny_model
    diag_dir = str(tmp_path / "diag")
    tel = StepTelemetry(TelemetryConfig(diagnostics=diag_dir))
    tel.add_sink(PrometheusTextSink(path=None))
    engine = ServingEngine(
        model, params, max_slots=2, block_size=8, telemetry=tel,
        max_queue=4, max_queue_delay_s=0.05,
        slo=SLOConfig(
            ttft_objective_s=0.5, e2e_objective_s=5.0, target=0.9,
            interval_steps=4, min_requests=2,
        ),
        gauge_interval=1,
    )
    exporter = engine.start_http()
    rng = np.random.default_rng(0)
    # overload: far more work than 2 slots and a 4-deep queue can hold
    rids = [
        engine.add_request(
            rng.integers(1, 50, size=int(rng.integers(4, 12))),
            max_new_tokens=int(rng.integers(4, 12)),
        )
        for _ in range(16)
    ]
    mid_run_metrics = None
    while engine.has_work:
        engine.step()
        if mid_run_metrics is None:
            mid_run_metrics = urllib.request.urlopen(
                f"http://127.0.0.1:{exporter.port}/metrics", timeout=5
            ).read().decode()

    # zero requests in limbo: every id is terminal
    finished = [r for r in rids if engine.result(r) is not None]
    shed = [r for r in rids if engine.shed_reason(r) is not None]
    assert len(finished) + len(shed) == len(rids)
    assert shed, "overload trace must actually shed"
    assert engine.trace_counts()["decode"] == 1  # zero retraces

    # the mid-run scrape saw live gauges
    assert "accelerate_tpu_serve_queue_depth" in mid_run_metrics
    assert "accelerate_tpu_serve_slot_occupancy" in mid_run_metrics

    state = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{exporter.port}/debug/state", timeout=5
    ).read())
    assert state["shed_total"] == len(shed)
    engine.stop_http()

    trace_path = str(tmp_path / "trace.json")
    engine.export_trace(trace_path)
    with open(trace_path) as f:
        assert json.load(f)["traceEvents"]

    tel.close()  # final flight dump
    report = build_report(diag_dir)
    text = format_report(report)
    serving = report["serving"][0]
    total_shed = (
        (serving["shed_queue_full_total"] or 0)
        + (serving["shed_queue_deadline_total"] or 0)
    )
    assert total_shed == len(shed)
    assert serving["slo_ttft_attainment"] is not None
    assert "Serving (latest posture per rank):" in text
    assert "shed:" in text and "SLO" in text
