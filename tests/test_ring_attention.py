"""Ring attention (context parallelism) vs the single-device reference, on
the virtual 8-device CPU mesh — forward and gradients, causal and full."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu import Accelerator
from accelerate_tpu.ops.attention import xla_attention
from accelerate_tpu.ops.ring_attention import ring_attention
from accelerate_tpu.utils.dataclasses import ParallelismPlugin


@pytest.fixture()
def sp_mesh():
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(dp_size=2, sp_size=4, fsdp_size=1)
    )
    return acc.mesh


def _qkv(S=64, B=4, H=4, Hkv=2, D=16, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32),
        jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32),
        jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32),
    )


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference(sp_mesh, causal):
    q, k, v = _qkv()
    ref = xla_attention(q, k, v, causal=causal)
    sharding = NamedSharding(sp_mesh, P("dp", "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(
        lambda q, k, v: ring_attention(q, k, v, causal=causal, mesh=sp_mesh)
    )(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=2e-5)


def test_ring_gradients_match(sp_mesh):
    q, k, v = _qkv(S=32, B=2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, causal=True, mesh=sp_mesh) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    sharding = NamedSharding(sp_mesh, P("dp", "sp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(qs, ks, vs)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_ring_falls_back_without_sp():
    acc = Accelerator(parallelism_plugin=ParallelismPlugin(dp_size=8))
    q, k, v = _qkv(S=32, B=2)
    out = ring_attention(q, k, v, causal=True, mesh=acc.mesh)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), atol=1e-5)


@pytest.mark.slow
def test_ring_32k_sp8_no_dense_fallback(monkeypatch):
    """The long-context proof point (VERDICT r2 #10): S=32768 over an sp=8
    ring on the CPU mesh. The dense path is monkeypatched to explode, so
    passing PROVES the ring ran (a dense fallback would also need a 4 GiB
    score matrix). Correctness via a row-subset oracle: full dense logits
    for sampled query rows — a complete dense reference at 32k is
    infeasible by design."""
    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(dp_size=1, sp_size=8)
    )
    mesh = acc.mesh
    S, B, H, D = 32768, 1, 1, 8
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    sh = NamedSharding(mesh, P(None, "sp"))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    import accelerate_tpu.ops.attention as attn_mod

    def _no_dense(*a, **kw):
        raise AssertionError("ring_attention took the dense fallback at 32k")

    monkeypatch.setattr(attn_mod, "xla_attention", _no_dense)
    out = np.asarray(
        jax.jit(lambda q, k, v: ring_attention(q, k, v, causal=True, mesh=mesh))(
            q, k, v
        )
    )

    scale = D ** -0.5
    rows = np.sort(rng.choice(S, 16, replace=False))
    kn, vn, qn = np.asarray(k), np.asarray(v), np.asarray(q)
    for i in rows:
        logits = (qn[0, i, 0] @ kn[0, : i + 1, 0].T) * scale
        w = np.exp(logits - logits.max())
        w /= w.sum()
        ref = w @ vn[0, : i + 1, 0]
        np.testing.assert_allclose(out[0, i, 0], ref, rtol=2e-4, atol=2e-5)
