"""Prefill/decode disaggregation: block-granular KV hand-off.

Three layers, matching the PR's split. Engine: a prefill-role engine
publishes finished chains as transfer manifests (the existing compiled
swap gather — int8 scale rows included — so the payload round-trips
bitwise) and a decode-role engine seats them with CACHED-index dedup
against the manifest's chain keys. Router: ``placement="disagg"``
routes prompts to the prefill pool and pumps manifests to the decode
replica with the deepest cached-chain overlap, with stall/drop chaos
bounded to a re-queue. The headline invariant everywhere: greedy
outputs across the hand-off are BITWISE what the colocated engine
produces, and the decode pool never compiles a prefill program.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import CausalLM, TransformerConfig
from accelerate_tpu.router import FleetRouter, HTTPReplica, InProcessReplica
from accelerate_tpu.serving import ServingEngine, TransferPlane
from accelerate_tpu.test_utils.fault_injection import (
    FaultInjector,
    FaultSpec,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def tick(self, dt: float = 0.01) -> None:
        self.t += dt

    def __call__(self) -> float:
        return self.t


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return model, params


PROMPTS = [
    list(range(3, 15)),   # 12 tokens: 1 full block + tail @ block_size=8
    list(range(5, 21)),   # 16 tokens: block-aligned
    list(range(3, 15)),   # identical to [0]: the dedup donor
    list(range(7, 30)),   # 23 tokens: long
]


def _engine(model, params, role="colocated", plane=None, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefix_cache", True)
    return ServingEngine(
        model, params, role=role, transfer_plane=plane, **kw
    )


def _run_colocated(model, params, prompts, **kw):
    eng = _engine(model, params, **kw)
    rids = [
        eng.add_request(p, max_new_tokens=6, request_id=f"r{i}")
        for i, p in enumerate(prompts)
    ]
    while eng.has_work:
        eng.step()
    return {rid: eng.result(rid) for rid in rids}


def _pump_pair(pre, dec, budget=300):
    """Drive a prefill/decode engine pair by hand (no router)."""
    for _ in range(budget):
        if not (pre.has_work or dec.has_work):
            return
        pre.step()
        for m in pre.pop_manifests():
            dec.acquire(m)
        dec.step()
    raise AssertionError("disagg pair did not drain")


# ---------------------------------------------------------------------- #
# engine roles
# ---------------------------------------------------------------------- #
def test_role_validation(tiny_model):
    model, params = tiny_model
    with pytest.raises(ValueError, match="role"):
        _engine(model, params, role="verifier")
    eng = _engine(model, params)
    assert eng.role == "colocated"
    eng.set_role("prefill")
    assert eng.role == "prefill"
    with pytest.raises(ValueError, match="role"):
        eng.set_role("nope")


def test_colocated_gauge_schema_is_unchanged(tiny_model):
    """Disaggregation is default-off: a colocated engine's gauge record
    carries NO transfer fields — the pre-PR 19 schema byte-for-byte."""
    model, params = tiny_model
    eng = _engine(model, params)
    fields = eng._gauge_fields()
    assert "role" not in fields
    assert not any(k.startswith("transfer_") for k in fields)
    assert "manifests_out" not in fields
    pre = _engine(model, params, role="prefill")
    fields = pre._gauge_fields()
    assert fields["role"] == "prefill"
    assert fields["manifests_out"] == 0


def test_handoff_outputs_bitwise_vs_colocated(tiny_model):
    model, params = tiny_model
    base = _run_colocated(model, params, PROMPTS)
    plane = TransferPlane("inprocess")
    pre = _engine(model, params, role="prefill", plane=plane)
    dec = _engine(model, params, role="decode", plane=plane)
    for i, p in enumerate(PROMPTS):
        pre.add_request(p, max_new_tokens=6, request_id=f"r{i}")
    _pump_pair(pre, dec)
    got = {rid: dec.result(rid) for rid in base}
    assert got == base
    # prompt ingestion only: the prefill engine retains no results and
    # the decode engine compiled ZERO prefill programs
    assert all(pre.result(rid) is None for rid in base)
    assert dec.trace_counts()["prefill"] == 0
    assert dec.trace_counts()["decode"] == 1  # the one (max_slots, 1)


def test_manifest_acquire_dedups_cached_blocks(tiny_model):
    """The CACHED-index dedup satellite: an identical prompt's second
    hand-off moves ONLY the tail block — every full prompt block is
    found warm in the decode pool's content index and refcounted
    instead of restored."""
    model, params = tiny_model
    plane = TransferPlane("inprocess")
    pre = _engine(model, params, role="prefill", plane=plane)
    dec = _engine(model, params, role="decode", plane=plane)
    prompt = PROMPTS[0]  # 12 tokens: 1 full block + 4-token tail
    pre.add_request(prompt, max_new_tokens=4, request_id="a")
    while pre.has_work:
        pre.step()
    (m1,) = pre.pop_manifests()
    res1 = dec.acquire(m1)
    assert res1["seated"] and res1["reused_blocks"] == 0
    assert res1["moved_blocks"] == 2  # full block + partial tail
    while dec.has_work:
        dec.step()
    pre.add_request(prompt, max_new_tokens=4, request_id="b")
    while pre.has_work:
        pre.step()
    (m2,) = pre.pop_manifests()
    res2 = dec.acquire(m2)
    assert res2["seated"] and res2["reused_blocks"] == 1
    assert res2["moved_blocks"] == 1  # only the partial tail moved
    assert res2["moved_bytes"] == m2.bytes_per_block()
    while dec.has_work:
        dec.step()
    assert dec.result("b") == dec.result("a")
    gauges = dec.transfer_gauges()
    assert gauges["blocks_deduped"] == 1 and gauges["manifests_in"] == 2


def test_acquire_defers_to_inbox_when_full(tiny_model):
    model, params = tiny_model
    plane = TransferPlane("inprocess")
    pre = _engine(model, params, role="prefill", plane=plane)
    dec = _engine(model, params, role="decode", plane=plane, max_slots=1)
    for i in (0, 3):
        pre.add_request(PROMPTS[i], max_new_tokens=4, request_id=f"r{i}")
    while pre.has_work:
        pre.step()
    manifests = pre.pop_manifests()
    assert len(manifests) == 2
    assert dec.acquire(manifests[0])["seated"]
    assert dec.acquire(manifests[1]) == {"seated": False}
    assert dec.transfer_gauges()["transfer_inbox_depth"] == 1
    assert dec.has_work  # the parked manifest IS work
    while dec.has_work:
        dec.step()  # seat frees -> inbox drains -> both finish
    assert dec.result("r0") is not None and dec.result("r3") is not None


# ---------------------------------------------------------------------- #
# int8 swap round-trip (PR 17 x PR 17 interaction)
# ---------------------------------------------------------------------- #
def test_int8_swap_roundtrip_is_bitwise_including_scales(tiny_model):
    """swap_out -> swap_in of int8-quantized KV blocks is bitwise: the
    quantized codes AND the per-token fp32 scale rows ride the same
    gather/scatter, so a restored block dequantizes identically."""
    model, params = tiny_model
    eng = _engine(model, params, kv_dtype="int8", prefix_cache=False)
    eng.add_request(PROMPTS[3], max_new_tokens=4, request_id="q")
    eng.step()  # admit + prefill: blocks now hold real quantized KV
    (slot,) = [s for s in eng.scheduler.slots if s.busy]
    blocks = list(slot.blocks)
    data, nbytes = eng._swap_out_blocks(blocks)
    assert nbytes > 0
    dtypes = {d.dtype for d in data}
    assert np.dtype(np.int8) in dtypes     # quantized K/V pools
    assert np.dtype(np.float32) in dtypes  # per-token scale rows
    # scale rows are per-token: (blocks, layers, block_size) fp32
    scale_leaves = [d for d in data if d.dtype == np.float32]
    assert scale_leaves and all(
        d.shape[0] == len(blocks) and d.shape[-1] == eng.block_size
        for d in scale_leaves
    )
    fresh = eng.pool.allocate(len(blocks))
    eng._restore_blocks(fresh, data)
    again, nbytes2 = eng._swap_out_blocks(fresh)
    assert nbytes2 == nbytes
    for a, b in zip(data, again):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_int8_handoff_outputs_match_int8_colocated(tiny_model):
    model, params = tiny_model
    base = _run_colocated(model, params, PROMPTS[:2], kv_dtype="int8")
    pre = _engine(model, params, role="prefill", kv_dtype="int8")
    dec = _engine(model, params, role="decode", kv_dtype="int8")
    for i, p in enumerate(PROMPTS[:2]):
        pre.add_request(p, max_new_tokens=6, request_id=f"r{i}")
    _pump_pair(pre, dec)
    assert {rid: dec.result(rid) for rid in base} == base


# ---------------------------------------------------------------------- #
# disagg routing
# ---------------------------------------------------------------------- #
def _disagg_fleet(model, params, clock=None, n_prefill=2, n_decode=2):
    clock = clock or time.monotonic
    plane = TransferPlane("inprocess", now=clock)
    reps = [
        InProcessReplica(
            f"p{i}",
            _engine(model, params, role="prefill", plane=plane, now=clock),
        )
        for i in range(n_prefill)
    ] + [
        InProcessReplica(
            f"d{i}",
            _engine(model, params, role="decode", plane=plane, now=clock),
        )
        for i in range(n_decode)
    ]
    router = FleetRouter(
        reps, policy="prefix_affinity", placement="disagg",
        transfer_plane=plane, now=clock,
    )
    return router, plane


def _drain(router, budget=500):
    for _ in range(budget):
        if not router.has_work:
            return
        router.step()
    raise AssertionError("disagg fleet did not drain")


def test_router_disagg_end_to_end_bitwise(tiny_model):
    model, params = tiny_model
    base = _run_colocated(model, params, PROMPTS)
    router, plane = _disagg_fleet(model, params)
    for i, p in enumerate(PROMPTS):
        router.add_request(p, max_new_tokens=6, request_id=f"r{i}")
    _drain(router)
    assert {rid: router.result(rid) for rid in base} == base
    summary = router.transfer_summary()
    assert summary["placement"] == "disagg"
    assert summary["delivered_total"] == 4
    assert summary["in_flight"] == 0
    assert summary["plane"]["transfers_total"] == 4
    assert 0.0 <= summary["plane"]["dedup_ratio"] <= 1.0
    rec = router.transfer_record("r0")
    assert rec is not None and rec["src"].startswith("p")
    assert rec["dst"].startswith("d") and rec["bytes"] > 0
    # no prompt ever landed on a decode replica
    assert all(
        router.routed_by_replica[f"d{i}"] == 0 for i in range(2)
    )


def test_transfer_stall_damage_bounded_to_waiting(tiny_model):
    """transfer_stall: deliveries wedge but nothing is lost — every
    affected request finishes after the window, seated decodes never
    notice, and the recovery time is reported."""
    model, params = tiny_model
    clock = FakeClock()
    router, plane = _disagg_fleet(model, params, clock=clock)
    for i, p in enumerate(PROMPTS):
        router.add_request(p, max_new_tokens=6, request_id=f"r{i}")
    router.stall_transfers(2.0)
    for _ in range(50):
        router.step()
        clock.tick(0.01)
    assert router.transfer_summary()["in_flight"] > 0  # wedged, not lost
    assert router.requests_lost == 0
    clock.tick(5.0)  # stall expires
    _drain(router)
    base = _run_colocated(model, params, PROMPTS)
    assert {rid: router.result(rid) for rid in base} == base
    summary = router.transfer_summary()
    assert router.requests_lost == 0
    assert summary["stalls_total"] == 1
    assert summary["stall_recovery_s"] > 0.0


def test_transfer_drop_requeues_under_original_id(tiny_model):
    model, params = tiny_model
    clock = FakeClock()
    router, plane = _disagg_fleet(
        model, params, clock=clock, n_prefill=1, n_decode=1
    )
    router.add_request(PROMPTS[0], max_new_tokens=6, request_id="r0")
    router.stall_transfers(60.0)  # hold the manifest on the wire
    for _ in range(50):
        router.step()
        clock.tick(0.01)
        if router.transfer_summary()["in_flight"]:
            break
    assert router.transfer_summary()["in_flight"] == 1
    out = router.drop_transfers()
    assert out["dropped"] == 1
    assert router.requests_lost == 0
    assert router.requests_requeued == 1
    clock.tick(120.0)
    _drain(router)
    base = _run_colocated(model, params, PROMPTS[:1])
    assert router.result("r0") == base["r0"]
    assert router.transfer_summary()["dropped_total"] == 1


def test_kill_mid_transfer_requeues_parked_manifests(tiny_model):
    """A decode replica dying with manifests parked in its inbox gives
    those prompts back to the fleet instead of losing them."""
    model, params = tiny_model
    clock = FakeClock()
    router, plane = _disagg_fleet(
        model, params, clock=clock, n_prefill=1, n_decode=2
    )
    for i, p in enumerate(PROMPTS):
        router.add_request(p, max_new_tokens=6, request_id=f"r{i}")
    for _ in range(30):
        router.step()
        clock.tick(0.01)
        if router.transfers_delivered_total:
            break
    victim = router.transfer_record(
        next(
            rid for rid in ("r0", "r1", "r2", "r3")
            if router.transfer_record(rid)
        )
    )["dst"]
    router.kill(victim)
    _drain(router)
    base = _run_colocated(model, params, PROMPTS)
    for rid in base:
        got = router.result(rid)
        # seated decodes on the victim died with it (counted as lost);
        # everything that re-ran must still be bitwise-correct
        assert got is None or got == base[rid]
    assert router.transfer_summary()["in_flight"] == 0


# ---------------------------------------------------------------------- #
# fault grammar + chaos
# ---------------------------------------------------------------------- #
def test_fault_grammar_accepts_transfer_actions():
    spec = FaultSpec.parse("transfer_stall@3:secs=2:replica=1")
    assert spec.action == "transfer_stall"
    assert spec.stall_secs == 2.0 and spec.replica == 1
    spec = FaultSpec.parse("transfer_drop@5")
    assert spec.action == "transfer_drop" and spec.replica is None
    with pytest.raises(ValueError, match="secs"):
        FaultSpec.parse("transfer_drop@5:secs=2")


def test_chaos_transfer_actions_fire_against_disagg_fleet(tiny_model):
    from accelerate_tpu.loadgen.chaos import ChaosAdapter

    model, params = tiny_model
    clock = FakeClock()
    router, plane = _disagg_fleet(
        model, params, clock=clock, n_prefill=1, n_decode=1
    )
    injector = FaultInjector([], rank=0, generation=0)
    chaos = ChaosAdapter(router, injector, clock)
    injector.specs = [FaultSpec.parse("transfer_stall@0:secs=3:replica=0")]
    injector.maybe_fire(0)
    (event,) = [e for e in chaos.events if e["action"] == "transfer_stall"]
    assert event["secs"] == 3.0 and event["replica"] == "p0"
    assert router.transfer_summary()["stalls_total"] == 1
    injector.specs = [FaultSpec.parse("transfer_drop@1")]
    injector.maybe_fire(1)
    (event,) = [e for e in chaos.events if e["action"] == "transfer_drop"]
    assert event["dropped"] == 0  # nothing in flight yet: still bounded


def test_chaos_transfer_actions_skip_plain_engine(tiny_model):
    """New SERVING_ACTIONS must not break existing soaks: ChaosAdapter
    installs the transfer handlers against ANY engine and they skip
    inert (with an event) when the engine is not a disagg router."""
    from accelerate_tpu.loadgen.chaos import ChaosAdapter

    model, params = tiny_model
    eng = _engine(model, params)
    injector = FaultInjector([], rank=0, generation=0)
    chaos = ChaosAdapter(eng, injector, FakeClock())  # must not raise
    injector.specs = [
        FaultSpec.parse("transfer_stall@0:secs=1"),
        FaultSpec.parse("transfer_drop@0"),
    ]
    injector.maybe_fire(0)
    skips = [e for e in chaos.events if e.get("skipped")]
    assert len(skips) == 2
    assert all(e["skipped"] == "not_a_disagg_fleet" for e in skips)


# ---------------------------------------------------------------------- #
# HTTPReplica digest degradation (bugfix satellite)
# ---------------------------------------------------------------------- #
def test_http_digest_degrades_to_empty_instead_of_raising():
    rep = HTTPReplica("r0", "http://127.0.0.1:1", timeout_s=0.05)
    digest = rep.fetch_digest(16)  # connection refused: must NOT raise
    assert digest["entries"] == []
    assert digest["block_size"] == 0 and digest["fingerprint"] == ""
    assert digest["stale"] is True
    assert rep.digest_failures_total == 1
    rep.fetch_digest(16)
    assert rep.digest_failures_total == 2


def test_router_prefers_last_known_digest_over_degraded():
    class Rep:
        name = "r0"
        alive = True
        draining = False

        def __init__(self):
            self.good = True

        def fetch_digest(self, max_entries):
            if self.good:
                return {
                    "entries": ["aa"], "block_size": 4, "fingerprint": "fp",
                }
            return {
                "entries": [], "block_size": 0, "fingerprint": "",
                "stale": True,
            }

    clock = FakeClock()
    router = FleetRouter(now=clock, digest_max_age_s=0.0)
    rep = Rep()
    router.register(rep)
    assert router._digest(rep)["keys"] == {"aa"}
    rep.good = False
    clock.tick(1.0)
    # the degraded empty digest must not wipe the cached warm view
    assert router._digest(rep)["keys"] == {"aa"}
