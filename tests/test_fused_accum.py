"""Fused gradient accumulation: the `lax.scan`-over-stacked-microbatches
step (one dispatch per OPTIMIZER step) must be arithmetically identical to
the per-microbatch `lax.cond` path, compose with the superbatch dataloader
and AOT warmup (zero retraces), and fix the metric semantics (no fake
grad_norm=0.0 on non-sync steps) on BOTH paths."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, DataLoader
from accelerate_tpu.state import AcceleratorState, GradientState
from accelerate_tpu.telemetry import TelemetryConfig
from accelerate_tpu.telemetry.sinks import TrackerBridgeSink
from accelerate_tpu.utils.dataclasses import GradientAccumulationPlugin


class RegressionDataset:
    def __init__(self, n=96, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(n, 1)).astype(np.float32)
        self.y = (2.0 * self.x[:, 0] + 3.0 + 0.05 * rng.normal(size=n)).astype(
            np.float32
        )

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return {"x": self.x[i], "y": self.y[i]}


def loss_fn(params, batch):
    pred = batch["x"][:, 0] * params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _reset():
    AcceleratorState._reset_state()
    GradientState._reset_state()


def _run_mode(
    fused,
    *,
    K=4,
    n=96,
    batch_size=8,
    mixed_precision=None,
    policy=None,
    max_grad_norm=None,
    w0=0.0,
    remat_policy=None,
    optimizer=None,
    telemetry=False,
):
    """One full pass over the dataset in one accumulation mode; returns
    (final carry, accelerator, last metrics)."""
    _reset()
    kwargs = {}
    if mixed_precision is not None:
        kwargs["mixed_precision"] = mixed_precision
    if policy is not None:
        kwargs["mixed_precision_policy"] = policy
    acc = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=K, fused=fused
        ),
        telemetry=telemetry,
        **kwargs,
    )
    ds = RegressionDataset(n)
    loader = DataLoader(ds, batch_size=batch_size, shuffle=False)
    params = {"w": jnp.asarray(w0), "b": jnp.asarray(0.0)}
    params, opt, prepared = acc.prepare(
        params, optimizer or optax.adam(0.1), loader
    )
    step = acc.unified_step(
        loss_fn, opt, max_grad_norm=max_grad_norm, remat_policy=remat_policy
    )
    carry = acc.init_carry(params, opt)
    metrics = None
    for batch in prepared:
        carry, metrics = step(carry, batch)
    return carry, acc, metrics


def _tree_bitwise_equal(a, b):
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(leaves_a) == len(leaves_b)
    for la, lb in zip(leaves_a, leaves_b):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_fused_parity_fp32_bitwise():
    """ISSUE 4 acceptance: fused and unfused bitwise-match params (and
    opt_state) after 3 optimizer steps in fp32."""
    unfused, acc_u, _ = _run_mode(False)
    fused, acc_f, _ = _run_mode(True)
    assert int(unfused["opt_step"]) == int(fused["opt_step"]) == 3
    _tree_bitwise_equal(unfused["params"], fused["params"])
    _tree_bitwise_equal(unfused["opt_state"], fused["opt_state"])
    # carry layout: the fused mode dropped the per-call bookkeeping
    assert "micro_step" in unfused and "accum_grads" in unfused
    assert "micro_step" not in fused and "accum_grads" not in fused
    # host-mirror recovery works on both carry layouts
    acc_f.sync_from_carry(fused)
    assert acc_f.step == 3 and acc_f.gradient_state.sync_gradients
    acc_u.sync_from_carry(unfused)
    assert acc_u.step == 12


def test_fused_parity_with_clipping():
    unfused, _, mu = _run_mode(False, max_grad_norm=0.5, w0=50.0)
    fused, _, mf = _run_mode(True, max_grad_norm=0.5, w0=50.0)
    _tree_bitwise_equal(unfused["params"], fused["params"])
    # the sync-step gradient norm is the same real (pre-clip) norm
    assert float(mu["grad_norm"]) == float(mf["grad_norm"]) > 0.5


def test_fused_parity_bf16_compute():
    unfused, _, _ = _run_mode(False, mixed_precision="bf16")
    fused, _, _ = _run_mode(True, mixed_precision="bf16")
    for key in ("w", "b"):
        np.testing.assert_allclose(
            float(unfused["params"][key]),
            float(fused["params"][key]),
            rtol=2e-2,
        )
    # master params stay fp32 in both modes
    assert fused["params"]["w"].dtype == jnp.float32


def test_fused_fp16_overflow_skip_parity():
    """fp16 loss-scaling overflow: a huge w makes the scaled backward
    overflow fp16, so BOTH paths must skip the update (params held), halve
    the scale, and still advance opt_step — identically."""
    from accelerate_tpu import MixedPrecisionPolicy

    def make_policy():
        policy = MixedPrecisionPolicy.from_precision("fp16")
        policy.loss_scale_init = 2.0**15
        return policy

    results = {}
    for fused in (False, True):
        carry, _, metrics = _run_mode(
            fused, policy=make_policy(), mixed_precision="fp16", w0=1e4,
            optimizer=optax.sgd(1e-4),
        )
        assert not bool(metrics["grads_finite"])  # the overflow was real
        results[fused] = carry
    unfused, fused = results[False], results[True]
    assert int(unfused["opt_step"]) == int(fused["opt_step"]) == 3
    _tree_bitwise_equal(unfused["params"], fused["params"])
    # every step overflowed: params held at init, scale halved per step
    assert float(fused["params"]["w"]) == 1e4
    assert float(unfused["loss_scale"].scale) == float(
        fused["loss_scale"].scale
    ) == 2.0**15 / 2**3


def test_fused_remat_policy_parity():
    plain, _, _ = _run_mode(True)
    remat, _, _ = _run_mode(True, remat_policy=True)
    np.testing.assert_allclose(
        float(plain["params"]["w"]), float(remat["params"]["w"]), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(plain["params"]["b"]), float(remat["params"]["b"]), rtol=1e-6
    )


def test_fused_zero_retraces_after_warmup():
    """ISSUE 4 acceptance: the fused path compiles exactly one executable
    per optimizer step — after AOT warmup from the superbatch loader's
    spec, no real call traces; telemetry shows one record per optimizer
    step with microbatches=K and dispatches_per_opt_step=1."""
    _reset()
    K = 4
    acc = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=K, fused=True
        ),
        telemetry=True,
    )
    ds = RegressionDataset(64)
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    params = {"w": jnp.asarray(0.0), "b": jnp.asarray(0.0)}
    params, opt, prepared = acc.prepare(params, optax.adam(0.1), loader)
    assert prepared.superbatch == K  # auto-wired from the fused plugin
    step = acc.unified_step(loss_fn, opt)
    carry = acc.init_carry(params, opt)

    record = acc.warmup(step, carry, prepared)
    assert record["compile_time_s"] > 0
    detector = acc.telemetry.detector(step.label)
    signatures = len(detector._seen)

    opt_steps = 0
    for batch in prepared:
        carry, metrics = step(carry, batch)
        opt_steps += 1
    assert opt_steps == 2  # 8 microbatches / K — every call IS an opt step
    assert int(carry["opt_step"]) == opt_steps
    assert detector.retraces == 0
    assert len(detector._seen) == signatures  # true AOT dispatch

    recs = [r for r in acc.telemetry.records if r.get("kind") == "step"]
    assert len(recs) == opt_steps
    for rec in recs:
        assert rec["retraced"] is False
        assert rec["microbatches"] == K
        assert rec["dispatches_per_opt_step"] == 1
        assert rec["is_sync_step"] == 1.0
        assert np.isfinite(rec["grad_norm"])


def test_trackers_never_see_fake_grad_norm():
    """Satellite: non-sync microbatch steps must not report grad_norm=0.0.
    The unfused path's hold branch reports NaN and the collector OMITS the
    field, so JSONL records and tracker charts only ever see real
    sync-step norms."""

    class CaptureTracker:
        def __init__(self):
            self.logged = []

        def log(self, values, step=None):
            self.logged.append(values)

    _reset()
    K = 2
    acc = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(num_steps=K),
        telemetry=TelemetryConfig(),
    )
    tracker = CaptureTracker()
    acc.telemetry.add_sink(TrackerBridgeSink([tracker]))
    ds = RegressionDataset(64)
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    params = {"w": jnp.asarray(0.0), "b": jnp.asarray(0.0)}
    params, opt, prepared = acc.prepare(params, optax.adam(0.1), loader)
    step = acc.unified_step(loss_fn, opt)
    carry = acc.init_carry(params, opt)
    for batch in prepared:
        carry, _ = step(carry, batch)

    recs = [r for r in acc.telemetry.records if r.get("kind") == "step"]
    sync = [r for r in recs if r["is_sync_step"] == 1.0]
    nonsync = [r for r in recs if r["is_sync_step"] != 1.0]
    assert len(sync) == 4 and len(nonsync) == 4
    for rec in nonsync:
        assert "grad_norm" not in rec  # omitted, not NaN and never 0.0
        assert "loss" in rec  # per-microbatch loss still reported
    for rec in sync:
        assert np.isfinite(rec["grad_norm"]) and rec["grad_norm"] > 0.0
    # trackers: a grad_norm of exactly 0.0 never reaches a chart
    logged_norms = [
        v["telemetry/grad_norm"]
        for v in tracker.logged
        if "telemetry/grad_norm" in v
    ]
    assert len(logged_norms) == len(sync)
    assert all(n > 0.0 for n in logged_norms)
    assert tracker.logged  # the bridge did forward the other fields


def test_fused_step_rejects_unfused_carry():
    _reset()
    acc = Accelerator(
        gradient_accumulation_plugin=GradientAccumulationPlugin(
            num_steps=2, fused=True
        )
    )
    params = {"w": jnp.asarray(0.0)}

    def l(p, b):
        return jnp.mean((b["x"][:, 0] * p["w"]) ** 2)

    params = acc.prepare(params)
    opt = acc.prepare(optax.sgd(0.1))
    step = acc.unified_step(l, opt)
    stale = acc.init_carry(params, opt, fused_accumulation=False)
    batch = {"x": jnp.ones((2, 8, 1))}
    with pytest.raises(ValueError, match="fused accumulation carries no"):
        step(stale, batch)


def test_fused_env_knob(monkeypatch):
    monkeypatch.setenv("ACCELERATE_TPU_FUSED_ACCUM", "1")
    plugin = GradientAccumulationPlugin(num_steps=4)
    assert plugin.fused
    _reset()
    acc = Accelerator(gradient_accumulation_steps=4)
    assert acc.gradient_state.fused
    params = acc.prepare({"w": jnp.asarray(0.0)})
    opt = acc.prepare(optax.sgd(0.1))
    carry = acc.init_carry(params, opt)
    assert "micro_step" not in carry and "accum_grads" not in carry


def test_fused_rejects_sync_each_batch():
    with pytest.raises(ValueError, match="sync_each_batch"):
        GradientAccumulationPlugin(num_steps=2, fused=True, sync_each_batch=True)
