"""Big-model machinery tests — models reference tests/test_big_modeling.py
(1050 LoC) and test_modeling_utils.py (773): abstract init, size
computation, auto device maps, tiered dispatch, checkpoint streaming, and
the OOM-retry decorator."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import (
    OffloadedLeaf,
    check_device_map,
    compute_module_sizes,
    cpu_offload,
    disk_offload,
    dispatch_params,
    get_balanced_memory,
    get_max_memory,
    infer_auto_device_map,
    init_empty_weights,
    load_checkpoint_and_dispatch,
    materialize_offloaded,
    streamed_apply,
)
from accelerate_tpu.checkpointing import save_model_weights
from accelerate_tpu.models import CausalLM, TransformerConfig
from accelerate_tpu.utils.memory import (
    find_executable_batch_size,
    release_memory,
    should_reduce_batch_size,
)
from accelerate_tpu.utils.offload import (
    OffloadedWeightsLoader,
    PrefixedDataset,
    offload_state_dict,
)


def _params():
    return {
        "embed": {"w": jnp.ones((64, 32))},
        "layer1": {"kernel": jnp.ones((32, 32)), "bias": jnp.zeros((32,))},
        "layer2": {"kernel": jnp.ones((32, 32)), "bias": jnp.zeros((32,))},
        "head": {"w": jnp.ones((32, 64))},
    }


def test_init_empty_weights_allocates_nothing():
    cfg = TransformerConfig.tiny()
    model = CausalLM(cfg)
    abstract = init_empty_weights(
        model.init, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    leaves = jax.tree.leaves(abstract)
    assert all(isinstance(l, jax.ShapeDtypeStruct) for l in leaves)
    assert len(leaves) > 5


def test_compute_module_sizes():
    sizes = compute_module_sizes(_params())
    assert sizes[""] == sum(
        int(np.prod(l.shape)) * 4 for l in jax.tree.leaves(_params())
    )
    assert sizes["layer1"] == (32 * 32 + 32) * 4
    assert sizes["layer1//kernel"] == 32 * 32 * 4


def test_get_max_memory_override_and_probe():
    mm = get_max_memory({0: "1GB", "cpu": 2 * 2**30})
    assert mm == {0: 2**30, "cpu": 2 * 2**30}
    probed = get_max_memory()
    assert "cpu" in probed and 0 in probed and probed[0] > 0


def test_infer_auto_device_map_spills_tiers():
    params = _params()
    # budget fits embed only on device 0; rest spills to cpu then disk
    sizes = compute_module_sizes(params)
    mm = {0: sizes["embed"] + 64, "cpu": sizes["layer1"] + 64}
    dm = infer_auto_device_map(params, mm)
    assert dm["embed//w"] == 0
    assert dm["layer1//kernel"] == "cpu"
    # later groups must be on disk
    assert dm["head//w"] == "disk"
    check_device_map(params, dm)


def test_dispatch_and_reload_disk(tmp_path):
    params = _params()
    dm = {"embed": 0, "layer1": "cpu", "layer2": "disk", "head": 0}
    placed = dispatch_params(params, dm, offload_dir=str(tmp_path))
    assert isinstance(placed["embed"]["w"], jax.Array)
    assert isinstance(placed["layer1"]["kernel"], (np.ndarray, jax.Array))
    # disk leaves come back as lazy, loadable handles (VERDICT r1 weak#5:
    # a disk-offloaded model must still be executable)
    handle = placed["layer2"]["kernel"]
    assert isinstance(handle, OffloadedLeaf)
    assert handle.shape == (32, 32) and handle.dtype == jnp.float32
    np.testing.assert_allclose(
        handle.load(), np.asarray(params["layer2"]["kernel"])
    )
    loader = OffloadedWeightsLoader(save_folder=str(tmp_path))
    np.testing.assert_allclose(
        loader["layer2//kernel"], np.asarray(params["layer2"]["kernel"])
    )


def _forward(p, x):
    h = x @ p["embed"]["w"]
    h = jnp.tanh(h @ p["layer1"]["kernel"] + p["layer1"]["bias"])
    h = jnp.tanh(h @ p["layer2"]["kernel"] + p["layer2"]["bias"])
    return h @ p["head"]["w"]


def test_disk_offloaded_model_forward(tmp_path):
    """The AlignDevicesHook capability (reference hooks.py:219): a model
    with disk-offloaded weights still produces correct logits."""
    params = jax.tree.map(
        lambda l: jax.random.normal(jax.random.PRNGKey(l.size % 97), l.shape),
        _params(),
    )
    x = jax.random.normal(jax.random.PRNGKey(3), (4, 64))
    ref = _forward(params, x)
    placed = dispatch_params(
        params,
        {"embed": 0, "layer1": "disk", "layer2": "disk", "head": "cpu"},
        offload_dir=str(tmp_path),
    )
    live = materialize_offloaded(placed)
    np.testing.assert_allclose(
        np.asarray(_forward(live, x)), np.asarray(ref), rtol=2e-5, atol=1e-5
    )


def test_streamed_apply_matches_dense(tmp_path):
    """Layer-group streaming from disk: only group_size layers are live at
    once, output identical to the dense stacked forward."""
    L, D = 6, 16
    stacked = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) / np.sqrt(D),
        "b": jax.random.normal(jax.random.PRNGKey(1), (L, D)) * 0.01,
    }
    x = jax.random.normal(jax.random.PRNGKey(2), (4, D))

    def block_fn(group, h):
        for i in range(group["w"].shape[0]):
            h = jnp.tanh(h @ group["w"][i] + group["b"][i])
        return h

    ref = block_fn(stacked, x)
    disk = disk_offload(stacked, str(tmp_path))
    assert all(
        isinstance(l, OffloadedLeaf)
        for l in jax.tree.leaves(
            disk, is_leaf=lambda l: isinstance(l, OffloadedLeaf)
        )
    )
    out = streamed_apply(block_fn, disk, x, group_size=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5)


def test_cpu_and_disk_offload_whole_tree(tmp_path):
    params = _params()
    host = cpu_offload(params)
    assert all(
        isinstance(l, (np.ndarray, jax.Array)) for l in jax.tree.leaves(host)
    )
    disk = disk_offload(params, str(tmp_path))
    assert os.path.isfile(tmp_path / "index.json")


def test_load_checkpoint_and_dispatch_gspmd(tmp_path):
    """The TPU-idiomatic path: stream safetensors straight onto mesh
    shardings (no hooks)."""
    from accelerate_tpu import Accelerator
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin

    acc = Accelerator(
        parallelism_plugin=ParallelismPlugin(fsdp_size=8, min_weight_size=16)
    )
    params = _params()
    save_model_weights(params, str(tmp_path))
    abstract = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params
    )
    loaded = load_checkpoint_and_dispatch(
        abstract, str(tmp_path), mesh=acc.mesh,
        plugin=acc.state.parallelism_plugin,
    )
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    k = loaded["layer1"]["kernel"]
    assert "fsdp" in jax.tree.leaves(tuple(k.sharding.spec))


def test_load_checkpoint_and_dispatch_device_map(tmp_path):
    params = _params()
    save_model_weights(params, str(tmp_path / "ckpt"))
    abstract = jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), params
    )
    loaded = load_checkpoint_and_dispatch(
        abstract, str(tmp_path / "ckpt"), device_map={"": 0},
    )
    np.testing.assert_allclose(
        np.asarray(loaded["head"]["w"]), np.asarray(params["head"]["w"])
    )


def test_offload_state_dict_roundtrip(tmp_path):
    sd = {"a": np.arange(6, dtype=np.float32).reshape(2, 3), "b": np.ones((4,))}
    offload_state_dict(str(tmp_path), sd)
    loader = OffloadedWeightsLoader(save_folder=str(tmp_path))
    assert set(loader) == {"a", "b"}
    np.testing.assert_allclose(loader["a"], sd["a"])
    pre = PrefixedDataset(loader, "a")
    assert len(pre) == 1


def test_should_reduce_batch_size():
    assert should_reduce_batch_size(
        RuntimeError("RESOURCE_EXHAUSTED: Ran out of memory in memory space hbm")
    )
    assert not should_reduce_batch_size(ValueError("shape mismatch"))


def test_find_executable_batch_size():
    tried = []

    @find_executable_batch_size(starting_batch_size=16)
    def train(batch_size):
        tried.append(batch_size)
        if batch_size > 4:
            raise RuntimeError("RESOURCE_EXHAUSTED: Ran out of memory")
        return batch_size

    assert train() == 4
    assert tried == [16, 8, 4]


def test_find_executable_batch_size_requires_arg():
    @find_executable_batch_size(starting_batch_size=8)
    def bad(x):
        return x

    with pytest.raises(TypeError):
        bad()


def test_release_memory():
    x = jnp.ones((8, 8))
    release_memory(x)
    assert x.is_deleted()
