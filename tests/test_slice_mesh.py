"""Hierarchical (multi-slice) mesh tests: slice-aware device assignment,
the DCN-aware gradient reduction (reduce-scatter in-slice -> all-reduce
cross-slice -> all-gather in-slice) proven numerically equivalent to the
flat all-reduce, the collective-overlap policy for DCN-crossing meshes,
and zero retraces after warmup on the hierarchical layout.

All CPU-runnable: ``ACCELERATE_TPU_NUM_SLICES`` simulates a multi-slice
topology on the virtual 8-device backend (CPU devices carry no
``slice_index``, so the env override is the only way to exercise these
paths off-TPU — which is exactly what it exists for).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from accelerate_tpu import Accelerator, ParallelismPlugin
from accelerate_tpu.compilation.overlap import (
    DCN_OVERLAP_OPTIONS,
    overlap_options,
)
from accelerate_tpu.parallel.mesh import (
    NUM_SLICES_ENV,
    build_mesh,
    fault_domain_of_rank,
    mesh_num_slices,
    resolve_num_slices,
)
from accelerate_tpu.parallel.sharding import (
    hierarchical_psum,
    wants_collective_overlap,
)
from accelerate_tpu.utils.dataclasses import ShardingStrategy


def _fresh_accelerator(**kwargs) -> Accelerator:
    from accelerate_tpu.state import AcceleratorState, GradientState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    return Accelerator(**kwargs)


def _hier_mesh(monkeypatch, num_slices=2, dp=2, fsdp=4):
    """A dp(DCN) x fsdp(ICI) mesh simulating ``num_slices`` slices."""
    monkeypatch.setenv(NUM_SLICES_ENV, str(num_slices))
    return build_mesh(
        ParallelismPlugin(dp_size=dp, fsdp_size=fsdp, min_weight_size=1)
    )


# ---------------------------------------------------------------------- #
# slice resolution + slice-aware device assignment
# ---------------------------------------------------------------------- #
def test_resolve_num_slices_env_overrides(monkeypatch):
    monkeypatch.setenv(NUM_SLICES_ENV, "3")
    assert resolve_num_slices() == 3
    monkeypatch.delenv(NUM_SLICES_ENV)
    # CPU devices carry no slice_index -> single slice
    assert resolve_num_slices() == 1


def test_resolve_num_slices_rejects_nonpositive(monkeypatch):
    monkeypatch.setenv(NUM_SLICES_ENV, "0")
    with pytest.raises(ValueError, match="NUM_SLICES"):
        resolve_num_slices()


def test_build_mesh_hierarchical_layout(monkeypatch):
    mesh = _hier_mesh(monkeypatch)
    assert int(mesh.shape["dp"]) == 2
    assert int(mesh.shape["fsdp"]) == 4
    assert mesh_num_slices(mesh) == 2
    # slice-major assignment: each dp block (one slice in the simulation)
    # is a contiguous id range, so fsdp collectives stay inside a slice
    # and only the dp hop crosses DCN
    ids = [d.id for d in mesh.devices.flat]
    assert ids == sorted(ids)
    blocks = np.asarray(ids).reshape(2, 4)
    assert blocks[0].tolist() == [0, 1, 2, 3]
    assert blocks[1].tolist() == [4, 5, 6, 7]


def test_build_mesh_rejects_layout_that_cannot_tile_slices(monkeypatch):
    monkeypatch.setenv(NUM_SLICES_ENV, "2")
    # dp*pp = 1 cannot tile 2 slices: fsdp would span DCN silently
    with pytest.raises(ValueError, match="tile"):
        build_mesh(
            ParallelismPlugin(dp_size=1, fsdp_size=8, min_weight_size=1)
        )


def test_fault_domain_of_rank():
    assert [fault_domain_of_rank(r, 8, 2) for r in range(8)] == [
        0, 0, 0, 0, 1, 1, 1, 1,
    ]
    assert [fault_domain_of_rank(r, 4, 4) for r in range(4)] == [0, 1, 2, 3]
    # single slice: everything is domain 0
    assert fault_domain_of_rank(3, 4, 1) == 0
    with pytest.raises(ValueError, match="divisible"):
        fault_domain_of_rank(0, 6, 4)


# ---------------------------------------------------------------------- #
# hierarchical gradient reduction == flat all-reduce (CPU-mesh parity)
# ---------------------------------------------------------------------- #
def _psum_fns(mesh):
    spec = P(("dp", "fsdp"))
    flat = shard_map(
        lambda v: jax.lax.psum(v, ("dp", "fsdp")),
        mesh=mesh,
        in_specs=spec,
        out_specs=P(),
    )
    # check_rep=False: shard_map's static replication checker cannot
    # infer that the closing all_gather replicates over fsdp
    hier = shard_map(
        hierarchical_psum,
        mesh=mesh,
        in_specs=spec,
        out_specs=P(),
        check_rep=False,
    )
    return flat, hier


def test_hierarchical_psum_matches_flat_psum(monkeypatch):
    mesh = _hier_mesh(monkeypatch)
    flat, hier = _psum_fns(mesh)
    # 32 rows / 8 devices = 4 local rows, divisible by fsdp=4: the real
    # reduce-scatter -> cross-slice all-reduce -> all-gather path runs
    x = np.random.default_rng(0).normal(size=(32, 3)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(hier(x)), np.asarray(flat(x)), rtol=1e-6
    )
    # integer-valued floats sum exactly in any reduction order: the two
    # lowerings must agree BITWISE, proving they compute the same sum
    xi = np.arange(32 * 3, dtype=np.float32).reshape(32, 3)
    np.testing.assert_array_equal(np.asarray(hier(xi)), np.asarray(flat(xi)))


def test_hierarchical_psum_lowers_to_reduce_scatter(monkeypatch):
    # structured auditor inventory instead of HLO-text string matching:
    # the hierarchical path must lower to reduce-scatter + all-gather
    # (plus the cross-slice reduction), with the in-slice legs on ICI
    # and cross-slice traffic attributed to DCN under the slice-major
    # device assignment
    from accelerate_tpu.profiling import audit_compiled

    mesh = _hier_mesh(monkeypatch)
    _, hier = _psum_fns(mesh)
    x = jnp.zeros((32, 3), jnp.float32)
    compiled = jax.jit(hier).lower(x).compile()
    audit = audit_compiled("hier_psum", compiled, num_slices=2)
    kinds = set(audit.by_kind)
    assert {"reduce-scatter", "all-gather"} <= kinds
    # every collective's bytes estimate is positive and attributed
    for op in audit.collectives:
        if op.kind in ("reduce-scatter", "all-gather", "all-reduce"):
            assert op.bytes_moved > 0
            assert op.fabric in ("ici", "dcn")
    # the in-slice scatter/gather legs stay on ICI
    assert audit.ici_bytes > 0


def test_hierarchical_psum_fallback_when_rows_do_not_tile(monkeypatch):
    mesh = _hier_mesh(monkeypatch)
    flat, hier = _psum_fns(mesh)
    # 8 rows / 8 devices = 1 local row, not divisible by fsdp=4: the
    # divisibility guard must fall back to the flat psum, bitwise
    x = np.random.default_rng(1).normal(size=(8,)).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(hier(x)), np.asarray(flat(x)))


# ---------------------------------------------------------------------- #
# collective-overlap policy: DCN-crossing collectives ranked first
# ---------------------------------------------------------------------- #
def test_wants_overlap_for_multislice_even_under_no_shard(monkeypatch):
    plugin = ParallelismPlugin(
        dp_size=2,
        fsdp_size=4,
        sharding_strategy=ShardingStrategy.NO_SHARD,
        min_weight_size=1,
    )
    assert wants_collective_overlap(plugin, _hier_mesh(monkeypatch)) is True
    # single slice, NO_SHARD: nothing worth scheduling (original policy)
    monkeypatch.setenv(NUM_SLICES_ENV, "1")
    flat_mesh = build_mesh(plugin)
    assert wants_collective_overlap(plugin, flat_mesh) is False


def test_overlap_options_adds_dcn_ranking_on_multislice(monkeypatch):
    plugin = ParallelismPlugin(dp_size=2, fsdp_size=4, min_weight_size=1)
    hier = overlap_options(plugin, _hier_mesh(monkeypatch), backend="tpu")
    for key in DCN_OVERLAP_OPTIONS:
        assert key in hier
    monkeypatch.setenv(NUM_SLICES_ENV, "1")
    single = overlap_options(plugin, build_mesh(plugin), backend="tpu")
    assert single  # still wants overlap (FULL_SHARD)...
    for key in DCN_OVERLAP_OPTIONS:
        assert key not in single  # ...but no DCN ranking on one slice
    # non-TPU backends get nothing, as before
    assert overlap_options(plugin, _hier_mesh(monkeypatch), backend="cpu") == {}


def test_zero2_shardings_pin_grads_on_multislice_replicated_params(
    monkeypatch,
):
    """On a hierarchical mesh, even replicated-param strategies (ZeRO-0/1)
    pin the grad buffer to fsdp shards so the accumulation lowers to
    reduce-scatter in-slice and only 1/fsdp of the bytes cross DCN."""
    monkeypatch.setenv(NUM_SLICES_ENV, "2")
    acc = _fresh_accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=2,
            fsdp_size=4,
            sharding_strategy=ShardingStrategy.SHARD_OPT,
            min_weight_size=1,
        )
    )
    params = acc.prepare({"w": jnp.zeros((16, 4), jnp.float32)})
    shardings = acc._zero2_grad_shardings(params)
    assert shardings is not None
    assert "fsdp" in jax.tree.leaves(shardings)[0].spec

    # single slice keeps the old behavior: ZeRO-1 grads stay replicated
    monkeypatch.setenv(NUM_SLICES_ENV, "1")
    acc = _fresh_accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=2,
            fsdp_size=4,
            sharding_strategy=ShardingStrategy.SHARD_OPT,
            min_weight_size=1,
        )
    )
    params = acc.prepare({"w": jnp.zeros((16, 4), jnp.float32)})
    assert acc._zero2_grad_shardings(params) is None


# ---------------------------------------------------------------------- #
# zero retraces after warmup on the hierarchical layout
# ---------------------------------------------------------------------- #
def test_hierarchical_layout_zero_retraces_after_warmup(monkeypatch):
    monkeypatch.setenv(NUM_SLICES_ENV, "2")
    acc = _fresh_accelerator(
        parallelism_plugin=ParallelismPlugin(
            dp_size=2, fsdp_size=4, min_weight_size=1
        )
    )
    assert mesh_num_slices(acc.mesh) == 2

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"]
        return jnp.mean((pred - batch["y"]) ** 2)

    params = acc.prepare({"w": jnp.zeros((8, 8), jnp.float32)})
    opt = acc.prepare(optax.sgd(0.1))
    carry = acc.init_carry(params, opt)
    step = acc.unified_step(loss_fn)

    def batch(i):
        g = np.random.default_rng(i)
        x = g.normal(size=(16, 8)).astype(np.float32)
        return {"x": x, "y": (x * 2.0).astype(np.float32)}

    acc.warmup(step, carry, batch(0))
    detector = acc.telemetry.detector(step.label)
    signatures = len(detector._seen)
    for i in range(3):
        carry, metrics = step(carry, batch(i))
    assert np.isfinite(float(metrics["loss"]))
    assert detector.retraces == 0
    assert len(detector._seen) == signatures
