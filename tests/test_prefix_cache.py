"""Prefix caching: refcounted shared KV blocks + copy-on-write.

Four layers, matching the feature's split: pool bookkeeping (refcounts,
content index, cached LRU — pure host policy, no jax), the rolling-hash
keying scheme (tenant/model isolation by construction), the engine's
warm path (shared-prefix admission, tail prefill, COW — outputs must be
bitwise identical to a cold run with zero decode retraces), and the
observability plumbing (gauges, spans, Prometheus export).
"""

import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import CausalLM, TransformerConfig
from accelerate_tpu.serving import (
    BlockPool,
    PrefixCache,
    ServingEngine,
    prefix_keys,
)


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return cfg, model, params


def _invariant(pool: BlockPool) -> bool:
    """The pool's conservation law: every allocatable block is in exactly
    one of FREE / ALLOCATED / CACHED (the garbage block is in none)."""
    return (
        pool.num_free + pool.num_allocated + pool.num_cached
        == pool.num_blocks - 1
    )


# ---------------------------------------------------------------------- #
# pool: refcounts
# ---------------------------------------------------------------------- #
def test_refcount_acquire_release_roundtrip():
    pool = BlockPool(num_blocks=8, block_size=4)
    blocks = pool.allocate(2)
    assert all(pool.refcount(b) == 1 for b in blocks)
    pool.acquire(blocks)  # second holder
    assert all(pool.refcount(b) == 2 for b in blocks)
    assert pool.num_shared == 2
    pool.free(blocks)  # first holder releases: blocks stay live
    assert all(pool.refcount(b) == 1 for b in blocks)
    assert pool.num_free == 5  # nothing returned yet
    pool.free(blocks)  # refcount 0, unpublished -> free list
    assert all(pool.refcount(b) == 0 for b in blocks)
    assert pool.num_free == 7
    assert _invariant(pool)


def test_double_free_raises():
    pool = BlockPool(num_blocks=8, block_size=4)
    (b,) = pool.allocate(1)
    pool.free([b])
    with pytest.raises(ValueError, match="not allocated"):
        pool.free([b])
    assert _invariant(pool)


def test_free_while_shared_keeps_block_live():
    """A shared block survives any single holder's release — the other
    holder's KV can never be pulled out from under it."""
    pool = BlockPool(num_blocks=8, block_size=4)
    (b,) = pool.allocate(1)
    pool.acquire([b])
    pool.free([b])
    assert pool.refcount(b) == 1  # still someone's block
    assert b not in pool._free
    # over-freeing past the last reference is the double-free error
    pool.free([b])
    with pytest.raises(ValueError, match="not allocated"):
        pool.free([b])


def test_acquire_unknown_block_raises_and_rolls_back():
    pool = BlockPool(num_blocks=8, block_size=4)
    blocks = pool.allocate(2)
    with pytest.raises(ValueError, match="neither allocated nor cached"):
        pool.acquire(blocks + [99])  # partial chain must roll back
    assert all(pool.refcount(b) == 1 for b in blocks)
    assert _invariant(pool)


# ---------------------------------------------------------------------- #
# pool: content index + cached LRU
# ---------------------------------------------------------------------- #
def test_published_block_retires_to_cache_and_is_reacquirable():
    pool = BlockPool(num_blocks=8, block_size=4)
    (b,) = pool.allocate(1)
    key = b"k" * 32
    assert pool.publish(b, key) == b
    pool.free([b])
    assert pool.num_cached == 1 and pool.num_free == 6
    assert pool.lookup([key]) == [b]
    pool.acquire([b])  # the warm-hit path: cached -> allocated
    assert pool.refcount(b) == 1 and pool.num_cached == 0
    assert _invariant(pool)


def test_publish_first_writer_wins():
    pool = BlockPool(num_blocks=8, block_size=4)
    a, b = pool.allocate(2)
    key = b"same-key" * 4
    assert pool.publish(a, key) == a
    # concurrent identical prefill: the second publisher is told the
    # canonical block; its own stays private
    assert pool.publish(b, key) == a
    assert pool.lookup([key]) == [a]


def test_lru_eviction_prefers_coldest_and_never_touches_refcounted():
    pool = BlockPool(num_blocks=6, block_size=4)
    blocks = pool.allocate(5)  # everything
    keys = [bytes([i]) * 32 for i in range(5)]
    for b, k in zip(blocks, keys):
        pool.publish(b, k)
    pool.free(blocks)  # all 5 retire to the LRU, oldest-first
    assert pool.num_cached == 5 and pool.num_free == 0
    pool.acquire([blocks[0]])  # pin the coldest
    got = pool.allocate(2)  # pressure: must evict from the LRU
    assert blocks[0] not in got  # refcount>0 is never evicted
    assert pool.lookup([keys[0]]) == [blocks[0]]  # still indexed
    # the two coldest UNPINNED entries were evicted, their keys dropped
    assert pool.lookup([keys[1]]) == []
    assert pool.evictions_total == 2
    assert _invariant(pool)


def test_can_allocate_counts_cached_as_capacity():
    pool = BlockPool(num_blocks=6, block_size=4)
    blocks = pool.allocate(5)
    for i, b in enumerate(blocks):
        pool.publish(b, bytes([i]) * 32)
    pool.free(blocks)
    assert pool.num_free == 0
    assert pool.can_allocate(5)  # a hot cache never blocks admission
    assert not pool.can_allocate(6)


def test_clear_cache_returns_lru_blocks_to_free_list():
    pool = BlockPool(num_blocks=6, block_size=4)
    blocks = pool.allocate(3)
    for i, b in enumerate(blocks):
        pool.publish(b, bytes([i]) * 32)
    pool.free(blocks[:2])  # two cached, one still in flight
    pool.clear_cache()
    assert pool.num_cached == 0 and pool.num_free == 4
    assert pool.lookup([bytes([2]) * 32]) == []  # in-flight unindexed too
    assert pool.refcount(blocks[2]) == 1  # ... but still its holder's
    assert _invariant(pool)


def test_pool_fuzz_invariant_holds_after_every_op():
    """Randomized allocate/free/acquire/publish/lookup churn: the
    conservation law must hold after EVERY op, and no op may corrupt a
    neighbour's refcount."""
    rng = random.Random(0)
    pool = BlockPool(num_blocks=17, block_size=4)
    held: list[int] = []  # one entry per reference we own
    published = 0
    for _ in range(2000):
        op = rng.random()
        if op < 0.35 and pool.can_allocate(n := rng.randint(1, 3)):
            held.extend(pool.allocate(n))
        elif op < 0.55 and held:
            b = held.pop(rng.randrange(len(held)))
            pool.free([b])
        elif op < 0.70 and held:
            b = held[rng.randrange(len(held))]
            pool.acquire([b])
            held.append(b)
        elif op < 0.85 and held:
            b = held[rng.randrange(len(held))]
            pool.publish(b, published.to_bytes(4, "big") * 8)
            published += 1
        elif pool.num_cached:
            # warm hit on a random cached block
            b = next(iter(pool._lru))
            pool.acquire([b])
            held.append(b)
        assert _invariant(pool), "conservation law broken mid-fuzz"
        # our ledger and the pool's must agree exactly
        counts: dict[int, int] = {}
        for b in held:
            counts[b] = counts.get(b, 0) + 1
        assert all(pool.refcount(b) == n for b, n in counts.items())
    for b in held:
        pool.free([b])
    assert _invariant(pool)
    assert pool.num_allocated == 0


# ---------------------------------------------------------------------- #
# keying scheme
# ---------------------------------------------------------------------- #
def test_prefix_keys_are_rolling_and_full_blocks_only():
    toks = list(range(10))
    keys = prefix_keys("fp", None, toks, block_size=4)
    assert len(keys) == 2  # 10 tokens / 4 = 2 full blocks, tail unkeyed
    # same prefix -> same keys; a divergent SECOND block changes only
    # keys from that block on (key[0] commits to block 0 alone)
    other = prefix_keys("fp", None, toks[:4] + [99] * 4, block_size=4)
    assert other[0] == keys[0] and other[1] != keys[1]
    # a divergent FIRST block changes every key (rolling hash chains)
    shifted = prefix_keys("fp", None, [99] + toks[1:], block_size=4)
    assert shifted[0] != keys[0] and shifted[1] != keys[1]


def test_prefix_keys_fold_in_adapter_and_fingerprint():
    toks = list(range(8))
    base = prefix_keys("fp", None, toks, 4)
    # two tenants with identical prompts get fully disjoint key chains
    assert set(prefix_keys("fp", "tenant-a", toks, 4)).isdisjoint(base)
    assert set(prefix_keys("fp", "tenant-a", toks, 4)).isdisjoint(
        prefix_keys("fp", "tenant-b", toks, 4)
    )
    # and so do two different models
    assert set(prefix_keys("fp2", None, toks, 4)).isdisjoint(base)


def test_prefix_cache_match_isolates_tenants():
    pool = BlockPool(num_blocks=8, block_size=4)
    cache = PrefixCache(pool, fingerprint="fp")
    toks = list(range(8))
    blocks = pool.allocate(2)
    cache.publish(toks, "tenant-a", blocks)
    assert cache.match(toks, "tenant-a") == blocks
    assert cache.match(toks, "tenant-b") == []  # never cross-served
    assert cache.match(toks, None) == []


# ---------------------------------------------------------------------- #
# engine: warm path, COW, bitwise parity
# ---------------------------------------------------------------------- #
def _drain(engine, prompt, max_new=6, adapter=None):
    rid = engine.add_request(
        list(prompt), max_new_tokens=max_new, adapter=adapter
    )
    for _ in engine.stream():
        pass
    return engine.result(rid)


def test_warm_hit_skips_prefill_and_matches_cold_bitwise(tiny_model):
    cfg, model, params = tiny_model
    template = list(range(1, 17))  # 4 full blocks of 4
    prompts = [template + [21, 22, 23], template + [31, 32], template]
    cold = ServingEngine(model, params, max_slots=2, block_size=4, seed=7)
    warm = ServingEngine(
        model, params, max_slots=2, block_size=4, seed=7, prefix_cache=True
    )
    cold_out = [_drain(cold, p) for p in prompts]
    warm_out = [_drain(warm, p) for p in prompts]
    assert cold_out == warm_out  # caching changes WHEN KV is computed,
    # never WHAT is computed
    stats = warm.prefix_cache.stats()
    assert stats["hits"] == 2  # requests 2 and 3 reuse request 1's chain
    assert stats["prefill_tokens_saved_total"] == 16 + 15
    # request 3's prompt == the cached chain exactly: the >= 1-token
    # tail re-writes the last shared block -> exactly one COW
    assert stats["cow_copies_total"] == 1
    # decode compiled ONCE across both engines' traffic
    assert warm.trace_counts()["decode"] == 1
    pool = warm.pool
    assert (
        pool.num_free + pool.num_allocated + pool.num_cached
        == pool.num_blocks - 1
    )


def test_cow_leaves_donor_chain_intact(tiny_model):
    """After the full-prompt-hit COW, the DONOR blocks stay published:
    a later identical request must still hit the original chain (the
    copy serviced one writer; the canonical content is untouched)."""
    cfg, model, params = tiny_model
    template = list(range(1, 13))  # 3 full blocks of 4
    engine = ServingEngine(
        model, params, max_slots=2, block_size=4, seed=3, prefix_cache=True
    )
    first = _drain(engine, template)  # publishes the chain
    second = _drain(engine, template)  # full hit -> COW of last block
    assert engine.prefix_cache.cow_copies_total == 1
    third = _drain(engine, template)  # must STILL hit the intact chain
    assert engine.prefix_cache.stats()["hits"] == 2
    assert engine.prefix_cache.cow_copies_total == 2
    assert first == second == third
    cold = ServingEngine(model, params, max_slots=2, block_size=4, seed=3)
    assert _drain(cold, template) == first


def test_tenant_a_cached_prefix_never_serves_tenant_b(tiny_model):
    """Two tenants, identical prompts: tenant A warms the cache, tenant
    B must MISS (adapter_id is folded into every key) and produce output
    bitwise equal to its own cold single-tenant reference."""
    from accelerate_tpu.adapters import AdapterRegistry, LoraConfig, init_adapter
    from accelerate_tpu.adapters.runtime import A_KEY, B_KEY

    cfg, model, params = tiny_model
    lcfg = LoraConfig(rank=4, alpha=8.0, target_modules=("q_proj", "v_proj"))

    def rand_adapter(seed):
        ad = init_adapter(jax.random.PRNGKey(seed), cfg, lcfg)
        return {
            t: {
                A_KEY: pair[A_KEY],
                B_KEY: 0.05 * jax.random.normal(
                    jax.random.PRNGKey(seed * 977 + i), pair[B_KEY].shape
                ),
            }
            for i, (t, pair) in enumerate(sorted(ad.items()))
        }

    def fresh(prefix_cache):
        reg = AdapterRegistry(
            cfg, capacity=2, max_rank=lcfg.rank,
            target_modules=lcfg.target_modules,
        )
        reg.load("tenant-a", rand_adapter(11), lcfg)
        reg.load("tenant-b", rand_adapter(22), lcfg)
        return ServingEngine(
            model, params, max_slots=2, block_size=4, seed=5,
            adapters=reg, prefix_cache=prefix_cache,
        )

    prompt = list(range(1, 13))
    engine = fresh(prefix_cache=True)
    out_a = _drain(engine, prompt, adapter="tenant-a")
    assert engine.prefix_cache.hits == 0  # A was cold
    out_b = _drain(engine, prompt, adapter="tenant-b")
    assert engine.prefix_cache.hits == 0  # B MISSED A's chain
    # A's own repeat DOES hit — the index works, it just isolates
    assert _drain(engine, prompt, adapter="tenant-a") == out_a
    assert engine.prefix_cache.hits == 1
    # B's warm-engine output equals B alone on a cold engine
    cold = fresh(prefix_cache=False)
    _drain(cold, prompt, adapter="tenant-a")
    assert _drain(cold, prompt, adapter="tenant-b") == out_b


def test_set_prefix_cache_toggles_on_warm_engine_without_retrace(tiny_model):
    cfg, model, params = tiny_model
    engine = ServingEngine(model, params, max_slots=2, block_size=4, seed=1)
    template = list(range(1, 17))
    cold = _drain(engine, template + [5])
    engine.set_prefix_cache(True)  # warm toggle: pure host policy
    assert _drain(engine, template + [5]) == cold  # publishes
    assert _drain(engine, template + [5]) == cold  # first hit: its tail
    # bucket compiles once, like any prompt-width warmup
    traces = engine.trace_counts()
    assert _drain(engine, template + [5]) == cold  # steady-state hit
    assert engine.prefix_cache.hits == 2
    assert engine.trace_counts() == traces  # not one new program
    assert traces["decode"] == 1  # decode NEVER retraced across toggles
    engine.set_prefix_cache(False)
    assert engine.pool.num_cached == 0  # OFF clears the index
    assert engine.prefix_cache is None
    assert _drain(engine, template + [5]) == cold


def test_speculation_on_warm_prefix_matches_cold_and_cows(tiny_model):
    """Prefix caching x speculative decoding: a warm full-prompt hit
    seats the request ON the shared chain, and the verify pass writes up
    to k positions past the cursor — the engine must copy the shared
    block private BEFORE any speculative write (a rejected draft's KV
    landing in a published block would corrupt every other holder).
    Outputs stay bitwise equal to a cold spec-off engine throughout."""
    from accelerate_tpu.serving import SpecConfig

    cfg, model, params = tiny_model
    template = list(range(1, 13))  # 3 full blocks of 4
    cold = ServingEngine(model, params, max_slots=2, block_size=4, seed=4)
    want = _drain(cold, template, max_new=8)
    engine = ServingEngine(
        model, params, max_slots=2, block_size=4, seed=4,
        prefix_cache=True, spec_decode=SpecConfig(k=3),
    )
    assert _drain(engine, template, max_new=8) == want  # publishes
    before = engine.prefix_cache.cow_copies_total
    assert _drain(engine, template, max_new=8) == want  # full hit
    # the speculative write span crossed into the shared last block:
    # exactly one private copy, made before verify touched it
    assert engine.prefix_cache.cow_copies_total == before + 1
    # donor chain intact: a third identical request still hits it
    assert _drain(engine, template, max_new=8) == want
    assert engine.prefix_cache.stats()["hits"] == 2
    spec = engine.summary()["speculation"]
    assert spec["rounds"] > 0  # the speculative path really ran


def test_pool_exhaustion_rolls_back_acquired_prefix(tiny_model):
    """If the pool can't fund a request's UNCACHED remainder, admission
    must release the chain it just pinned (no leaked refcounts)."""
    cfg, model, params = tiny_model
    engine = ServingEngine(
        model, params, max_slots=2, block_size=4, num_blocks=16,
        prefix_cache=True, seed=2,
    )
    template = list(range(1, 17))  # 4 blocks
    _drain(engine, template, max_new=4)  # publish the chain
    assert engine.pool.num_cached == 4
    held = engine.pool.allocate(5)  # external pressure: free drops to 6
    # needs 4 shared + 9 private but only 6 free: blocked, chain released
    rid = engine.add_request(template + [7] * 15, max_new_tokens=20)
    engine.step()
    assert engine.result(rid) is None
    assert engine.scheduler.blocked_reasons["pool_exhausted"] >= 1
    pool = engine.pool
    assert pool.num_allocated == 5  # only our hold: nothing leaked
    assert pool.num_cached == 4  # the pinned chain went BACK to cached
    assert all(pool.refcount(b) == 0 for b in pool._lru)
    pool.free(held)
    assert (
        pool.num_free + pool.num_allocated + pool.num_cached
        == pool.num_blocks - 1
    )


# ---------------------------------------------------------------------- #
# observability plumbing
# ---------------------------------------------------------------------- #
def test_gauges_spans_and_prometheus_export(tiny_model):
    from accelerate_tpu.telemetry import PrometheusTextSink, StepTelemetry

    cfg, model, params = tiny_model
    tele = StepTelemetry(True)
    prom = PrometheusTextSink(path=None)
    tele.add_sink(prom)
    engine = ServingEngine(
        model, params, max_slots=2, block_size=4, seed=9,
        prefix_cache=True, telemetry=tele,
    )
    template = list(range(1, 17))
    _drain(engine, template + [3])
    _drain(engine, template + [4])
    gauges = engine._gauge_fields()
    assert gauges["prefix_cache_hit_rate"] == 0.5
    assert gauges["prefill_tokens_saved_total"] == 16
    assert "shared_blocks" in gauges and "cow_copies_total" in gauges
    assert gauges["pool_blocks_cached"] == engine.pool.num_cached
    # the warm request's span carries the cached token count
    spans = {s.request_id: s for s in engine.span_log.closed}
    assert sorted(
        s.cached_prefix_tokens for s in spans.values()
    ) == [0, 16]
    assert all(
        "cached_prefix_tokens" in s.to_record() for s in spans.values()
    )
    text = prom.render()
    assert "accelerate_tpu_serve_prefix_cache_hit_rate" in text
    assert "accelerate_tpu_serve_shared_blocks" in text
    assert "accelerate_tpu_serve_cow_copies_total" in text
    assert "accelerate_tpu_serve_prefill_tokens_saved_total" in text
    assert engine.summary()["prefix_cache"]["hits"] == 1
    tele.close()


# ---------------------------------------------------------------------- #
# the prefix-smoke acceptance scenario (make prefix-smoke)
# ---------------------------------------------------------------------- #
@pytest.mark.slow
def test_prefix_smoke_end_to_end(tiny_model):
    """Two requests share a long template: the second must skip prefill
    for every shared full block and decode bitwise-equal to a cold-cache
    control; a third divergent request (prompt == the cached chain
    exactly) exercises copy-on-write and still matches ITS cold control
    — all with zero decode retraces after warmup."""
    cfg, model, params = tiny_model
    bs = 4
    template = [(7 * i + 3) % cfg.vocab_size for i in range(40)]  # 10 blocks
    first = template + [101, 102, 103]
    second = template + [201, 202]
    divergent = list(template)  # full-prompt hit -> COW path

    cold = ServingEngine(model, params, max_slots=2, block_size=bs, seed=13)
    control = {
        "first": _drain(cold, first, max_new=8),
        "second": _drain(cold, second, max_new=8),
        "divergent": _drain(cold, divergent, max_new=8),
    }

    engine = ServingEngine(
        model, params, max_slots=2, block_size=bs, seed=13, prefix_cache=True
    )
    out_first = _drain(engine, first, max_new=8)  # cold: publishes chain
    decode_traces_warm = engine.trace_counts()["decode"]
    saved0 = engine.prefix_cache.tokens_saved_total

    out_second = _drain(engine, second, max_new=8)
    # the second request skipped prefill for EVERY shared full block
    shared_tokens = len(template) // bs * bs
    assert engine.prefix_cache.tokens_saved_total - saved0 >= shared_tokens
    span = {s.request_id: s for s in engine.span_log.closed}
    assert max(
        s.cached_prefix_tokens for s in span.values()
    ) == shared_tokens
    assert out_second == control["second"]  # bitwise equal to cold

    cow0 = engine.prefix_cache.cow_copies_total
    out_divergent = _drain(engine, divergent, max_new=8)
    assert engine.prefix_cache.cow_copies_total > cow0  # COW exercised
    assert out_divergent == control["divergent"]
    assert out_first == control["first"]
    # zero decode retraces across the whole warm phase
    assert engine.trace_counts()["decode"] == decode_traces_warm == 1
    # and the pool's conservation law survived the churn
    pool = engine.pool
    assert (
        pool.num_free + pool.num_allocated + pool.num_cached
        == pool.num_blocks - 1
    )
