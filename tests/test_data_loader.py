"""Data pipeline tests — ports of reference tests/test_data_loader.py's
BatchSamplerShard enumeration plus sharded-device-batch checks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import AcceleratorState, DataLoaderConfiguration, GradientState
from accelerate_tpu.data_loader import (
    BatchSamplerShard,
    DataLoader,
    DataLoaderShard,
    IterableDatasetShard,
    SeedableRandomSampler,
    SequentialSampler,
    prepare_data_loader,
    skip_first_batches,
)


class RangeDataset:
    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return {"x": np.full((2,), i, dtype=np.float32), "y": np.int32(i)}


def test_seedable_sampler_deterministic():
    s1 = SeedableRandomSampler(10, seed=3, epoch=0)
    s2 = SeedableRandomSampler(10, seed=3, epoch=0)
    assert list(s1) == list(s2)
    s2.set_epoch(1)
    assert list(s1) != list(s2)
    assert sorted(list(s2)) == list(range(10))


@pytest.mark.parametrize("num_processes", [1, 2, 4])
def test_batch_sampler_shard_even(num_processes):
    sampler = SequentialSampler(16)
    shards = [
        BatchSamplerShard(sampler, 2, num_processes=num_processes, process_index=i)
        for i in range(num_processes)
    ]
    batches = [list(s) for s in shards]
    # every process sees the same number of batches, union covers dataset
    for b in batches:
        assert len(b) == len(shards[0])
    seen = [
        i for step in zip(*batches) for local, _ in step for i in local
    ]
    assert sorted(seen) == list(range(16))


def test_batch_sampler_shard_uneven_wraparound():
    # 10 samples, global batch 8 (bs=2 x 4 procs): tail of 2 wraps to 8
    sampler = SequentialSampler(10)
    shards = [
        BatchSamplerShard(sampler, 2, num_processes=4, process_index=i)
        for i in range(4)
    ]
    lasts = [list(s)[-1] for s in shards]
    total = [i for local, _ in lasts for i in local]
    assert len(total) == 8
    valid = lasts[0][1]
    assert valid == 2  # only 2 real samples in the tail batch


def test_batch_sampler_drop_last():
    sampler = SequentialSampler(10)
    shard = BatchSamplerShard(sampler, 2, num_processes=4, drop_last=True)
    assert len(list(shard)) == 1


def test_iterable_dataset_shard():
    shards = [
        IterableDatasetShard(range(10), batch_size=2, num_processes=2, process_index=i)
        for i in range(2)
    ]
    out = [list(s) for s in shards]
    assert len(out[0]) == 3
    first_global = out[0][0][0] + out[1][0][0]
    assert first_global == [0, 1, 2, 3]


def test_prepare_data_loader_shards_batches():
    state = AcceleratorState()
    loader = DataLoader(RangeDataset(16), batch_size=8, shuffle=False)
    prepared = prepare_data_loader(loader, state)
    batches = list(prepared)
    assert len(batches) == 2
    batch = batches[0]
    assert isinstance(batch["x"], jax.Array)
    assert batch["x"].shape == (8, 2)
    # sharded over the dp axis
    assert batch["x"].sharding.spec[0] in ("dp", ("dp",))
    np.testing.assert_allclose(np.asarray(batch["y"]), np.arange(8))


def test_dataloader_gradient_state_bookkeeping():
    state = AcceleratorState()
    gs = GradientState()
    loader = DataLoader(RangeDataset(10), batch_size=8, shuffle=False)
    prepared = prepare_data_loader(loader, state)
    remainders = []
    for batch in prepared:
        remainders.append((gs.in_dataloader, gs.end_of_dataloader, gs.remainder))
    # 2 batches: 8, tail valid=2 (wraparound keeps shape 8)
    assert remainders[0] == (True, False, -1)
    assert remainders[-1][1] is True
    assert remainders[-1][2] == 2
    assert not gs.in_dataloader


def test_dataloader_length_and_epoch():
    state = AcceleratorState()
    loader = DataLoader(RangeDataset(16), batch_size=8, shuffle=True, seed=0)
    prepared = prepare_data_loader(loader, state)
    assert len(prepared) == 2
    first_epoch = [np.asarray(b["y"]).tolist() for b in prepared]
    prepared.set_epoch(1)
    second_epoch = [np.asarray(b["y"]).tolist() for b in prepared]
    assert first_epoch != second_epoch
    # same epoch replays identically (determinism)
    prepared.set_epoch(0)
    replay = [np.asarray(b["y"]).tolist() for b in prepared]
    assert replay == first_epoch


def test_skip_first_batches():
    state = AcceleratorState()
    loader = DataLoader(RangeDataset(16), batch_size=8, shuffle=False)
    prepared = prepare_data_loader(loader, state)
    skipped = skip_first_batches(prepared, 1)
    batches = [np.asarray(b["y"]).tolist() for b in skipped]
    assert len(batches) == 1
    assert batches[0] == list(range(8, 16))
    # skip is one-shot: next epoch is full again
    assert len(list(prepared)) == 2


def test_prepare_iterable_of_batches():
    state = AcceleratorState()
    raw = [{"x": np.ones((8, 2), dtype=np.float32) * i} for i in range(3)]
    prepared = prepare_data_loader(raw, state)
    batches = list(prepared)
    assert len(batches) == 3
    assert isinstance(batches[0]["x"], jax.Array)
    assert batches[0]["x"].sharding.spec[0] in ("dp", ("dp",))


def test_prepare_torch_dataloader():
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader as TorchDataLoader, TensorDataset

    ds = TensorDataset(torch.arange(16).float().reshape(16, 1))
    tl = TorchDataLoader(ds, batch_size=8)
    state = AcceleratorState()
    prepared = prepare_data_loader(tl, state)
    batches = list(prepared)
    assert len(batches) == 2
    assert isinstance(batches[0][0], jax.Array)
    assert batches[0][0].shape == (8, 1)


def test_prepare_rejects_indivisible_batch():
    state = AcceleratorState()
    loader = DataLoader(RangeDataset(16), batch_size=4, shuffle=False)
    with pytest.raises(ValueError, match="divisible by the data-parallel"):
        prepare_data_loader(loader, state)


# --------------------------------------------------------------------- #
# superbatch mode (fused gradient accumulation's stacked input contract)
# --------------------------------------------------------------------- #
def test_superbatch_loader_stacks_microbatches():
    state = AcceleratorState()
    loader = DataLoader(RangeDataset(32), batch_size=8, shuffle=False)
    prepared = prepare_data_loader(loader, state, superbatch=2)
    assert prepared.superbatch == 2
    assert len(prepared) == 2  # 4 microbatches stacked in pairs
    batches = list(prepared)
    assert len(batches) == 2
    # stacked [K, micro, ...]; K axis replicated, batch axis keeps dp
    assert batches[0]["x"].shape == (2, 8, 2)
    assert batches[0]["y"].shape == (2, 8)
    spec = batches[0]["x"].sharding.spec
    assert spec[0] is None
    assert spec[1] in ("dp", ("dp",))
    # slot k is exactly the k-th consecutive microbatch
    np.testing.assert_array_equal(
        np.asarray(batches[0]["y"]), np.arange(16).reshape(2, 8)
    )
    np.testing.assert_array_equal(
        np.asarray(batches[1]["y"]), np.arange(16, 32).reshape(2, 8)
    )
    assert prepared.remainder == 0


def test_superbatch_batch_spec_matches_batches():
    """batch_spec() must report the STACKED shape (the AOT warmup and
    retrace-detector contract for the fused step)."""
    state = AcceleratorState()
    loader = DataLoader(RangeDataset(32), batch_size=8, shuffle=False)
    prepared = prepare_data_loader(loader, state, superbatch=2)
    spec = prepared.batch_spec()
    batch = next(iter(prepared))
    got = jax.tree.map(lambda s: (s.shape, jnp.dtype(s.dtype)), spec)
    want = jax.tree.map(lambda a: (a.shape, jnp.dtype(a.dtype)), batch)
    assert got == want
    assert spec["x"].sharding == batch["x"].sharding


def test_superbatch_partial_final_batch_padded():
    """24 samples / (gbs=8 x K=2): the final superbatch holds ONE real
    microbatch — padded by repeating it (static shape) with the true
    sample count threaded through as the remainder for loss masking."""
    state = AcceleratorState()
    gs = GradientState()
    loader = DataLoader(RangeDataset(24), batch_size=8, shuffle=False)
    prepared = prepare_data_loader(loader, state, superbatch=2)
    assert len(prepared) == 2  # ceil(3 microbatches / 2)
    seen = []
    for batch in prepared:
        assert batch["y"].shape == (2, 8)  # shape stays static
        seen.append((np.asarray(batch["y"]), gs.end_of_dataloader, gs.remainder))
    first, last = seen[0], seen[-1]
    np.testing.assert_array_equal(first[0], np.arange(16).reshape(2, 8))
    assert first[1] is False and first[2] == -1
    # pad slot repeats the last real microbatch; remainder = 8 real samples
    np.testing.assert_array_equal(last[0][0], np.arange(16, 24))
    np.testing.assert_array_equal(last[0][1], np.arange(16, 24))
    assert last[1] is True
    assert last[2] == 8
    # spec still matches the padded static shape
    assert prepared.batch_spec()["y"].shape == (2, 8)
