"""ZeRO-1/2 (SHARD_OPT / SHARD_GRAD_OP) semantics.

Reference capability: DeepSpeed ZeRO stages 1/2 (utils/dataclasses.py:739,
utils/deepspeed.py) — optimizer-state (and grad-buffer) sharding with
replicated params. Here the TPU expression: explicit out_shardings on
optax.init over the fsdp mesh axis + a sharded accumulated-grad carry.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy


def _params(key=0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(key))
    return {
        "w1": jax.random.normal(k1, (16, 32)),
        "w2": jax.random.normal(k2, (32, 8)),
    }


def _loss(p, b):
    h = jnp.tanh(b["x"] @ p["w1"])
    return jnp.mean((h @ p["w2"] - b["y"]) ** 2)


def _train(strategy, num_accum=1, steps=6, seed=0):
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    plugin = ParallelismPlugin(
        fsdp_size=8, sharding_strategy=strategy, min_weight_size=8
    )
    acc = Accelerator(
        parallelism_plugin=plugin, gradient_accumulation_steps=num_accum
    )
    params = acc.prepare(_params())
    opt = acc.prepare(optax.adam(1e-2))
    carry = acc.init_carry(params, opt)
    step = acc.unified_step(_loss)
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        batch = {
            "x": jnp.asarray(rng.normal(size=(8, 16)), jnp.float32),
            "y": jnp.asarray(rng.normal(size=(8, 8)), jnp.float32),
        }
        from accelerate_tpu.parallel.sharding import batch_sharding

        batch = jax.device_put(batch, batch_sharding(acc.mesh))
        carry, metrics = step(carry, batch)
    return acc, carry


def _specs(tree):
    return [
        tuple(l.sharding.spec) if hasattr(l.sharding, "spec") else None
        for l in jax.tree.leaves(tree)
    ]


def test_zero1_shards_opt_state_replicates_params():
    acc, carry = _train(ShardingStrategy.SHARD_OPT)
    # params replicated
    for spec in _specs(carry["params"]):
        assert all(s is None for s in spec), spec
    # at least the Adam moment buffers (shape == param shape) fsdp-sharded
    moment_specs = [
        s for s, l in zip(_specs(carry["opt_state"]), jax.tree.leaves(carry["opt_state"]))
        if getattr(l, "ndim", 0) >= 2
    ]
    assert moment_specs, "no moment buffers found"
    for spec in moment_specs:
        assert any(s == "fsdp" for s in spec), spec


def test_zero2_additionally_shards_grad_buffer():
    acc, carry = _train(ShardingStrategy.SHARD_GRAD_OP, num_accum=2)
    for spec in _specs(carry["params"]):
        assert all(s is None for s in spec), spec
    accum_specs = [
        s for s, l in zip(_specs(carry["accum_grads"]), jax.tree.leaves(carry["accum_grads"]))
        if getattr(l, "ndim", 0) >= 2
    ]
    for spec in accum_specs:
        assert any(s == "fsdp" for s in spec), spec


@pytest.mark.parametrize(
    "strategy", [ShardingStrategy.SHARD_OPT, ShardingStrategy.SHARD_GRAD_OP]
)
def test_zero_trains_equivalently_to_dp(strategy):
    """Sharding opt state / grads must not change the math (reference
    training_check pattern: identical weights across configs)."""
    _, carry_dp = _train(ShardingStrategy.NO_SHARD, num_accum=2)
    _, carry_z = _train(strategy, num_accum=2)
    for a, b in zip(
        jax.tree.leaves(carry_dp["params"]), jax.tree.leaves(carry_z["params"])
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_zero1_step_built_before_init_carry():
    """Building unified_step before init_carry must still pin ZeRO-1 opt
    shardings (review finding: build-time capture silently disabled it)."""
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    plugin = ParallelismPlugin(
        fsdp_size=8, sharding_strategy=ShardingStrategy.SHARD_OPT,
        min_weight_size=8,
    )
    acc = Accelerator(parallelism_plugin=plugin)
    params = acc.prepare(_params())
    opt = acc.prepare(optax.adam(1e-2))
    step = acc.unified_step(_loss)  # built FIRST: opt state not created yet
    carry = acc.init_carry(params, opt)
    batch = {
        "x": jnp.ones((8, 16), jnp.float32),
        "y": jnp.zeros((8, 8), jnp.float32),
    }
    carry, _ = step(carry, batch)
    moment_specs = [
        tuple(l.sharding.spec)
        for l in jax.tree.leaves(carry["opt_state"])
        if getattr(l, "ndim", 0) >= 2
    ]
    assert moment_specs
    for spec in moment_specs:
        assert any(s == "fsdp" for s in spec), spec


def test_zero1_keeps_embedding_replicated():
    """The embedding's ("vocab","zero") annotation is a WEIGHT-shard seat:
    under ZeRO-1 (SHARD_OPT) params stay replicated — the fsdp axis must
    not leak into param shardings through the zero rule (code-review r3)."""
    from accelerate_tpu.models import CausalLM, TransformerConfig
    from accelerate_tpu.parallel.mesh import build_mesh
    from accelerate_tpu.parallel.sharding import (
        get_logical_specs,
        infer_param_shardings,
        unbox_params,
    )
    from accelerate_tpu.utils.dataclasses import ParallelismPlugin, ShardingStrategy

    plugin = ParallelismPlugin(
        fsdp_size=8, sharding_strategy=ShardingStrategy.SHARD_OPT,
        min_weight_size=16,
    )
    mesh = build_mesh(plugin)
    cfg = TransformerConfig.tiny()
    variables = CausalLM(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )
    specs = infer_param_shardings(
        unbox_params(variables)["params"], mesh, plugin,
        logical_specs=get_logical_specs(variables)["params"],
    )
    embed_spec = specs["embed"]["embedding"].spec
    assert "fsdp" not in str(embed_spec), embed_spec
