"""Capacity features: chunked prefill, preemption with KV swap, int8 KV.

Three coupled serve-more-users-per-chip levers, each tested against the
engine's core contracts: chunked prefill must keep decode running every
step and change NOTHING about greedy outputs or the zero-retrace
guarantee; preemption must round-trip a victim's KV through host RAM
bitwise-identically; int8 paged KV must shrink bytes-per-cached-token
>= 1.8x while greedy outputs stay exact. Plus the pool's swap ledger
under fuzz (the conservation law extended with the SWAPPED state) and
the preempt telemetry counter.
"""

import math
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.models import CausalLM, TransformerConfig
from accelerate_tpu.serving import BlockPool, ServingEngine


@pytest.fixture(scope="module")
def tiny_model():
    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]
    return cfg, model, params


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt

    def __call__(self) -> float:
        return self.t


#: random-token prompts with robust greedy logit gaps (the 9-token draw
#: has a near-tied top-2 at its first step, so int8 quantization noise
#: can legitimately flip it — parity tests use the first three)
PROMPT_LENS = (23, 5, 17, 9)


def _prompts(cfg, lens=PROMPT_LENS):
    rng = np.random.default_rng(0)
    return [rng.integers(1, cfg.vocab_size, size=n).tolist() for n in lens]


def _run_all(engine, prompts, max_new_tokens=6, **kw):
    ids = [engine.add_request(p, max_new_tokens=max_new_tokens, **kw)
           for p in prompts]
    while engine.has_work:
        engine.step()
    return [engine.result(rid) for rid in ids]


# ---------------------------------------------------------------------- #
# chunked prefill
# ---------------------------------------------------------------------- #
def test_chunked_prefill_greedy_parity_zero_retrace(tiny_model):
    """Chunking is scheduling, not math: the same prompts produce the
    same greedy tokens chunked or not, and the decode step still
    compiles exactly once (chunk offsets are traced data)."""
    cfg, model, params = tiny_model
    prompts = _prompts(cfg)
    base = ServingEngine(model, params, max_slots=4, block_size=8)
    expected = _run_all(base, prompts)
    assert all(e is not None for e in expected)

    eng = ServingEngine(
        model, params, max_slots=4, block_size=8, prefill_chunk_tokens=8
    )
    got = _run_all(eng, prompts)
    assert got == expected
    counts = eng.trace_counts()
    assert counts["decode"] == 1, "chunked prefill retraced decode"
    assert counts["prefill"] <= int(math.log2(cfg.max_seq_len))
    assert eng._prefill_chunks_total >= sum(
        math.ceil(len(p) / 8) for p in prompts
    ) - len(prompts)  # at least the unavoidable multi-chunk splits
    recs = {r["request_id"]: r for r in eng.stats.requests}
    assert all(r["prefill_chunks"] >= 1 for r in recs.values())


def test_chunked_prefill_decode_never_starves(tiny_model):
    """A long prompt ingesting under a per-step token budget must not
    stall a decoding neighbour: the active slot emits exactly one token
    on EVERY step the newcomer spends mid-prefill."""
    cfg, model, params = tiny_model
    clock = FakeClock()
    eng = ServingEngine(
        model, params, max_slots=2, block_size=8, num_blocks=32,
        prefill_chunk_tokens=8, now=clock,
    )
    a = eng.add_request([1, 2, 3, 4], max_new_tokens=20)
    for _ in range(2):  # A prefills, then decodes one token
        eng.step()
        clock.tick()
    long_prompt = np.random.default_rng(7).integers(
        1, cfg.vocab_size, size=33
    ).tolist()
    b = eng.add_request(long_prompt, max_new_tokens=2)
    steps = 0
    a_tokens_during = 0
    while True:
        events = eng.step()
        clock.tick()
        steps += 1
        a_tokens_during += sum(1 for e in events if e.request_id == a)
        if any(e.request_id == b for e in events):
            break
        assert steps < 20, "B never produced a first token"
    # 33 tokens / 8-token budget = 5 chunked steps; A decoded through all
    assert steps == math.ceil(33 / 8)
    assert a_tokens_during == steps
    while eng.has_work:
        eng.step()
        clock.tick()
    recs = {r["request_id"]: r for r in eng.stats.requests}
    assert recs[b]["prefill_chunks"] == math.ceil(33 / 8)
    assert eng.trace_counts()["decode"] == 1


def test_chunked_prefill_srpt_orders_short_prompt_first(tiny_model):
    """Shortest-remaining-prompt-first: a short prompt submitted AFTER a
    long one still reaches its first token sooner — the budget goes to
    whoever can clear it fastest."""
    cfg, model, params = tiny_model
    clock = FakeClock()
    eng = ServingEngine(
        model, params, max_slots=2, block_size=8, num_blocks=32,
        prefill_chunk_tokens=8, now=clock,
    )
    prompts = _prompts(cfg, lens=(17, 5))
    long_id = eng.add_request(prompts[0], max_new_tokens=2)
    short_id = eng.add_request(prompts[1], max_new_tokens=2)
    while eng.has_work:
        eng.step()
        clock.tick()
    recs = {r["request_id"]: r for r in eng.stats.requests}
    assert recs[short_id]["ttft_s"] < recs[long_id]["ttft_s"]


def test_chunked_stall_preempts_instead_of_wedging(tiny_model):
    """The failure mode chunk-aware admission can produce: every seat
    mid-prefill, pool exhausted, nothing decoding — so nothing ever
    frees a block and nothing progresses. With preemption on, a stalled
    chunk parks the least-progressed prefill (KV swapped to host) so
    the leader finishes and the pool drains; every request still
    completes with exact greedy outputs."""
    cfg, model, params = tiny_model
    prompts = _prompts(cfg, lens=(40, 39, 38))
    base = ServingEngine(model, params, max_slots=3, block_size=4,
                         num_blocks=40)
    expected = _run_all(base, prompts, max_new_tokens=4)
    assert all(e is not None for e in expected)

    eng = ServingEngine(
        model, params, max_slots=3, block_size=4, num_blocks=13,
        prefill_chunk_tokens=8, preemption=True,
    )
    ids = [eng.add_request(p, max_new_tokens=4) for p in prompts]
    steps = 0
    while eng.has_work:
        eng.step()
        steps += 1
        assert steps < 300, "engine wedged: mid-prefill seats starved"
    assert [eng.result(r) for r in ids] == expected
    assert eng._preempt_counts["growth"] >= 1
    assert eng._resumes_total == sum(eng._preempt_counts.values()) >= 1
    stats = eng.pool.stats()
    assert stats["allocated"] == 0 and stats["swapped"] == 0
    assert eng.trace_counts()["decode"] == 1


# ---------------------------------------------------------------------- #
# preemption with KV swap
# ---------------------------------------------------------------------- #
def test_preempt_swap_resume_bitwise_parity(tiny_model):
    """A high-priority arrival evicts the low-priority seat; the victim's
    KV round-trips through host RAM and its final tokens are bitwise
    identical to an uncontended run. The swap programs compile once per
    pow2 width; the pool ends drained (nothing leaked, nothing stranded
    on the host)."""
    cfg, model, params = tiny_model
    prompts = _prompts(cfg, lens=(23, 17, 5))
    base = ServingEngine(model, params, max_slots=2, block_size=4,
                         num_blocks=33)
    expected = _run_all(base, prompts)

    eng = ServingEngine(
        model, params, max_slots=2, block_size=4, num_blocks=13,
        preemption=True,
    )
    victim = eng.add_request(prompts[0], max_new_tokens=6)
    for _ in range(2):  # seat it, decode a little — KV worth preserving
        eng.step()
    urgent = eng.add_request(prompts[1], max_new_tokens=6, priority=5)
    filler = eng.add_request(prompts[2], max_new_tokens=6)
    while eng.has_work:
        eng.step()

    assert [eng.result(r) for r in (victim, urgent, filler)] == expected
    assert eng._preempt_counts["priority"] == 1
    assert eng._resumes_total == 1
    counts = eng.trace_counts()
    assert counts["swap_out"] == 1 and counts["swap_in"] == 1
    stats = eng.pool.stats()
    assert stats["swap_outs_total"] == 1 and stats["swap_ins_total"] == 1
    assert stats["swapped"] == 0 and stats["allocated"] == 0
    recs = {r["request_id"]: r for r in eng.stats.requests}
    assert recs[victim]["preempted_count"] == 1
    assert recs[urgent]["preempted_count"] == 0
    assert counts["decode"] == 1, "preemption retraced decode"


def test_preemption_off_never_swaps(tiny_model):
    """Default-off contract: without ``preemption=True`` the same
    contended workload sees zero preemptions — the urgent request just
    waits its turn."""
    cfg, model, params = tiny_model
    prompts = _prompts(cfg, lens=(23, 17, 5))
    eng = ServingEngine(model, params, max_slots=2, block_size=4,
                        num_blocks=13)
    results = _run_all(eng, prompts)
    assert all(r is not None for r in results)
    assert eng._preempt_counts == {"priority": 0, "pool": 0, "growth": 0}
    assert eng.pool.stats()["swap_outs_total"] == 0
    assert eng.trace_counts().get("swap_out", 0) == 0


# ---------------------------------------------------------------------- #
# pool: swap ledger under fuzz
# ---------------------------------------------------------------------- #
def _invariant(pool: BlockPool, swapped: int) -> bool:
    """Device conservation (FREE/ALLOCATED/CACHED partition the
    allocatable blocks) plus the swap ledger: host images are counted
    OUTSIDE device occupancy and must match ours exactly."""
    return (
        pool.num_free + pool.num_allocated + pool.num_cached
        == pool.num_blocks - 1
    ) and pool.num_swapped == swapped


def test_block_pool_fuzz_with_swap_ops():
    """Randomized allocate/free/acquire/publish/swap_out/swap_in/
    swap_drop churn: the extended conservation law holds after EVERY op
    and no op corrupts a neighbour's refcount."""
    rng = random.Random(1)
    pool = BlockPool(num_blocks=17, block_size=4)
    held: list[int] = []  # one entry per reference we own
    swapped = 0
    published = 0
    for _ in range(3000):
        op = rng.random()
        if op < 0.30 and pool.can_allocate(n := rng.randint(1, 3)):
            held.extend(pool.allocate(n))
        elif op < 0.45 and held:
            b = held.pop(rng.randrange(len(held)))
            pool.free([b])
        elif op < 0.55 and held:
            b = held[rng.randrange(len(held))]
            pool.acquire([b])
            held.append(b)
        elif op < 0.65 and held:
            b = held[rng.randrange(len(held))]
            pool.publish(b, published.to_bytes(4, "big") * 8)
            published += 1
        elif op < 0.80 and held:
            # preempt: drop one of our references, grow the host ledger
            b = held.pop(rng.randrange(len(held)))
            pool.swap_out([b])
            swapped += 1
        elif op < 0.90 and swapped:
            n = rng.randint(1, swapped)
            if pool.can_allocate(n):
                held.extend(pool.swap_in(n))
                swapped -= n
        elif swapped:
            n = rng.randint(1, swapped)
            pool.swap_drop(n)
            swapped -= n
        assert _invariant(pool, swapped), "conservation law broken mid-fuzz"
        counts: dict[int, int] = {}
        for b in held:
            counts[b] = counts.get(b, 0) + 1
        assert all(pool.refcount(b) == n for b, n in counts.items())
    for b in held:
        pool.free([b])
    pool.swap_drop(swapped)
    assert _invariant(pool, 0)
    assert pool.num_allocated == 0


def test_swap_ledger_rejects_bad_ops():
    pool = BlockPool(num_blocks=8, block_size=4)
    with pytest.raises(ValueError, match="not allocated"):
        pool.swap_out([3])
    with pytest.raises(ValueError, match="swap_in"):
        pool.swap_in(1)
    with pytest.raises(ValueError, match="swap_drop"):
        pool.swap_drop(1)
    blocks = pool.allocate(2)
    pool.swap_out(blocks)
    assert pool.num_swapped == 2 and pool.num_free == 7
    back = pool.swap_in(2)
    assert len(back) == 2 and pool.num_swapped == 0


# ---------------------------------------------------------------------- #
# int8 paged KV
# ---------------------------------------------------------------------- #
def test_int8_kv_greedy_parity(tiny_model):
    """Per-block-scaled int8 KV must not change greedy outputs on
    prompts whose argmax has a healthy logit gap (quantization noise may
    flip genuine near-ties; that is the documented contract)."""
    cfg, model, params = tiny_model
    prompts = _prompts(cfg, lens=(23, 5, 17))
    base = ServingEngine(model, params, max_slots=4, block_size=8,
                         num_blocks=16)
    expected = _run_all(base, prompts)
    eng = ServingEngine(model, params, max_slots=4, block_size=8,
                        num_blocks=16, kv_dtype="int8")
    assert _run_all(eng, prompts) == expected
    assert eng.trace_counts()["decode"] == 1


def test_int8_kv_capacity_arithmetic(tiny_model):
    """The headline: int8 KV fits >= 1.8x the concurrent requests in the
    same HBM budget. bytes/token drops from 2*kvH*hd*itemsize to
    2*kvH*hd*1 + 2*4 (the fp32 per-token scales) per layer."""
    cfg, model, params = tiny_model
    fp = ServingEngine(model, params, max_slots=2, block_size=8,
                       num_blocks=16)
    i8 = ServingEngine(model, params, max_slots=2, block_size=8,
                       num_blocks=16, kv_dtype="int8")
    kv_heads, head_dim = cfg.num_kv_heads, cfg.head_dim
    itemsize = fp.kv_bytes_per_token / (
        cfg.num_layers * 2 * kv_heads * head_dim
    )
    assert itemsize in (2.0, 4.0)  # native KV is bf16/fp32, nothing else
    per_layer_i8 = 2 * kv_heads * head_dim * 1 + 2 * 4
    assert i8.kv_bytes_per_token == cfg.num_layers * per_layer_i8
    ratio = fp.kv_bytes_per_token / i8.kv_bytes_per_token
    assert ratio >= 1.8
    # same HBM budget, same per-request token footprint: strictly more
    # seats. 64 MiB budget, 512-token requests:
    budget, tokens = 64 << 20, 512
    fits_fp = budget // int(fp.kv_bytes_per_token * tokens)
    fits_i8 = budget // int(i8.kv_bytes_per_token * tokens)
    assert fits_i8 >= 1.8 * fits_fp


def test_kv_dtype_validation(tiny_model):
    cfg, model, params = tiny_model
    with pytest.raises(ValueError, match="kv_dtype"):
        ServingEngine(model, params, max_slots=2, kv_dtype="fp8")
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ServingEngine(model, params, max_slots=2, prefill_chunk_tokens=0)


# ---------------------------------------------------------------------- #
# composition
# ---------------------------------------------------------------------- #
def test_all_three_features_compose(tiny_model):
    """Chunked prefill + preemption + int8 KV together produce the same
    greedy outputs as int8 alone — the levers are orthogonal."""
    cfg, model, params = tiny_model
    prompts = _prompts(cfg)
    ref = ServingEngine(model, params, max_slots=4, block_size=8,
                        num_blocks=24, kv_dtype="int8")
    expected = _run_all(ref, prompts)
    eng = ServingEngine(
        model, params, max_slots=4, block_size=8, num_blocks=24,
        prefill_chunk_tokens=8, preemption=True, kv_dtype="int8",
    )
    assert _run_all(eng, prompts) == expected
    assert eng.trace_counts()["decode"] == 1


# ---------------------------------------------------------------------- #
# telemetry
# ---------------------------------------------------------------------- #
def test_preempt_counter_reaches_prometheus_sink():
    from accelerate_tpu.telemetry import PrometheusTextSink, StepTelemetry

    tel = StepTelemetry(True)
    sink = PrometheusTextSink(path=None)
    tel.add_sink(sink)
    tel.record_preempt(request_id="r1", reason="priority", blocks=8,
                       swap_bytes=4096, cache_len=25, priority=0)
    tel.record_preempt(request_id="r2", reason="growth")
    tel.record_preempt(request_id="r3", reason="priority")
    text = sink.render()
    assert "# TYPE accelerate_tpu_serve_preempt_total counter" in text
    assert 'accelerate_tpu_serve_preempt_total{reason="priority"} 2.0' in text
    assert 'accelerate_tpu_serve_preempt_total{reason="growth"} 1.0' in text
