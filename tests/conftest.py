"""Test environment: a virtual 8-device CPU backend.

This is the TPU build's equivalent of the reference's gloo/CPU debug
launcher (reference launchers.py:263, SURVEY §4 pattern 2): real XLA
collectives over 8 fake host devices so every sharding/mesh/collective path
runs anywhere. The axon sitecustomize forces ``jax_platforms=axon,cpu`` at
interpreter start, so we must override via jax.config (env vars are too
late), before any backend initializes.
"""

import os

os.environ.setdefault("ACCELERATE_TPU_TEST_NUM_DEVICES", "8")

import jax

if os.environ.get("ACCELERATE_TPU_TEST_ON_TPU", "0") != "1":
    jax.config.update("jax_platforms", "cpu")
    _num_devices = int(os.environ["ACCELERATE_TPU_TEST_NUM_DEVICES"])
    try:
        jax.config.update("jax_num_cpu_devices", _num_devices)
    except AttributeError:
        # jax < 0.5 has no jax_num_cpu_devices; the XLA flag still works
        # here because no backend has initialized at conftest import
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_num_devices}"
        ).strip()

# Persistent XLA compilation cache (VERDICT r4 weak #6: 34 min
# single-threaded on a 1-core box, nearly all of it XLA:CPU compiles of
# programs that do not change between runs). The cache key includes the
# program, the 8-device topology and the compile options, so hits are
# exact; a cold run populates ~/.cache-adjacent state in-repo (gitignored)
# and repeat runs skip recompilation. Disable with
# ACCELERATE_TPU_TEST_NO_CACHE=1 when hunting compiler-level issues.
if os.environ.get("ACCELERATE_TPU_TEST_NO_CACHE", "0") != "1":
    _cache_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_compile_cache",
    )
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    # XLA:CPU is not in the default allowlist; opt it in explicitly
    jax.config.update(
        "jax_persistent_cache_enable_xla_caches", "all"
    )
    # Deliberately NOT exported to subprocess tests via env vars: a
    # measured attempt deadlocked the multiprocess debug_launcher tier
    # (workers contending on the cache while racing their collective
    # rendezvous — 40 min hung at 13% CPU). Children recompile; the
    # in-process majority hits the cache.

import pytest


@pytest.fixture(autouse=True)
def reset_singletons():
    """Reference AccelerateTestCase (test_utils/testing.py:429) resets
    singleton state between tests; we do it for every test."""
    yield
    from accelerate_tpu.profiling import reset_program_registry
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
    reset_program_registry()
