"""Test environment: a virtual 8-device CPU backend.

This is the TPU build's equivalent of the reference's gloo/CPU debug
launcher (reference launchers.py:263, SURVEY §4 pattern 2): real XLA
collectives over 8 fake host devices so every sharding/mesh/collective path
runs anywhere. The axon sitecustomize forces ``jax_platforms=axon,cpu`` at
interpreter start, so we must override via jax.config (env vars are too
late), before any backend initializes.
"""

import os

os.environ.setdefault("ACCELERATE_TPU_TEST_NUM_DEVICES", "8")

import jax

if os.environ.get("ACCELERATE_TPU_TEST_ON_TPU", "0") != "1":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update(
        "jax_num_cpu_devices", int(os.environ["ACCELERATE_TPU_TEST_NUM_DEVICES"])
    )

import pytest


@pytest.fixture(autouse=True)
def reset_singletons():
    """Reference AccelerateTestCase (test_utils/testing.py:429) resets
    singleton state between tests; we do it for every test."""
    yield
    from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

    AcceleratorState._reset_state()
    GradientState._reset_state()
    PartialState._reset_state()
