"""Launcher tests — the reference's debug-launcher pattern (tests/
test_grad_sync.py:35 debug_launcher(...)): real multi-process collectives
on localhost CPU."""

import pytest

from accelerate_tpu.launchers import debug_launcher, notebook_launcher
from accelerate_tpu.test_utils.scripts.multiprocess_worker import (
    collective_worker,
    sharded_checkpoint_worker,
    training_worker,
)


def test_notebook_launcher_single_process():
    out = notebook_launcher(lambda x: x * 2, (21,), num_processes=1)
    assert out == 42


@pytest.mark.slow
def test_debug_launcher_collectives():
    debug_launcher(collective_worker, num_processes=2)


@pytest.mark.slow
def test_debug_launcher_training():
    debug_launcher(training_worker, num_processes=2)


@pytest.mark.slow
def test_debug_launcher_sharded_checkpoint(tmp_path):
    debug_launcher(sharded_checkpoint_worker, (str(tmp_path),), num_processes=2)


@pytest.mark.slow
def test_debug_launcher_local_sgd():
    from accelerate_tpu.test_utils.scripts.multiprocess_worker import (
        local_sgd_worker,
    )

    debug_launcher(local_sgd_worker, num_processes=2)


@pytest.mark.slow
@pytest.mark.parametrize("world", [2, 4])
def test_debug_launcher_full_test_script(world):
    """The reference runs its whole in-package assertion script under the
    launcher (test_utils/scripts/test_script.py); same here at world 2/4."""
    from accelerate_tpu.test_utils.scripts.test_script import run_all_checks

    debug_launcher(run_all_checks, num_processes=world)


@pytest.mark.slow
def test_notebook_launcher_multi_process():
    """notebook_launcher with num_processes > 1 on a CPU backend delegates
    to the debug launcher: real multi-process collectives, not a silent
    single-process run (VERDICT r2 weak #7 — this path was unexercised)."""
    notebook_launcher(collective_worker, num_processes=2)
