"""Profiling subsystem tests (SURVEY §5.1; reference
benchmarks/measures_util.py + ProfileKwargs handler shape)."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.profiling import (
    PeakHostMemory,
    ProfileKwargs,
    StepTimer,
    annotate,
    device_memory_stats,
    end_measure,
    host_memory_rss,
    profile,
    start_measure,
)


def test_measure_roundtrip():
    start = start_measure()
    x = jnp.ones((256, 256)) @ jnp.ones((256, 256))
    jax.block_until_ready(x)
    out = end_measure(start)
    assert out["time"] > 0
    assert "host" in out and "host-peak" in out
    assert "device:0" in out


def test_host_memory_rss_positive():
    assert host_memory_rss() > 1 << 20  # a Python process is >1MiB


def test_peak_host_memory_monitor():
    tracker = PeakHostMemory()
    tracker.start()
    blob = np.ones((4 << 20,), np.uint8)  # 4MiB spike
    peak = tracker.stop()
    assert peak >= host_memory_rss() - (64 << 20)
    del blob


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert set(stats) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}


def test_step_timer_skips_compile():
    timer = StepTimer(skip=1)
    with timer:
        for i in range(4):
            y = jnp.sin(jnp.ones((64,)) * i).sum()
            timer.tick(y)
    s = timer.summary()
    assert s["steps"] == 3  # first (compile) tick excluded
    assert s["mean_s"] >= 0 and s["p90_s"] >= s["median_s"] >= 0


def test_profile_noop_without_dir():
    with profile() as p:
        assert p is None


def test_profile_writes_trace(tmp_path):
    target = str(tmp_path / "trace")
    with profile(target) as p:
        assert p.dir == target
        with annotate("matmul-region"):
            jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    # xplane trace files land under plugins/profile/<ts>/
    found = glob.glob(os.path.join(target, "**", "*.xplane.pb"), recursive=True)
    assert found, os.listdir(target)


def test_profile_skip_first_defers_start(tmp_path):
    target = str(tmp_path / "trace")
    kw = ProfileKwargs(output_trace_dir=target, skip_first=2)
    with profile(kwargs=kw) as p:
        assert not p._started  # warmup: trace not yet running
        p.step()
        assert not p._started
        p.step()  # skip_first-th step: trace starts here
        assert p._started
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    found = glob.glob(os.path.join(target, "**", "*.xplane.pb"), recursive=True)
    assert found, os.listdir(target)


def test_profile_user_error_propagates(tmp_path):
    """A TypeError inside the profiled region must propagate unchanged
    (review finding: the old fallback swallowed it and double-yielded)."""
    with pytest.raises(TypeError, match="user bug"):
        with profile(str(tmp_path / "t")):
            raise TypeError("user bug")


def test_accelerator_profile_context(tmp_path):
    acc = Accelerator(
        profile_kwargs=ProfileKwargs(output_trace_dir=str(tmp_path / "t"))
    )
    with acc.profile() as p:
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    assert p.dir == str(tmp_path / "t")
    assert os.path.isdir(p.dir)


def test_accelerator_profile_noop_default():
    acc = Accelerator()
    with acc.profile() as p:
        pass
    assert p is None
