"""Profiling subsystem tests (SURVEY §5.1; reference
benchmarks/measures_util.py + ProfileKwargs handler shape)."""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu import Accelerator
from accelerate_tpu.utils.profiling import (
    PeakHostMemory,
    ProfileKwargs,
    StepTimer,
    annotate,
    device_memory_stats,
    end_measure,
    host_memory_rss,
    profile,
    start_measure,
)


def test_measure_roundtrip():
    start = start_measure()
    x = jnp.ones((256, 256)) @ jnp.ones((256, 256))
    jax.block_until_ready(x)
    out = end_measure(start)
    assert out["time"] > 0
    assert "host" in out and "host-peak" in out
    assert "device:0" in out


def test_host_memory_rss_positive():
    assert host_memory_rss() > 1 << 20  # a Python process is >1MiB


def test_peak_host_memory_monitor():
    tracker = PeakHostMemory()
    tracker.start()
    blob = np.ones((4 << 20,), np.uint8)  # 4MiB spike
    peak = tracker.stop()
    assert peak >= host_memory_rss() - (64 << 20)
    del blob


def test_device_memory_stats_shape():
    stats = device_memory_stats()
    assert set(stats) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}


def test_step_timer_skips_compile():
    timer = StepTimer(skip=1)
    with timer:
        for i in range(4):
            y = jnp.sin(jnp.ones((64,)) * i).sum()
            timer.tick(y)
    s = timer.summary()
    assert s["steps"] == 3  # first (compile) tick excluded
    assert s["mean_s"] >= 0 and s["p90_s"] >= s["median_s"] >= 0


def test_profile_noop_without_dir():
    with profile() as p:
        assert p is None


def test_profile_writes_trace(tmp_path):
    target = str(tmp_path / "trace")
    with profile(target) as p:
        assert p.dir == target
        with annotate("matmul-region"):
            jax.block_until_ready(jnp.ones((64, 64)) @ jnp.ones((64, 64)))
    # xplane trace files land under plugins/profile/<ts>/
    found = glob.glob(os.path.join(target, "**", "*.xplane.pb"), recursive=True)
    assert found, os.listdir(target)


def test_profile_skip_first_defers_start(tmp_path):
    target = str(tmp_path / "trace")
    kw = ProfileKwargs(output_trace_dir=target, skip_first=2)
    with profile(kwargs=kw) as p:
        assert not p._started  # warmup: trace not yet running
        p.step()
        assert not p._started
        p.step()  # skip_first-th step: trace starts here
        assert p._started
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    found = glob.glob(os.path.join(target, "**", "*.xplane.pb"), recursive=True)
    assert found, os.listdir(target)


def test_profile_user_error_propagates(tmp_path):
    """A TypeError inside the profiled region must propagate unchanged
    (review finding: the old fallback swallowed it and double-yielded)."""
    with pytest.raises(TypeError, match="user bug"):
        with profile(str(tmp_path / "t")):
            raise TypeError("user bug")


def test_accelerator_profile_context(tmp_path):
    acc = Accelerator(
        profile_kwargs=ProfileKwargs(output_trace_dir=str(tmp_path / "t"))
    )
    with acc.profile() as p:
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    assert p.dir == str(tmp_path / "t")
    assert os.path.isdir(p.dir)


def test_accelerator_profile_noop_default():
    acc = Accelerator()
    with acc.profile() as p:
        pass
    assert p is None


# ===================================================================== #
# HBM & compute attribution plane: program registry, live-buffer census,
# OOM forensics, op-level step breakdown (ISSUE 15)
# ===================================================================== #
import json
import subprocess
import sys

import optax

from accelerate_tpu import DataLoader, TelemetryConfig
from accelerate_tpu.profiling import (
    BufferCensus,
    ProgramRegistry,
    get_program_registry,
    read_oom_report,
    write_oom_report,
)
from accelerate_tpu.profiling.oom import (
    is_resource_exhausted,
    parse_requested_bytes,
)


def _loss(params, batch):
    pred = batch["x"] * params["w"] + params["b"]
    return jnp.mean(pred**2)


def _train_setup(acc):
    ds = [{"x": np.full((2,), float(i), np.float32)} for i in range(24)]
    loader = DataLoader(ds, batch_size=8, shuffle=False)
    params = {"w": jnp.asarray(1.0), "b": jnp.asarray(0.5)}
    # adam, not sgd: real optimizer-state arrays for the census to claim
    params, opt, prepared = acc.prepare(params, optax.adam(0.1), loader)
    step = acc.unified_step(_loss, opt)
    carry = acc.init_carry(params, opt)
    return step, carry, prepared


# --------------------------------------------------------------------- #
# program registry
# --------------------------------------------------------------------- #
def test_register_compiled_extracts_real_cost_numbers():
    reg = ProgramRegistry()
    compiled = (
        jax.jit(lambda a, b: a @ b)
        .lower(
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
            jax.ShapeDtypeStruct((64, 64), jnp.float32),
        )
        .compile()
    )
    rec = reg.register_compiled("toy_matmul", compiled, kind="train",
                                compile_seconds=0.25, note="unit")
    assert rec is reg.get("toy_matmul")
    assert rec.kind == "train"
    assert rec.compile_seconds == 0.25
    assert rec.meta["note"] == "unit"
    # XLA:CPU reports real numbers for both analyses on this program
    assert rec.argument_bytes == 2 * 64 * 64 * 4
    assert rec.flops > 0
    assert rec.bytes_accessed > 0
    assert rec.arithmetic_intensity > 0
    d = rec.as_dict()
    assert d["label"] == "toy_matmul" and d["flops"] == rec.flops


def test_registry_reregister_idempotent_and_top_programs_order():
    reg = ProgramRegistry()
    reg.register_analysis("small", kind="serve", temp_bytes=10)
    reg.register_analysis("big", kind="train", temp_bytes=1000)
    reg.register_analysis("mid", kind="serve", temp_bytes=100)
    # re-registering a label replaces, never duplicates
    reg.register_analysis("small", kind="serve", temp_bytes=20)
    assert len(reg) == 3
    top = reg.top_programs(2)
    assert [t["label"] for t in top] == ["big", "mid"]
    assert reg.temp_peak_bytes() == 1000  # MAX, not sum: serial execution


def test_ledger_sums_owned_plus_temp_peak_with_headroom():
    reg = ProgramRegistry()
    reg.register_analysis("a", kind="train", temp_bytes=300)
    reg.register_analysis("b", kind="serve", temp_bytes=700)
    led = reg.ledger(
        owner_bytes={"params": 1000, "kv_pool": 500},
        capacity_bytes=10_000,
    )
    assert led["owned_bytes"] == 1500
    assert led["program_temp_peak_bytes"] == 700
    assert led["budget_bytes"] == 1500 + 700
    assert led["capacity_bytes"] == 10_000
    assert led["headroom_bytes"] == 10_000 - 2200
    assert led["num_programs"] == 2
    assert led["owners"] == {"params": 1000, "kv_pool": 500}


def test_roofline_compute_vs_memory_bound_and_attribution_gap():
    reg = ProgramRegistry()
    # peak 100 FLOP/s, 10 B/s -> ridge intensity 10 FLOP/B
    reg.register_analysis("hot", kind="train", flops=1000.0,
                          bytes_accessed=10.0)  # intensity 100: compute
    reg.register_analysis("cold", kind="train", flops=10.0,
                          bytes_accessed=10.0)  # intensity 1: memory
    hot = reg.roofline("hot", peak_flops=100.0, peak_bytes_per_s=10.0)
    assert hot["bound"] == "compute"
    assert hot["peak_bound_mfu"] == 1.0
    cold = reg.roofline("cold", achieved_step_s=10.0,
                        peak_flops=100.0, peak_bytes_per_s=10.0)
    assert cold["bound"] == "memory"
    assert cold["peak_bound_mfu"] == pytest.approx(0.1)
    # memory-bound floor: 10 bytes / 10 B/s = 1s is the physics limit
    assert cold["peak_bound_step_s"] == pytest.approx(1.0)
    # achieved 10 FLOP in 10s on a 100 FLOP/s part = 1% MFU
    assert cold["achieved_mfu"] == pytest.approx(0.01)
    assert cold["attribution_gap"] == pytest.approx(0.1 - 0.01)


def test_roofline_unknown_label_or_missing_cost_is_none():
    reg = ProgramRegistry()
    reg.register_analysis("nocost", kind="train")  # CPU partial analysis
    assert reg.roofline("nope", peak_flops=1.0, peak_bytes_per_s=1.0) is None
    assert reg.roofline("nocost", peak_flops=1.0, peak_bytes_per_s=1.0) is None


# --------------------------------------------------------------------- #
# live-buffer census
# --------------------------------------------------------------------- #
def test_census_owner_sum_invariant_and_single_counting():
    a = jnp.ones((128, 128), jnp.float32)  # 64 KiB
    b = jnp.ones((64,), jnp.float32)
    census = BufferCensus()
    census.set_owner("mine", lambda: {"w": a})
    census.set_owner("mine_too", lambda: [a, b])  # a already claimed
    out = census.sample()
    owners = out["census_owner_bytes"]
    assert owners["mine"] == a.nbytes
    # each live array is counted exactly once, first claimant wins
    assert owners["mine_too"] == b.nbytes
    assert (
        sum(owners.values()) + out["census_unowned_bytes"]
        == out["census_total_bytes"]
    )
    assert out["census_arrays"] >= 2
    assert out["host_rss_bytes"] > 1 << 20
    assert out["host_rss_peak_bytes"] >= out["host_rss_bytes"]
    assert census.last is out  # the crash handler's snapshot


def test_census_provider_exception_falls_to_unowned():
    x = jnp.ones((256,), jnp.float32)
    census = BufferCensus()

    def bad():
        raise RuntimeError("provider broke")

    census.set_owner("broken", bad)
    census.set_owner("constant", x)  # non-callable wrapped as constant
    out = census.sample()
    assert out["census_owner_bytes"]["broken"] == 0
    assert out["census_owner_bytes"]["constant"] == x.nbytes
    assert out["census_unowned_bytes"] >= 0  # never fatal, stays summable


def test_census_wall_clock_throttle_and_force():
    census = BufferCensus(min_interval_s=3600.0)
    assert census.maybe_sample() is not None  # first sample always lands
    assert census.maybe_sample() is None  # throttled for the next hour
    assert census.maybe_sample(force=True) is not None  # bypass


# --------------------------------------------------------------------- #
# OOM forensics
# --------------------------------------------------------------------- #
def test_parse_requested_bytes_units_and_max():
    assert parse_requested_bytes("failed to allocate 1024 bytes") == 1024
    assert parse_requested_bytes(
        "allocating 2.5KiB after reserving 1KiB"
    ) == 2560  # MAX across matches, not the first
    assert parse_requested_bytes(
        "trying to allocate 12.5GiB"
    ) == int(12.5 * (1 << 30))
    assert parse_requested_bytes("no numbers here") is None


def test_is_resource_exhausted_markers():
    assert is_resource_exhausted(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_resource_exhausted(
        ValueError("XLA: Ran out of memory on device")
    )
    assert not is_resource_exhausted(TypeError("user bug"))


def test_oom_report_round_trip_with_ledger_census_pool(tmp_path):
    reg = ProgramRegistry()
    reg.register_analysis("decode", kind="serve", temp_bytes=512,
                          flops=10.0, bytes_accessed=5.0)
    census = {
        "census_total_bytes": 900,
        "census_unowned_bytes": 100,
        "census_owner_bytes": {"params": 500, "kv_pool": 300},
    }
    exc = RuntimeError(
        "RESOURCE_EXHAUSTED: failed to allocate 1048576 bytes"
    )
    path = write_oom_report(
        exc, context="unit", registry=reg, census=census,
        pool_stats={"num_blocks": 8}, directory=str(tmp_path),
        extra={"engine_steps": 3},
    )
    assert path == str(tmp_path / "oom-report.json")
    report = read_oom_report(str(tmp_path))
    assert report["kind"] == "oom_report"
    assert report["context"] == "unit"
    assert report["error_type"] == "RuntimeError"
    assert report["requested_bytes"] == 1048576
    assert report["ledger"]["owners"] == census["census_owner_bytes"]
    assert report["ledger"]["program_temp_peak_bytes"] == 512
    assert report["top_programs"][0]["label"] == "decode"
    assert report["census"] == census
    assert report["pool_stats"] == {"num_blocks": 8}
    assert report["extra"] == {"engine_steps": 3}
    # a file path is accepted too (diagnose hands either)
    assert read_oom_report(path)["context"] == "unit"
    assert read_oom_report(str(tmp_path / "missing")) is None


def test_oom_report_env_dir_override(tmp_path, monkeypatch):
    env_dir = tmp_path / "env_dir"
    monkeypatch.setenv("ACCELERATE_TPU_OOM_DIR", str(env_dir))
    path = write_oom_report(
        RuntimeError("RESOURCE_EXHAUSTED"), context="env",
        directory=str(tmp_path / "arg_dir"),
    )
    assert path == str(env_dir / "oom-report.json")
    assert read_oom_report(str(env_dir))["context"] == "env"


def test_oom_autopsy_survives_crashing_subprocess(tmp_path):
    """A RESOURCE_EXHAUSTED thrown inside the real train-step boundary
    must leave a parseable autopsy behind even though the process dies
    with a traceback — the report is written before the re-raise."""
    script = """
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import jax.numpy as jnp
import optax
from accelerate_tpu import Accelerator, DataLoader, TelemetryConfig

acc = Accelerator(telemetry=TelemetryConfig(census_interval=1,
                                            census_min_interval_s=0.0))
ds = [{"x": np.full((2,), float(i), np.float32)} for i in range(16)]
loader = DataLoader(ds, batch_size=8, shuffle=False)
params = {"w": jnp.asarray(1.0), "b": jnp.asarray(0.5)}
params, opt, prepared = acc.prepare(params, optax.sgd(0.1), loader)

def loss_fn(params, batch):
    raise RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "2147483648 bytes."
    )

step = acc.unified_step(loss_fn, opt)
carry = acc.init_carry(params, opt)
for batch in prepared:
    carry, _ = step(carry, batch)
"""
    env = dict(os.environ)
    env["ACCELERATE_TPU_OOM_DIR"] = str(tmp_path)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", script], env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode != 0  # the crash still crashes
    assert "RESOURCE_EXHAUSTED" in proc.stderr
    report = read_oom_report(str(tmp_path))
    assert report is not None, proc.stderr[-2000:]
    assert report["context"].startswith("train_step")
    assert report["requested_bytes"] == 2147483648
    assert "ledger" in report and "top_programs" in report


# --------------------------------------------------------------------- #
# op-level step breakdown (xplane wire reader)
# --------------------------------------------------------------------- #
def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field, payload):  # length-delimited field
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _vi(field, value):  # varint field
    return _varint(field << 3) + _varint(value)


def _meta_entry(mid, name):
    return _vi(1, mid) + _ld(2, _vi(1, mid) + _ld(2, name))


def _plane(name, events, metas):
    line = _ld(2, b"xla-ops") + _vi(3, 0)
    for mid, offset_ps, dur_ps in events:
        line += _ld(4, _vi(1, mid) + _vi(2, offset_ps) + _vi(3, dur_ps))
    plane = _ld(2, name) + _ld(3, line)
    for mid, mname in metas:
        plane += _ld(4, _meta_entry(mid, mname))
    return plane


def test_xplane_topk_self_time_subtracts_nested_children(tmp_path):
    from accelerate_tpu.compilation.overlap import (
        parse_xspace_planes,
        top_ops_from_plane,
    )

    metas = [(1, b"fusion.parent"), (2, b"sub.child"), (3, b"other.op")]
    # parent [0us,100us) encloses child [20us,50us): parent self = 70us
    us = 1_000_000  # ps per microsecond
    events = [(1, 0, 100 * us), (2, 20 * us, 30 * us), (3, 200 * us, 40 * us)]
    space = _ld(1, _plane(b"/device:TPU:0", events, metas))
    (plane,) = parse_xspace_planes(space)
    top = top_ops_from_plane(plane, k=2)
    assert [t["op"] for t in top] == ["fusion.parent", "other.op"]
    assert top[0]["self_time_ms"] == pytest.approx(0.070)
    assert top[1]["self_time_ms"] == pytest.approx(0.040)
    assert top[0]["count"] == 1


def test_top_self_time_ops_dir_walk_prefers_device_plane(tmp_path):
    from accelerate_tpu.compilation import top_self_time_ops

    host = _plane(b"/host:CPU", [(1, 0, 50)], [(1, b"host.noise")])
    dev = _plane(b"/device:TPU:0", [(1, 0, 80)], [(1, b"real.kernel")])
    (tmp_path / "t.xplane.pb").write_bytes(_ld(1, host) + _ld(1, dev))
    top = top_self_time_ops(str(tmp_path), k=5)
    assert [t["op"] for t in top] == ["real.kernel"]  # host plane dropped
    # host-only capture still yields a breakdown (the CPU test backend)
    host_only = tmp_path / "host_only"
    host_only.mkdir()
    (host_only / "h.xplane.pb").write_bytes(_ld(1, host))
    assert [t["op"] for t in top_self_time_ops(str(host_only))] == [
        "host.noise"
    ]


def test_top_self_time_ops_missing_or_empty_dir_is_none(tmp_path):
    from accelerate_tpu.compilation import top_self_time_ops

    assert top_self_time_ops(str(tmp_path / "nope")) is None
    (tmp_path / "garbage.xplane.pb").write_bytes(b"\xff\xff not a proto")
    assert top_self_time_ops(str(tmp_path)) is None  # never raises


# --------------------------------------------------------------------- #
# telemetry plumbing: sink gauges, unified record, leak rule
# --------------------------------------------------------------------- #
def test_prometheus_memory_gauges_with_label_escaping():
    from accelerate_tpu.telemetry import PrometheusTextSink

    sink = PrometheusTextSink(path=None)
    sink.emit({
        "kind": "memory", "label": "memory",
        "census_owner_bytes": {"params": 7.0, 'kv "pool"\n': 3.0},
        "census_unowned_bytes": 2,
        "census_total_bytes": 12,
        "hbm_bytes_in_use": 12,
    })
    text = sink.render()
    assert 'accelerate_tpu_hbm_bytes{owner="params"} 7.0' in text
    assert 'accelerate_tpu_hbm_bytes{owner="unowned"} 2.0' in text
    # Prometheus text exposition: " and newline escaped inside the label
    assert 'owner="kv \\"pool\\"\\n"' in text
    # the scalar fields ride as {prefix}_memory_* gauges
    assert "accelerate_tpu_memory_hbm_bytes_in_use" in text
    assert "accelerate_tpu_memory_census_total_bytes" in text


def test_collector_sample_memory_unifies_host_and_device(tmp_path):
    from accelerate_tpu.telemetry import StepTelemetry

    jsonl = tmp_path / "t.jsonl"
    tel = StepTelemetry(TelemetryConfig(
        jsonl_path=str(jsonl), census_min_interval_s=0.0,
    ))
    w = jnp.ones((64, 64), jnp.float32)
    tel.census.set_owner("weights", lambda: w)
    rec = tel.sample_memory(step=7, force=True)
    assert rec["kind"] == "memory"
    assert rec["step"] == 7
    assert rec["census_owner_bytes"]["weights"] == w.nbytes
    # one schema, host + device: the old PeakHostMemory RSS folded in
    assert rec["host_rss_bytes"] > 0
    assert "hbm_bytes_in_use" in rec and "hbm_bytes_limit" in rec
    tel.close()
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert any(l["kind"] == "memory" for l in lines)


def test_leak_rule_fires_on_monotone_unowned_growth():
    from accelerate_tpu.diagnostics import AnomalyDetector, DiagnosticsConfig

    det = AnomalyDetector(DiagnosticsConfig(
        leak_min_samples=3, leak_min_growth_bytes=1000,
    ))
    mk = lambda step, b: {  # noqa: E731
        "kind": "memory", "step": step, "census_unowned_bytes": b,
    }
    assert det.observe_memory(mk(1, 1000), now=1.0) == []
    assert det.observe_memory(mk(2, 3000), now=2.0) == []
    out = det.observe_memory(mk(3, 5000), now=3.0)
    assert len(out) == 1
    rec = out[0]
    assert rec["kind"] == "anomaly"
    assert rec["anomaly_type"] == "memory_leak"
    assert rec["growth_bytes"] == 4000
    assert rec["samples"] == 3


def test_leak_rule_flat_census_resets_the_trail():
    from accelerate_tpu.diagnostics import AnomalyDetector, DiagnosticsConfig

    det = AnomalyDetector(DiagnosticsConfig(
        leak_min_samples=3, leak_min_growth_bytes=1000,
    ))
    mk = lambda step, b: {  # noqa: E731
        "kind": "memory", "step": step, "census_unowned_bytes": b,
    }
    det.observe_memory(mk(1, 1000), now=1.0)
    det.observe_memory(mk(2, 3000), now=2.0)
    # one flat census resets the trail: a filling-then-stable pool is
    # NOT the leak shape
    assert det.observe_memory(mk(3, 3000), now=3.0) == []
    # three monotone samples but sub-threshold growth: still quiet
    assert det.observe_memory(mk(4, 3100), now=4.0) == []
    assert det.observe_memory(mk(5, 3200), now=5.0) == []
    assert det.observe_memory(mk(6, 9000), now=6.0) != []
    # owned growth and step records never reach the rule
    assert det.observe_memory({"kind": "step", "step": 7}, now=7.0) == []
    assert det.observe_memory({"kind": "memory", "step": 8}, now=8.0) == []


# --------------------------------------------------------------------- #
# integration: the plane attached to real train / serve programs
# --------------------------------------------------------------------- #
def test_warmup_registers_program_and_ledger_sums(tmp_path):
    """AOT warmup registers the real unified_step executable — the
    registry's ledger then sums owners + the program temp peak into one
    HBM budget."""
    acc = Accelerator(telemetry=TelemetryConfig(
        jsonl_path=str(tmp_path / "t.jsonl"),
    ))
    step, carry, prepared = _train_setup(acc)
    acc.warmup(step, carry, prepared)

    reg = get_program_registry()
    assert step.label in reg
    rec = reg.get(step.label)
    assert rec.kind == "train"
    assert rec.compile_seconds > 0
    assert rec.argument_bytes > 0  # XLA:CPU memory_analysis is real
    assert rec.meta.get("microbatches") == 1
    assert any(p["label"] == step.label for p in reg.top_programs(5))

    led = reg.ledger(owner_bytes={"params": 1 << 20, "opt_state": 1 << 19},
                     capacity_bytes=1 << 30)
    assert led["owned_bytes"] == (1 << 20) + (1 << 19)
    assert led["budget_bytes"] == (
        led["owned_bytes"] + led["program_temp_peak_bytes"]
    )
    assert led["headroom_bytes"] == (1 << 30) - led["budget_bytes"]
    assert led["num_programs"] == len(reg)
    acc.telemetry.close()


def test_census_owner_attribution_on_warmed_step(tmp_path):
    """With the census cadence on, a real warmed train loop emits
    kind="memory" records that attribute the live carry to the params /
    opt_state owners — and owners + unowned always sum to the total."""
    jsonl = tmp_path / "t.jsonl"
    acc = Accelerator(telemetry=TelemetryConfig(
        jsonl_path=str(jsonl), census_interval=1,
        census_min_interval_s=0.0,
    ))
    step, carry, prepared = _train_setup(acc)
    acc.warmup(step, carry, prepared)
    for batch in prepared:
        carry, metrics = step(carry, batch)
    assert np.isfinite(float(metrics["loss"]))
    acc.telemetry.close()

    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    mems = [l for l in lines if l["kind"] == "memory"]
    assert len(mems) >= 3  # cadence 1: one census per step
    last = mems[-1]
    owners = last["census_owner_bytes"]
    # the donated carry is re-resolved through providers at sample time,
    # so attribution survives buffer replacement every step
    assert owners["params"] > 0
    assert owners["opt_state"] > 0
    assert (
        sum(owners.values()) + last["census_unowned_bytes"]
        == last["census_total_bytes"]
    )
    assert last["host_rss_bytes"] > 0
    assert "hbm_bytes_in_use" in last and "step" in last


def test_zero_retraces_after_warmup_with_plane_enabled(tmp_path):
    """The attribution plane is passive: census cadence + program
    registry on, the warmed step still never retraces (the zero-retrace
    contract the trace counters pin)."""
    acc = Accelerator(telemetry=TelemetryConfig(
        jsonl_path=str(tmp_path / "t.jsonl"), census_interval=1,
        census_min_interval_s=0.0,
    ))
    step, carry, prepared = _train_setup(acc)
    acc.warmup(step, carry, prepared)
    detector = acc.telemetry.detector(step.label)
    signatures_after_warmup = len(detector._seen)
    steps = 0
    for batch in prepared:
        carry, _ = step(carry, batch)
        steps += 1
    assert steps >= 3
    assert detector.retraces == 0
    assert len(detector._seen) == signatures_after_warmup
    assert step.label in get_program_registry()
    acc.telemetry.close()


@pytest.fixture(scope="module")
def tiny_serving_model():
    from accelerate_tpu.models import CausalLM, TransformerConfig

    cfg = TransformerConfig.tiny(max_seq_len=64)
    model = CausalLM(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return cfg, model, params


def test_engine_capture_programs_registers_without_new_traces(
    tiny_serving_model,
):
    """capture_programs AOT-compiles the engine's warmed programs into
    the registry (prefill buckets, the ONE decode program, COW, the key
    chain) without disturbing the zero-retrace trace counters."""
    from accelerate_tpu.serving import ServingEngine

    cfg, model, params = tiny_serving_model
    engine = ServingEngine(model, params, max_slots=2, block_size=8)
    engine.add_request([1, 2, 3], max_new_tokens=2)
    for _ in engine.stream():
        pass
    counts_before = dict(engine.trace_counts())
    assert counts_before["decode"] == 1

    reg = ProgramRegistry()
    labels = engine.capture_programs(reg)
    assert "serve_decode" in labels
    assert "serve_cow" in labels
    assert "serve_key_chain" in labels
    assert any(l.startswith("serve_prefill_b") for l in labels)
    # AOT lower/compile shares nothing with the jit call cache: the
    # engine's retrace counters must be bit-identical afterwards
    assert dict(engine.trace_counts()) == counts_before
    dec = reg.get("serve_decode")
    assert dec is not None and dec.kind == "serve"
    assert dec.argument_bytes > 0
    pre = next(l for l in labels if l.startswith("serve_prefill_b"))
    assert reg.get(pre).meta["bucket"] >= 4
    # the Compiled artifacts are memoized: a second capture reuses every
    # one of them (zero fresh compiles) and the counters still hold
    compiles_after_first = engine.capture_compile_count
    assert compiles_after_first == len(labels)
    labels2 = engine.capture_programs(reg)
    assert labels2 == labels
    assert engine.capture_compile_count == compiles_after_first
    assert dict(engine.trace_counts()) == counts_before
    # ... and the auditor reuses the same capture-time artifacts too:
    # auditing adds no compiles and leaves the trace counters untouched
    audits = engine.audit_programs(reg, emit=False)
    assert set(audits) == set(labels)
    assert engine.capture_compile_count == compiles_after_first
    assert dict(engine.trace_counts()) == counts_before


# --------------------------------------------------------------------- #
# bench regression trend
# --------------------------------------------------------------------- #
def test_stamp_trend_flags_regressions_in_both_directions():
    from accelerate_tpu.benchmarks.runner import BenchRunner

    logs = []
    runner = BenchRunner(
        None, None, None, None,
        emit=lambda s: None, log=logs.append,
        baseline={
            "lat": {"value": 100.0, "unit": "s", "prev_round": "r06"},
            "thru": {"value": 100.0, "unit": "tokens/s/chip"},
        },
    )
    # lower-is-better metric got 20% slower: regression
    rec = {"variant": "lat", "metric": "t", "value": 120.0, "unit": "s"}
    runner._stamp_trend("lat", rec)
    assert rec["prev_value"] == 100.0
    assert rec["prev_round"] == "r06"
    assert rec["prev_delta_pct"] == pytest.approx(20.0)
    assert rec["regression"] is True
    # lower-is-better metric improved: clean
    rec = {"variant": "lat", "metric": "t", "value": 80.0, "unit": "s"}
    runner._stamp_trend("lat", rec)
    assert "regression" not in rec and rec["prev_delta_pct"] == -20.0
    # higher-is-better throughput dropped 20%: regression
    rec = {"variant": "thru", "metric": "t", "value": 80.0,
           "unit": "tokens/s/chip"}
    runner._stamp_trend("thru", rec)
    assert rec["regression"] is True
    # within the 10% band: stamped but never flagged
    rec = {"variant": "thru", "metric": "t", "value": 95.0,
           "unit": "tokens/s/chip"}
    runner._stamp_trend("thru", rec)
    assert "regression" not in rec
    # a budget-killed partial is stamped but not evidence of regression
    rec = {"variant": "lat", "metric": "t", "value": 200.0, "unit": "s",
           "partial": True}
    runner._stamp_trend("lat", rec)
    assert rec["prev_value"] == 100.0 and "regression" not in rec
    # unknown variant: untouched
    rec = {"variant": "new", "metric": "t", "value": 1.0, "unit": "s"}
    runner._stamp_trend("new", rec)
    assert "prev_value" not in rec


def test_parse_baseline_records_wrapper_and_final_wins(tmp_path):
    from accelerate_tpu.benchmarks.runner import (
        load_baseline,
        parse_baseline_records,
    )

    tail = "\n".join([
        "bench: starting",  # non-JSON noise in the tail
        json.dumps({"variant": "dense", "value": 50.0, "unit": "tokens/s",
                    "provisional": True}),
        json.dumps({"variant": "dense", "value": 55.0, "unit": "tokens/s"}),
        json.dumps({"variant": "ckpt", "skipped": "budget"}),
        json.dumps({"variant": "moe", "value": None}),
        json.dumps({"variant": "serve", "value": 9.0, "unit": "x",
                    "provisional": True}),
    ])
    wrapper = json.dumps({"n": "r06", "cmd": "bench", "rc": 0, "tail": tail})
    base = parse_baseline_records(wrapper)
    assert set(base) == {"dense", "serve"}  # skipped/null never a baseline
    assert base["dense"]["value"] == 55.0  # final displaced provisional
    assert base["dense"]["prev_round"] == "r06"
    assert base["serve"]["value"] == 9.0  # provisional-only still counts

    path = tmp_path / "BENCH_r06.json"
    path.write_text(wrapper)
    assert load_baseline(str(path))["dense"]["value"] == 55.0
    assert load_baseline(None, search_dir=str(tmp_path))["dense"][
        "value"] == 55.0
    assert load_baseline(None, search_dir=str(tmp_path / "empty")) == {}


# --------------------------------------------------------------------- #
# diagnose: the autopsy + census + top-ops sections
# --------------------------------------------------------------------- #
def test_diagnose_reports_memory_top_ops_and_oom_autopsy(tmp_path):
    import time

    from accelerate_tpu.diagnostics import build_report, format_report

    d = str(tmp_path)
    mem_rec = {
        "kind": "memory", "step": 5,
        "census_total_bytes": 1000, "census_unowned_bytes": 100,
        "census_owner_bytes": {"params": 600, "kv_pool": 300},
        "census_arrays": 12, "hbm_bytes_in_use": 1000,
        "host_rss_bytes": 5 << 20,
    }
    step_rec = {
        "kind": "step", "step": 6,
        "top_ops": [
            {"op": "fusion.1", "self_time_ms": 1.5, "count": 3},
            {"op": "all-reduce.2", "self_time_ms": 0.5, "count": 1},
        ],
        "top_ops_capture_dir": "/tmp/cap0",
    }
    payload = {
        "kind": "flight_recorder", "schema": 1, "process_index": 0,
        "pid": 1234, "reason": "periodic", "time_unix": time.time(),
        "last_step": 6, "last_checkpoint": None, "dumps": 1,
        "events": [], "records": [mem_rec, step_rec],
    }
    with open(os.path.join(d, "flightrec-rank0.json"), "w") as f:
        json.dump(payload, f)
    reg = ProgramRegistry()
    reg.register_analysis("serve_decode", kind="serve", temp_bytes=2048)
    write_oom_report(
        RuntimeError("RESOURCE_EXHAUSTED: could not allocate 4096 bytes"),
        context="serving_step", registry=reg,
        census=mem_rec, pool_stats={"num_blocks": 4}, directory=d,
    )

    report = build_report(d, stall_timeout_s=300.0)
    assert report["memory"][0]["census_owner_bytes"]["params"] == 600
    assert report["memory"][0]["step"] == 5
    assert report["top_ops"]["rank"] == 0
    assert report["top_ops"]["ops"][0]["op"] == "fusion.1"
    assert report["oom_report"]["context"] == "serving_step"
    assert report["oom_report"]["requested_bytes"] == 4096

    text = format_report(report)
    assert "Memory (latest census per rank)" in text
    assert "params" in text
    assert "fusion.1" in text
    assert "OOM AUTOPSY (serving_step)" in text
    assert "serve_decode" in text
